"""AWS EC2 provider.

Analog of fleetflow-cloud-aws (SURVEY.md §2.7). The reference feature-gates
this crate to dodge 6-7 GB builds (root Cargo.toml:39-45); this build
shells to the `aws` CLI for the same reason (no SDK dependency): instance
CRUD + power over EC2, with the instance-type mapping the reference keeps
in its models.
"""

from __future__ import annotations

import json
import shutil
import subprocess
from typing import Optional

from ..core.errors import CloudError
from ..core.model import CloudProviderDecl, ServerResource
from .action import Action, ActionType, ApplyResult, Plan
from .provider import (CloudProvider, ServerInfo, ServerProvider,
                       register_provider)
from .state import ProviderState, ResourceState

__all__ = ["AwsServerProvider", "AwsProvider", "instance_type_for"]

# plan -> instance type mapping (aws crate instance-type models)
_PLAN_MAP = {
    "nano": "t3.nano", "micro": "t3.micro", "small": "t3.small",
    "medium": "t3.medium", "large": "t3.large", "xlarge": "t3.xlarge",
}

# (vcpu, mem_gb) -> type ladder; picked as the smallest type satisfying
# BOTH axes (the reference keeps the same table in its instance-type
# models, fleetflow-cloud-aws instance type mapping)
_SIZE_LADDER = [
    (2, 1, "t3.micro"), (2, 2, "t3.small"), (2, 4, "t3.medium"),
    (2, 8, "t3.large"), (4, 16, "t3.xlarge"), (8, 32, "t3.2xlarge"),
    (8, 64, "m5.4xlarge"), (16, 128, "m5.8xlarge"),
]


def instance_type_for(plan: Optional[str], capacity_cpu: float = 2.0,
                      capacity_mem_mb: float = 4096.0) -> str:
    """Resolve an instance type from a plan alias, a literal type, or the
    declared capacity (smallest ladder entry covering cpu AND memory)."""
    if plan in _PLAN_MAP:
        return _PLAN_MAP[plan]
    if plan:
        return plan                    # already an instance type
    mem_gb = capacity_mem_mb / 1024.0
    for vcpu, gb, itype in _SIZE_LADDER:
        if capacity_cpu <= vcpu and mem_gb <= gb:
            return itype
    return "m5.8xlarge"


def _default_runner(args: list[str]) -> tuple[int, str]:
    if shutil.which("aws") is None:
        raise CloudError("aws CLI not found")
    proc = subprocess.run(["aws", *args], capture_output=True, text=True)
    return proc.returncode, proc.stdout if proc.returncode == 0 else proc.stderr


_MANAGED_TAG = "fleetflow:managed"


class AwsNetwork:
    """Subnet + security-group management (cloud_provider.rs:53-222).
    Resources created here carry the fleetflow:managed tag so list/destroy
    only ever touch what we made."""

    def __init__(self, provider: "AwsServerProvider"):
        self._p = provider

    # -- subnets -------------------------------------------------------
    def create_subnet(self, name: str, vpc_id: str, cidr: str,
                      az: Optional[str] = None) -> str:
        args = ["ec2", "create-subnet", "--vpc-id", vpc_id,
                "--cidr-block", cidr,
                "--tag-specifications",
                ("ResourceType=subnet,Tags=[{Key=Name,Value=%s},"
                 "{Key=%s,Value=true}]" % (name, _MANAGED_TAG))]
        if az:
            args += ["--availability-zone", az]
        doc = self._p._json(*args)
        sid = doc.get("Subnet", {}).get("SubnetId", "")
        if not sid:
            raise CloudError(f"create-subnet for {name!r} returned no id")
        return sid

    def delete_subnet(self, subnet_id: str) -> bool:
        rc, _ = self._p.runner(["ec2", "delete-subnet", "--subnet-id",
                                subnet_id, "--region", self._p.region,
                                "--output", "json"])
        return rc == 0

    def list_managed_subnets(self) -> list[tuple[str, str]]:
        """(subnet_id, name) pairs carrying the managed tag
        (cloud_provider.rs list_managed_subnets:96)."""
        doc = self._p._json("ec2", "describe-subnets", "--filters",
                            f"Name=tag:{_MANAGED_TAG},Values=true")
        out = []
        for s in doc.get("Subnets", []):
            name = next((t["Value"] for t in s.get("Tags", [])
                         if t.get("Key") == "Name"), "")
            out.append((s.get("SubnetId", ""), name))
        return out

    # -- security groups ----------------------------------------------
    def find_security_group(self, name: str) -> Optional[str]:
        doc = self._p._json("ec2", "describe-security-groups", "--filters",
                            f"Name=group-name,Values={name}")
        groups = doc.get("SecurityGroups", [])
        return groups[0].get("GroupId") if groups else None

    def create_security_group(self, name: str, vpc_id: str,
                              description: str = "fleetflow managed") -> str:
        doc = self._p._json(
            "ec2", "create-security-group", "--group-name", name,
            "--description", description, "--vpc-id", vpc_id,
            "--tag-specifications",
            ("ResourceType=security-group,Tags=[{Key=Name,Value=%s},"
             "{Key=%s,Value=true}]" % (name, _MANAGED_TAG)))
        gid = doc.get("GroupId", "")
        if not gid:
            raise CloudError(f"create-security-group {name!r} returned no id")
        return gid

    def authorize_ingress(self, sg_id: str, rules: list[dict]) -> None:
        """rules: [{port, protocol?, cidr?}] -> one authorize call each
        (cloud_provider.rs authorize_ingress:173). Duplicate-rule errors
        are tolerated: ensure_security_group re-runs on every apply."""
        for rule in rules:
            rc, out = self._p.runner([
                "ec2", "authorize-security-group-ingress",
                "--group-id", sg_id,
                "--protocol", str(rule.get("protocol", "tcp")),
                "--port", str(rule["port"]),
                "--cidr", str(rule.get("cidr", "0.0.0.0/0")),
                "--region", self._p.region, "--output", "json"])
            if rc != 0 and "Duplicate" not in out:
                raise CloudError(f"authorize ingress {rule} failed: "
                                 f"{out.strip()}")

    def ensure_security_group(self, name: str, vpc_id: str,
                              rules: list[dict]) -> str:
        gid = self.find_security_group(name)
        if gid is None:
            gid = self.create_security_group(name, vpc_id)
        self.authorize_ingress(gid, rules)
        return gid

    def delete_security_group(self, sg_id: str) -> bool:
        rc, _ = self._p.runner(["ec2", "delete-security-group",
                                "--group-id", sg_id, "--region",
                                self._p.region, "--output", "json"])
        return rc == 0


class AwsServerProvider(ServerProvider):
    name = "aws"

    def __init__(self, region: str = "ap-northeast-1", runner=None):
        self.region = region
        self.runner = runner or _default_runner
        self.network = AwsNetwork(self)

    def _json(self, *args: str) -> dict:
        rc, out = self.runner([*args, "--region", self.region,
                               "--output", "json"])
        if rc != 0:
            raise CloudError(f"aws {' '.join(args[:3])} failed: {out.strip()}")
        try:
            return json.loads(out or "{}")
        except json.JSONDecodeError:
            raise CloudError(f"aws returned non-JSON: {out[:200]}") from None

    @staticmethod
    def _info(inst: dict) -> ServerInfo:
        name = next((t["Value"] for t in inst.get("Tags", [])
                     if t.get("Key") == "Name"), inst.get("InstanceId", ""))
        return ServerInfo(
            id=inst.get("InstanceId", ""),
            name=name,
            status={"running": "up", "stopped": "down"}.get(
                inst.get("State", {}).get("Name", ""), "unknown"),
            ip=inst.get("PublicIpAddress") or inst.get("PrivateIpAddress"),
            plan=inst.get("InstanceType"),
            zone=inst.get("Placement", {}).get("AvailabilityZone"),
            tags=[t["Value"] for t in inst.get("Tags", [])
                  if t.get("Key") != "Name"])

    def list_servers(self) -> list[ServerInfo]:
        doc = self._json("ec2", "describe-instances")
        out = []
        for res in doc.get("Reservations", []):
            for inst in res.get("Instances", []):
                if inst.get("State", {}).get("Name") != "terminated":
                    out.append(self._info(inst))
        return out

    def get_server(self, server_id: str) -> Optional[ServerInfo]:
        for s in self.list_servers():
            if s.id == server_id or s.name == server_id:
                return s
        return None

    def create_server(self, spec: ServerResource,
                      subnet_id: Optional[str] = None,
                      security_group_ids: Optional[list[str]] = None,
                      script_vars: Optional[dict] = None) -> ServerInfo:
        """run-instances with the network objects + startup script
        (cloud_provider.rs create path): instance type from plan/capacity
        (cpu AND memory), builtin startup scripts ride --user-data with
        @@VAR@@ substitution, root disk size from disk_size."""
        args = ["ec2", "run-instances",
                "--instance-type", instance_type_for(spec.plan,
                                                     spec.capacity.cpu,
                                                     spec.capacity.memory),
                "--tag-specifications",
                ("ResourceType=instance,Tags=[{Key=Name,Value=%s},"
                 "{Key=%s,Value=true}]" % (spec.name, _MANAGED_TAG)),
                "--count", "1"]
        if spec.os:
            args += ["--image-id", spec.os]
        if subnet_id:
            args += ["--subnet-id", subnet_id]
        if security_group_ids:
            args += ["--security-group-ids", *security_group_ids]
        if spec.ssh_keys:
            args += ["--key-name", spec.ssh_keys[0]]
        if spec.disk_size:
            args += ["--block-device-mappings",
                     json.dumps([{"DeviceName": "/dev/sda1",
                                  "Ebs": {"VolumeSize": spec.disk_size,
                                          "DeleteOnTermination": True}}])]
        if spec.startup_script:
            from .startup_scripts import get_builtin_script, substitute_vars
            content = (get_builtin_script(spec.startup_script)
                       or spec.startup_script)
            content = substitute_vars(content, script_vars,
                                      context=spec.startup_script)
            # raw text: the AWS CLI base64-encodes --user-data itself;
            # pre-encoding here would double-encode and cloud-init would
            # see base64 soup instead of a shebang
            args += ["--user-data", content]
        doc = self._json(*args)
        instances = doc.get("Instances", [])
        return (self._info(instances[0]) if instances
                else ServerInfo(id="", name=spec.name))

    def delete_server(self, server_id: str) -> bool:
        rc, _ = self.runner(["ec2", "terminate-instances", "--instance-ids",
                             server_id, "--region", self.region,
                             "--output", "json"])
        return rc == 0

    def power_on(self, server_id: str) -> bool:
        rc, _ = self.runner(["ec2", "start-instances", "--instance-ids",
                             server_id, "--region", self.region,
                             "--output", "json"])
        return rc == 0

    def power_off(self, server_id: str) -> bool:
        rc, _ = self.runner(["ec2", "stop-instances", "--instance-ids",
                             server_id, "--region", self.region,
                             "--output", "json"])
        return rc == 0


class AwsProvider(CloudProvider):
    name = "aws"

    def __init__(self, region: str = "ap-northeast-1", runner=None):
        self.servers = AwsServerProvider(region=region, runner=runner)

    def check_auth(self) -> bool:
        try:
            rc, _ = self.servers.runner(["sts", "get-caller-identity",
                                         "--output", "json"])
            return rc == 0
        except CloudError:
            return False

    def get_state(self) -> ProviderState:
        st = ProviderState(provider=self.name)
        for s in self.servers.list_servers():
            st.upsert(ResourceState(id=s.id, type="server", name=s.name,
                                    attributes={"status": s.status,
                                                "ip": s.ip,
                                                "type": s.plan}))
        return st

    def plan(self, decl: CloudProviderDecl,
             servers: list[ServerResource]) -> Plan:
        """Diff model incl. network objects: when the provider declaration
        carries `vpc` (+ optional `subnet-cidr`, `ingress` port list), the
        plan ensures one managed security group (and subnet) ahead of the
        instances that reference them (cloud_provider.rs plan path)."""
        current = {r.name: r for r in self.get_state().by_type("server")}
        plan = Plan(provider=self.name)
        opts = decl.options or {}
        vpc = opts.get("vpc")
        sg_name = sn_name = None
        if vpc:
            sg_name = opts.get("security-group",
                               f"fleetflow-{decl.name or self.name}")
            if self.servers.network.find_security_group(sg_name) is None:
                plan.actions.append(Action(
                    ActionType.CREATE, "security_group", sg_name,
                    f"vpc={vpc} ingress={opts.get('ingress', [])}",
                    desired={"vpc": vpc,
                             "ingress": list(opts.get("ingress", []))}))
            if opts.get("subnet-cidr"):
                have = {n for _, n in
                        self.servers.network.list_managed_subnets()}
                sn_name = opts.get("subnet",
                                   f"fleetflow-{self.servers.region}")
                if sn_name not in have:
                    plan.actions.append(Action(
                        ActionType.CREATE, "subnet", sn_name,
                        f"cidr={opts['subnet-cidr']}",
                        desired={"vpc": vpc, "cidr": opts["subnet-cidr"],
                                 "az": opts.get("az")}))
        desired = set()
        for spec in servers:
            if spec.provider not in (None, self.name):
                continue
            desired.add(spec.name)
            if spec.name in current:
                plan.actions.append(Action(ActionType.NOOP, "server",
                                           spec.name, "exists"))
            else:
                plan.actions.append(Action(
                    ActionType.CREATE, "server", spec.name,
                    instance_type_for(spec.plan, spec.capacity.cpu,
                                      spec.capacity.memory),
                    desired={"name": spec.name, "plan": spec.plan,
                             "os": spec.os, "disk_size": spec.disk_size,
                             "startup_script": spec.startup_script,
                             "ssh_keys": spec.ssh_keys,
                             "cpu": spec.capacity.cpu,
                             "memory": spec.capacity.memory,
                             # network objects BY NAME: apply resolves them
                             # whether created this run or pre-existing
                             "sg_name": sg_name, "subnet_name": sn_name,
                             "script_vars": dict(
                                 opts.get("script-vars") or {},
                                 SERVER_SLUG=spec.name)}))
        for name, res in current.items():
            if name not in desired:
                plan.actions.append(Action(ActionType.DELETE, "server", name,
                                           "not in config",
                                           current={"id": res.id}))
        return plan

    def apply(self, plan: Plan) -> ApplyResult:
        result = ApplyResult()
        # name -> id caches; seeded by CREATE actions in this run, filled
        # by lookup for pre-existing network objects (apply #2 onward must
        # wire new servers into the SG/subnet created by apply #1)
        sg_cache: dict[str, str] = {}
        subnet_cache: dict[str, str] = {}

        def resolve_sg(name: Optional[str]) -> Optional[list[str]]:
            if not name:
                return None
            if name not in sg_cache:
                gid = self.servers.network.find_security_group(name)
                if gid is None:
                    raise CloudError(f"security group {name!r} not found")
                sg_cache[name] = gid
            return [sg_cache[name]]

        def resolve_subnet(name: Optional[str]) -> Optional[str]:
            if not name:
                return None
            if name not in subnet_cache:
                for sid, n in self.servers.network.list_managed_subnets():
                    subnet_cache.setdefault(n, sid)
                if name not in subnet_cache:
                    raise CloudError(f"managed subnet {name!r} not found")
            return subnet_cache[name]

        for action in plan.changes:
            try:
                if (action.type is ActionType.CREATE
                        and action.resource_type == "security_group"):
                    d = action.desired or {}
                    gid = self.servers.network.ensure_security_group(
                        action.resource_id, d["vpc"],
                        [{"port": p} for p in d.get("ingress", [])])
                    sg_cache[action.resource_id] = gid
                    result.outputs[action.resource_id] = {"id": gid}
                elif (action.type is ActionType.CREATE
                        and action.resource_type == "subnet"):
                    d = action.desired or {}
                    sid = self.servers.network.create_subnet(
                        action.resource_id, d["vpc"], d["cidr"],
                        az=d.get("az"))
                    subnet_cache[action.resource_id] = sid
                    result.outputs[action.resource_id] = {"id": sid}
                elif action.type is ActionType.CREATE:
                    d = action.desired or {}
                    from ..core.model import ResourceSpec
                    spec = ServerResource(
                        name=action.resource_id, plan=d.get("plan"),
                        os=d.get("os"), disk_size=d.get("disk_size"),
                        startup_script=d.get("startup_script"),
                        ssh_keys=list(d.get("ssh_keys") or []))
                    if d.get("cpu") or d.get("memory"):
                        spec.capacity = ResourceSpec(
                            cpu=float(d.get("cpu") or 2.0),
                            memory=float(d.get("memory") or 4096.0))
                    info = self.servers.create_server(
                        spec, subnet_id=resolve_subnet(d.get("subnet_name")),
                        security_group_ids=resolve_sg(d.get("sg_name")),
                        script_vars=d.get("script_vars") or None)
                    if not info.id:
                        raise CloudError(
                            f"create of {action.resource_id} returned no id")
                    result.outputs[action.resource_id] = {"id": info.id}
                elif action.type is ActionType.DELETE:
                    if not self.servers.delete_server(
                            (action.current or {}).get("id",
                                                       action.resource_id)):
                        raise CloudError(
                            f"delete of {action.resource_id} failed")
                result.succeeded.append(action)
            except CloudError as e:
                result.failed.append((action, str(e)))
        return result


register_provider("aws", AwsProvider)
