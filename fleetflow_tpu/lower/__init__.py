"""Lowering pass: Flow → dense constraint tensors for the TPU solver."""

from .tensors import (LOCAL_NODE_NAME, ProblemTensors, dependency_depths,
                      lower_stage, synthetic_problem)
