"""Lowering: Flow + stage → dense constraint tensors (the TPU on-ramp).

This is the reformulation at the heart of the framework (BASELINE.json
north star): the reference's placement inputs — `depends_on` DAGs
(engine.rs:67-85), host-port bindings (converter.rs port bindings), volume
binds, server capacity/labels and placement policies (control-plane
model.rs:82-95,400-442) — become dense, device-ready arrays:

  demand        (S, R) f32   per-service resource demand (cpu, memMiB, diskMiB)
  capacity      (N, R) f32   per-node capacity
  dep_adj       (S, S) bool  dep_adj[i, j] = i depends on j (start ordering)
  dep_depth     (S,)   i32   topological depth (Kahn levels; cycles rejected)
  port_ids      (S, P) i32   host-port conflict ids, -1 padded (anti-affinity)
  volume_ids    (S, V) i32   exclusive-volume conflict ids, -1 padded
  anti_ids      (S, A) i32   explicit anti-affinity group ids, -1 padded
  coloc_ids     (S, C) i32   colocation group ids, -1 padded (soft)
  eligible      (S, N) bool  label/tier eligibility mask
  node_valid    (N,)   bool  membership/health mask (churn flips bits here)
  node_topology (N,)   i32   topology-domain id for the spread constraint

Everything is numpy here (host, pure, unit-testable); the solver uploads
once and keeps the tensors device-resident across re-solves.

Replicas are expanded at lowering time: `service "w" { replicas 3 }` becomes
rows w#0, w#1, w#2 sharing demand/ports/volumes; replica host-port conflicts
make replicas of a port-publishing service mutually anti-affine exactly like
the reference's one-host-port-per-node reality.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..core.errors import SolverError
from ..core.model import (ServiceType, Flow, PlacementPolicy, PlacementStrategy,
                          ResourceSpec, ServerResource, Service)

__all__ = ["ProblemTensors", "lower_stage", "dependency_depths",
           "LOCAL_NODE_NAME", "local_node", "synthetic_problem"]

LOCAL_NODE_NAME = "local"

# fallback-policy constraint-class aliases that relax the eligibility mask
# (single source; sched/fallback.py imports these)
ELIGIBILITY_RELAX_CLASSES = ("tier", "required_labels", "labels",
                             "eligibility")
SPREAD_RELAX_CLASSES = ("spread", "spread_constraint")
PREF_RELAX_CLASSES = ("preferred_labels", "preferred")
_R = len(ResourceSpec.axes())  # cpu, memory, disk


@dataclass
class ProblemTensors:
    service_names: list[str]
    node_names: list[str]
    demand: np.ndarray          # (S, R) f32
    capacity: np.ndarray        # (N, R) f32
    dep_adj: np.ndarray         # (S, S) bool
    dep_depth: np.ndarray       # (S,) i32
    port_ids: np.ndarray        # (S, P) i32, -1 pad
    volume_ids: np.ndarray      # (S, V) i32, -1 pad
    anti_ids: np.ndarray        # (S, A) i32, -1 pad
    coloc_ids: np.ndarray       # (S, C) i32, -1 pad
    eligible: np.ndarray        # (S, N) bool
    node_valid: np.ndarray      # (N,) bool
    node_topology: np.ndarray   # (N,) i32
    strategy: PlacementStrategy = PlacementStrategy.SPREAD_ACROSS_POOL
    max_skew: int = 0           # 0 = no spread constraint
    preferred: Optional[np.ndarray] = None  # (S, N) f32 soft preference, or None
    replica_of: list[str] = field(default_factory=list)  # base service per row
    # constraint classes to relax, in order, when infeasible (stage
    # placement fallback{}; reference model.rs:49 FallbackPolicy)
    relax_order: list[str] = field(default_factory=list)

    @property
    def S(self) -> int:
        return self.demand.shape[0]

    @property
    def N(self) -> int:
        return self.capacity.shape[0]

    def validate(self) -> None:
        S, N = self.S, self.N
        assert self.demand.shape == (S, _R)
        assert self.capacity.shape == (N, _R)
        assert self.dep_adj.shape == (S, S)
        assert self.dep_depth.shape == (S,)
        assert self.eligible.shape == (S, N)
        assert self.node_valid.shape == (N,)
        assert self.node_topology.shape == (N,)
        for arr in (self.port_ids, self.volume_ids, self.anti_ids, self.coloc_ids):
            assert arr.ndim == 2 and arr.shape[0] == S


def dependency_depths(dep_adj: np.ndarray,
                      names: Optional[list[str]] = None,
                      edges: Optional[list[tuple[int, int]]] = None,
                      ) -> np.ndarray:
    """Kahn-style level assignment: depth(s) = 1 + max(depth(deps)), 0 for
    roots. Rejects cycles. This replaces the reference's single-pass
    partition (engine.rs:67-85 `order_by_dependencies`, which is NOT a true
    topo sort) with an exact level schedule that vectorizes: all services at
    depth d can start concurrently once depth d-1 is ready."""
    S = dep_adj.shape[0]
    # Kahn over the edge LIST, not the dense matrix: per-level scans of a
    # fancy-indexed (S, unresolved) submatrix copy cost ~2.5 s at 10k
    # services (pipeline bench, VERDICT r4 item 3); with E edges this is
    # O(S + E) after one pass extracting the edges.  A caller that already
    # holds the (src, dst) pairs (lower_stage fills dep_adj from them)
    # passes `edges` to skip the full-matrix nonzero scan (~0.25 s at 10k).
    if edges is not None:
        # two accepted forms: a (src_array, dst_array) PAIR — required to
        # actually be arrays, so a tuple of exactly two (src, dst) edge
        # pairs can never be misread as one — or any sequence of pairs
        if (isinstance(edges, tuple) and len(edges) == 2
                and isinstance(edges[0], np.ndarray)
                and isinstance(edges[1], np.ndarray)):
            src = edges[0].astype(np.int64, copy=False)
            dst = edges[1].astype(np.int64, copy=False)
        else:
            src = np.fromiter((e[0] for e in edges), dtype=np.int64,
                              count=len(edges))
            dst = np.fromiter((e[1] for e in edges), dtype=np.int64,
                              count=len(edges))
    else:
        src, dst = np.nonzero(dep_adj)      # src depends on dst
    indeg = np.bincount(src, minlength=S).astype(np.int64)
    # CSR adjacency dst -> [dependents]: each level then processes ALL its
    # outgoing edges with array gathers/scatters instead of a per-edge
    # Python loop (the loop was ~45 ms of every 10k-service lowering)
    order = np.argsort(dst, kind="stable")
    src_by_dst = src[order]
    counts = np.bincount(dst, minlength=S)
    indptr = np.zeros(S + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    depth = np.zeros(S, dtype=np.int32)
    level = np.flatnonzero(indeg == 0)
    resolved = int(level.size)
    while level.size:
        starts, ends = indptr[level], indptr[level + 1]
        n_out = ends - starts
        if not n_out.any():
            break
        # flatten this level's CSR ranges: edge i runs from dep d=level[k]
        # to dependent s=src_by_dst[starts[k] + j]
        reps = np.repeat(level, n_out)
        offs = np.arange(int(n_out.sum())) - np.repeat(
            np.cumsum(n_out) - n_out, n_out)
        ss = src_by_dst[np.repeat(starts, n_out) + offs]
        np.maximum.at(depth, ss, depth[reps] + 1)
        np.subtract.at(indeg, ss, 1)
        cand = np.unique(ss)
        level = cand[indeg[cand] == 0]
        resolved += int(level.size)
    if resolved < S:
        cyc = np.flatnonzero(indeg > 0)
        label = ([names[i] for i in cyc[:5]] if names else cyc[:5].tolist())
        raise SolverError(f"dependency cycle among services {label}")
    return depth


def _pad_ids(groups: list[list[int]], pad_to_multiple: int = 1) -> np.ndarray:
    """list-of-id-lists → (S, K) int32 padded with -1 (vectorized: the
    per-row slice-assign loop cost ~90 ms of every 10k-service lowering)."""
    n = len(groups)
    lens = np.fromiter(map(len, groups), dtype=np.int64, count=n)
    total = int(lens.sum())
    k = max(int(lens.max(initial=0)), 1)
    if pad_to_multiple > 1:
        k = ((k + pad_to_multiple - 1) // pad_to_multiple) * pad_to_multiple
    out = np.full((n, k), -1, dtype=np.int32)
    if total:
        flat = np.fromiter(
            (g for row in groups for g in row), dtype=np.int32, count=total)
        rows = np.repeat(np.arange(n), lens)
        cols = np.arange(total) - np.repeat(np.cumsum(lens) - lens, lens)
        out[rows, cols] = flat
    return out


def _server_matches(policy: Optional[PlacementPolicy],
                    server: ServerResource) -> bool:
    if policy is None:
        return True
    labels = server.labels.as_dict()
    if policy.tier is not None and labels.get("tier") not in (None, policy.tier):
        return False
    for k, v in policy.required_labels.items():
        if labels.get(k) != v:
            return False
    return True


def _preference_row(policy: Optional[PlacementPolicy],
                    server: ServerResource) -> float:
    if policy is None or not policy.preferred_labels:
        return 0.0
    labels = server.labels.as_dict()
    hits = sum(1 for k, v in policy.preferred_labels.items()
               if labels.get(k) == v)
    return hits / max(len(policy.preferred_labels), 1)


def local_node(name: str = LOCAL_NODE_NAME) -> ServerResource:
    """The single implicit node of local execution (`fleet up` / CP-local
    deploys) or an agent's synthetic level-schedule node: generous
    capacity, so placement degenerates to ordering."""
    return ServerResource(
        name=name,
        capacity=ResourceSpec(cpu=1e6, memory=1e9, disk=1e9))


def lower_stage(flow: Flow, stage_name: str,
                nodes: Optional[list[ServerResource]] = None,
                local: bool = False) -> ProblemTensors:
    """Lower one stage of a Flow into ProblemTensors.

    Node set: explicit `nodes` arg > stage.servers > all flow.servers > a
    single implicit "local" node with generous capacity (the `fleet up local`
    story, where placement degenerates to ordering).

    `local=True` lowers for single-machine execution: node-targeting
    constraints (label/tier eligibility, explicit anti-affinity, spread)
    are dropped — they describe cross-node placement and would otherwise
    fail a local deploy of a policied stage — while port/volume conflicts
    stay (two containers genuinely cannot bind one host port here).
    """
    stage = flow.stage(stage_name)
    # static sites ship via wrangler Pages, not containers: they consume no
    # node capacity and must not occupy port/conflict groups in the solve;
    # dependencies pointing AT them are vacuous for placement (the static
    # build/deploy runs before the container loop)
    resolved = stage.resolved_services(flow)
    static_names = {s.name for s in resolved
                    if s.service_type is ServiceType.STATIC}
    services = [s for s in resolved if s.name not in static_names]
    if not services and static_names:
        raise SolverError(
            f"stage {stage_name!r} is static-only (services "
            f"{sorted(static_names)} deploy via Pages); nothing to place")
    policy = stage.placement
    if local:
        # single-machine execution: the policy's node-targeting parts
        # (eligibility/preference/spread) describe a fleet this machine
        # isn't; quotas still apply (they bound the stage, not a node)
        policy = None if stage.placement is None else dataclasses.replace(
            stage.placement, tier=None, required_labels={},
            preferred_labels={}, spread_constraint=None)

    if nodes is None:
        if stage.servers:
            missing = [s for s in stage.servers if s not in flow.servers]
            if missing:
                raise SolverError(
                    f"stage {stage_name!r} references unknown servers {missing}")
            nodes = [flow.servers[s] for s in stage.servers]
        elif flow.servers:
            nodes = list(flow.servers.values())
        else:
            nodes = [local_node()]

    # ---- replica expansion -------------------------------------------------
    if all(s.replicas <= 1 for s in services):
        # no expansion at all (the fleet-scale aggregation shape): rows
        # ARE the services, and every per-row list is built in one pass
        rows = list(services)
        row_names = [s.name for s in services]
        replica_of = row_names
        base_index = {n: [i] for i, n in enumerate(row_names)}
    else:
        rows: list[Service] = []
        row_names, replica_of = [], []
        base_index = {}
        for svc in services:
            reps = max(svc.replicas, 1)
            name = svc.name
            if reps == 1:
                base_index[name] = [len(rows)]
                rows.append(svc)
                row_names.append(name)
                replica_of.append(name)
                continue
            idxs = list(range(len(rows), len(rows) + reps))
            rows.extend([svc] * reps)
            row_names.extend(f"{name}#{r}" for r in range(reps))
            replica_of.extend([name] * reps)
            base_index[name] = idxs
    S, N = len(rows), len(nodes)
    if S == 0:
        raise SolverError(f"stage {stage_name!r} has no services")

    # ---- demand / capacity -------------------------------------------------
    # per BASE service, expanded to rows with np.repeat: replicas share
    # demand, so the 10k-row as_tuple loop collapses to one per service
    reps_arr = np.fromiter((max(s.replicas, 1) for s in services),
                           dtype=np.int64, count=len(services))
    base_demand = np.array([s.resources.as_tuple() for s in services],
                           dtype=np.float32).reshape(len(services), _R)
    demand = np.repeat(base_demand, reps_arr, axis=0)
    capacity = np.array([n.capacity.as_tuple() for n in nodes], dtype=np.float32)

    # ---- dependency DAG over expanded rows ---------------------------------
    # edge endpoints are COLLECTED in python (dict lookups) but written to
    # the dense matrix in one fancy-index scatter: per-edge scalar
    # dep_adj[i, j] = True assignments cost ~1 us each in numpy, which at
    # ~15k edges was a visible slice of every fleet-scale lowering
    dep_adj = np.zeros((S, S), dtype=bool)
    esrc: list[int] = []
    edst: list[int] = []
    for svc in services:
        deps = svc.depends_on
        if not deps:
            continue
        rows_of = base_index[svc.name]
        single = len(rows_of) == 1
        for dep in deps:
            if dep in static_names:
                continue   # static targets ship before the container loop
            targets = base_index.get(dep)
            if targets is None:
                raise SolverError(
                    f"service {svc.name!r} depends on unknown service {dep!r}")
            if single and len(targets) == 1:   # common case: no replicas
                esrc.append(rows_of[0])
                edst.append(targets[0])
            else:
                for i in rows_of:
                    esrc.extend([i] * len(targets))
                    edst.extend(targets)
    src_a = np.asarray(esrc, dtype=np.int64)
    dst_a = np.asarray(edst, dtype=np.int64)
    dep_adj[src_a, dst_a] = True
    dep_depth = dependency_depths(dep_adj, row_names, edges=(src_a, dst_a))

    # ---- conflict id groups ------------------------------------------------
    port_key_ids: dict[tuple, int] = {}
    vol_key_ids: dict[str, int] = {}
    anti_key_ids: dict = {}   # str labels + ('pair', ...) tuples
    coloc_key_ids: dict[str, int] = {}

    # colocation groups are keyed by the TARGET service name, and the
    # target's own rows are members too: one-sided `a colocate_with b`
    # otherwise lowers to the singleton group {a}, whose coloc score
    # cc*(cc-1)/2 is identically 0 — the declared preference would have
    # no effect at all (found by the r5 close review; the production
    # example's api colocate-with cache was a no-op). anti_affinity gets
    # the symmetric treatment: its keys are group LABELS (all declarers
    # of "db-tier" mutually exclude), but when a key names a service,
    # that service joins the group too, so one-sided target-style
    # `a anti_affinity "db"` separates a from db instead of silently
    # doing nothing.
    coloc_targets = {k for svc in services for k in svc.colocate_with}
    unknown_coloc = coloc_targets - {s.name for s in services}
    if unknown_coloc:
        # unlike depends_on (hard error), colocation is a soft preference
        # and static services legitimately drop out of the container rows
        # — but a typo'd target means the declaration scores nothing, so
        # say so instead of silently lowering a dead preference
        from ..obs import get_logger
        get_logger("lower").warning(
            "colocate_with targets not in stage %r: %s (preference has "
            "no effect)", stage_name, sorted(unknown_coloc))

    # Target-style anti-affinity — a key naming a stage service means
    # "separate ME from THAT service" — lowers to one 2-member group per
    # (declarer row, target row) PAIR. Any shared-group formulation
    # over-constrains someone: a single group per target forces the
    # target's replicas apart from each other, and a group shared by all
    # declarer rows forces the declarer's replicas apart too — hard
    # constraints nobody declared (r5 close review: web anti_affinity
    # "db" with db replicas=2 on 2 nodes went infeasible). Pair groups
    # encode exactly the declared relation. `svc anti_affinity "<own
    # name>"` (self-anti, i.e. hard replica spreading) is special-cased:
    # mutual exclusion among all R replicas is exactly ONE shared group,
    # and lowering it pairwise would add R(R-1)/2 groups per service —
    # inflating the dense (N, G) group-counts plane on device at fleet
    # scale for identical semantics.
    anti_pair_ids: dict[int, list[int]] = {}
    if not local:
        for i, svc in enumerate(rows):
            for k in svc.anti_affinity:
                if k not in base_index:
                    continue
                if k == replica_of[i]:
                    # self-anti: all replicas of k share one group
                    gid = anti_key_ids.setdefault(("self", k),
                                                  len(anti_key_ids))
                    anti_pair_ids.setdefault(i, []).append(gid)
                    continue
                for j in base_index[k]:
                    if j == i:
                        continue
                    pair = ("pair", k, min(i, j), max(i, j))
                    gid = anti_key_ids.setdefault(pair, len(anti_key_ids))
                    anti_pair_ids.setdefault(i, []).append(gid)
                    anti_pair_ids.setdefault(j, []).append(gid)

    # Per BASE service (replicas share ports/volumes/labels/colocation, so
    # the id-assignment loop runs once per service, not once per row —
    # at 10k rows the per-row version was a visible slice of lower_ms);
    # only the pairwise anti groups are per-row and merged below.
    port_groups, vol_groups, anti_groups, coloc_groups = [], [], [], []
    _empty: list[int] = []     # shared by constraint-free rows, never mutated
    i = 0
    for svc, reps in zip(services, reps_arr):
        pg = ([port_key_ids.setdefault(p.key(), len(port_key_ids))
               for p in svc.ports] if svc.ports else _empty)
        vg = _empty
        if svc.volumes:
            vg = []
            for v in svc.volumes:
                ck = v.conflict_key()
                if ck is not None:
                    vg.append(vol_key_ids.setdefault(ck, len(vol_key_ids)))
        # anti_affinity keys that do NOT name a stage service stay
        # LABEL-style: all declarers of "db-tier" mutually exclude.
        # Target-style keys (naming a service) are handled via the
        # pairwise groups prepared above the loop.
        base_ag = ([anti_key_ids.setdefault(k, len(anti_key_ids))
                    for k in svc.anti_affinity if k not in base_index]
                   if svc.anti_affinity and not local else _empty)
        cg = _empty
        if svc.colocate_with or svc.name in coloc_targets:
            cg = [coloc_key_ids.setdefault(k, len(coloc_key_ids))
                  for k in svc.colocate_with]
            if svc.name in coloc_targets:
                cg.append(coloc_key_ids.setdefault(svc.name,
                                                   len(coloc_key_ids)))
            cg = list(dict.fromkeys(cg))
        for _ in range(reps):
            port_groups.append(pg)
            vol_groups.append(vg)
            if base_ag or i in anti_pair_ids:
                ag = base_ag + anti_pair_ids.get(i, [])
                anti_groups.append(list(dict.fromkeys(ag)))
            else:
                anti_groups.append(base_ag)
            coloc_groups.append(cg)
            i += 1

    # ---- eligibility / preference / validity / topology --------------------
    # policy matching is per-NODE (every service row in a stage shares the
    # stage's placement policy), so compute one row of N verdicts and
    # broadcast — a per-element Python loop here is O(S*N) = 10M iterations
    # at north-star scale and dominated the whole lowering
    node_ok = np.fromiter((_server_matches(policy, n) for n in nodes),
                          dtype=bool, count=N)
    node_pref = np.fromiter((_preference_row(policy, n) for n in nodes),
                            dtype=np.float32, count=N)
    eligible = (np.ones((S, N), dtype=bool) if node_ok.all()
                else np.broadcast_to(node_ok, (S, N)).copy())
    # the dense (S, N) f32 preference plane is 40 MB at 10k x 1k; only
    # materialize it when some node actually scores (node_pref decides —
    # the plane is a row broadcast, so an all-zero row means an all-zero
    # plane, which ProblemTensors represents as preferred=None)
    preferred = (np.broadcast_to(node_pref, (S, N)).copy()
                 if node_pref.any() else None)
    # quota enforcement (model.rs:40 ResourceQuota, FSC-26 Phase B-3): the
    # stage's aggregate demand must fit the declared ceiling — a violated
    # quota is a config error, reported at lowering with the excess named
    if policy and policy.resource_quota:
        q = policy.resource_quota
        if q.max_services is not None and S > q.max_services:
            raise SolverError(
                f"stage exceeds quota: {S} service rows > "
                f"max-services {q.max_services}")
        # float64 sum + float32-epsilon slack: ten services of float32 cpu
        # 0.1 must not "exceed" a quota of exactly 1
        totals = demand.astype(np.float64).sum(axis=0)
        for i, (name, cap_q) in enumerate(
                (("cpu", q.cpu), ("memory", q.memory), ("disk", q.disk))):
            if cap_q is not None and totals[i] > cap_q * (1 + 1e-6) + 1e-9:
                raise SolverError(
                    f"stage exceeds quota: total {name} demand "
                    f"{totals[i]:g} > quota {cap_q:g}")

    relax_order = list(policy.fallback_policy.relax_order) \
        if policy and policy.fallback_policy else []
    if not eligible.any(axis=1).all():
        # with an eligibility-class fallback declared, the solve pipeline
        # relaxes the mask instead of lowering failing outright
        can_relax = any(w in ELIGIBILITY_RELAX_CLASSES for w in relax_order)
        if not can_relax:
            bad = [row_names[i]
                   for i in np.flatnonzero(~eligible.any(axis=1))[:5]]
            raise SolverError(
                f"services {bad} have no eligible node under the placement "
                f"policy (declare a fallback{{}} to relax)")
    node_valid = np.ones(N, dtype=bool)

    topo_key = (policy.spread_constraint.topology_key
                if policy and policy.spread_constraint else None)
    topo_ids: dict[str, int] = {}
    node_topology = np.zeros(N, dtype=np.int32)
    if topo_key and topo_key != "node":
        for j, node in enumerate(nodes):
            lbl = node.labels.as_dict().get(topo_key, f"__node_{j}")
            node_topology[j] = topo_ids.setdefault(lbl, len(topo_ids))
    else:
        node_topology = np.arange(N, dtype=np.int32)

    pt = ProblemTensors(
        service_names=row_names,
        node_names=[n.name for n in nodes],
        demand=demand,
        capacity=capacity,
        dep_adj=dep_adj,
        dep_depth=dep_depth,
        port_ids=_pad_ids(port_groups),
        volume_ids=_pad_ids(vol_groups),
        anti_ids=_pad_ids(anti_groups),
        coloc_ids=_pad_ids(coloc_groups),
        eligible=eligible,
        node_valid=node_valid,
        node_topology=node_topology,
        strategy=policy.strategy if policy else PlacementStrategy.SPREAD_ACROSS_POOL,
        max_skew=(policy.spread_constraint.max_skew
                  if policy and policy.spread_constraint else 0),
        preferred=preferred,
        relax_order=relax_order,
        replica_of=replica_of,
    )
    pt.validate()
    return pt


# --------------------------------------------------------------------------
# Synthetic problem generator (BASELINE.json eval configs 2-4)
# --------------------------------------------------------------------------

# Demand distribution of the synthetic/eval instances (BASELINE.json
# configs); fleetgen.py generates KDL with the SAME ranges so the pipeline
# bench's solve is comparable to the headline synthetic numbers — change
# them here and both stay in sync.
SYNTH_CPU_RANGE = (0.05, 0.5)
SYNTH_MEM_RANGE = (32.0, 512.0)       # MiB
SYNTH_DISK_RANGE = (0.0, 1024.0)      # MiB


def synthetic_problem(S: int, N: int, seed: int = 0,
                      dep_depth_max: int = 5,
                      port_fraction: float = 0.2,
                      volume_fraction: float = 0.1,
                      n_tenants: int = 1,
                      strategy: PlacementStrategy = PlacementStrategy.SPREAD_ACROSS_POOL,
                      ) -> ProblemTensors:
    """Generate a synthetic placement instance shaped like the BASELINE.json
    eval configs: depends_on chains of depth ≤ dep_depth_max, a fraction of
    services publishing host ports (mutual anti-affinity per port), exclusive
    volumes, and optional multi-tenant eligibility blocks (config 4's
    registry-aggregation analog: tenants share the node pool but only see a
    slice)."""
    rng = np.random.default_rng(seed)

    demand = np.stack([
        rng.uniform(*SYNTH_CPU_RANGE, S),
        rng.uniform(*SYNTH_MEM_RANGE, S),
        rng.uniform(*SYNTH_DISK_RANGE, S),
    ], axis=1).astype(np.float32)

    # dependency chains: partition services into chains of length ≤ depth max
    dep_adj = np.zeros((S, S), dtype=bool)
    order = rng.permutation(S)
    i = 0
    while i < len(order):
        chain_len = int(rng.integers(1, dep_depth_max + 1))
        chain = order[i : i + chain_len]
        for a, b in zip(chain[1:], chain[:-1]):
            dep_adj[a, b] = True
        i += chain_len
    dep_depth = dependency_depths(dep_adj)

    # port conflicts: port_fraction of services publish 1-2 host ports drawn
    # from a pool sized so each port is shared by a handful of services
    # Each port id is capped at N-1 members: a group of k services needs k
    # distinct nodes, and the cap keeps instances solvable even after a
    # single-node churn event (BASELINE config 5 kills one node).
    n_ports = max(int(S * port_fraction / 4), 1)
    members = np.zeros(n_ports, dtype=np.int64)
    port_groups: list[list[int]] = []
    for s in range(S):
        if rng.random() < port_fraction:
            k = int(rng.integers(1, 3))
            open_ids = np.flatnonzero(members < N - 1)
            pick = open_ids[rng.permutation(open_ids.size)[:k]].tolist()
            members[pick] += 1
            port_groups.append(pick)
        else:
            port_groups.append([])
    n_vols = max(int(S * volume_fraction / 3), 1)
    vol_groups = [([int(rng.integers(0, n_vols))] if rng.random() < volume_fraction else [])
                  for _ in range(S)]

    # multi-tenant eligibility: tenant t's services may only use its node slice
    eligible = np.ones((S, N), dtype=bool)
    if n_tenants > 1:
        svc_tenant = rng.integers(0, n_tenants, S)
        node_tenant = rng.integers(0, n_tenants, N)
        # shared pool: a third of nodes serve everyone
        shared = rng.random(N) < 0.33
        eligible = (svc_tenant[:, None] == node_tenant[None, :]) | shared[None, :]
        # guarantee every service has at least one eligible node
        for s in np.flatnonzero(~eligible.any(axis=1)):
            eligible[s, int(rng.integers(0, N))] = True

    # Capacity sized from a feasibility witness: place every service on an
    # eligible node with no port/volume conflict (round-robin least-loaded),
    # then set capacity = witness load / 0.7. This makes the instance feasible
    # BY CONSTRUCTION even when tenant eligibility slices the pool unevenly —
    # a tenant with many services and few eligible nodes gets bigger nodes,
    # the way a real operator would size a dedicated pool.
    w_load = np.zeros((N, _R), dtype=np.float64)
    occupied: set[tuple[int, str, int]] = set()
    for s in np.argsort(-demand.sum(axis=1)):  # biggest first
        cands = np.flatnonzero(eligible[s])
        free = [n for n in cands
                if not any((int(n), "p", g) in occupied for g in port_groups[s])
                and not any((int(n), "v", g) in occupied for g in vol_groups[s])]
        if not free:  # drop this service's conflicts rather than go infeasible
            port_groups[s], vol_groups[s] = [], []
            free = list(cands)
        util = w_load[free].sum(axis=1)
        n = int(free[int(np.argmin(util))])
        w_load[n] += demand[s]
        occupied.update((n, "p", g) for g in port_groups[s])
        occupied.update((n, "v", g) for g in vol_groups[s])
    floor = demand.max(axis=0)  # every node can host any single service
    capacity = np.maximum(w_load / 0.7, floor[None, :]).astype(np.float32)
    capacity *= rng.uniform(1.0, 1.15, (N, _R)).astype(np.float32)

    pt = ProblemTensors(
        service_names=[f"svc{s}" for s in range(S)],
        node_names=[f"node{n}" for n in range(N)],
        demand=demand,
        capacity=capacity,
        dep_adj=dep_adj,
        dep_depth=dep_depth,
        port_ids=_pad_ids(port_groups),
        volume_ids=_pad_ids(vol_groups),
        anti_ids=_pad_ids([[] for _ in range(S)]),
        coloc_ids=_pad_ids([[] for _ in range(S)]),
        eligible=eligible,
        node_valid=np.ones(N, dtype=bool),
        node_topology=np.arange(N, dtype=np.int32),
        strategy=strategy,
        replica_of=[f"svc{s}" for s in range(S)],
    )
    pt.validate()
    return pt
