"""Fleet-scale KDL generators for end-to-end pipeline benchmarks.

The reference pays discovery + templating + KDL parse + conversion on every
deploy (fleetflow-core loader.rs:25-74) before its engine loop ever runs a
container; our headline bench used to stage synthetic tensors directly, so
the config->placement pipeline had never been timed at north-star scale
(VERDICT r4 item 3).  These generators produce the INPUT side of that
pipeline: real KDL text for a multi-tenant fleet registry, shaped like
lower.synthetic_problem's instances (dependency chains, shared host ports,
exclusive volumes) so the resulting solve is comparable to the headline
10k x 1k numbers.

The pipeline under test is then exactly production's:

    KDL text --parse_kdl_string--> Flow    (native kdl.cpp fast path)
        --aggregate_fleets--> ProblemTensors   (namespacing + lower_stage)
        --prepare_problem--> DeviceProblem     (device staging)
        --solve--> assignment

Feasibility by construction: server capacity is sized ~3x the mean
per-node demand, port/volume pools cap conflict-group sizes well under the
node count, and all services are eligible everywhere (the aggregate stage
carries no placement policy — aggregation semantics, registry/aggregate.py).
"""

from __future__ import annotations

import numpy as np

__all__ = ["generate_fleet_kdl", "generate_servers_kdl"]


def generate_fleet_kdl(fleet: str, n_services: int, *, seed: int = 0,
                       port_fraction: float = 0.2,
                       volume_fraction: float = 0.1,
                       dep_depth_max: int = 5,
                       n_nodes_hint: int = 1000,
                       port_base: int = 10000,
                       replica_fraction: float = 0.05,
                       coloc_fraction: float = 0.05) -> str:
    """KDL text for one tenant fleet: top-level service nodes plus a
    `stage "prod"` listing them.

    Structure mirrors lower.synthetic_problem (shared demand ranges from
    tensors.SYNTH_*): services form dependency chains of depth <=
    dep_depth_max; `port_fraction` of services publish a host port drawn
    from a pool sized so ~4 services share each port (mutual
    anti-affinity); `volume_fraction` claim an exclusive host volume from a
    pool with ~3 claimants each.  Group sizes stay far below
    `n_nodes_hint` so instances survive churn events.

    `port_base` must give each fleet in a registry a DISJOINT port range:
    conflict identity is (ip, port, proto), so aggregation merges
    same-numbered ports across fleets, and a merged group can exceed the
    per-fleet membership cap (up to fleets x cap services on one port) —
    past n_nodes it would be infeasible by construction.  Volumes are safe
    without this: their conflict key is the host path, which embeds the
    fleet name.
    """
    from .tensors import SYNTH_CPU_RANGE, SYNTH_DISK_RANGE, SYNTH_MEM_RANGE

    rng = np.random.default_rng(seed)
    names = [f"{fleet}-svc-{i:05d}" for i in range(n_services)]

    n_ports = max(int(n_services * port_fraction / 4), 1)
    port_members = np.zeros(n_ports, dtype=np.int64)
    n_vols = max(int(n_services * volume_fraction / 3), 1)

    # dependency chains over a shuffled order, like synthetic_problem
    dep_of: dict[int, int] = {}
    order = rng.permutation(n_services)
    i = 0
    while i < len(order):
        chain_len = int(rng.integers(1, dep_depth_max + 1))
        chain = order[i:i + chain_len]
        for a, b in zip(chain[1:], chain[:-1]):
            dep_of[int(a)] = int(b)
        i += chain_len

    lines: list[str] = [f'project "{fleet}"', ""]
    for s, name in enumerate(names):
        cpu = rng.uniform(*SYNTH_CPU_RANGE)
        mem = rng.uniform(*SYNTH_MEM_RANGE)
        disk = rng.uniform(*SYNTH_DISK_RANGE)
        lines.append(f'service "{name}" {{')
        lines.append(f'    image "registry.example/{fleet}/app:1.0"')
        lines.append('    resources {')
        lines.append(f'        cpu {cpu:.3f}')
        lines.append(f'        memory {mem:.1f}')
        lines.append(f'        disk {disk:.1f}')
        lines.append('    }')
        if s in dep_of:
            lines.append(f'    depends_on "{names[dep_of[s]]}"')
        has_port = False
        if rng.random() < port_fraction:
            open_ids = np.flatnonzero(port_members < n_nodes_hint - 1)
            if open_ids.size:          # pool exhausted: skip, stay feasible
                p = int(open_ids[int(rng.integers(0, open_ids.size))])
                port_members[p] += 1
                lines.append(f'    port host={port_base + p} container=8080')
                has_port = True
        if rng.random() < volume_fraction:
            v = int(rng.integers(0, n_vols))
            lines.append(
                f'    volume "/data/{fleet}/vol-{v:04d}" "/var/data"')
        # replica expansion + colocation exercise the remaining constraint
        # classes at pipeline scale (the solve must handle every KDL
        # construct the config layer accepts, not just ports/volumes).
        # Port-publishing services stay replicas=1 — identical host ports
        # on every replica would be infeasible by construction — and
        # colocation targets the service's dependency (the natural
        # "run next to what I call" shape).
        if not has_port and rng.random() < replica_fraction:
            lines.append(f'    replicas {int(rng.integers(2, 4))}')
        if s in dep_of and rng.random() < coloc_fraction:
            lines.append(f'    colocate_with "{names[dep_of[s]]}"')
        lines.append('}')
    lines.append("")
    lines.append('stage "prod" {')
    lines.append('    placement "spread_across_pool"')
    for name in names:
        lines.append(f'    service "{name}"')
    lines.append('}')
    return "\n".join(lines) + "\n"


def generate_servers_kdl(n_nodes: int, *, seed: int = 0,
                         cpu: float = 8.0, memory_mb: float = 8192.0,
                         disk_mb: float = 32768.0) -> str:
    """KDL text declaring the registry's shared server pool.

    Default capacity gives ~3x headroom over the mean per-node demand of a
    10k-service fleet on 1k nodes (mean service: 0.275 cpu / 272 MiB mem /
    512 MiB disk -> ~2.75 cpu / 2.7 GiB / 5.1 GiB per node at 10 services
    per node).
    """
    rng = np.random.default_rng(seed)
    lines: list[str] = []
    for j in range(n_nodes):
        jitter = rng.uniform(1.0, 1.25)
        lines.append(f'server "node-{j:04d}" {{')
        lines.append('    capacity {')
        lines.append(f'        cpu {cpu * jitter:.2f}')
        lines.append(f'        memory {memory_mb * jitter:.0f}')
        lines.append(f'        disk {disk_mb * jitter:.0f}')
        lines.append('    }')
        lines.append('}')
    return "\n".join(lines) + "\n"
