"""Build input resolution.

Analog of fleetflow-build resolver.rs:6-130: given a Service with a
`build{}` block and the project root, resolve the dockerfile path (explicit
-> context/Dockerfile), the context directory, merged build args (config +
FLEET_BUILD_* env), and the image tag (explicit image_tag -> image:version
-> service name:latest, with the stage registry prefixed when present).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from ..core.errors import FlowError
from ..core.model import Service

__all__ = ["BuildResolver", "ResolvedBuild"]

ENV_ARG_PREFIX = "FLEET_BUILD_"


class BuildError(FlowError):
    pass


@dataclass
class ResolvedBuild:
    dockerfile: Path
    context: Path
    args: dict[str, str] = field(default_factory=dict)
    tag: str = ""
    target: Optional[str] = None
    no_cache: bool = False


class BuildResolver:
    def __init__(self, project_root: str = ".",
                 registry: Optional[str] = None,
                 env: Optional[dict[str, str]] = None):
        self.root = Path(project_root).resolve()
        self.registry = registry
        self.env = os.environ if env is None else env

    def resolve(self, svc: Service) -> ResolvedBuild:
        if svc.build is None:
            raise BuildError(f"service {svc.name!r} has no build{{}} config")
        b = svc.build
        context = self.resolve_context(b.context)
        return ResolvedBuild(
            dockerfile=self.resolve_dockerfile(b.dockerfile, context),
            context=context,
            args=self.resolve_build_args(b.args),
            tag=self.resolve_image_tag(svc),
            target=b.target,
            no_cache=b.no_cache,
        )

    def resolve_context(self, context: str) -> Path:
        """resolver.rs resolve_context:66."""
        p = (self.root / context).resolve()
        if not p.is_dir():
            raise BuildError(f"build context {p} does not exist")
        return p

    def resolve_dockerfile(self, dockerfile: Optional[str],
                           context: Path) -> Path:
        """resolver.rs resolve_dockerfile:23: explicit path (relative to
        project root) or context/Dockerfile."""
        if dockerfile:
            p = (self.root / dockerfile).resolve()
        else:
            p = context / "Dockerfile"
        if not p.is_file():
            raise BuildError(f"dockerfile {p} does not exist")
        return p

    def resolve_build_args(self, args: dict[str, str]) -> dict[str, str]:
        """resolver.rs resolve_build_args:93: config args + FLEET_BUILD_*
        env (env wins)."""
        out = dict(args)
        for k, v in self.env.items():
            if k.startswith(ENV_ARG_PREFIX):
                out[k[len(ENV_ARG_PREFIX):]] = v
        return out

    def resolve_image_tag(self, svc: Service) -> str:
        """resolver.rs resolve_image_tag:130."""
        if svc.build and svc.build.image_tag:
            tag = svc.build.image_tag
        else:
            tag = svc.image_name()
        # prefix the stage registry only when the tag has no registry host
        # already (first path component with '.'/':' = host, like
        # auth.registry_for_image)
        first = tag.split("/", 1)[0]
        has_registry = "/" in tag and ("." in first or ":" in first
                                       or first == "localhost")
        if self.registry and not has_registry:
            tag = f"{self.registry.rstrip('/')}/{tag}"
        return tag
