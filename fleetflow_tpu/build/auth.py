"""Registry authentication.

Analog of fleetflow-build auth.rs:43-84: read credentials from
~/.docker/config.json (`auths` entries with base64 `auth` or split
username/password; Docker Hub aliases normalized) for push operations.
Credential helpers are reported, not executed.
"""

from __future__ import annotations

import base64
import json
import os
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

__all__ = ["RegistryAuth", "registry_for_image", "load_docker_config"]

DOCKER_HUB_ALIASES = {"docker.io", "index.docker.io",
                      "https://index.docker.io/v1/", "registry-1.docker.io"}


@dataclass
class RegistryAuth:
    registry: str
    username: Optional[str] = None
    password: Optional[str] = None
    identity_token: Optional[str] = None
    cred_helper: Optional[str] = None

    @property
    def resolved(self) -> bool:
        return bool(self.username or self.identity_token or self.cred_helper)


def registry_for_image(image: str) -> str:
    """The registry host of an image ref: explicit host (contains '.' or
    ':' or is 'localhost') else Docker Hub."""
    first = image.split("/", 1)[0]
    if "/" in image and ("." in first or ":" in first or first == "localhost"):
        return first
    return "docker.io"


def load_docker_config(path: Optional[str] = None) -> dict:
    p = Path(path or os.environ.get("DOCKER_CONFIG",
                                    "~/.docker")).expanduser()
    if p.is_dir():
        p = p / "config.json"
    try:
        return json.loads(p.read_text())
    except (OSError, json.JSONDecodeError):
        return {}


def auth_for_registry(registry: str,
                      config: Optional[dict] = None) -> RegistryAuth:
    """auth.rs:43-84."""
    cfg = load_docker_config() if config is None else config
    out = RegistryAuth(registry=registry)

    helpers = cfg.get("credHelpers", {})
    if registry in helpers:
        out.cred_helper = helpers[registry]
        return out
    if cfg.get("credsStore"):
        out.cred_helper = cfg["credsStore"]

    auths = cfg.get("auths", {})
    keys = [registry]
    if registry in DOCKER_HUB_ALIASES or registry == "docker.io":
        keys = list(DOCKER_HUB_ALIASES)
    for key, entry in auths.items():
        norm = key.replace("https://", "").replace("http://", "").rstrip("/")
        if key in keys or norm == registry or norm.split("/")[0] == registry:
            if "auth" in entry:
                try:
                    user, _, pw = base64.b64decode(
                        entry["auth"]).decode().partition(":")
                    out.username, out.password = user, pw
                except Exception:
                    pass
            out.username = entry.get("username", out.username)
            out.password = entry.get("password", out.password)
            out.identity_token = entry.get("identitytoken")
            break
    return out
