"""Image build/push subsystem (L1b).

Analog of fleetflow-build (SURVEY.md §2.1b): resolve build inputs from a
service's `build{}` config (dockerfile / context / args / tag), pack the
context into a tar.gz honoring .dockerignore, authenticate against
registries from ~/.docker/config.json, and drive `docker build` / `docker
push` (the reference streams through Bollard's build API; the CLI carries
the same operations).
"""

from .resolver import BuildResolver, ResolvedBuild
from .context import create_context, load_dockerignore
from .auth import RegistryAuth, registry_for_image
from .builder import ImageBuilder, ImagePusher

__all__ = ["BuildResolver", "ResolvedBuild", "create_context",
           "load_dockerignore", "RegistryAuth", "registry_for_image",
           "ImageBuilder", "ImagePusher"]
