"""Build-context packing.

Analog of fleetflow-build context.rs:13: pack the context directory into a
tar.gz honoring `.dockerignore` (glob patterns, `!` re-includes, directory
prefixes), the archive the engine's build API consumes.
"""

from __future__ import annotations

import fnmatch
import io
import tarfile
from pathlib import Path

__all__ = ["load_dockerignore", "create_context", "is_ignored"]


def load_dockerignore(context: Path) -> list[str]:
    f = context / ".dockerignore"
    if not f.is_file():
        return []
    patterns = []
    for line in f.read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            patterns.append(line.rstrip("/"))
    return patterns


def is_ignored(rel: str, patterns: list[str]) -> bool:
    """Last match wins; `!pattern` re-includes (dockerignore semantics)."""
    ignored = False
    for pat in patterns:
        negate = pat.startswith("!")
        if negate:
            pat = pat[1:]
        hit = (fnmatch.fnmatch(rel, pat)
               or fnmatch.fnmatch(rel, pat + "/*")
               or rel == pat
               or rel.startswith(pat + "/"))
        if hit:
            ignored = not negate
    return ignored


def create_context(context: Path, dockerfile: Path | None = None) -> bytes:
    """context.rs create_context:13 — tar.gz bytes of the context with
    .dockerignore applied; an out-of-context dockerfile is injected as
    `Dockerfile` at the archive root (docker's remote-dockerfile behavior)."""
    patterns = load_dockerignore(context)
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for path in sorted(context.rglob("*")):
            rel = path.relative_to(context).as_posix()
            if is_ignored(rel, patterns):
                continue
            if path.is_file() or path.is_symlink():
                tar.add(path, arcname=rel, recursive=False)
        if dockerfile is not None:
            try:
                dockerfile.relative_to(context)
            except ValueError:
                tar.add(dockerfile, arcname="Dockerfile", recursive=False)
    return buf.getvalue()
