"""Image build/push drivers.

Analog of fleetflow-build builder.rs:23 / pusher.rs:41: run `docker build`
with resolved inputs (streaming output to a line callback the way the
reference streams Bollard build events) and `docker push` with auth
pre-flight. The subprocess runner is injectable so tests exercise argv
construction without docker.
"""

from __future__ import annotations

import subprocess
from typing import Callable, Optional

from ..core.errors import FlowError
from .auth import auth_for_registry, registry_for_image
from .resolver import ResolvedBuild

__all__ = ["ImageBuilder", "ImagePusher", "BuildFailed"]


class BuildFailed(FlowError):
    pass


def _default_runner(args: list[str],
                    on_line: Optional[Callable[[str], None]] = None
                    ) -> tuple[int, str]:
    proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    lines = []
    for line in proc.stdout:
        line = line.rstrip("\n")
        lines.append(line)
        if on_line:
            on_line(line)
    proc.wait()
    return proc.returncode, "\n".join(lines)


class ImageBuilder:
    def __init__(self, runner=None):
        self.runner = runner or _default_runner

    def build(self, resolved: ResolvedBuild,
              on_line: Optional[Callable[[str], None]] = None) -> str:
        """builder.rs build_image_from_path:23. Returns the tag."""
        args = ["docker", "build", "-t", resolved.tag,
                "-f", str(resolved.dockerfile)]
        for k, v in sorted(resolved.args.items()):
            args += ["--build-arg", f"{k}={v}"]
        if resolved.target:
            args += ["--target", resolved.target]
        if resolved.no_cache:
            args.append("--no-cache")
        args.append(str(resolved.context))
        rc, out = self.runner(args, on_line)
        if rc != 0:
            raise BuildFailed(f"docker build failed (rc={rc}):\n{out[-2000:]}")
        return resolved.tag


class ImagePusher:
    def __init__(self, runner=None):
        self.runner = runner or _default_runner

    def push(self, tag: str,
             on_line: Optional[Callable[[str], None]] = None) -> str:
        """pusher.rs push:41 with auth.rs pre-flight: surface a actionable
        error when no credentials exist for the target registry."""
        registry = registry_for_image(tag)
        auth = auth_for_registry(registry)
        if not auth.resolved:
            raise BuildFailed(
                f"no credentials for registry {registry!r} in docker config "
                "(run `docker login` first)")
        rc, out = self.runner(["docker", "push", tag], on_line)
        if rc != 0:
            raise BuildFailed(f"docker push failed (rc={rc}):\n{out[-2000:]}")
        return tag
