"""Compile-contract registry: the solver's hot-path executables, with the
donation/sharding declarations each one must keep.

The perf contracts (PRs 4-9, 14) live or die on five jitted programs:

  resident.merge    the donated single-chip delta-merge kernel
                    (solver/resident._merge_fn) — churn folds into the
                    resident buffers in place, no second (S, N) copy
  sharded.merge     the mesh-sharded variant (sharded._merge_fn_sharded)
                    with explicit sharding constraints pinning every
                    output to its input layout
  refine.warm       the fused solve pipeline (api._refine) in its warm
                    resident configuration — the steady-state dispatch
  subsolve.localized  the churn-localized gather -> mini-anneal ->
                    scatter -> exact-gate dispatch (subsolve._subsolve_fn);
                    pinned donation-FREE — the original assignment must
                    outlive a gate-rejected attempt
  sharded.anneal    the SPMD anneal + tempering dispatch
                    (sharded.anneal_sharded) on a tempered mesh

Each :class:`KernelContract` names the executable, anchors its jit
declaration in source (module + lexical qualname, consumed by
analysis/jitspec AST extraction — the recompile-axis ground truth), and
builds *lowerable cases at representative bucket tiers* using the same
staging code the production path runs (ResidentProblem.merge_inputs,
ShardedResident, the api._solve warm-config derivations). The auditor
(fleetflow_tpu/analysis/auditor.py) lowers each case and checks donation
aliasing, host-callback absence, and output shardings against
tests/goldens/compile_contract.json.

Keeping the registry inside solver/ is deliberate: whoever changes a
kernel's jit declaration is looking at this module's neighbors, and the
contract entry is the documentation of record for what the declaration
promises.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Optional

import numpy as np

__all__ = ["KernelCase", "KernelContract", "hot_path_kernels",
           "problem_static_fields", "AUDIT_TIERS"]

# representative (S, N) instances: one inside the first bucket tier (64)
# and one in the next (80) — enough to prove tier drift stays inside the
# declared static set without paying fleet-scale compile time in CI
AUDIT_TIERS: tuple[tuple[int, int], ...] = ((60, 12), (73, 12))


@dataclass
class KernelCase:
    """One lowerable instance of a kernel at a concrete bucket tier."""
    tier: str                       # "<padded_S>x<N>" label
    fn: Any                         # the jitted callable
    args: tuple                     # positional args (device-staged)
    kwargs: dict                    # static kwargs, exactly as dispatched
    arg_names: tuple                # names for the positional args
    # declared output shardings: flat leaf-path -> normalized spec string
    # ("P('svc')", "P()" ...); None = single-device kernel, not checked
    out_shardings: Optional[dict] = None


@dataclass
class KernelContract:
    name: str                       # registry key, e.g. "resident.merge"
    module: str                     # dotted module holding the jit decl
    qualname: str                   # lexical path for jitspec extraction
    cases: Callable[[], list[KernelCase]]
    # donated leaf names (arg.field) that MUST alias an output in the
    # lowered artifact — the buffers whose in-place reuse IS the perf
    # story; a dropped alias here is a silent memory/latency regression
    must_alias: tuple = ()
    needs_devices: int = 1
    # packed-plane policing (solver/problem.py): the staged problem must
    # carry a bit-packed uint32 eligibility plane and NO preference plane
    # — an f32/bool (S, N) plane reappearing in a hot-path executable is
    # an intrinsic audit violation, not just a golden diff
    packed_planes: bool = True


def problem_static_fields() -> list[str]:
    """DeviceProblem fields that are static jit metadata — every one is a
    recompile axis for ALL kernels taking a problem, exactly like a
    static_argnames entry. Enumerated from the dataclass so a new static
    field shows up as a contract diff, not a latent compile cliff."""
    from .problem import DeviceProblem
    return sorted(f.name for f in dataclasses.fields(DeviceProblem)
                  if f.metadata.get("static"))


def _synthetic(S: int, N: int):
    from ..lower import synthetic_problem
    return synthetic_problem(S, N, seed=0, port_fraction=0.3,
                             volume_fraction=0.2)


def _rich_delta(pt, n_rows: int = 3):
    """A delta exercising every merge input: validity + capacity drift
    plus demand/eligibility row scatters (has_demand/has_eligible both
    True — the richest static variant, the one whose lowering touches
    every donated plane)."""
    from .resident import ProblemDelta
    rows = np.arange(min(n_rows, pt.S), dtype=np.int32)
    return ProblemDelta(
        node_valid=np.asarray(pt.node_valid, dtype=bool).copy(),
        capacity=np.asarray(pt.capacity, dtype=np.float32).copy(),
        demand_rows=(rows, np.asarray(pt.demand, np.float32)[rows]),
        eligible_rows=(rows, np.asarray(pt.eligible, bool)[rows]))


_MERGE_ARG_NAMES = ("prob", "assignment", "node_valid", "capacity",
                    "dem_idx", "dem_val", "elig_idx", "elig_rows", "n_real")

# the donated (S, .) buffers whose in-place reuse the merge kernels exist
# for; small node-state leaves may or may not alias (XLA's choice) and
# prob.n_real is replaced by the n_real argument, so none of those gate.
# prob.preferred is ABSENT from the packed layout (solver/problem.py): the
# hot-path stagings carry no preference plane, so there is nothing to
# alias — and the packed-plane policing below guarantees one can never
# silently reappear.
_MERGE_MUST_ALIAS = ("prob.demand", "prob.eligible", "prob.conflict_ids",
                     "prob.coloc_ids", "assignment")


def _merge_case(rp, pt, tier: str,
                out_shardings: Optional[dict]) -> KernelCase:
    uploads, n_real, has_demand, has_eligible = rp.merge_inputs(
        pt, _rich_delta(pt))
    if rp.assignment is None:
        rp.adopt_host(np.zeros(pt.S, np.int32), pt.node_valid, warm=False)
    return KernelCase(
        tier=tier, fn=rp._merge(),
        args=(rp.prob, rp.assignment, *uploads, n_real),
        kwargs=dict(has_demand=has_demand, has_eligible=has_eligible),
        arg_names=_MERGE_ARG_NAMES,
        out_shardings=out_shardings)


def _resident_merge_cases() -> list[KernelCase]:
    from .resident import ResidentProblem
    out = []
    for S, N in AUDIT_TIERS:
        pt = _synthetic(S, N)
        rp = ResidentProblem(pt)
        out.append(_merge_case(rp, pt, f"{rp.prob.S}x{N}", None))
    return out


def _sharded_mesh(replicas: int = 1, svc_shards: int = 4):
    from .sharded import tempering_mesh
    return tempering_mesh(replicas, svc_shards)


def _sharded_merge_decl_shardings() -> dict:
    """The layout contract of the sharded merge: every (S, .) plane and
    the assignment stay service-sharded, node state replicated."""
    svc = "P('svc')"
    rep = "P()"
    return {
        "prob.demand": svc, "prob.eligible": svc,
        "prob.conflict_ids": svc, "prob.coloc_ids": svc,
        "prob.capacity": rep, "prob.node_valid": rep,
        "prob.node_topology": rep, "prob.n_real": rep,
        "assignment": svc,
    }


def _sharded_merge_cases() -> list[KernelCase]:
    from .sharded import ShardedResident
    mesh = _sharded_mesh(1, 4)
    out = []
    for S, N in AUDIT_TIERS:
        pt = _synthetic(S, N)
        rp = ShardedResident(pt, mesh=mesh)
        out.append(_merge_case(rp, pt, f"{rp.prob.S}x{N}",
                               _sharded_merge_decl_shardings()))
    return out


_REFINE_ARG_NAMES = ("prob", "seed_assignment", "key", "t0", "t1",
                     "migration_weight")


def _refine_cases() -> list[KernelCase]:
    """api._refine in the warm resident configuration — the steady-state
    dispatch of the churn path, statics derived exactly as api._solve
    derives them (drift there IS the recompile event the contract
    exists to catch)."""
    import jax

    from .api import _refine
    from .resident import ResidentProblem

    out = []
    for S, N in AUDIT_TIERS:
        pt = _synthetic(S, N)
        rp = ResidentProblem(pt)
        rp.adopt_host(np.zeros(pt.S, np.int32), pt.node_valid, warm=False)
        prob = rp.prob
        from .anneal import backend_proposals_per_step, solve_trace_blocks
        proposals = backend_proposals_per_step(prob.S)
        t0_d, t1_d, mw_d = rp.warm_scalars(0.1, 1e-3, 0.5)
        key = jax.random.PRNGKey(0)
        out.append(KernelCase(
            tier=f"{prob.S}x{N}", fn=_refine,
            args=(prob, rp.assignment, key, t0_d, t1_d, mw_d),
            kwargs=dict(chains=1, steps=16, warm=True, adaptive=True,
                        anneal_block=1, proposals_per_step=proposals,
                        sharding=None, fused_prerepair=True,
                        prerepair_moves=max(16, min(prob.S, 256)),
                        skip_feasible_polish=True,
                        # the flight-deck buffer length IS a static of
                        # the warm executable (ISSUE 15): auditing with
                        # it pins that telemetry stays compiled-in —
                        # zero extra dispatches, no donation drift
                        trace_blocks=solve_trace_blocks()),
            arg_names=_REFINE_ARG_NAMES,
            out_shardings=None))
    return out


def _mux_refine_cases() -> list[KernelCase]:
    """multiplex._mux_refine — the batched (vmapped) warm pipeline — at
    K=2 stacked lanes per audit tier, statics derived exactly as
    multiplex._solve_batch derives them. The leading lane axis is a
    recompile axis by design (bucketed on the mux_k ladder); the
    contract pins that the batched executable keeps the serial warm
    path's structure: no donation, no host callbacks, packed planes."""
    import jax
    import jax.numpy as jnp

    from ..lower import synthetic_problem
    from .anneal import backend_proposals_per_step, solve_trace_blocks
    from .multiplex import stack_problems
    from .multiplex import _mux_refine
    from .resident import ResidentProblem

    K = 2
    out = []
    for S, N in AUDIT_TIERS:
        lanes = []
        for lane in range(K):
            pt = synthetic_problem(S, N, seed=lane, port_fraction=0.3,
                                   volume_fraction=0.2)
            rp = ResidentProblem(pt)
            rp.adopt_host(np.zeros(pt.S, np.int32), pt.node_valid,
                          warm=False)
            lanes.append(rp)
        prob = lanes[0].prob
        stacked = stack_problems([rp.prob for rp in lanes])
        seeds = jnp.stack([rp.assignment for rp in lanes])
        keys = jnp.stack([jax.random.PRNGKey(i) for i in range(K)])
        scal = [rp.warm_scalars(0.1, 1e-3, 0.5) for rp in lanes]
        t0v = jnp.stack([s[0] for s in scal])
        t1v = jnp.stack([s[1] for s in scal])
        mwv = jnp.stack([s[2] for s in scal])
        out.append(KernelCase(
            tier=f"{prob.S}x{N}:k{K}", fn=_mux_refine,
            args=(stacked, seeds, keys, t0v, t1v, mwv),
            kwargs=dict(chains=1, steps=16, warm=True, adaptive=True,
                        anneal_block=1,
                        proposals_per_step=backend_proposals_per_step(
                            prob.S),
                        fused_prerepair=True,
                        prerepair_moves=max(16, min(prob.S, 256)),
                        skip_feasible_polish=True,
                        trace_blocks=solve_trace_blocks()),
            arg_names=_REFINE_ARG_NAMES,
            out_shardings=None))
    return out


_SUBSOLVE_ARG_NAMES = ("prob", "assignment", "rows", "sub_conflict",
                       "sub_coloc", "load0", "used0", "coloc0", "topo0",
                       "n_sub", "key", "t0", "t1", "migration_weight")


def _subsolve_cases() -> list[KernelCase]:
    """The churn-localized sub-solve (solver/subsolve.py) in its warm
    production configuration: a staged resident problem, a killed-node
    delta, the planner's own closure/frozen-base staging, and the statics
    derived exactly as api._solve derives them."""
    import dataclasses as _dc

    import jax

    from .resident import ProblemDelta, ResidentProblem
    from .subsolve import (ActiveIndex, SubsolveConfig, _subsolve_fn,
                           plan_active, stage_subsolve)

    # permissive gates: the audit instances sit far below the production
    # mini-tier ladder, and the contract pins kernel structure, not the
    # production closure heuristics
    cfg = SubsolveConfig(enabled=True, frac=1.0, min_tier=8, max_tier=4096)
    out = []
    for S, N in AUDIT_TIERS:
        pt = _synthetic(S, N)
        rp = ResidentProblem(pt)
        rp.adopt_host(np.arange(pt.S, dtype=np.int32) % N, pt.node_valid,
                      warm=False)
        valid = np.asarray(pt.node_valid, dtype=bool).copy()
        valid[0] = False                     # kill one node: evictions
        cur = _dc.replace(pt, node_valid=valid)
        rp.apply_delta(cur, ProblemDelta(node_valid=valid))
        index = ActiveIndex(rp.pt)
        pending = (rp._pending_rows if rp._pending_rows is not None
                   else np.empty(0, dtype=np.int64))
        plan, outcome = plan_active(index, rp.pt, rp._mirror, rp.prob.S,
                                    rp.prob.T, pending, cfg,
                                    G_full=rp.prob.G, Gc_full=rp.prob.Gc)
        assert plan is not None, f"audit sub-plan fell back: {outcome}"
        staged = stage_subsolve(rp, plan)
        from .anneal import backend_proposals_per_step, solve_trace_blocks
        t0_d, t1_d, mw_d = rp.warm_scalars(0.1, 1e-3, 0.5)
        key = jax.random.PRNGKey(0)
        out.append(KernelCase(
            tier=f"{rp.prob.S}x{N}:t{plan.tier}", fn=_subsolve_fn(),
            args=(rp.prob, rp.assignment, *staged, key, t0_d, t1_d, mw_d),
            kwargs=dict(chains=1, steps=16, block=1,
                        proposals_per_step=backend_proposals_per_step(
                            plan.tier),
                        prerepair_moves=max(16, min(plan.tier, 256)),
                        Gc_sub=plan.Gc_sub,
                        trace_blocks=solve_trace_blocks()),
            arg_names=_SUBSOLVE_ARG_NAMES,
            out_shardings=None))
    return out


_ANNEAL_SHARDED_ARG_NAMES = ("prob", "init_assignment", "key")


def _anneal_sharded_cases() -> list[KernelCase]:
    """sharded.anneal_sharded on a tempered 2x4 mesh with return_stats
    (the solve_sharded production shape): assignment stays svc-sharded,
    every stat scalar replicated."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .sharded import ShardedResident, anneal_sharded

    from .anneal import solve_trace_blocks

    mesh = _sharded_mesh(2, 4)
    stats_fields = ("assignment", "sweeps", "capacity", "conflicts",
                    "eligibility", "skew", "soft", "swap_attempts",
                    "swap_accepts", "telemetry")
    decl = {f: ("P('svc')" if f == "assignment" else "P()")
            for f in stats_fields}
    out = []
    for S, N in AUDIT_TIERS:
        pt = _synthetic(S, N)
        rp = ShardedResident(pt, mesh=mesh)
        rp.adopt_host(np.zeros(pt.S, np.int32), pt.node_valid, warm=False)
        t0_d, t1_d, lad_d = rp.warm_scalars(0.1, 1e-3, 1.3)
        key = jax.device_put(jax.random.PRNGKey(0),
                             NamedSharding(mesh, P()))
        out.append(KernelCase(
            tier=f"{rp.prob.S}x{N}", fn=anneal_sharded,
            args=(rp.prob, rp.assignment, key),
            kwargs=dict(steps=16, t0=t0_d, t1=t1_d,
                        proposals_per_step=None, mesh=mesh, adaptive=True,
                        block=8, ladder=lad_d, exchange_every=1,
                        return_stats=True,
                        trace_blocks=solve_trace_blocks()),
            arg_names=_ANNEAL_SHARDED_ARG_NAMES,
            out_shardings=decl))
    return out


def hot_path_kernels() -> list[KernelContract]:
    """The registry the auditor iterates. Order is the order findings
    print in; keep the single-chip pair first (they audit without a
    mesh)."""
    return [
        KernelContract(
            name="resident.merge",
            module="fleetflow_tpu.solver.resident",
            qualname="_merge_fn.merge",
            cases=_resident_merge_cases,
            must_alias=_MERGE_MUST_ALIAS),
        KernelContract(
            name="refine.warm",
            module="fleetflow_tpu.solver.api",
            qualname="_refine",
            cases=_refine_cases),
        KernelContract(
            name="mux.anneal",
            module="fleetflow_tpu.solver.multiplex",
            qualname="_mux_refine",
            # like refine.warm, donation-free by design: every lane's
            # resident seed must outlive the dispatch (it re-seeds the
            # serial path if the batch's exact gate rejects a lane)
            cases=_mux_refine_cases),
        KernelContract(
            name="subsolve.localized",
            module="fleetflow_tpu.solver.subsolve",
            qualname="_subsolve_fn.subsolve",
            # deliberately NO donation (must_alias empty): the original
            # assignment must outlive the dispatch — a gate-rejected
            # sub-solve re-seeds the full path from it — and a donated
            # variant of this kernel deserialized from the persistent
            # compile cache corrupted its output (r09 bring-up). The
            # contract pins the ABSENCE: a donated_params entry
            # appearing here is a reviewed golden diff.
            cases=_subsolve_cases),
        KernelContract(
            name="sharded.merge",
            module="fleetflow_tpu.solver.sharded",
            qualname="_merge_fn_sharded.merge",
            cases=_sharded_merge_cases,
            must_alias=_MERGE_MUST_ALIAS,
            needs_devices=4),
        KernelContract(
            name="sharded.anneal",
            module="fleetflow_tpu.solver.sharded",
            qualname="anneal_sharded",
            cases=_anneal_sharded_cases,
            needs_devices=8),
    ]
