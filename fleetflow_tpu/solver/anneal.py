"""Simulated-annealing refinement: vmapped independent chains.

The pmapped/mesh-sharded annealing pass of the north star ("a pmapped
simulated-annealing pass"). Each chain keeps an incremental view of the
placement state — node loads (N, R), conflict-group occupancy (N, G),
colocation occupancy (N, Gc), topology-domain counts (T,) — so one proposal
costs O(R + K + T), not a full re-score. Chains are vmapped; sharding the
chain axis over a jax.sharding.Mesh makes the whole sweep SPMD with a single
argmin all-reduce at the end (solver/api.py), which is how the solver scales
to a v5e-8 the way the reference scales agents over QUIC fan-out.

The annealing cost mirrors kernels.total_cost in *shape* (hard >> soft) but
uses overflow mass instead of overflow cell count so moves feel a gradient.
Chain ranking and adaptive-exit checks read the carried state (cheap, exact
by construction); the WINNER's final stats are re-derived from scratch with
kernels.violation_stats so float32 drift in the carried load can never flip
the feasibility gate.
"""

from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .kernels import real_row_weights
from .problem import DeviceProblem, eligible_lookup, eligible_row

__all__ = ["anneal", "anneal_adaptive", "anneal_states",
           "anneal_adaptive_states", "chain_states_from_assignment",
           "prerepair_state", "prerepair_state_counted",
           "state_violation_stats", "state_soft_score",
           "ChainState", "TRACE_COLS", "solve_trace_blocks",
           "empty_trace"]

W_CAP = 1e3     # per-unit overflow mass (normalized units)
W_CONF = 1e4    # per conflicting co-placement
W_ELIG = 1e6    # per ineligible placement
W_SKEW = 1e3    # per unit of excess skew

# -- in-dispatch telemetry (the solver flight deck, docs/guide/10) ----------
# One fixed-shape f32 row per sweep-BLOCK, recorded inside the jitted
# dispatch and returned alongside the result, so it rides the existing
# fetch: zero extra compiles (the buffer length is the static knob below,
# not a traced shape), zero host transfers on the warm path, and no new
# donation edges. Column order is the schema `SolveResult.telemetry` and
# `fleet solve trace` speak.
TRACE_COLS = ("sweep", "temperature", "best_violations", "best_soft",
              "live_violations", "accepted")


def solve_trace_blocks(default: int = 16) -> int:
    """The telemetry buffer length (sweep-block rows) — a STATIC jit knob
    read from FLEET_SOLVE_TRACE_BLOCKS (default 16; 0 disables the
    buffer entirely, restoring the pre-telemetry program byte for byte).
    Static by design: a traced length would make tier drift a recompile
    axis, which the compile-contract auditor pins against."""
    try:
        v = int(os.environ.get("FLEET_SOLVE_TRACE_BLOCKS", "") or default)
    except ValueError:
        v = default
    return max(0, min(v, 512))


def empty_trace(trace_blocks: int):
    """The telemetry pytree at its zero value — the treedef every
    returning path (adaptive, fixed-budget, 0-sweep exit) must share so
    the telemetry can never fork an executable's output signature."""
    return {
        "blocks": jnp.zeros((trace_blocks, len(TRACE_COLS)), jnp.float32),
        "filled": jnp.int32(0),
        "init_violations": jnp.float32(0.0),
        "init_soft": jnp.float32(0.0),
    }


class ChainState(NamedTuple):
    assignment: jax.Array   # (S,) i32
    load: jax.Array         # (N, R) f32
    used: jax.Array         # (N, G) i32   conflict-group occupancy
    coloc: jax.Array        # (N, Gc) i32  colocation occupancy (Gc>=1)
    topo: jax.Array         # (T,) i32     services per topology domain


def chain_states_from_assignment(prob: DeviceProblem,
                                 assignment: jax.Array,
                                 base: tuple | None = None) -> ChainState:
    """Build the incremental state for one chain from a dense assignment.

    `base` is an optional frozen remainder ``(load0, used0, coloc0,
    topo0)`` the scatters accumulate ONTO instead of zeros — the active-set
    sub-solve (solver/subsolve.py) seeds the mini problem's carried state
    with the frozen rows' contribution so capacity/conflict/skew gradients
    against the untouched fleet stay exact without streaming its planes."""
    R = prob.demand.shape[1]
    load0, used0, coloc0, topo0 = (
        base if base is not None else
        (jnp.zeros((prob.N, R), jnp.float32),
         jnp.zeros((prob.N, prob.G), jnp.int32),
         jnp.zeros((prob.N, max(prob.Gc, 1)), jnp.int32),
         jnp.zeros(prob.T, jnp.int32)))
    load = load0.at[assignment].add(prob.demand)

    valid = prob.conflict_ids >= 0
    safe = jnp.where(valid, prob.conflict_ids, 0)
    nodes = jnp.broadcast_to(assignment[:, None], safe.shape)
    used = used0.at[nodes, safe].add(valid.astype(jnp.int32))

    cvalid = prob.coloc_ids >= 0
    csafe = jnp.where(cvalid, prob.coloc_ids, 0)
    cnodes = jnp.broadcast_to(assignment[:, None], csafe.shape)
    coloc = coloc0.at[cnodes, csafe].add(cvalid.astype(jnp.int32))

    # phantom rows (bucket padding, rows >= n_real) carry no topology
    # weight: a parked phantom must not shift a spread constraint
    tw = real_row_weights(prob)
    topo = topo0.at[prob.node_topology[assignment]].add(tw)
    return ChainState(assignment, load, used, coloc, topo)


def prerepair_state(prob: DeviceProblem, st: ChainState,
                    max_moves: int) -> ChainState:
    """Fused churn pre-repair (see :func:`prerepair_state_counted`);
    returns only the repaired state — the compatibility face every
    pre-telemetry caller keeps."""
    st, _moves = prerepair_state_counted(prob, st, max_moves)
    return st


def prerepair_state_counted(prob: DeviceProblem, st: ChainState,
                            max_moves: int) -> tuple[ChainState, jax.Array]:
    """Fused churn pre-repair: relocate services stranded on invalid or
    ineligible nodes, one per `lax.while_loop` iteration, entirely on
    device. This replaces the host `repair.py` pre-pass on the warm path
    (~27 ms of host numpy + a host->device seed upload at 10k x 1k,
    BENCH_r05): the resident warm path never leaves the device between the
    CP's churn delta and the anneal.

    Each iteration picks the first not-yet-attempted stranded service and
    moves it to the least-utilized node that fits (capacity + conflicts +
    eligibility), falling back to the least-utilized eligible node when
    nothing fits cleanly (the anneal's targeted proposals and the host
    repair backstop keep the zero-violation contract). The loop exits as
    soon as nothing is stranded, so a quiet warm solve pays one mask
    reduction; `max_moves` bounds pathological churn. Feasibility of the
    incoming state is preserved: a clean relocation only ever lands on a
    node it verified against the live carried state.

    Returns ``(state, moves)`` — `moves` counts the relocations actually
    APPLIED (attempts on genuinely unplaceable services don't count):
    the prologue half of the solver flight-deck telemetry."""
    ar = jnp.arange(prob.S)

    def stranded_of(st):
        return (~eligible_lookup(prob.eligible, ar, st.assignment)
                | ~prob.node_valid[st.assignment])

    def cond(carry):
        st, attempted, i, _moves = carry
        return (i < max_moves) & (stranded_of(st) & ~attempted).any()

    def body(carry):
        st, attempted, i, moves = carry
        todo = stranded_of(st) & ~attempted
        s = jnp.argmax(todo)
        attempted = attempted.at[s].set(True)
        d = prob.demand[s]
        ids = prob.conflict_ids[s]
        valid = ids >= 0
        safe = jnp.where(valid, ids, 0)
        cids = prob.coloc_ids[s]
        cvalid = cids >= 0
        csafe = jnp.where(cvalid, cids, 0)

        fits = ((st.load + d[None, :])
                <= prob.capacity * (1 + 1e-6)).all(-1)          # (N,)
        conf_free = ((st.used[:, safe] * valid).sum(-1) == 0)    # (N,)
        elig = eligible_row(prob.eligible, s, prob.N) & prob.node_valid  # (N,)
        ok = fits & conf_free & elig
        util = (st.load / jnp.maximum(prob.capacity, 1e-6)).max(-1)
        # clean candidates rank first; any eligible node beats staying
        # stranded (W_ELIG dwarfs a capacity/conflict residual); inf when
        # no eligible valid node exists at all (genuinely unplaceable)
        score = jnp.where(ok, util, jnp.where(elig, util + 1e6, jnp.inf))
        b = jnp.argmin(score)
        can = jnp.isfinite(score[b])
        a = st.assignment[s]
        w = can.astype(jnp.float32)
        wi = can.astype(jnp.int32)

        load = st.load.at[a].add(-d * w).at[b].add(d * w)
        vi = valid.astype(jnp.int32) * wi
        used = st.used.at[a, safe].add(-vi).at[b, safe].add(vi)
        ci = cvalid.astype(jnp.int32) * wi
        coloc = st.coloc.at[a, csafe].add(-ci).at[b, csafe].add(ci)
        r = (wi if prob.n_real is None
             else wi * (s < prob.n_real).astype(jnp.int32))
        topo = (st.topo.at[prob.node_topology[a]].add(-r)
                .at[prob.node_topology[b]].add(r))
        assignment = st.assignment.at[s].set(
            jnp.where(can, b, a).astype(jnp.int32))
        return (ChainState(assignment, load, used, coloc, topo),
                attempted, i + 1, moves + wi)

    st, _, _, moves = jax.lax.while_loop(
        cond, body,
        (st, jnp.zeros(prob.S, dtype=bool), jnp.int32(0), jnp.int32(0)))
    return st, moves


def state_violation_stats(prob: DeviceProblem, st: ChainState) -> dict:
    """Exact hard-violation stats computed from the CARRIED chain state —
    identical results to kernels.violation_stats (the state's load/used/topo
    are maintained move-by-move with the same scatter semantics used to
    build them), but without rebuilding the (N, G) occupancy: an (N, G)
    elementwise reduce instead of a scatter, ~20x cheaper on TPU. This is
    what makes cheap adaptive-exit checks possible."""
    cap_cells = (st.load > prob.capacity * (1 + 1e-6)).sum().astype(jnp.float32)
    c = st.used.astype(jnp.float32)
    conflict_pairs = (c * (c - 1.0) / 2.0).sum()
    inelig = (~eligible_lookup(prob.eligible, jnp.arange(prob.S),
                               st.assignment)).sum()
    invalid = (~prob.node_valid[st.assignment]).sum()
    elig = (inelig + invalid).astype(jnp.float32)
    if prob.max_skew > 0:
        skew = jnp.maximum(
            (st.topo.max() - st.topo.min()) - prob.max_skew, 0
        ).astype(jnp.float32)
    else:
        skew = jnp.float32(0.0)
    return {
        "capacity": cap_cells,
        "conflicts": conflict_pairs,
        "eligibility": elig,
        "skew": skew,
        "total": cap_cells + conflict_pairs + elig + skew,
    }


def violation_total_from_parts(prob: DeviceProblem, load: jax.Array,
                               used: jax.Array, topo: jax.Array,
                               inelig_count: jax.Array) -> jax.Array:
    """Total hard violations from node-state components + a precomputed
    ineligibility count. Shared by the carried-state stats above and the
    sharded adaptive exit (which psums its shard-local inelig counts) so
    the feasibility definition cannot drift between them."""
    cap_cells = (load > prob.capacity * (1 + 1e-6)).sum().astype(jnp.float32)
    c = used.astype(jnp.float32)
    conflict_pairs = (c * (c - 1.0) / 2.0).sum()
    if prob.max_skew > 0:
        skew = jnp.maximum(
            (topo.max() - topo.min()) - prob.max_skew, 0).astype(jnp.float32)
    else:
        skew = jnp.float32(0.0)
    return (cap_cells + conflict_pairs + skew
            + inelig_count.astype(jnp.float32))


def state_soft_score(prob: DeviceProblem, st: ChainState) -> jax.Array:
    """kernels.soft_score evaluated from the carried state (same formulas,
    no group_counts rebuild). Pass the ORIGINAL problem to report without a
    warm-start bonus, or one carrying `sticky_prev` for ranking
    consistency: staying on the previous (still eligible+valid) node earns
    `sticky_w` per service, computed from (S,) gathers instead of a
    materialized bonus plane."""
    u = st.load / jnp.maximum(prob.capacity, 1e-6)
    usq = (u * u).sum()
    denom = jnp.float32(max(prob.N, 1))
    if prob.strategy == 0:
        strat = usq / denom
    elif prob.strategy == 1:
        strat = -usq / denom
    else:
        strat = (st.assignment.astype(jnp.float32) / denom).mean()
    if prob.preferred is None:
        pref = jnp.float32(0.0)   # absent plane: no zeros to stream
    else:
        pref = -prob.preferred[jnp.arange(prob.S), st.assignment].mean()
    if prob.sticky_prev is not None:
        prev = prob.sticky_prev
        anchored = (eligible_lookup(prob.eligible, jnp.arange(prob.S), prev)
                    & prob.node_valid[prev])
        at_prev = ((st.assignment == prev) & anchored)
        # the materialized plane added sticky_w * S at [s, prev[s]], whose
        # pref mean contributed -sticky_w per anchored stay — same scale
        pref = pref - prob.sticky_w * at_prev.sum().astype(jnp.float32)
    if prob.Gc > 0:
        cc = st.coloc.astype(jnp.float32)
        coloc = -(cc * (cc - 1.0) / 2.0).sum() / jnp.float32(max(prob.S, 1))
    else:
        coloc = jnp.float32(0.0)
    return strat + pref + coloc


def _overflow_mass(prob: DeviceProblem, load_rows: jax.Array,
                   cap_rows: jax.Array) -> jax.Array:
    """Normalized overflow mass for the given (k, R) rows."""
    return (jnp.maximum(load_rows - cap_rows, 0.0)
            / jnp.maximum(cap_rows, 1e-6)).sum()


def _skew_pen(prob: DeviceProblem, topo: jax.Array) -> jax.Array:
    if prob.max_skew <= 0:
        return jnp.float32(0.0)
    skew = (topo.max() - topo.min()).astype(jnp.float32)
    return jnp.maximum(skew - prob.max_skew, 0.0) * W_SKEW


def _soft_rows(prob: DeviceProblem, load_rows: jax.Array,
               cap_rows: jax.Array) -> jax.Array:
    """Strategy soft term restricted to the touched node rows."""
    u = load_rows / jnp.maximum(cap_rows, 1e-6)
    usq = (u * u).sum()
    if prob.strategy == 0:
        return usq / prob.N
    if prob.strategy == 1:
        return -usq / prob.N
    return jnp.float32(0.0)


def _move_delta_core(prob: DeviceProblem, *, capacity: jax.Array,
                     node_topology: jax.Array, load: jax.Array,
                     used: jax.Array, coloc: jax.Array, topo: jax.Array,
                     a: jax.Array, b: jax.Array, d: jax.Array,
                     ids: jax.Array, cids: jax.Array, elig_a: jax.Array,
                     elig_b: jax.Array, d_pref: jax.Array,
                     r: jax.Array) -> jax.Array:
    """Annealing-cost delta of moving one service from node `a` to node `b`,
    shared term for term between the single-device sweep (_proposal_delta)
    and the service-axis sharded sweep (solver/sharded.py) — "a legal sweep
    here is a legal sweep there" is enforced by construction, not by
    parallel maintenance of two copies.

    `prob` supplies only statics (strategy, max_skew, N, S). Tensor inputs
    are the caller's views: the single-device anneal passes the problem
    planes + carried ChainState, the sharded sweep passes its shard-local
    gathers against the replicated node state. `elig_a`/`elig_b` are the
    node_valid-masked eligibility bits of the two endpoints, `d_pref` the
    preference delta (including any warm-start stickiness), `r` the row's
    topology weight (0 for bucket-padding phantoms)."""
    valid = (ids >= 0)
    safe = jnp.where(valid, ids, 0)
    cvalid = (cids >= 0)
    csafe = jnp.where(cvalid, cids, 0)

    cap_a, cap_b = capacity[a], capacity[b]
    load_a, load_b = load[a], load[b]

    # -- hard deltas ---------------------------------------------------------
    # capacity overflow mass on the two touched rows
    over_before = (_overflow_mass(prob, load_a, cap_a)
                   + _overflow_mass(prob, load_b, cap_b))
    load_a2, load_b2 = load_a - d, load_b + d
    over_after = (_overflow_mass(prob, load_a2, cap_a)
                  + _overflow_mass(prob, load_b2, cap_b))
    d_cap = (over_after - over_before) * W_CAP

    # conflicts: occupancy excluding s itself on its current node
    conf_a = ((used[a, safe] - 1) * valid).sum()
    conf_b = (used[b, safe] * valid).sum()
    d_conf = (conf_b - conf_a).astype(jnp.float32) * W_CONF

    # eligibility / validity
    d_elig = (elig_a.astype(jnp.float32) - elig_b.astype(jnp.float32)) * W_ELIG

    # skew (phantom rows carry no topology weight)
    ta, tb = node_topology[a], node_topology[b]
    topo2 = topo.at[ta].add(-r).at[tb].add(r)
    d_skew = _skew_pen(prob, topo2) - _skew_pen(prob, topo)

    # -- soft deltas ---------------------------------------------------------
    soft_before = _soft_rows(prob, jnp.stack([load_a, load_b]),
                             jnp.stack([cap_a, cap_b]))
    soft_after = _soft_rows(prob, jnp.stack([load_a2, load_b2]),
                            jnp.stack([cap_a, cap_b]))
    col_a = ((coloc[a, csafe] - 1) * cvalid).sum()
    col_b = (coloc[b, csafe] * cvalid).sum()
    d_coloc = (col_a - col_b).astype(jnp.float32) / max(prob.S, 1)

    return (d_cap + d_conf + d_elig + d_skew
            + (soft_after - soft_before) + d_pref + d_coloc)


def _proposal_delta(prob: DeviceProblem, state: ChainState,
                    s: jax.Array, b: jax.Array) -> jax.Array:
    """Annealing-cost delta of moving service s to node b (no apply)."""
    a = state.assignment[s]
    elig_a = eligible_lookup(prob.eligible, s, a) & prob.node_valid[a]
    elig_b = eligible_lookup(prob.eligible, s, b) & prob.node_valid[b]
    r = (jnp.int32(1) if prob.n_real is None
         else (s < prob.n_real).astype(jnp.int32))
    if prob.preferred is None:
        d_pref = jnp.float32(0.0)
    else:
        d_pref = (prob.preferred[s, a] - prob.preferred[s, b]) / prob.S
    if prob.sticky_prev is not None:
        # on-the-fly migration stickiness: the materialized plane's
        # bonus[s, prev[s]] = sticky_w * S contributed exactly
        # sticky_w * (at_prev(a) - at_prev(b)) through d_pref's /S
        prev = prob.sticky_prev[s]
        anchored = (eligible_lookup(prob.eligible, s, prev)
                    & prob.node_valid[prev])
        d_pref = d_pref + prob.sticky_w * (
            ((a == prev) & anchored).astype(jnp.float32)
            - ((b == prev) & anchored).astype(jnp.float32))
    return _move_delta_core(
        prob, capacity=prob.capacity, node_topology=prob.node_topology,
        load=state.load, used=state.used, coloc=state.coloc, topo=state.topo,
        a=a, b=b, d=prob.demand[s], ids=prob.conflict_ids[s],
        cids=prob.coloc_ids[s], elig_a=elig_a, elig_b=elig_b,
        d_pref=d_pref, r=r)


def _batched_step(prob: DeviceProblem, state: ChainState,
                  key: jax.Array, temp: jax.Array,
                  M: int) -> tuple[ChainState, jax.Array]:
    """One parallel-Metropolis step: M simultaneous proposals. Returns the
    stepped state plus the number of APPLIED moves (post winner-resolution)
    — the acceptance signal the adaptive path accumulates for telemetry.

    Deltas are evaluated against the shared pre-step state, so accepted
    moves that touch the same node interact slightly — the standard
    accelerator-SA approximation; the exact kernels re-rank chains and the
    repair backstop guards the zero-violation contract. Duplicate proposals
    for one service are resolved winner-takes-first so the scatter state
    update stays exact for the chosen move set.
    """
    ks, kb, ka, kt = jax.random.split(key, 4)
    # Half the proposals are TARGETED at services that currently sit on a
    # violating node (overloaded, conflicted) or an invalid/ineligible one.
    # Uniform proposals alone need O(S/M) sweeps just to *mention* each of a
    # handful of offenders (measured: 9 leftover seed violations cost ~96
    # sweeps at 10k x 1k); targeting finds them in a few sweeps, and churn
    # reschedules hit the dead node's services immediately. When nothing is
    # flagged the logits are flat and the "targeted" half is plain uniform.
    over_node = (state.load > prob.capacity * (1 + 1e-6)).any(-1)    # (N,)
    u = state.used
    conf_node = ((u * (u - 1)).sum(-1) > 0)                          # (N,)
    hot_node = over_node | conf_node
    svc_bad = (~eligible_lookup(prob.eligible, jnp.arange(prob.S),
                                state.assignment)
               | ~prob.node_valid[state.assignment])
    hot = hot_node[state.assignment] | svc_bad                       # (S,)
    logits = jnp.where(hot, 0.0, -30.0)
    s_tgt = jax.random.categorical(kt, logits, shape=(M,))
    s_uni = jax.random.randint(ks, (M,), 0, prob.S)
    half = M // 2
    s_idx = jnp.where(jnp.arange(M) < half, s_tgt, s_uni)
    b_idx = jax.random.randint(kb, (M,), 0, prob.N)
    a_idx = state.assignment[s_idx]

    delta = jax.vmap(lambda s, b: _proposal_delta(prob, state, s, b))(
        s_idx, b_idx)
    u = jax.random.uniform(ka, (M,))
    accept = ((delta < 0) | (u < jnp.exp(-delta / jnp.maximum(temp, 1e-8)))) \
        & (a_idx != b_idx)

    # winner-per-service: the lowest proposal index with accept wins
    order = jnp.arange(M, dtype=jnp.int32)
    winner = jnp.full((prob.S,), M, dtype=jnp.int32).at[s_idx].min(
        jnp.where(accept, order, M))
    applied = accept & (winner[s_idx] == order)
    # winner-per-TARGET-node: at most one move lands on any node per sweep.
    # This makes the sweep feasibility-preserving despite stale deltas: the
    # single entrant was evaluated against the pre-sweep node state, and
    # every other change to that node is a departure (which only frees
    # capacity and conflict groups). A feasible chain therefore stays
    # feasible through the whole anneal.
    tgt_winner = jnp.full((prob.N,), M, dtype=jnp.int32).at[b_idx].min(
        jnp.where(applied, order, M))
    applied = applied & (tgt_winner[b_idx] == order)
    w = applied.astype(jnp.float32)
    wi = applied.astype(jnp.int32)

    d = prob.demand[s_idx]                                       # (M, R)
    load = (state.load.at[a_idx].add(-d * w[:, None])
            .at[b_idx].add(d * w[:, None]))

    ids = prob.conflict_ids[s_idx]                               # (M, K)
    valid = (ids >= 0).astype(jnp.int32) * wi[:, None]
    safe = jnp.where(ids >= 0, ids, 0)
    a_rows = jnp.broadcast_to(a_idx[:, None], safe.shape)
    b_rows = jnp.broadcast_to(b_idx[:, None], safe.shape)
    used = (state.used.at[a_rows, safe].add(-valid)
            .at[b_rows, safe].add(valid))

    cids = prob.coloc_ids[s_idx]
    cvalid = (cids >= 0).astype(jnp.int32) * wi[:, None]
    csafe = jnp.where(cids >= 0, cids, 0)
    coloc = (state.coloc.at[a_rows[:, : csafe.shape[1]], csafe].add(-cvalid)
             .at[b_rows[:, : csafe.shape[1]], csafe].add(cvalid))

    wt = (wi if prob.n_real is None
          else wi * (s_idx < prob.n_real).astype(jnp.int32))
    topo = (state.topo.at[prob.node_topology[a_idx]].add(-wt)
            .at[prob.node_topology[b_idx]].add(wt))

    # .set scatters route non-applied writes to a dump row (value writes
    # from losers must not race the winner's)
    dump = prob.S
    tgt = jnp.where(applied, s_idx, dump)
    assignment = jnp.zeros((prob.S + 1,), jnp.int32).at[:prob.S].set(
        state.assignment).at[tgt].set(b_idx.astype(jnp.int32))[:prob.S]

    return ChainState(assignment, load, used, coloc, topo), wi.sum()


def default_proposals_per_step(S: int) -> int:
    """Batch width: enough parallel proposals to keep the device busy,
    capped so tiny instances don't over-propose. 256 targets the
    accelerator knee — below it a sweep costs the same fixed overhead,
    above it the sweep goes bandwidth-bound (and winner-per-target wastes
    the surplus). Hardware re-validation is pending TPU access; the CPU
    path overrides to 64, where sweep cost is ~linear in width (measured
    round 3, docs/guide/03-placement-and-the-tpu-solver.md tuning notes +
    docs/profiles/)."""
    return max(1, min(256, S // 2))


def backend_proposals_per_step(S: int) -> int:
    """The backend-aware width both the full pipeline (api._solve) and
    the active-set sub-solve derive from: the CPU knee is 64 (sweep cost
    ~linear in width there — no free MXU width), accelerators take the
    256 knee above. ONE helper so a re-tuned knee cannot update one call
    site and silently leave the other stale."""
    import jax
    if jax.default_backend() == "cpu":
        return max(1, min(64, S // 2))
    return default_proposals_per_step(S)


@partial(jax.jit, static_argnames=("steps", "proposals_per_step", "unroll"))
def anneal_states(prob: DeviceProblem, init_assignments: jax.Array,
                  key: jax.Array, steps: int = 2000, t0: float = 1.0,
                  t1: float = 1e-3, proposals_per_step: int | None = None,
                  unroll: int = 1) -> ChainState:
    """Run `steps` batched-Metropolis sweeps on C independent chains.

    Returns each chain's FINAL carried state — unlike the adaptive path,
    there is no best-ever tracking here: callers that rank these states
    (api adaptive=False, tests comparing carried state against rebuilds)
    rely on exact final-state semantics, and the production default is
    the adaptive path.

    init_assignments: (C, S) int32; returns refined assignments (C, S).
    Each sweep evaluates `proposals_per_step` moves per chain in parallel
    (one device dispatch), so total proposals = steps x M x C while the
    sequential depth stays `steps` — the shape that keeps a TPU fed, vs the
    classic one-move-per-step SA whose wall-clock is all dispatch latency.
    Temperature decays geometrically t0 → t1 (in units of soft-score; hard
    violation weights are orders of magnitude above t0, so hard-violating
    moves are only ever accepted to escape an existing violation).
    """
    C, S = init_assignments.shape
    M = (proposals_per_step if proposals_per_step is not None
         else default_proposals_per_step(S))
    states = jax.vmap(partial(chain_states_from_assignment, prob))(init_assignments)
    keys = jax.random.split(key, C)

    decay = (t1 / t0) ** (1.0 / max(steps - 1, 1))

    def sweep(carry, i):
        states, keys = carry
        temp = t0 * decay ** i.astype(jnp.float32)
        keys = jax.vmap(lambda k: jax.random.fold_in(k, i))(keys)
        states, _acc = jax.vmap(
            lambda st, k: _batched_step(prob, st, k, temp, M))(states, keys)
        return (states, keys), None

    (states, _), _ = jax.lax.scan(sweep, (states, keys),
                                  jnp.arange(steps, dtype=jnp.int32),
                                  unroll=unroll)
    return states


def anneal(prob: DeviceProblem, init_assignments: jax.Array, key: jax.Array,
           steps: int = 2000, t0: float = 1.0, t1: float = 1e-3,
           proposals_per_step: int | None = None,
           unroll: int = 1) -> jax.Array:
    """Fixed-budget anneal; returns refined assignments (C, S)."""
    return anneal_states(prob, init_assignments, key, steps=steps, t0=t0,
                         t1=t1, proposals_per_step=proposals_per_step,
                         unroll=unroll).assignment


@partial(jax.jit, static_argnames=("max_steps", "block",
                                   "proposals_per_step",
                                   "exit_on_feasible_init", "trace_blocks"))
def anneal_adaptive_states(prob: DeviceProblem, init_assignments: jax.Array,
                           key: jax.Array, max_steps: int = 128,
                           block: int = 32, t0: float = 1.0, t1: float = 1e-3,
                           proposals_per_step: int | None = None,
                           init_states: ChainState | None = None,
                           exit_on_feasible_init: bool = False,
                           trace_blocks: int = 0):
    """Anneal in `block`-sweep chunks, stopping as soon as any chain has
    SEEN an exactly feasible state (or at max_steps). Returns
    (best_assignments (C, S), best_viols (C,), best_softs (C,),
    sweeps_run scalar, accepted (C,), telemetry), where best is each
    chain's lexicographically lowest (violations, soft) state EVER
    VISITED, not its final state, and accepted counts the applied
    Metropolis moves per chain across every sweep that ran — the
    acceptance telemetry that surfaces through SolveResult and the
    fleet_solver_* metrics.

    `trace_blocks` > 0 (static — see solve_trace_blocks) additionally
    carries a fixed-shape (trace_blocks, len(TRACE_COLS)) f32 buffer
    through the block loop and writes one row per completed sweep-block:
    cumulative sweeps, the block-end temperature, the best-ever
    (violations, soft) across chains, the min LIVE violation count of the
    carried states, and the cumulative accepted-move total. The buffer is
    observation only — it never feeds back into a proposal, a key fold or
    an exit check, so the refined assignment is bit-identical to the
    trace_blocks=0 program (pinned by the telemetry parity test). Blocks
    past the buffer drop (mode="drop"): a long anneal keeps its FIRST
    trace_blocks rows, where acceptance collapse and gate rejections
    live.

    Best-ever tracking (r5): Metropolis acceptance takes uphill soft moves
    by design, so a chain's final state can be worse than one it already
    walked through — measured on the 1k x 100 instance, an 8-sweep run
    RETURNED soft 1.3714 where a 2-sweep run returned 1.3390, i.e. more
    annealing made the answer worse. Tracking argmin over visited states
    restores monotonicity (more sweeps can only help) and decouples
    `block` from quality: the block size is now purely an exit-check
    granularity / latency knob. Cost per sweep is one carried-state
    elementwise reduce per chain (the same price the per-block exit check
    already paid), not a scatter rebuild.

    The stop check runs ON DEVICE inside a lax.while_loop — no host round
    trips — so easy instances (and especially warm-start reschedules, which
    start one churn event away from feasible) pay one block instead of the
    full budget, while hard instances still get max_steps. The temperature
    schedule is fixed against max_steps, so early exit truncates the cool
    tail rather than reshaping it. When max_steps is not a block multiple
    the budget rounds UP to whole blocks; overflow sweeps hold the floor
    temperature t1 (the exponent is clamped), and sweeps_run reports what
    actually ran.
    """
    C, S = init_assignments.shape
    M = (proposals_per_step if proposals_per_step is not None
         else default_proposals_per_step(S))
    n_blocks = -(-max_steps // block)
    # init_states skips the per-chain scatter rebuild when the caller
    # already carries the states (warm fused pre-repair: every chain
    # starts from the repaired seed, so the prologue's state IS the init)
    states = (init_states if init_states is not None else
              jax.vmap(partial(chain_states_from_assignment,
                               prob))(init_assignments))
    keys = jax.random.split(key, C)
    decay = (t1 / t0) ** (1.0 / max(max_steps - 1, 1))

    def chain_scores(states):
        """(violations (C,), soft (C,)) from carried state — an
        elementwise reduce, not a scatter rebuild (an exact-kernel check
        here cost ~18 ms per block at 10k x 1k). Kept as SEPARATE scalars:
        a folded W_HARD * v + soft float32 rounds the O(1) soft term away
        entirely once v exceeds ~1e3 (ulp(2e7) = 2), which would turn the
        soft tie-break among equal-violation states into a no-op on
        heavily infeasible instances."""
        v = jax.vmap(
            lambda st: state_violation_stats(prob, st)["total"])(states)
        soft = jax.vmap(lambda st: state_soft_score(prob, st))(states)
        return v, soft

    def sweep(carry, i):
        (states, keys, best_assign, best_viol, best_soft,
         seen_feasible, accepted, *live) = carry
        # clamp: overflow sweeps of a rounded-up final block hold t1
        temp = t0 * decay ** jnp.minimum(
            i, max_steps - 1).astype(jnp.float32)
        keys = jax.vmap(lambda k: jax.random.fold_in(k, i))(keys)
        states, acc = jax.vmap(
            lambda st, k: _batched_step(prob, st, k, temp, M))(states, keys)
        accepted = accepted + acc
        viol, soft = chain_scores(states)
        # lexicographic (violations, soft) — NOT a folded cost: the
        # warm-start migration bonus can push soft below -W_HARD in
        # aggregate (bonus gap ~ migration_weight x forced moves), where a
        # folded comparison would prefer a 1-violation maximally-sticky
        # state over a feasible one; feasibility must dominate
        # unconditionally, and soft must stay a full-precision tie-break
        better = (viol < best_viol) | ((viol == best_viol)
                                       & (soft < best_soft))
        best_viol = jnp.where(better, viol, best_viol)
        best_soft = jnp.where(better, soft, best_soft)
        best_assign = jnp.where(better[:, None], states.assignment,
                                best_assign)
        seen_feasible = seen_feasible | (viol.min() == 0)
        out = (states, keys, best_assign, best_viol, best_soft,
               seen_feasible, accepted)
        if trace_blocks:
            # thread the LIVE scores this sweep already computed out to
            # the block boundary — the telemetry row reads them for free
            # instead of re-running chain_scores per block (which, at the
            # warm path's block=1, would double the per-sweep stats cost
            # — measured as the admission p99 regrowing 30 → 65 ms)
            out = out + (viol,)
        return out, None

    def best_soft_of(best_viol, best_soft):
        """Soft of the lexicographically leading chain — what one
        telemetry row can say about C chains without C columns."""
        return jnp.min(jnp.where(best_viol == best_viol.min(),
                                 best_soft, jnp.inf))

    viol0, soft0 = chain_scores(states)
    telem0 = jnp.zeros((trace_blocks, len(TRACE_COLS)), jnp.float32)
    init = (states, keys, states.assignment, viol0, soft0,
            viol0.min() == 0, jnp.zeros((C,), jnp.int32), telem0)

    def cond(carry):
        *_rest, b, done = carry
        return (~done) & (b < n_blocks)

    def body(carry):
        (states, keys, best_assign, best_viol, best_soft, seen,
         accepted, telem, b, _done) = carry
        offsets = b * block + jnp.arange(block, dtype=jnp.int32)
        inner = (states, keys, best_assign, best_viol, best_soft, seen,
                 accepted)
        if trace_blocks:
            # placeholder live scores; block >= 1 so the first sweep of
            # the block always overwrites them
            inner = inner + (best_viol,)
        res, _ = jax.lax.scan(sweep, inner, offsets)
        (states, keys, best_assign, best_viol, best_soft,
         seen, accepted) = res[:7]
        # flight-deck row for this block: PURE observation of scores the
        # sweeps already computed (no extra reduces — pinned by the
        # admission bench's tail assert), written with mode="drop" so
        # rows past the static buffer vanish instead of clamping onto
        # the last slot. trace_blocks == 0 (static) skips everything:
        # the pre-telemetry program, byte for byte — the parity
        # reference.
        if trace_blocks:
            live_viol = res[7]
            end_sweep = (b + 1) * block
            temp_end = t0 * decay ** jnp.minimum(
                end_sweep - 1, max_steps - 1).astype(jnp.float32)
            row = jnp.stack([end_sweep.astype(jnp.float32),
                             temp_end,
                             best_viol.min(),
                             best_soft_of(best_viol, best_soft),
                             live_viol.min(),
                             accepted.sum().astype(jnp.float32)])
            telem = telem.at[b].set(row, mode="drop")
        return (states, keys, best_assign, best_viol, best_soft, seen,
                accepted, telem, b + 1, seen)

    # done starts False: even an already-feasible start gets one block of
    # soft polish (the exit trades polish for latency only after that).
    # exit_on_feasible_init (the resident warm path) skips even that: the
    # fused pre-repair prologue hands over a feasible state whose
    # displaced services already sit on least-utilized fitting nodes, and
    # migration stickiness rejects nearly every polish proposal anyway —
    # the sweep was pure latency (~30 ms of the 10k x 1k warm dispatch).
    start_done = ((viol0.min() == 0) if exit_on_feasible_init
                  else jnp.bool_(False))
    (_, _, best_assign, best_viol, best_soft, _, accepted, telem, b,
     _) = jax.lax.while_loop(cond, body, init + (jnp.int32(0),
                                                 start_done))
    telemetry = {
        "blocks": telem,
        "filled": jnp.minimum(b, trace_blocks),
        # the prologue/seed scores: the whole story of a 0-sweep exit
        "init_violations": viol0.min(),
        "init_soft": best_soft_of(viol0, soft0),
    }
    return best_assign, best_viol, best_soft, b * block, accepted, telemetry


def anneal_adaptive(prob: DeviceProblem, init_assignments: jax.Array,
                    key: jax.Array, max_steps: int = 128, block: int = 32,
                    t0: float = 1.0, t1: float = 1e-3,
                    proposals_per_step: int | None = None):
    """Adaptive anneal; returns (assignments (C, S), sweeps_run,
    accepted (C,))."""
    best_assign, _viol, _soft, sweeps, accepted, _telem = \
        anneal_adaptive_states(
            prob, init_assignments, key, max_steps=max_steps, block=block,
            t0=t0, t1=t1, proposals_per_step=proposals_per_step)
    return best_assign, sweeps, accepted
