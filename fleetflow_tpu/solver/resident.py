"""Device-resident fleet state: the padded placement problem and the last
committed assignment live ON DEVICE between solves, and CP churn arrives as
structured deltas applied by a donated, jitted merge kernel.

Before this module the warm path still rebuilt host state every burst:
`sched/tpu.py` re-staged the padded DeviceProblem whenever capacity drifted
(identity-keyed cache), `solver/api._solve` uploaded the previous assignment
from host numpy and ran the churn pre-repair in host numpy (`prerepair_ms`
~27 ms of the ~101 ms r05 CPU warm reschedule). The paper's thesis is that
the placement hot loop lives on TPU; this closes the remaining host
round-trips:

  ResidentProblem      owns the padded, bucketed DeviceProblem + the last
                       assignment as device buffers across bursts
  ProblemDelta         what churn actually is: node up/down (valid-mask
                       flip), capacity drift, demand drift, arrivals into
                       phantom rows (row scatters + an n_real bump)
  apply_delta          ONE jitted dispatch, `donate_argnums` on the problem
                       and assignment buffers (SNIPPETS.md [1]-[3] donation
                       pattern) — the old buffers are reused in place, and
                       phantom rows are re-parked on a valid node on device

The warm re-solve itself then runs with every input already resident
(problem pytree, seed assignment, temperature scalars), provable with
``FLEET_TRANSFER_GUARD=disallow``: `jax.transfer_guard("disallow")` wraps
the dispatch and any host->device transfer of problem tensors raises.
Pre-repair is fused into the anneal entry (`anneal.prerepair_state`), so
the warm path is: small delta upload -> one donated merge dispatch -> one
fused solve dispatch -> scalars back.

Delta reuse is gated by bucket identity: the candidate ProblemTensors must
sit in the same shape tier with the same strategy/skew statics AND share
(by object identity) every tensor the delta does not cover — content drift
beyond the delta falls back to cold staging (counted in
`fleet_solver_resident_reuse_total{outcome="cold"}` and, on warm attempts,
`fleet_solver_host_transfers_total`). docs/guide/11-performance.md covers
tuning and transfer-guard debugging.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
from dataclasses import dataclass
from functools import lru_cache
from typing import Any, Optional

import numpy as np

from ..obs import get_logger, kv
from ..obs.metrics import MS_BUCKETS, REGISTRY
from .buckets import bucket_config, bucket_size

log = get_logger("solver.resident")

__all__ = ["ProblemDelta", "ResidentProblem", "transfer_guard_ctx"]

# metric catalog: docs/guide/10-observability.md
_M_REUSE = REGISTRY.counter(
    "fleet_solver_resident_reuse_total",
    "Resident-state staging decisions: delta = on-device delta applied to "
    "the resident problem, cold = full host (re)staging",
    labels=("outcome",))
_M_DELTA_MS = REGISTRY.histogram(
    "fleet_solver_delta_stage_ms",
    "Milliseconds spent applying on-device churn deltas per warm solve "
    "(upload + donated merge dispatch)",
    buckets=MS_BUCKETS)
_M_HOST_XFER = REGISTRY.counter(
    "fleet_solver_host_transfers_total",
    "Warm-path solves that had to move problem tensors across the host "
    "boundary (cold restage on a warm attempt, or a host repair re-upload) "
    "— each is an event the transfer guard would have caught in disallow "
    "mode")


def transfer_guard_ctx():
    """The context the resident warm path dispatches under.
    FLEET_TRANSFER_GUARD= unset/off/allow -> no guard; log -> jax logs every
    host transfer; disallow -> any host->device transfer raises (the proof
    mode the resident tests and the bench burst leg run in)."""
    mode = os.environ.get("FLEET_TRANSFER_GUARD", "").strip().lower()
    if mode in ("", "0", "off", "false", "allow"):
        return contextlib.nullcontext()
    if mode not in ("log", "disallow", "log_explicit", "disallow_explicit"):
        mode = "disallow"
    import jax
    return jax.transfer_guard(mode)


@dataclass
class ProblemDelta:
    """Structured churn: what changed since the resident staging.

    `node_valid`/`capacity` are FULL small arrays ((N,) / (N, R) — a few KB
    at fleet scale); row-sparse fields scatter into the big (S, ·) tensors.
    Fields left None mean "unchanged" (node_valid/capacity then upload from
    the accompanying ProblemTensors, which is the truth either way). The
    contract for delta staging: the new ProblemTensors differs from the
    resident one ONLY by fields this delta covers — anything else (new
    conflict ids, a relowered fleet) must cold-stage, and
    `ResidentProblem.compatible` enforces it by object identity."""
    node_valid: Optional[np.ndarray] = None       # (N,) new validity mask
    capacity: Optional[np.ndarray] = None         # (N, R) new capacity
    # demand drift / arrivals: (rows (k,), values (k, R))
    demand_rows: Optional[tuple[np.ndarray, np.ndarray]] = None
    # arrival eligibility: (rows (k,), masks (k, N))
    eligible_rows: Optional[tuple[np.ndarray, np.ndarray]] = None
    # new real-row count (arrivals activate phantom rows; None = unchanged)
    n_real: Optional[int] = None


def _row_tier(k: int) -> int:
    """Scatter-row padding tier (8, 32, 128, ...): delta sizes drift burst
    to burst and each distinct row count would otherwise be a fresh XLA
    program for the merge kernel."""
    tier = 8
    while tier < k:
        tier *= 4
    return tier


@lru_cache(maxsize=1)
def _merge_fn():
    """The donated delta-merge kernel, built lazily so importing
    ProblemDelta never pays JAX startup (cp/ imports this module on the
    host path)."""
    import jax
    import jax.numpy as jnp

    def merge(prob, assignment, node_valid, capacity, dem_idx, dem_val,
              elig_idx, elig_rows, n_real, *, has_demand, has_eligible):
        # scatter rows ride padded tiers; pad slots carry an out-of-range
        # index and mode="drop" discards them. The static has_* flags keep
        # the common mask/capacity-only delta from touching the big (S, ·)
        # planes at all — they alias straight through the donation.
        demand = (prob.demand.at[dem_idx].set(dem_val, mode="drop")
                  if has_demand else prob.demand)
        eligible = (prob.eligible.at[elig_idx].set(elig_rows, mode="drop")
                    if has_eligible else prob.eligible)
        # re-park phantom rows on a valid node: the previous winner may
        # have left them on a node this delta just killed, and a phantom
        # on an invalid node is the one way it stops being inert
        first_valid = jnp.argmax(node_valid).astype(jnp.int32)
        ar = jnp.arange(prob.S)
        assignment = jnp.where(ar >= n_real, first_valid, assignment)
        prob = dataclasses.replace(
            prob, demand=demand, eligible=eligible, node_valid=node_valid,
            capacity=capacity, n_real=n_real)
        return prob, assignment

    # donation: the stale problem/assignment buffers are dead the moment
    # the merge lands, so XLA reuses them in place — no second copy of the
    # (S, N) planes ever exists (SNIPPETS.md [1]-[3])
    return jax.jit(merge, donate_argnums=(0, 1),
                   static_argnames=("has_demand", "has_eligible"))


class ResidentProblem:
    """The device-resident placement state a TpuSolverScheduler owns.

    Lifecycle: `cold_stage(pt)` pads + uploads once; each churn burst calls
    `apply_delta(pt, delta)` (donated on-device merge); `solver.api._solve`
    seeds the warm anneal from `self.assignment` (device) and calls
    `adopt()` with the padded winner. `compatible()` is the bucket-identity
    gate deciding delta reuse vs cold fallback.

    The staging primitives (`_merge`, `_put_small`, `_put_n_real`,
    `_put_assignment`, `_stage_scalars`, `_expected_padded_S`) are hooks:
    the single-chip default stages onto the default device, and
    solver/sharded.ShardedResident overrides them to keep the same state
    mesh-sharded (committed NamedShardings + a sharding-constrained
    donated merge) for the pod-scale path."""

    # the mesh this staging is committed to (None = single chip); the
    # scheduler's slot matching keys on it so a routing flip mid-life can
    # never hand a sharded staging to the single-chip path or vice versa
    mesh = None
    # the single-chip staging supports churn-localized sub-solves
    # (solver/subsolve.py); the mesh-sharded subclass runs its own SPMD
    # anneal and opts out
    supports_subsolve = True

    def __init__(self, pt, *, bucket: bool = True,
                 cfg=None):
        self.cfg = cfg or bucket_config()
        self.bucket = bool(bucket and self.cfg.enabled)
        self.pt: Any = None
        self.prob: Any = None                 # padded DeviceProblem
        self.assignment: Any = None           # (padded_S,) i32 device array
        self.n_real: int = 0
        self._valid_fp: Optional[np.ndarray] = None
        self._cap_fp: Optional[np.ndarray] = None
        self._delta_ms: float = 0.0
        self._scalars: dict[tuple, tuple] = {}
        self._staged_fp: tuple = (None, None)
        # active-set sub-solve state (solver/subsolve.py): host mirror of
        # the padded device assignment as of the last solve, the host
        # constraint index (built lazily per staging), and the row set
        # churn deltas have touched since that solve
        self._mirror: Optional[np.ndarray] = None
        self._mirror_feasible: bool = False
        self._index: Any = None
        self._pending_rows: Optional[np.ndarray] = None
        self._pending_churn: bool = False
        self.cold_stage(pt)

    # -- staging -----------------------------------------------------------

    def cold_stage(self, pt) -> None:
        """Full host staging: prepare + pad + upload. Also the fallback
        when a delta's compatibility gate fails."""
        import jax.numpy as jnp

        from .buckets import stage_problem_tiers
        from .problem import prepare_problem

        if self.bucket:
            # arena staging (compile-free), but with PRIVATE device
            # buffers: the resident merge kernels donate these planes, so
            # the shared device-constant cache must not hand the same
            # array to two stagings
            prob, _ = stage_problem_tiers(
                pt, self.cfg, device=self._staging_device(),
                reuse_device_constants=False)
        else:
            prob = prepare_problem(pt, device=self._staging_device())
        if prob.n_real is None:
            # always traced, even unpadded/on-tier: keeps one treedef for
            # every resident solve and lets the merge kernel re-park
            prob = dataclasses.replace(
                prob, n_real=jnp.asarray(pt.S, jnp.int32))
        self.pt = pt
        self.prob = prob
        self.assignment = None
        self.n_real = int(pt.S)
        self._valid_fp = np.asarray(pt.node_valid, dtype=bool).copy()
        self._cap_fp = np.asarray(pt.capacity, dtype=np.float32).copy()
        self._delta_ms = 0.0
        # a cold staging invalidates the sub-solve state: the mirror is
        # of a dead assignment and the index of dead tensors
        self._mirror = None
        self._mirror_feasible = False
        self._index = None
        self._pending_rows = None
        self._pending_churn = False
        _M_REUSE.inc(outcome="cold")

    def compatible(self, pt, delta: Optional[ProblemDelta] = None) -> bool:
        """Bucket-identity gate for delta reuse: same shape tier and solver
        statics, and every tensor the delta does NOT cover is the same
        OBJECT as the resident staging's (dataclasses.replace shares the
        untouched arrays, which is exactly how the CP mutates churn).
        Content drift the delta cannot express -> False -> cold staging."""
        if self.pt is None or self.prob is None:
            return False
        old = self.pt
        if pt is old:
            return True
        if pt.N != old.N:
            return False
        if pt.strategy != old.strategy or pt.max_skew != old.max_skew:
            return False
        if pt.S != old.S:
            return self._arrivals_compatible(pt, delta, old)
        if self.bucket and self._expected_padded_S(pt) != self.prob.S:
            return False
        same = (pt.port_ids is old.port_ids
                and pt.volume_ids is old.volume_ids
                and pt.anti_ids is old.anti_ids
                and pt.coloc_ids is old.coloc_ids
                and pt.node_topology is old.node_topology
                and pt.preferred is old.preferred)
        if delta is None or delta.demand_rows is None:
            same = same and pt.demand is old.demand
        if delta is None or delta.eligible_rows is None:
            same = same and pt.eligible is old.eligible
        return same

    def _arrivals_compatible(self, pt, delta: Optional[ProblemDelta],
                             old) -> bool:
        """Can a GROWN pt (arrivals appended since the resident staging)
        still ride the delta path? Yes when the new rows activate phantom
        rows already on device: the fleet stays inside the padded tier,
        the delta writes the arrivals' demand + eligibility and bumps
        n_real, and the appended rows bring no new hard-constraint ids
        (the padded id planes already read -1 there). Anything richer —
        a crossed tier, an arrival with ports/volumes/anti-affinity, a
        preference plane — cold-stages."""
        if delta is None or delta.n_real != pt.S or pt.S <= old.S:
            return False
        if not self.bucket or self._expected_padded_S(pt) != self.prob.S:
            return False
        if delta.demand_rows is None or delta.eligible_rows is None:
            return False
        new = np.arange(old.S, pt.S)
        if not (np.isin(new, np.asarray(delta.demand_rows[0])).all()
                and np.isin(new, np.asarray(delta.eligible_rows[0])).all()):
            return False
        if (pt.node_topology is not old.node_topology
                or pt.preferred is not None or old.preferred is not None):
            return False
        for name in ("port_ids", "volume_ids", "anti_ids", "coloc_ids"):
            a, b = getattr(pt, name), getattr(old, name)
            if (a.shape[1] != b.shape[1]
                    or not np.array_equal(a[:old.S], b)
                    or (a[old.S:] != -1).any()):
                return False
        return True

    def merge_inputs(self, pt, delta: Optional[ProblemDelta] = None):
        """Stage the per-burst merge-kernel inputs for `delta`: returns
        ``(uploads, n_real, has_demand, has_eligible)`` where `uploads`
        is the device-staged small tuple the merge kernel consumes after
        ``(prob, assignment)``. Split out of :meth:`apply_delta` so the
        compile-contract auditor (solver/contracts.py) can lower the
        EXACT argument shapes the production dispatch uses — not a
        hand-built approximation that would drift. Mutates `self.n_real`
        when the delta bumps it (the staging is the commit point)."""
        delta = delta or ProblemDelta()
        S = self.prob.S
        R = self.prob.demand.shape[1]
        N = self.prob.N

        valid = np.asarray(
            delta.node_valid if delta.node_valid is not None
            else pt.node_valid, dtype=bool)
        cap = np.asarray(
            delta.capacity if delta.capacity is not None
            else pt.capacity, dtype=np.float32)

        def pad_rows(rows_vals, width, fill_dtype):
            idx, vals = rows_vals
            idx = np.asarray(idx, dtype=np.int32)
            vals = np.asarray(vals, dtype=fill_dtype)
            k = _row_tier(max(idx.shape[0], 1))
            pad = k - idx.shape[0]
            if pad:
                idx = np.concatenate([idx, np.full(pad, S, dtype=np.int32)])
                vals = np.concatenate(
                    [vals, np.zeros((pad, width), dtype=fill_dtype)])
            return idx, vals

        has_demand = delta.demand_rows is not None
        has_eligible = delta.eligible_rows is not None
        dem_idx, dem_val = (pad_rows(delta.demand_rows, R, np.float32)
                            if has_demand else (None, None))
        if has_eligible:
            # the delta contract stays host-friendly ((k, N) bool masks);
            # the rows are packed HERE to match the resident plane's
            # layout, so the donated merge scatters packed words — an
            # arrival costs k*ceil(N/32)*4 bytes on the wire, not k*N
            idx, masks = delta.eligible_rows
            if self.prob.eligible.dtype == np.uint32:
                from .problem import pack_bool_rows, packed_width
                masks = pack_bool_rows(
                    np.asarray(masks, dtype=bool).reshape(-1, N))
                elig_idx, elig_rows = pad_rows((idx, masks),
                                               packed_width(N), np.uint32)
            else:
                elig_idx, elig_rows = pad_rows((idx, masks), N, bool)
        else:
            elig_idx, elig_rows = None, None
        if delta.n_real is not None:
            self.n_real = int(delta.n_real)
        n_real = self._put_n_real()

        # explicit small uploads; the warm solve after the merge runs
        # with everything already resident
        uploads = self._put_small(
            (valid, cap, dem_idx, dem_val, elig_idx, elig_rows))
        # host fingerprints adopted by apply_delta AFTER a successful
        # merge (drifted() must keep matching the pre-merge staging when
        # the merge fails and cold_stage recovers)
        self._staged_fp = (valid, cap)
        return uploads, n_real, has_demand, has_eligible

    def _note_churn(self, pt, delta: Optional[ProblemDelta]) -> None:
        """Accumulate the row set this delta touches for the active-set
        planner (solver/subsolve.py) — called BEFORE the fingerprints
        roll over so capacity shrink is measured against the staging the
        mirror assignment was solved on. Node kills need no bookkeeping
        here: stranded rows are recomputed from the post-delta tensors at
        plan time."""
        if not self.supports_subsolve or self._mirror is None:
            return    # nothing to localize against (no previous solve)
        rows = [np.empty(0, dtype=np.int64)]
        if delta is not None:
            if delta.demand_rows is not None:
                rows.append(np.asarray(delta.demand_rows[0],
                                       dtype=np.int64))
            if delta.eligible_rows is not None:
                rows.append(np.asarray(delta.eligible_rows[0],
                                       dtype=np.int64))
        # capacity shrink: frozen rows on a shrunk node may overflow the
        # new capacity — they must join the active set (growth is safe)
        new_cap = np.asarray(
            delta.capacity if delta is not None and
            delta.capacity is not None else pt.capacity, dtype=np.float32)
        if self._cap_fp is not None and new_cap.shape == self._cap_fp.shape:
            shrunk = (new_cap < self._cap_fp - 1e-6).any(axis=1)
            if shrunk.any():
                n = min(self.n_real, self._mirror.shape[0])
                rows.append(np.nonzero(shrunk[self._mirror[:n]])[0])
        pending = np.unique(np.concatenate(rows))
        if self._pending_rows is not None:
            pending = np.union1d(self._pending_rows, pending)
        self._pending_rows = pending
        self._pending_churn = True

    def apply_delta(self, pt, delta: Optional[ProblemDelta] = None) -> float:
        """Merge churn into the resident buffers on device; returns the
        delta-staging wall ms (also accumulated for the next solve's
        `delta_stage_ms` timing). The caller has already checked
        `compatible`; node_valid/capacity always re-upload from `pt` (a few
        KB — the (S, N) problem planes are what never move)."""
        t0 = time.perf_counter()
        self._note_churn(pt, delta)
        uploads, n_real, has_demand, has_eligible = self.merge_inputs(
            pt, delta)
        valid, cap = self._staged_fp
        # ONE donated merge dispatch
        try:
            self.prob, self.assignment = self._merge()(
                self.prob, self.assignment, *uploads, n_real,
                has_demand=has_demand, has_eligible=has_eligible)
        except Exception:
            # a failed merge leaves donated buffers in an unknown state:
            # the only safe recovery is a full cold restage
            log.warning("delta merge failed; cold restaging %s",
                        kv(S=pt.S, N=pt.N))
            self.cold_stage(pt)
            raise
        self.pt = pt
        self._valid_fp = valid.copy()
        self._cap_fp = cap.copy()
        if self._mirror is not None:
            # replay the merge kernel's deterministic phantom re-park so
            # the mirror stays an exact host copy of the device assignment
            self._mirror[self.n_real:] = int(np.argmax(valid))
        ms = (time.perf_counter() - t0) * 1e3
        self._delta_ms += ms
        _M_DELTA_MS.observe(ms)
        _M_REUSE.inc(outcome="delta")
        return ms

    # -- staging hooks (overridden by solver/sharded.ShardedResident) ------

    def _expected_padded_S(self, pt) -> int:
        """The padded S a cold staging of `pt` would produce — the shape
        half of the bucket-identity gate."""
        return bucket_size(pt.S, growth=self.cfg.growth,
                           minimum=self.cfg.minimum, align=self.cfg.align)

    def _staging_device(self):
        """Where cold_stage materializes the prepared problem. None = the
        default device (the single-chip contract: staging IS the final
        placement). The sharded override stages on the host CPU backend so
        the whole (S, N) planes never materialize on one accelerator
        before being committed shard-by-shard to the mesh."""
        return None

    def _merge(self):
        """The donated delta-merge kernel for this staging's layout."""
        return _merge_fn()

    def _put_small(self, tree):
        """Stage the per-burst small uploads (masks, capacity, scatter
        rows) where the merge kernel expects them."""
        import jax
        return jax.device_put(tree)

    def _put_n_real(self):
        """The traced real-row count, staged for the merge kernel."""
        import jax.numpy as jnp
        return jnp.asarray(self.n_real, jnp.int32)

    def _put_assignment(self, padded: np.ndarray):
        """Upload a padded host assignment as the resident warm seed."""
        import jax
        return jax.device_put(padded)

    def _stage_scalars(self, key: tuple) -> tuple:
        import jax.numpy as jnp
        return tuple(jnp.float32(v) for v in key)

    def drifted(self, pt) -> bool:
        """Has node validity or capacity drifted since the last staging?
        (The implicit-delta check for callers that mutate ProblemTensors in
        place instead of sending a ProblemDelta.)"""
        return not (np.array_equal(self._valid_fp, pt.node_valid)
                    and np.array_equal(
                        self._cap_fp,
                        np.asarray(pt.capacity, dtype=np.float32)))

    # -- solve-side hooks (solver/api._solve) ------------------------------

    def consume_delta_ms(self) -> float:
        ms, self._delta_ms = self._delta_ms, 0.0
        return ms

    def warm_scalars(self, t0: float, t1: float, mw: float) -> tuple:
        """Device-staged anneal scalars: traced args to the fused solve
        must already be resident or the transfer guard fires. Keyed on the
        values; a scheduler re-uses one config so this stages once."""
        key = (float(t0), float(t1), float(mw))
        staged = self._scalars.get(key)
        if staged is None:
            staged = self._stage_scalars(key)
            self._scalars = {key: staged}    # one live config at a time
        return staged

    def adopt(self, padded_assignment) -> None:
        """Keep the padded winner (already on device) as the next warm
        seed — no transfer happens here."""
        self.assignment = padded_assignment

    def adopt_host(self, assignment: np.ndarray, node_valid, *,
                   warm: bool = True) -> None:
        """Host repair rewrote the winner: re-upload the repaired
        assignment. On the warm path that is a host transfer the disallow
        guard would have caught — the event the counter exists for (a cold
        solve's upload is just staging)."""
        from .buckets import pad_assignment
        padded = pad_assignment(np.asarray(assignment, dtype=np.int32),
                                self.prob.S, np.asarray(node_valid))
        self.assignment = self._put_assignment(padded)
        self._mirror = padded.copy()
        if warm:
            _M_HOST_XFER.inc()

    def record_warm_fallback(self) -> None:
        """A warm attempt had to cold-stage: problem tensors crossed the
        host boundary where the disallow guard would have fired."""
        _M_HOST_XFER.inc()

    def eviction_snapshot(self) -> Optional[tuple[np.ndarray, bool]]:
        """Host snapshot for the scheduler's slot manager (sched/tpu.py):
        the committed PADDED assignment mirror + its feasibility flag.
        Padded — not the real-row slice — so a re-admission
        ``adopt_host`` restores the exact device seed, phantom parking
        included, and the readmitted warm solve is bit-identical to a
        never-evicted one. Costs no device transfer: the mirror is
        maintained host-side by note_host_assignment/adopt_host. None
        before the first committed solve (nothing worth snapshotting)."""
        if self._mirror is None:
            return None
        return np.array(self._mirror, copy=True), bool(self._mirror_feasible)

    def device_nbytes(self) -> int:
        """Resident device footprint: per-plane byte accounting over the
        staged problem + assignment. Packed planes count at their uint32
        width (solver/problem.py packed-plane math) — this is the number
        the slot manager's byte budget enforces at runtime."""
        import jax
        leaves = jax.tree_util.tree_leaves((self.prob, self.assignment))
        return int(sum(int(x.size) * x.dtype.itemsize for x in leaves))

    # -- active-set sub-solve hooks (solver/subsolve.py) -------------------

    def note_host_assignment(self, padded=None,
                             feasible: Optional[bool] = None) -> None:
        """api._solve's end-of-solve note: the padded winner it fetched
        (the sub-solve mirror — no extra transfer, the result crossed the
        boundary anyway) and whether the committed stats were feasible
        (the frozen-base precondition: frozen-frozen violations are zero
        only when the previous placement was). Clears the pending churn —
        whatever was pending is folded into this assignment now."""
        if padded is not None:
            arr = np.asarray(padded, dtype=np.int32)
            if self.prob is not None and arr.shape[0] == self.prob.S:
                self._mirror = arr.copy()
        if feasible is not None:
            self._mirror_feasible = bool(feasible)
        self._pending_rows = None
        self._pending_churn = False

    def take_active_plan(self):
        """The churn-localized sub-problem for the warm solve about to
        dispatch, or None for the full fused path. Consumes the pending
        churn either way. Fallback outcomes are counted here;
        "localized"/"fallback_infeasible" are counted by the caller after
        the exact gate rules."""
        pending, self._pending_rows = self._pending_rows, None
        churn, self._pending_churn = self._pending_churn, False
        if not churn:
            return None
        from .subsolve import (ActiveIndex, plan_active, record_outcome,
                               subsolve_config)
        cfg = subsolve_config()
        if not (cfg.enabled and self.supports_subsolve):
            return None
        if self._mirror is None or not self._mirror_feasible:
            return None
        if self._index is None:
            # ids cannot drift on the delta path (compatible() pins them
            # by object identity; appended arrival rows carry none), so
            # the index built from the current tensors stays valid for
            # the staging's whole life
            self._index = ActiveIndex(self.pt)
        plan, outcome = plan_active(
            self._index, self.pt, self._mirror, self.prob.S, self.prob.T,
            pending if pending is not None
            else np.empty(0, dtype=np.int64), cfg,
            G_full=self.prob.G, Gc_full=self.prob.Gc)
        if plan is None:
            record_outcome(outcome)
        return plan
