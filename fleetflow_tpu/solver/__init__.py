"""JAX placement solver: the device-resident heart of the framework.

Replaces the reference's sequential placement path (engine.rs
order_by_dependencies + per-service Docker loop) with greedy seeding +
mesh-sharded simulated annealing over dense constraint tensors.
"""

from .anneal import anneal, chain_states_from_assignment, prerepair_state
from .buckets import (BucketConfig, BucketInfo, bucket_config, bucket_size,
                      pad_problem_tiers, soft_score_host,
                      stage_problem_tiers, staging_arena_stats,
                      subsolve_tier)
from .resident import ProblemDelta, ResidentProblem, transfer_guard_ctx
from .subsolve import (ActiveIndex, ActivePlan, SubsolveConfig, plan_active,
                       subsolve_config)
from .sharded import SVC_AXIS, anneal_sharded, pad_problem, shard_problem
from .api import CHAIN_AXIS, SolveResult, make_chain_inits, solve
from .greedy import greedy_place, greedy_place_batched, placement_order
from .kernels import (node_loads, soft_score, total_cost, total_violations,
                      violation_stats)
from .problem import DeviceProblem, prepare_problem
from .repair import RepairResult, repair, verify
