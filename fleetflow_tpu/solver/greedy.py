"""Device greedy placer: vectorized first-fit-decreasing.

The seed stage of the solve pipeline (SURVEY.md section 7 phase 2: "greedy
seed (vectorized topo-order by dependency depth)"). Replaces the reference's
sequential `order_by_dependencies` partition + per-service Docker round-trip
(engine.rs:67-85,157-167) as the placement front-end.

Two implementations:

- `greedy_place`: one lax.scan step per service — exact FFD, but S sequential
  iterations. At 10k services the loop is latency-bound even on-device
  (round-1 VERDICT: seed_ms 181 at 10k×1k dwarfed the anneal).
- `greedy_place_batched` (default in solve()): scan over batches of M
  services. Each batch scores all M×N (service, node) pairs in one shot,
  services pick their best node, and within-batch collisions are resolved
  with pairwise masks — service m may land on its chosen node only if the
  demand of earlier same-node batch-mates still fits and none of them shares
  a conflict group. Losers retry against the updated state in a second round;
  the rare still-losers are committed best-effort (the annealer repairs
  them, matching the reference's FallbackPolicy relax-order semantics,
  model.rs:49, in spirit). Sequential depth drops from S to ~2·S/M.

When no node is feasible a service is placed best-effort (least overflow,
fewest conflicts) and the annealer repairs it.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .problem import DeviceProblem, eligible_row, eligible_rows

__all__ = ["greedy_place", "greedy_place_batched", "placement_order",
           "partitioned_seed"]

_NEG = -1e30


def placement_order(demand: np.ndarray, dep_depth: np.ndarray,
                    conflict_ids: np.ndarray | None = None) -> np.ndarray:
    """Host-side placement order: most-constrained-first, then
    first-fit-decreasing. Services carrying anti-affinity constraints (host
    ports, exclusive volumes) go first — they need conflict-free nodes while
    plenty remain — then by normalized demand descending; dependency depth
    breaks ties."""
    norm = demand / np.maximum(demand.max(axis=0, keepdims=True), 1e-6)
    weight = norm.sum(axis=1)
    if conflict_ids is not None and conflict_ids.size:
        n_constraints = (conflict_ids >= 0).sum(axis=1)
        weight = weight + n_constraints * (weight.max() + 1.0)
    return np.lexsort((dep_depth, -weight)).astype(np.int32)


@partial(jax.jit, static_argnames=("best_effort",))
def greedy_place(prob: DeviceProblem, order: jax.Array,
                 best_effort: bool = True) -> jax.Array:
    """Place services in `order`; returns assignment (S,) int32."""
    R = prob.demand.shape[1]
    eps = 1e-6

    def step(carry, s):
        load, used, assignment = carry
        d = prob.demand[s]                      # (R,)
        ids = prob.conflict_ids[s]              # (K,)
        valid_ids = (ids >= 0)
        safe = jnp.where(valid_ids, ids, 0)

        conflict = (used[:, safe] * valid_ids[None, :]).sum(-1) > 0   # (N,)
        new_load = load + d[None, :]                                   # (N, R)
        fits = (new_load <= prob.capacity + eps).all(-1)
        elig_s = eligible_row(prob.eligible, s, prob.N)
        ok = fits & elig_s & prob.node_valid & ~conflict

        u_after = new_load / jnp.maximum(prob.capacity, 1e-6)
        usq = (u_after * u_after).sum(-1)                              # (N,)
        if prob.strategy == 0:      # spread: balance → lowest resulting util²
            score = -usq
        elif prob.strategy == 1:    # pack: consolidate → highest resulting util²
            score = usq
        else:                       # fill_lowest: low node index first
            score = -jnp.arange(prob.N, dtype=jnp.float32)
        if prob.preferred is not None:
            score = score + prob.preferred[s] * 0.5

        best_ok = jnp.argmax(jnp.where(ok, score, _NEG))
        if best_effort:
            overflow = jnp.maximum(new_load - prob.capacity, 0.0).sum(-1)
            n_conf = (used[:, safe] * valid_ids[None, :]).sum(-1)
            fb_score = -(overflow * 1e3 + n_conf.astype(jnp.float32) * 1e3) + score
            fb_ok = elig_s & prob.node_valid
            best_fb = jnp.argmax(jnp.where(fb_ok, fb_score, fb_score - 1e15))
            node = jnp.where(ok.any(), best_ok, best_fb)
        else:
            node = best_ok

        load = load.at[node].add(d)
        used = used.at[node, safe].add(valid_ids.astype(used.dtype))
        assignment = assignment.at[s].set(node.astype(jnp.int32))
        return (load, used, assignment), None

    init = (
        jnp.zeros((prob.N, R), dtype=jnp.float32),
        jnp.zeros((prob.N, prob.G), dtype=jnp.int32),
        jnp.full((prob.S,), -1, dtype=jnp.int32),
    )
    # unroll: one fused device step per 8 services — the scan is dispatch-
    # bound at fleet scale (each step's math is tiny), so unrolling buys
    # ~40% wall-clock at 10k services
    (_, _, assignment), _ = jax.lax.scan(step, init, order, unroll=8)
    return assignment


def _node_scores(prob: DeviceProblem, load: jax.Array, svc: jax.Array):
    """Score all nodes for a batch of services against shared state.

    Returns (score (M,N), fits (M,N), new_load (M,N,R)-free util term reused
    by callers is not returned — only what the batch step needs)."""
    d = prob.demand[svc]                                    # (M, R)
    new_load = load[None, :, :] + d[:, None, :]             # (M, N, R)
    fits = (new_load <= prob.capacity[None] + 1e-6).all(-1)  # (M, N)

    u_after = new_load / jnp.maximum(prob.capacity[None], 1e-6)
    usq = (u_after * u_after).sum(-1)                       # (M, N)
    if prob.strategy == 0:       # spread: lowest resulting util²
        score = -usq
    elif prob.strategy == 1:     # pack: highest resulting util²
        score = usq
    else:                        # fill_lowest: low node index first
        score = jnp.broadcast_to(-jnp.arange(prob.N, dtype=jnp.float32),
                                 usq.shape)
    if prob.preferred is not None:
        score = score + prob.preferred[svc] * 0.5
    overflow = jnp.maximum(new_load - prob.capacity[None], 0.0).sum(-1)
    return score, fits, overflow


def _conflict_rows(prob: DeviceProblem, used: jax.Array, svc: jax.Array):
    """(M, N) bool: node already occupied by a conflicting service."""
    ids = prob.conflict_ids[svc]                            # (M, K)
    valid = ids >= 0
    safe = jnp.where(valid, ids, 0)
    occ = used[:, safe]                                     # (N, M, K)
    return ((occ * valid[None, :, :]).sum(-1) > 0).T        # (M, N)


def _pairwise_ok(prob: DeviceProblem, load: jax.Array, svc: jax.Array,
                 choice: jax.Array, live: jax.Array) -> jax.Array:
    """Within-batch resolution: may service m commit to choice[m] given the
    *earlier* live batch-mates that chose the same node? (M,) bool."""
    M = svc.shape[0]
    d = prob.demand[svc] * live[:, None]                    # (M, R)
    same = (choice[:, None] == choice[None, :]) & live[:, None] & live[None, :]
    earlier = jnp.tril(jnp.ones((M, M), bool), k=-1)
    mates = same & earlier                                  # (M, M)

    # capacity: earlier same-node mates' demand must still leave room
    prefix = mates.astype(jnp.float32) @ d                  # (M, R)
    cap_c = prob.capacity[choice]                           # (M, R)
    cap_ok = (load[choice] + prefix + prob.demand[svc]
              <= cap_c + 1e-6).all(-1)

    # conflicts: no earlier same-node mate shares a conflict id
    ids = prob.conflict_ids[svc]                            # (M, K)
    v = ids >= 0
    share = ((ids[:, None, :, None] == ids[None, :, None, :])
             & v[:, None, :, None] & v[None, :, None, :]).any((-1, -2))
    conf_ok = ~(mates & share).any(-1)
    return cap_ok & conf_ok


def _commit(prob: DeviceProblem, load, used, assignment, svc, choice, mask):
    """Scatter a masked batch of placements into the shared state."""
    w = mask.astype(jnp.float32)
    wi = mask.astype(jnp.int32)
    load = load.at[choice].add(prob.demand[svc] * w[:, None])

    ids = prob.conflict_ids[svc]
    valid = (ids >= 0).astype(jnp.int32) * wi[:, None]
    safe = jnp.where(ids >= 0, ids, 0)
    rows = jnp.broadcast_to(choice[:, None], safe.shape)
    used = used.at[rows, safe].add(valid)

    # dump-row trick: non-committed writes land on a scratch row
    tgt = jnp.where(mask, svc, prob.S)
    assignment = assignment.at[tgt].set(choice.astype(jnp.int32))
    return load, used, assignment


@partial(jax.jit, static_argnames=("batch", "rounds"))
def greedy_place_batched(prob: DeviceProblem, order: jax.Array,
                         batch: int = 256, rounds: int = 2) -> jax.Array:
    """Place services in `order`, `batch` at a time; returns (S,) int32.

    Semantics match greedy_place's FFD-with-fallback except that services in
    one batch cannot see each other's *soft* influence (they do see each
    other's capacity/conflict footprint through the pairwise resolution).
    Sequential depth is ceil(S/batch) scan steps instead of S.

    `rounds=1` skips the loser-retry round: collision losers tail-commit
    immediately, leaving more seed violations for the annealer's targeted
    proposals to fix — cheaper per step, worth it when an annealer follows.
    """
    if rounds not in (1, 2):
        raise ValueError(f"rounds must be 1 or 2, got {rounds}")
    S, N = prob.S, prob.N
    M = min(batch, S)
    n_batches = -(-S // M)
    pad = n_batches * M - S
    order_p = jnp.concatenate(
        [order.astype(jnp.int32), jnp.full((pad,), -1, jnp.int32)])
    batches = order_p.reshape(n_batches, M)

    # spread strategy fans each batch over the top-W near-equal nodes
    # (without this, all M batch-mates herd onto the same lowest-util node
    # and the pairwise gate rejects most of them every round)
    W = min(M, N)

    def step(carry, svc_raw):
        load, used, assignment = carry
        live0 = svc_raw >= 0
        svc = jnp.where(live0, svc_raw, 0)

        def choose(load, used, live):
            score, fits, overflow = _node_scores(prob, load, svc)
            conflict = _conflict_rows(prob, used, svc)
            elig_b = eligible_rows(prob.eligible, svc, prob.N)   # (M, N)
            hard_ok = (fits & elig_b & prob.node_valid[None]
                       & ~conflict)
            masked = jnp.where(hard_ok, score, _NEG)
            # Anti-herding ranks: a plain argmax sends every batch-mate to
            # the same node; the pairwise gate then admits only one node's
            # worth per round and the rest tail-commit with violations.
            _, topk = jax.lax.top_k(masked, W)                # (M, W)
            count_ok = jnp.minimum(hard_ok.sum(-1), W)        # only W columns
            if prob.strategy == 0:
                # spread: batch-mate m takes a rank spread over its OWN
                # feasible list ((m mod W) mapped proportionally onto
                # [0, count_ok)). Proportional mapping matters: tenant pools
                # give same-tenant services identical ~count_ok-node feasible
                # lists, and a clamped rank would pile every high-m
                # batch-mate onto one node.
                r = jnp.arange(M, dtype=jnp.int32) % W
                r_eff = jnp.minimum((r * count_ok) // W,
                                    jnp.maximum(count_ok - 1, 0))
            else:
                # pack / fill_lowest: fill nodes in score order, about one
                # node's capacity worth of batch-mates per rank — herding
                # onto a single node per round would strand the rest on the
                # best-effort tail.
                mean_d = jnp.maximum(prob.demand[svc].mean(0), 1e-6)  # (R,)
                med_cap = jnp.median(prob.capacity, axis=0)           # (R,)
                est = jnp.clip((med_cap / mean_d).min().astype(jnp.int32),
                               1, M)
                r = jnp.arange(M, dtype=jnp.int32) // est
                r_eff = jnp.minimum(r, jnp.maximum(count_ok - 1, 0))
            best_ok = jnp.take_along_axis(topk, r_eff[:, None], 1)[:, 0]
            # fallback: least overflow / fewest conflicts among eligible
            fb_score = score - overflow * 1e3 - conflict * 1e3
            fb_ok = elig_b & prob.node_valid[None]
            best_fb = jnp.argmax(jnp.where(fb_ok, fb_score, fb_score - 1e15),
                                 axis=-1)
            has_ok = hard_ok.any(-1)
            choice = jnp.where(has_ok, best_ok, best_fb).astype(jnp.int32)
            pair_ok = _pairwise_ok(prob, load, svc, choice, live)
            return choice, has_ok, live & pair_ok & has_ok

        # round 1: everyone proposes; winners commit
        c1, _, ok1 = choose(load, used, live0)
        load, used, assignment = _commit(prob, load, used, assignment,
                                         svc, c1, ok1)
        rest = live0 & ~ok1
        if rounds > 1:
            # round 2: losers re-propose against the updated state
            c2, _, ok2 = choose(load, used, rest)
            load, used, assignment = _commit(prob, load, used, assignment,
                                             svc, c2, ok2)
            rest, c_tail = rest & ~ok2, c2
        else:
            c_tail = c1
        # best-effort tail: anything still unplaced (no feasible node at all,
        # or collision-rejected in every round) commits at its last choice;
        # the annealer repairs (FallbackPolicy relax-order in spirit)
        load, used, assignment = _commit(prob, load, used, assignment,
                                         svc, c_tail, rest)
        return (load, used, assignment), None

    R = prob.demand.shape[1]
    init = (
        jnp.zeros((N, R), jnp.float32),
        jnp.zeros((N, prob.G), jnp.int32),
        jnp.full((S + 1,), -1, jnp.int32),   # +1 dump row
    )
    (_, _, assignment), _ = jax.lax.scan(step, init, batches)
    return assignment[:S]


def partitioned_seed(pt, parts: int) -> np.ndarray:
    """Host seed for mega-scale sharded solves: service slices x disjoint
    round-robin node subsets, one full-capacity FFD per slice.

    The exact host FFD is O(S*N) sequential work — 108.9 s at 100k x 10k
    (docs/profiles/r5-xl-sharded.md), outweighing the sharded anneal it
    feeds. This slices the NODE axis round-robin alongside a contiguous
    service split: slice g FFDs its services onto its own nodes at full
    capacity, cutting the work to O(S*N/parts) with a union feasible by
    construction for both capacity and conflict groups (disjoint nodes
    cannot share a port). The residue left for the anneal: services whose
    eligible nodes all fall in other slices (best-effort in-slice, an
    eligibility violation each) and packing fragmentation across node
    subsets — the same repair contract as the batched device seed's
    best-effort tail.

    Returns (S,) int32. Uses the native C++ FFD per group when available,
    the pure-numpy host greedy otherwise.
    """
    import numpy as _np

    from ..native.lib import available_nobuild, native_place

    S = pt.demand.shape[0]
    if not available_nobuild():
        # no native library: one whole-instance host greedy (correct, just
        # not partitioned — the fallback machine is not the mega-scale one)
        from ..sched.host import greedy_host_place
        return greedy_host_place(pt)[0].astype(_np.int32)
    N = pt.capacity.shape[0]
    parts = max(1, min(parts, S, N))
    if parts == 1:
        seg, _viol = native_place(
            pt.demand, pt.capacity, pt.eligible, pt.node_valid,
            pt.dep_depth, pt.port_ids, pt.volume_ids, pt.anti_ids,
            strategy=pt.strategy.value)
        return seg

    # Partition NODES, not capacity: slice g owns every (parts)-th node
    # (round-robin, so tenant-blocked eligibility spreads over slices)
    # and a contiguous 1/parts of the services, FFD'd onto its own nodes
    # at FULL capacity. Total FFD work drops from O(S*N) to O(S*N/parts),
    # the union is feasible by construction for capacity AND conflicts
    # (slices place on disjoint nodes, so no cross-slice port collision
    # is even possible), and big services see whole nodes — the two
    # failure modes of capacity-sharing designs (an equal cap/parts
    # starves any service over 1/parts of a node; flooring the share at
    # the slice max lets small services overbook it `parts` times,
    # measured 22 capacity violations on a feasible 64x16 instance).
    # What remains for the anneal: services whose eligible nodes all
    # live in OTHER slices get best-effort in-slice placements (an
    # eligibility violation each), and packing quality is fragmented
    # across node subsets — both repaired/polished by the sweeps.
    out = _np.empty(S, dtype=_np.int32)
    bounds = _np.linspace(0, S, parts + 1, dtype=int)

    def one_slice(g: int) -> None:
        lo, hi = int(bounds[g]), int(bounds[g + 1])
        if hi <= lo:
            return
        nodes_g = _np.arange(g, N, parts)
        seg, _viol = native_place(
            pt.demand[lo:hi],
            _np.ascontiguousarray(pt.capacity[nodes_g]),
            _np.ascontiguousarray(pt.eligible[lo:hi][:, nodes_g]),
            _np.ascontiguousarray(pt.node_valid[nodes_g]),
            pt.dep_depth[lo:hi], pt.port_ids[lo:hi],
            pt.volume_ids[lo:hi], pt.anti_ids[lo:hi],
            strategy=pt.strategy.value)
        out[lo:hi] = nodes_g[seg]

    # slices are independent (disjoint services AND nodes) and ctypes
    # releases the GIL for the duration of the C call, so a thread pool
    # gives real concurrency on multi-core hosts; the 1-core dev box just
    # runs them back to back. Each worker writes a disjoint out[lo:hi].
    import os as _os
    workers = min(parts, _os.cpu_count() or 1)
    if workers > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=workers) as ex:
            list(ex.map(one_slice, range(parts)))
    else:
        for g in range(parts):
            one_slice(g)
    return out
