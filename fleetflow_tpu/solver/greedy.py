"""Device greedy placer: vectorized first-fit-decreasing via lax.scan.

The seed stage of the solve pipeline (SURVEY.md section 7 phase 2: "greedy
seed (vectorized topo-order by dependency depth)"). One scan step places one
service: score every node at once (capacity fit, conflict freedom,
eligibility, strategy preference) and pick the best — O(N·(R+K)) per step,
S steps, no data-dependent shapes. Replaces the reference's sequential
`order_by_dependencies` partition + per-service Docker round-trip
(engine.rs:67-85,157-167) as the placement front-end.

When no node is feasible the service is placed best-effort (least overflow,
fewest conflicts) and the annealer repairs it — matching the reference's
FallbackPolicy relax-order semantics (model.rs:49) in spirit.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .problem import DeviceProblem

__all__ = ["greedy_place", "placement_order"]

_NEG = -1e30


def placement_order(demand: np.ndarray, dep_depth: np.ndarray,
                    conflict_ids: np.ndarray | None = None) -> np.ndarray:
    """Host-side placement order: most-constrained-first, then
    first-fit-decreasing. Services carrying anti-affinity constraints (host
    ports, exclusive volumes) go first — they need conflict-free nodes while
    plenty remain — then by normalized demand descending; dependency depth
    breaks ties."""
    norm = demand / np.maximum(demand.max(axis=0, keepdims=True), 1e-6)
    weight = norm.sum(axis=1)
    if conflict_ids is not None and conflict_ids.size:
        n_constraints = (conflict_ids >= 0).sum(axis=1)
        weight = weight + n_constraints * (weight.max() + 1.0)
    return np.lexsort((dep_depth, -weight)).astype(np.int32)


@partial(jax.jit, static_argnames=("best_effort",))
def greedy_place(prob: DeviceProblem, order: jax.Array,
                 best_effort: bool = True) -> jax.Array:
    """Place services in `order`; returns assignment (S,) int32."""
    R = prob.demand.shape[1]
    eps = 1e-6

    def step(carry, s):
        load, used, assignment = carry
        d = prob.demand[s]                      # (R,)
        ids = prob.conflict_ids[s]              # (K,)
        valid_ids = (ids >= 0)
        safe = jnp.where(valid_ids, ids, 0)

        conflict = (used[:, safe] * valid_ids[None, :]).sum(-1) > 0   # (N,)
        new_load = load + d[None, :]                                   # (N, R)
        fits = (new_load <= prob.capacity + eps).all(-1)
        ok = fits & prob.eligible[s] & prob.node_valid & ~conflict

        u_after = new_load / jnp.maximum(prob.capacity, 1e-6)
        usq = (u_after * u_after).sum(-1)                              # (N,)
        if prob.strategy == 0:      # spread: balance → lowest resulting util²
            score = -usq
        elif prob.strategy == 1:    # pack: consolidate → highest resulting util²
            score = usq
        else:                       # fill_lowest: low node index first
            score = -jnp.arange(prob.N, dtype=jnp.float32)
        score = score + prob.preferred[s] * 0.5

        best_ok = jnp.argmax(jnp.where(ok, score, _NEG))
        if best_effort:
            overflow = jnp.maximum(new_load - prob.capacity, 0.0).sum(-1)
            n_conf = (used[:, safe] * valid_ids[None, :]).sum(-1)
            fb_score = -(overflow * 1e3 + n_conf.astype(jnp.float32) * 1e3) + score
            fb_ok = prob.eligible[s] & prob.node_valid
            best_fb = jnp.argmax(jnp.where(fb_ok, fb_score, fb_score - 1e15))
            node = jnp.where(ok.any(), best_ok, best_fb)
        else:
            node = best_ok

        load = load.at[node].add(d)
        used = used.at[node, safe].add(valid_ids.astype(used.dtype))
        assignment = assignment.at[s].set(node.astype(jnp.int32))
        return (load, used, assignment), None

    init = (
        jnp.zeros((prob.N, R), dtype=jnp.float32),
        jnp.zeros((prob.N, prob.G), dtype=jnp.int32),
        jnp.full((prob.S,), -1, dtype=jnp.int32),
    )
    # unroll: one fused device step per 8 services — the scan is dispatch-
    # bound at fleet scale (each step's math is tiny), so unrolling buys
    # ~40% wall-clock at 10k services
    (_, _, assignment), _ = jax.lax.scan(step, init, order, unroll=8)
    return assignment
