"""Placement explanation: why is service X on node Y, and what else was
possible?

Operator-facing debugging the reference has no analog for (its placer is
an opaque dependency partition, engine.rs:67-85): given the lowered
instance and an assignment, break one service's placement down into the
solver's own terms — hard feasibility per node (eligibility, validity,
capacity fit, conflict-group occupancy) and the soft components the
anneal trades (strategy utilization delta, preference, colocation mates)
— mirroring anneal._proposal_delta term for term, but on the host in
numpy over one (1, N) slice plus the service's own conflict groups, so
an explain costs milliseconds even at fleet scale and needs no device.

Surfaced as PlacementService.explain -> REST
GET /api/placement/explain?stage=&service= -> MCP cp_placement_explain
-> CLI `fleet cp placement explain`.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..lower.tensors import ProblemTensors

__all__ = ["explain_assignment"]


def _own_group_hits(ids: np.ndarray, assignment: np.ndarray, N: int,
                    row: int) -> np.ndarray:
    """(N,) count of OTHER members of `row`'s conflict groups per node.

    Iterates row's own ids (typically 1-3) and bincounts each group's
    members — O(K_own * S + N), never the dense (N, G) occupancy plane a
    mega-scale instance would turn into gigabytes per explain request."""
    hits = np.zeros(N, dtype=np.int64)
    if ids.size == 0:
        return hits
    own = ids[row][ids[row] >= 0]
    for g in own:
        members = (ids == g).any(axis=1)
        members[row] = False   # a service never conflicts with itself
        hits += np.bincount(assignment[members], minlength=N)
    return hits


def explain_assignment(pt: ProblemTensors, assignment: np.ndarray,
                       service: str, top_k: int = 5,
                       node_valid: Optional[np.ndarray] = None) -> dict:
    """Explain one service row's placement. Returns a JSON-ready dict:
    the chosen node's full breakdown, the top_k best alternatives by the
    same scoring, and per-category counts of hard-blocked nodes."""
    assignment = np.asarray(assignment)
    try:
        i = pt.service_names.index(service)
    except ValueError:
        raise KeyError(f"unknown service {service!r}; rows are "
                       f"{pt.service_names[:8]}...") from None
    N = pt.capacity.shape[0]
    valid = (np.asarray(node_valid) if node_valid is not None
             else pt.node_valid).astype(bool)
    d = pt.demand[i]                                     # (R,)

    # node load WITHOUT this service — float64 so re-accumulation cannot
    # drift a packed node across the tolerance the solver itself uses
    load = np.zeros((N, pt.capacity.shape[1]), dtype=np.float64)
    np.add.at(load, assignment, pt.demand.astype(np.float64))
    load[assignment[i]] -= d

    new_load = load + d[None, :]                          # (N, R)
    # RELATIVE tolerance, same as every solver feasibility check
    # (kernels/anneal use cap * (1 + 1e-6)): an absolute +1e-6 here made
    # explain contradict the solver's verdict on exactly-packed nodes
    fits = (new_load <= pt.capacity * (1 + 1e-6)).all(axis=1)
    eligible = pt.eligible[i].astype(bool)

    # conflict hits per family, self-excluded
    conflict_hits = np.zeros(N, dtype=np.int64)
    families = {}
    for fam, ids in (("ports", pt.port_ids), ("volumes", pt.volume_ids),
                     ("anti_affinity", pt.anti_ids)):
        hits = _own_group_hits(ids, assignment, N, i)
        families[fam] = hits
        conflict_hits += hits
    conflict_free = conflict_hits == 0

    # soft components (kernels.soft_score orientation: lower = better)
    # Every term carries the SAME scale it has in kernels.soft_score's
    # per-service delta, so the ranking here reproduces the solver's own
    # trade-offs: preference and colocation enter the objective as means
    # over S (one service's contribution is -pref/S, -mates/S), and
    # fill_lowest as (n/N)/S — an unscaled -pref here would overweight
    # preference by a factor of S and misreport the solver's optimal
    # choice as suboptimal.
    S_total = max(pt.demand.shape[0], 1)
    cap_safe = np.maximum(pt.capacity, 1e-6)
    u_before = load / cap_safe
    u_after = new_load / cap_safe
    d_usq = ((u_after * u_after).sum(axis=1)
             - (u_before * u_before).sum(axis=1)) / max(N, 1)
    strat = pt.strategy.value
    if strat == "pack_into_dedicated":
        strategy_term = -d_usq
    elif strat == "fill_lowest":
        strategy_term = (np.arange(N, dtype=np.float64)
                         / max(N, 1)) / S_total
    else:                       # spread_across_pool
        strategy_term = d_usq
    pref = (pt.preferred[i] if pt.preferred is not None
            else np.zeros(N, dtype=np.float32))
    # colocation mates already on each node (soft bonus per mate)
    coloc_mates = _own_group_hits(pt.coloc_ids, assignment, N, i)

    score = (strategy_term - pref / S_total - coloc_mates / S_total)
    ok = eligible & valid & fits & conflict_free

    def node_row(n: int) -> dict:
        return {
            "node": pt.node_names[n],
            "feasible": bool(ok[n]),
            "eligible": bool(eligible[n]),
            "valid": bool(valid[n]),
            "fits_capacity": bool(fits[n]),
            "conflicts": {fam: int(families[fam][n]) for fam in families},
            "strategy_term": round(float(strategy_term[n]), 6),
            "preference": round(float(pref[n]), 6),
            "coloc_mates": int(coloc_mates[n]),
            "score": round(float(score[n]), 6),
            "utilization_after": [round(float(x), 4) for x in u_after[n]],
        }

    chosen = int(assignment[i])
    order = np.argsort(np.where(ok, score, np.inf), kind="stable")
    # top_k best feasible alternatives EXCLUDING chosen (filter first,
    # then slice — slicing first silently returned top_k-1 whenever the
    # chosen node wasn't itself among the top_k)
    alternatives = [node_row(int(n)) for n in order
                    if ok[n] and int(n) != chosen][:top_k]
    # a degraded placement (e.g. the node died and the re-solve is still
    # infeasible) can leave the service on an infeasible node: a "rank"
    # among np.inf ties would be an index-order artifact, not a position
    chosen_rank = (int(np.nonzero(order == chosen)[0][0]) + 1
                   if ok[chosen] else None)
    return {
        "service": service,
        "row": i,
        "replica_of": (pt.replica_of[i] if pt.replica_of else service),
        "demand": [round(float(x), 4) for x in d],
        "strategy": strat,
        "chosen": node_row(chosen),
        "chosen_rank": chosen_rank,
        "alternatives": alternatives,
        "blocked_counts": {
            "ineligible": int((~eligible).sum()),
            "invalid": int((~valid).sum()),
            "capacity": int((eligible & valid & ~fits).sum()),
            "conflicts": int((eligible & valid & fits
                              & ~conflict_free).sum()),
            "feasible": int(ok.sum()),
            "total_nodes": N,
        },
    }
