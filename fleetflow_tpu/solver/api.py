"""Top-level solve pipeline.

    ProblemTensors ──prepare──▶ DeviceProblem (staged once)
        ──greedy seed (lax.scan FFD)──▶ assignment
        ──perturbed chain fan-out──▶ (C, S)
        ──anneal (vmapped chains, mesh-shardable)──▶ (C, S)
        ──exact rank + pick best──▶ assignment
        ──host repair backstop──▶ SolveResult (zero violations or infeasible)

`mesh=` shards the chain axis over a jax.sharding.Mesh so chains run
data-parallel across devices (the "pmapped independent annealing chains" of
the north star); with mesh=None everything runs on one device.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .anneal import anneal
from .greedy import greedy_place, greedy_place_batched, placement_order
from .kernels import soft_score, total_cost, violation_stats
from .problem import DeviceProblem, prepare_problem
from .repair import RepairResult, repair, verify
from ..lower.tensors import ProblemTensors
from ..obs import get_logger, kv, profile_trace

log = get_logger("solver")

DEFAULT_STEPS = 128   # batched sweeps (anneal.default_proposals_per_step wide)

__all__ = ["solve", "SolveResult", "make_chain_inits"]

CHAIN_AXIS = "chains"


@dataclass
class SolveResult:
    assignment: np.ndarray          # (S,) node index per service
    stats: dict                     # exact violation stats (host-verified)
    soft: float                     # soft score of the final assignment
    feasible: bool
    moves_repaired: int = 0
    # violations of the device solver's own best assignment, before the host
    # repair backstop touched it — the honesty metric (VERDICT round 1: "we
    # cannot tell whether the device solver or the host numpy repair backstop
    # is doing the real work"). 0 means the TPU solve was already feasible.
    pre_repair_violations: int = 0
    timings_ms: dict = field(default_factory=dict)
    chains: int = 0
    steps: int = 0

    @property
    def violations(self) -> int:
        return int(self.stats["total"])


def make_chain_inits(prob: DeviceProblem, seed_assignment: jax.Array,
                     chains: int, key: jax.Array,
                     perturb_frac: float = 0.08) -> jax.Array:
    """(C, S) chain initializations: chain 0 is the pure greedy seed, the
    rest perturb a random `perturb_frac` of services onto random nodes for
    basin diversity."""
    def one(k):
        k1, k2 = jax.random.split(k)
        mask = jax.random.uniform(k1, (prob.S,)) < perturb_frac
        rand = jax.random.randint(k2, (prob.S,), 0, prob.N, dtype=jnp.int32)
        return jnp.where(mask, rand, seed_assignment)

    keys = jax.random.split(key, chains)
    inits = jax.vmap(one)(keys)
    return inits.at[0].set(seed_assignment)


def solve(pt: ProblemTensors, **kw) -> SolveResult:
    """Solve a placement instance end to end (see _solve for parameters).
    When FLEET_PROFILE_DIR is set the whole solve is captured as a
    jax.profiler trace (obs.profile_trace)."""
    with profile_trace("solve"):
        return _solve(pt, **kw)


def _solve(pt: ProblemTensors, *, chains: int = 8, steps: int = DEFAULT_STEPS,
           seed: int = 0, do_repair: bool = True,
           mesh: Optional[Mesh] = None,
           prob: Optional[DeviceProblem] = None,
           init_assignment: Optional[np.ndarray] = None,
           t0: float = 1.0, t1: float = 1e-3,
           migration_weight: float = 0.5,
           seed_impl: Optional[str] = None) -> SolveResult:
    """Solve a placement instance end to end.

    `init_assignment` warm-starts from a previous solve (streaming reschedule
    path: BASELINE config 5 — keep the old placement, anneal the delta).
    `migration_weight` makes warm starts sticky: each service pays that much
    soft score for leaving its previous node, so a reschedule moves only what
    churn forces (the analog of not restarting healthy containers on an
    unrelated node failure). `prob` reuses an already-staged DeviceProblem
    across re-solves.

    `seed_impl` picks the greedy seed: "scan" (one lax.scan step per service
    — exact FFD, best on CPU where the loop body is cheap), "batched"
    (ceil(S/256)-deep batch placement — the accelerator shape: sequential
    depth is what a TPU pays for, per-step width is nearly free), or None to
    choose by backend.
    """
    timings: dict[str, float] = {}
    t = time.perf_counter

    t_start = t()
    if prob is None:
        prob = prepare_problem(pt)
    orig_prob = prob  # soft score is reported against the un-bonused problem
    timings["stage_ms"] = (t() - t_start) * 1e3

    t_seed = t()
    if init_assignment is not None:
        seed_assignment = jnp.asarray(init_assignment, dtype=jnp.int32)
        if migration_weight > 0:
            # Stickiness as a preferred-node bonus on the previous placement.
            # d_pref in the anneal kernel is (pref[s,a]-pref[s,b])/S, so the
            # bonus is scaled by S to make one move cost `migration_weight`
            # soft units. Device-side delta: nothing crosses the host link.
            bonus = jnp.zeros_like(prob.preferred).at[
                jnp.arange(prob.S), seed_assignment].add(
                    migration_weight * prob.S)
            # dead/ineligible nodes get no bonus: churn-forced moves are free
            bonus = jnp.where(prob.eligible & prob.node_valid[None, :],
                              bonus, 0.0)
            prob = dataclasses.replace(prob, preferred=prob.preferred + bonus)
        t0 = min(t0, 0.1)  # warm start: refine, don't re-scramble
    else:
        order = jnp.asarray(placement_order(pt.demand, pt.dep_depth,
                                            np.asarray(prob.conflict_ids)))
        if seed_impl is None:
            seed_impl = "scan" if jax.default_backend() == "cpu" else "batched"
        if seed_impl not in ("scan", "batched"):
            raise ValueError(f"seed_impl must be 'scan', 'batched' or None, "
                             f"got {seed_impl!r}")
        seed_fn = greedy_place if seed_impl == "scan" else greedy_place_batched
        seed_assignment = seed_fn(prob, order)
    key = jax.random.PRNGKey(seed)
    k_init, k_anneal = jax.random.split(key)
    inits = make_chain_inits(prob, seed_assignment, chains, k_init)
    if mesh is not None:
        inits = jax.device_put(inits, NamedSharding(mesh, P(CHAIN_AXIS, None)))
    jax.block_until_ready(inits)
    timings["seed_ms"] = (t() - t_seed) * 1e3

    t_anneal = t()
    refined = anneal(prob, inits, k_anneal, steps=steps, t0=t0, t1=t1)
    costs = jax.vmap(lambda a: total_cost(prob, a))(refined)
    best = jnp.argmin(costs)
    best_assignment = refined[best]
    jax.block_until_ready(best_assignment)
    timings["anneal_ms"] = (t() - t_anneal) * 1e3

    t_verify = t()
    # device-first verification: the exact kernels run on-device (scalars
    # only cross the host link); the numpy ground-truth path is entered
    # only when violations remain and repair is needed
    dstats = jax.device_get(violation_stats(prob, best_assignment))
    assignment = np.asarray(best_assignment)
    if float(dstats["total"]) == 0:
        stats = {k: int(v) for k, v in dstats.items()}
        moves = 0
        pre_repair = 0
    else:
        stats = verify(pt, assignment)
        moves = 0
        pre_repair = int(stats["total"])
        if do_repair and stats["total"] > 0:
            rr: RepairResult = repair(pt, assignment)
            assignment, stats, moves = rr.assignment, rr.stats, rr.moves
    timings["verify_repair_ms"] = (t() - t_verify) * 1e3
    timings["total_ms"] = (t() - t_start) * 1e3

    soft = float(jax.device_get(soft_score(orig_prob, jnp.asarray(assignment))))
    log.info("solve %s", kv(
        S=prob.S, N=prob.N, chains=chains, steps=steps,
        violations=int(stats["total"]), pre_repair=pre_repair,
        repaired=moves or None, warm=init_assignment is not None or None,
        **{k: f"{v:.1f}" for k, v in timings.items()}))
    return SolveResult(
        assignment=assignment, stats=stats, soft=soft,
        feasible=stats["total"] == 0, moves_repaired=moves,
        pre_repair_violations=pre_repair,
        timings_ms=timings, chains=chains, steps=steps,
    )
