"""Top-level solve pipeline.

    ProblemTensors ──prepare──▶ DeviceProblem (staged once)
        ──greedy seed (lax.scan FFD)──▶ assignment
        ──perturbed chain fan-out──▶ (C, S)
        ──anneal (vmapped chains, mesh-shardable)──▶ (C, S)
        ──exact rank + pick best──▶ assignment
        ──host repair backstop──▶ SolveResult (zero violations or infeasible)

`mesh=` shards the chain axis over a jax.sharding.Mesh so chains run
data-parallel across devices (the "pmapped independent annealing chains" of
the north star); with mesh=None everything runs on one device.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .anneal import (TRACE_COLS, anneal_adaptive_states, anneal_states,
                     chain_states_from_assignment, empty_trace,
                     prerepair_state_counted, solve_trace_blocks,
                     state_soft_score, state_violation_stats)
from .buckets import (bucket_config, pad_assignment, pad_problem_tiers,
                      record_bucket, soft_score_host, stage_problem_tiers,
                      _env_flag)
from .greedy import greedy_place, greedy_place_batched, placement_order
from .kernels import soft_score, violation_stats
from .problem import DeviceProblem, prepare_problem
from .repair import RepairResult, repair, verify
from .resident import ResidentProblem, transfer_guard_ctx
from ..core.parsecache import M_FRONTEND_PHASE_MS as _M_FRONTEND_MS
from ..lower.tensors import ProblemTensors
from ..obs import get_logger, kv, profile_trace
from ..obs.metrics import REGISTRY, SOLVE_SECONDS_BUCKETS

log = get_logger("solver")

# metric catalog: docs/guide/10-observability.md
_M_SOLVES = REGISTRY.counter(
    "fleet_solver_solves_total", "Placement solves by backend and start mode",
    labels=("backend", "warm"))
_M_SOLVE_S = REGISTRY.histogram(
    "fleet_solver_solve_duration_seconds", "End-to-end solve() wall time",
    buckets=SOLVE_SECONDS_BUCKETS)
_M_SWEEPS = REGISTRY.counter(
    "fleet_solver_sweeps_total", "Annealing sweeps run across all solves")
_M_ACCEPTED = REGISTRY.counter(
    "fleet_solver_proposals_accepted_total",
    "Metropolis proposals accepted (adaptive anneal)")
_M_COMPILES = REGISTRY.counter(
    "fleet_solver_compile_events_total",
    "XLA compilations of the fused refine pipeline")
_M_VIOL = REGISTRY.gauge(
    "fleet_solver_violations",
    "Hard violations of the most recent solve (post-repair)")
_M_PRE_VIOL = REGISTRY.gauge(
    "fleet_solver_pre_repair_violations",
    "Device-solver violations of the most recent solve before host repair")
_M_BUCKET = REGISTRY.counter(
    "fleet_solver_bucket_solves_total",
    "Bucketed solves by executable reuse (hit = padded shape already "
    "compiled for in this process)", labels=("hit",))
_M_PAD_WASTE = REGISTRY.gauge(
    "fleet_solver_bucket_pad_waste_ratio",
    "Phantom fraction of the most recent bucketed solve's service rows")
_M_INFLIGHT = REGISTRY.gauge(
    "fleet_solver_dispatches_in_flight",
    "Solver anneal dispatches currently executing (full fused + "
    "localized sub-solve) — deep-sampled by the obs collector")
_M_DISPATCH_DELTA = REGISTRY.gauge(
    "fleet_solver_dispatch_device_delta_bytes",
    "Device bytes_in_use delta across the most recent profiled dispatch "
    "(FLEET_PROFILE_SOLVER=1; stays 0 when the backend reports no "
    "allocator stats, e.g. CPU)")

DEFAULT_STEPS = 128   # batched sweeps (anneal.default_proposals_per_step wide)

__all__ = ["solve", "SolveResult", "make_chain_inits"]

CHAIN_AXIS = "chains"


def _device_bytes_in_use() -> Optional[int]:
    """Allocator-reported bytes on the first local device, or None when
    the backend has no stats (CPU). A host-side allocator read — no
    device sync, safe under the disallow transfer guard."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return int(stats.get("bytes_in_use", 0))


@contextlib.contextmanager
def _dispatch_scope(label: str):
    """Every hot anneal dispatch runs inside this scope. Always: the
    in-flight gauge the obs collector deep-samples. Opt-in
    (FLEET_PROFILE_SOLVER=1): a jax.profiler TraceAnnotation named per
    dispatch (visible inside the FLEET_PROFILE_DIR trace around the
    whole solve) plus the device bytes_in_use delta the dispatch left
    behind, exported as a gauge so a leaking dispatch shows up as a
    climbing delta, not an eventual OOM."""
    profile = os.environ.get("FLEET_PROFILE_SOLVER", "").lower() in (
        "1", "true", "on", "yes")
    before = _device_bytes_in_use() if profile else None
    _M_INFLIGHT.inc()
    try:
        if profile:
            with jax.profiler.TraceAnnotation(f"fleet:{label}"):
                yield
        else:
            yield
    finally:
        _M_INFLIGHT.dec()
        if profile:
            after = _device_bytes_in_use()
            if before is not None and after is not None:
                _M_DISPATCH_DELTA.set(after - before)


@dataclass
class SolveResult:
    assignment: np.ndarray          # (S,) node index per service
    stats: dict                     # exact violation stats (host-verified)
    soft: float                     # soft score of the final assignment
    feasible: bool
    moves_repaired: int = 0
    # violations of the device solver's own best assignment, before the host
    # repair backstop touched it — the honesty metric (VERDICT round 1: "we
    # cannot tell whether the device solver or the host numpy repair backstop
    # is doing the real work"). 0 means the TPU solve was already feasible.
    pre_repair_violations: int = 0
    timings_ms: dict = field(default_factory=dict)
    chains: int = 0
    steps: int = 0
    # the proposal width the anneal actually ran (after backend defaults),
    # so artifacts report the config that produced the number
    proposals_per_step: int = 0
    # Metropolis moves applied across all chains (adaptive path only;
    # -1 = not tracked on the fixed-budget path). With sweeps/chains/
    # proposals_per_step this yields the acceptance rate the anneal ran at.
    accepted_moves: int = -1
    # shape bucketing applied to this solve (solver/buckets.py), or None
    # for an exact-shape solve: {"orig_S", "padded_S", "pad_waste", "hit"}
    bucket: Optional[dict] = None
    # churn pre-repair ran as a fused on-device prologue inside the anneal
    # dispatch (anneal.prerepair_state) instead of the host repair.py pass
    # — the warm path then has no prerepair_ms timing at all
    fused_prerepair: bool = False
    # pod-scale sharded solves (solver/sharded.solve_sharded) report their
    # parallel-tempering config + replica-exchange outcome here:
    # {replicas, ladder, exchange_every, swap_attempts, swap_accepts}
    tempering: Optional[dict] = None
    # churn-localized sub-solve (solver/subsolve.py): {rows, tier,
    # affected, outcome, ms} when a localized dispatch ran (outcome
    # "localized" = committed by the exact gate, "fallback_infeasible" =
    # the full fused path re-ran), None when the solve was full-problem
    subsolve: Optional[dict] = None
    # in-dispatch flight-deck telemetry (docs/guide/10, "solver flight
    # deck"): {"schema": TRACE_COLS, "blocks": [[...], ...] one row per
    # sweep-block, "init": {violations, soft} of the prologue/seed,
    # "prerepair_moves": fused-prologue relocations, "exit_sweep",
    # "path": "full" | "subsolve"}. None when the dispatch ran with
    # FLEET_SOLVE_TRACE_BLOCKS=0 or on the fixed-budget path.
    telemetry: Optional[dict] = None

    @property
    def acceptance_rate(self) -> float:
        """Accepted / proposed, or -1.0 when acceptance was not tracked."""
        proposed = self.steps * self.chains * self.proposals_per_step
        if self.accepted_moves < 0 or proposed <= 0:
            return -1.0
        return self.accepted_moves / proposed

    @property
    def violations(self) -> int:
        return int(self.stats["total"])


def make_chain_inits(prob: DeviceProblem, seed_assignment: jax.Array,
                     chains: int, key: jax.Array,
                     perturb_frac: float = 0.08) -> jax.Array:
    """(C, S) chain initializations: chain 0 is the pure greedy seed, the
    rest perturb a random `perturb_frac` of services onto random nodes for
    basin diversity."""
    def one(k):
        k1, k2 = jax.random.split(k)
        mask = jax.random.uniform(k1, (prob.S,)) < perturb_frac
        rand = jax.random.randint(k2, (prob.S,), 0, prob.N, dtype=jnp.int32)
        return jnp.where(mask, rand, seed_assignment)

    keys = jax.random.split(key, chains)
    inits = jax.vmap(one)(keys)
    return inits.at[0].set(seed_assignment)


@partial(jax.jit, static_argnames=("chains", "steps", "warm", "adaptive",
                                   "anneal_block", "proposals_per_step",
                                   "sharding", "fused_prerepair",
                                   "prerepair_moves",
                                   "skip_feasible_polish", "trace_blocks"))
def _refine(prob: DeviceProblem, seed_assignment: jax.Array, key: jax.Array,
            t0: float, t1: float, migration_weight: float, *,
            chains: int, steps: int, warm: bool, adaptive: bool = False,
            anneal_block: int = 8,
            proposals_per_step: Optional[int] = None,
            sharding=None, fused_prerepair: bool = False,
            prerepair_moves: int = 0, skip_feasible_polish: bool = False,
            trace_blocks: int = 0):
    """The fused device pipeline after the seed: chain fan-out, annealing,
    per-chain exact cost, best-chain selection, exact violation stats and the
    soft score of the winner — ONE dispatch, five scalars + the winning
    assignment come back. Under a remote-tunnel device every eager op pays a
    host round-trip, so everything between the seed and the host-side repair
    decision must live in a single XLA program (round-1 bench: the eager
    tail cost ~340 ms of the 764 ms solve).

    `warm` folds the migration-stickiness bonus in on-device: the previous
    placement earns `migration_weight` soft units per service for staying
    put, except on dead/ineligible nodes (churn-forced moves stay free).
    `sharding` (static, hashable NamedSharding) lays the chain axis over a
    mesh so chains anneal data-parallel across devices.

    `fused_prerepair` runs the churn pre-repair as an on-device prologue
    (anneal.prerepair_state, bounded by `prerepair_moves`) before the chain
    fan-out: services stranded on dead/ineligible nodes are relocated
    inside THIS dispatch, replacing the host repair.py pre-pass that cost
    ~27 ms + a seed re-upload per warm reschedule (BENCH_r05 CPU). The
    stickiness bonus is computed from the pre-repair seed (staying put is
    rewarded at the PREVIOUS placement; forced moves stay free either
    way)."""
    if warm:
        # stickiness rides the proposal delta + soft ranking on the fly
        # (problem.sticky_prev/sticky_w) instead of materializing a
        # bonused (S, N) preferred plane — three full-plane passes,
        # ~37 ms of the warm dispatch at 10k x 1k, for the same
        # semantics: staying on the previous still-eligible node earns
        # migration_weight; churn-forced moves stay free
        prob_a = dataclasses.replace(
            prob, sticky_prev=seed_assignment,
            sticky_w=jnp.asarray(migration_weight, jnp.float32))
    else:
        prob_a = prob
    init_states = None
    prerepair_applied = jnp.int32(0)
    if fused_prerepair:
        st0 = chain_states_from_assignment(prob_a, seed_assignment)
        st0, prerepair_applied = prerepair_state_counted(
            prob_a, st0, prerepair_moves)
        seed_assignment = st0.assignment
        if sharding is None:
            # warm chains are not perturbed: every chain starts from the
            # repaired state, so broadcast the prologue's carried state
            # instead of a per-chain scatter rebuild inside the anneal
            init_states = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (chains,) + x.shape),
                st0)
    k_init, k_anneal = jax.random.split(key)
    # warm starts are NOT perturbed: scattering 8% of a known-good placement
    # is anti-sticky by construction, and with adaptive early exit a
    # perturbed chain can win before restoring its perturbed services.
    # Chains still diverge through their proposal RNG streams.
    inits = make_chain_inits(prob_a, seed_assignment, chains, k_init,
                             perturb_frac=0.0 if warm else 0.08)
    if sharding is not None:
        inits = jax.lax.with_sharding_constraint(inits, sharding)
    if adaptive:
        # the adaptive anneal tracks each chain's best-ever state with its
        # (violations, soft) as SEPARATE scalars; chain ranking is
        # feasibility-first — a folded W_HARD*v+soft argmin would both
        # prefer an infeasible chain whose warm-bonused soft undercuts
        # W_HARD (aggregate bonus gap is unbounded in the fleet size) AND
        # round the soft tie-break away in float32 at large v
        (best_assign_c, best_viol_c, best_soft_c, sweeps_run, accepted_c,
         telem) = anneal_adaptive_states(
                prob_a, inits, k_anneal, max_steps=steps, block=anneal_block,
                t0=t0, t1=t1,
                proposals_per_step=proposals_per_step,
                init_states=init_states,
                exit_on_feasible_init=skip_feasible_polish,
                trace_blocks=trace_blocks)
        accepted = accepted_c.sum()
        # exact lexicographic (violations, soft): among minimal-violation
        # chains (0 when any chain saw feasibility), best soft wins
        min_viol = best_viol_c.min()
        best = jnp.argmin(jnp.where(best_viol_c == min_viol,
                                    best_soft_c, jnp.inf))
        winner = best_assign_c[best]
    else:
        states = anneal_states(prob_a, inits, k_anneal, steps=steps,
                               t0=t0, t1=t1,
                               proposals_per_step=proposals_per_step)
        sweeps_run = jnp.int32(steps)
        accepted = jnp.int32(-1)   # fixed-budget path does not track it
        telem = empty_trace(trace_blocks)   # same treedef as adaptive
        # rank from the CARRIED states: same exact numbers as the
        # kernels.* functions, but elementwise reduces instead of (N, G)
        # scatter rebuilds (~18 ms saved per evaluation at 10k x 1k)
        viol = jax.vmap(
            lambda st: state_violation_stats(prob_a, st)["total"])(states)
        soft_rank = jax.vmap(
            lambda st: state_soft_score(prob_a, st))(states)
        # same two-stage lexicographic rank as the adaptive path (a folded
        # W_HARD*viol+soft would drop the soft term in float32 at large v)
        mv = viol.min()
        winner = states.assignment[
            jnp.argmin(jnp.where(viol == mv, soft_rank, jnp.inf))]
    # The WINNER's stats are recomputed with the exact from-scratch kernels
    # (one scatter rebuild, ~5 ms): the carried float32 load accumulates
    # .add(+d)/.add(-d) round-off over thousands of proposals, and the
    # feasibility gate that decides whether the host repair backstop runs
    # must not trust drifted state. Chain RANKING above stays carried-state
    # (cheap, and an argmin among near-equals tolerates drift).
    #
    # EXCEPTION (ROADMAP item 2 shave): on the resident warm path
    # (skip_feasible_polish), a 0-sweep exit means ZERO proposals were
    # applied — the carried best state IS the prologue's scratch-built
    # state, so its violation count is exact, not drifted. When it says
    # feasible, every stat component is exactly 0 and the winner's soft
    # was scratch-built by the same prologue: trust them and skip the
    # final rebuild (~12 ms of the remaining warm CPU floor at 10k x 1k).
    if adaptive and skip_feasible_polish:
        best_viol = best_viol_c[best]
        trust = (sweeps_run == 0) & (best_viol == 0)
        zero = jnp.float32(0)
        stats, soft = jax.lax.cond(
            trust,
            lambda: ({"capacity": zero, "conflicts": zero,
                      "eligibility": zero, "skew": zero, "total": zero},
                     best_soft_c[best]),
            lambda: (violation_stats(prob, winner),
                     soft_score(prob, winner)))
    else:
        stats = violation_stats(prob, winner)
        soft = soft_score(prob, winner)
    telem = dict(telem, prerepair_moves=prerepair_applied)
    return winner, stats, soft, sweeps_run, accepted, telem


def solve(pt: ProblemTensors, **kw) -> SolveResult:
    """Solve a placement instance end to end (see _solve for parameters).
    When FLEET_PROFILE_DIR is set the whole solve is captured as a
    jax.profiler trace (obs.profile_trace).

    Pod-scale routing: instances above the FLEET_SHARDED_MIN_CELLS
    threshold (or any instance under FLEET_SHARDED=1) with >= 2 devices
    visible solve through the mesh-sharded resident path
    (solver/sharded.solve_sharded — service-axis sharding + parallel
    tempering) instead of the single-chip pipeline; explicit staging
    kwargs (prob/resident/mesh) always pin the call to this path."""
    # idempotent: callers that never pass through platform.ensure_platform
    # (library embedding, tests) still get FLEET_COMPILE_CACHE honored.
    # The self-check runs HERE, not in ensure_platform: the probe compiles
    # against a backend, and ensure_platform runs before the backend
    # decision is final
    from ..platform import maybe_enable_compile_cache, verify_compile_cache
    if maybe_enable_compile_cache() is not None:
        verify_compile_cache()
    with profile_trace("solve"):
        from .sharded import maybe_solve_sharded
        res = maybe_solve_sharded(pt, **kw)
        if res is not None:
            return res
        return _solve(pt, **kw)


def _solve(pt: ProblemTensors, *,
           chains: Optional[int] = None, steps: int = DEFAULT_STEPS,
           seed: int = 0, do_repair: bool = True,
           mesh: Optional[Mesh] = None,
           prob: Optional[DeviceProblem] = None,
           init_assignment: Optional[np.ndarray] = None,
           t0: float = 1.0, t1: float = 1e-3,
           migration_weight: float = 0.5,
           seed_impl: Optional[str] = None,
           seed_batch: int = 256,
           seed_rounds: int = 2,
           adaptive: bool = True,
           anneal_block: int = 1,
           warm_block: int = 1,
           prerepair: Optional[bool] = None,
           proposals_per_step: Optional[int] = None,
           bucket: Optional[bool] = None,
           resident: Optional[ResidentProblem] = None,
           resident_warm: bool = False,
           overlap_host_work=None) -> SolveResult:
    """Solve a placement instance end to end.

    `init_assignment` warm-starts from a previous solve (streaming reschedule
    path: BASELINE config 5 — keep the old placement, anneal the delta).
    `migration_weight` makes warm starts sticky: each service pays that much
    soft score for leaving its previous node, so a reschedule moves only what
    churn forces (the analog of not restarting healthy containers on an
    unrelated node failure). `prob` reuses an already-staged DeviceProblem
    across re-solves.

    `seed_impl` picks the greedy seed: "scan" (one lax.scan step per service
    — exact FFD, best when the device is fast but dispatch is cheap),
    "batched" (ceil(S/256)-deep batch placement — the accelerator shape:
    sequential depth is what a TPU pays for, per-step width is nearly
    free), "native" (host C++ FFD via native/placer.cpp — the violation-
    free floor, ~82 ms at 10k x 1k; VERDICT r2 item 5), "partitioned"
    (service slices x disjoint node subsets, one full-capacity native FFD
    each — ~22 ms at 10k x 1k at equal soft, greedy.partitioned_seed), or
    None to choose by backend: the CPU fallback prefers "partitioned" at
    fleet scale (S*N >= 1e6), "native" below it, "scan" when the library
    is absent; accelerators use "batched".

    `warm_block` is the adaptive-exit check granularity for warm starts:
    a churn reschedule starts one node-event away from feasible and the
    targeted proposal half re-places the dead node's services within a
    sweep or two, so checking every `warm_block` sweeps (instead of the
    cold path's `anneal_block`) exits earlier. Since best-ever tracking
    (r5) decoupled block size from quality, both defaults are small —
    the block is purely a latency/check-granularity knob and the exit
    keys on seen-feasibility, so a fine block exits at the earliest
    feasible boundary.

    `chains=None` resolves by backend: 1 on CPU (vmapped chains serialize
    on host, and the feasible-by-construction seed means extra chains buy
    nothing; measured r4) and 2 on accelerators (measured r5 on TPU:
    2 chains 102.6 ms vs 4 chains 123.9 ms at equal soft, 10k x 1k).

    `bucket` pads the problem to a shape tier (solver/buckets.py) so
    fleets whose sizes drift within one tier reuse the compiled
    executable instead of paying the XLA compile cliff. None defers to
    the environment (FLEET_BUCKET=1 opts direct solves in; the scheduler
    path passes True and FLEET_BUCKET=0 force-disables). Spread
    constraints (max_skew > 0) bucket too: padded problems carry a traced
    `n_real` and the kernels keep phantom rows out of topology/skew
    accounting. Violations/soft are always reported against the REAL rows
    (numpy-exact), and the returned assignment never contains phantoms.

    `resident` + `resident_warm=True` is the DELTA-STAGED warm path
    (solver/resident.py): the padded problem and the previous assignment
    are already on device (CP churn arrived as on-device deltas), the
    seed never crosses the host boundary, pre-repair runs fused inside
    the anneal dispatch, and the whole dispatch can run under
    `jax.transfer_guard("disallow")` (FLEET_TRANSFER_GUARD=disallow) to
    prove no problem tensor moved. `overlap_host_work` (zero-arg
    callable) runs between the async solve dispatch and the result
    fetch — host work (e.g. re-lowering a changed fleet) overlaps the
    in-flight anneal.
    """
    timings: dict[str, float] = {}
    t = time.perf_counter
    if chains is None:
        chains = 1 if jax.default_backend() == "cpu" else 2
    resident_warm = bool(resident is not None and resident_warm
                         and resident.assignment is not None)

    t_start = t()
    binfo = None
    staged_cold = False
    if prob is None:
        if resident is not None:
            prob = resident.prob
        else:
            # cold staging: the bucketed path stages DIRECTLY at the
            # padded tier shape through the host arenas
            # (buckets.stage_problem_tiers) — pure memcpy + upload, no
            # jnp.pad/fill ops, so a fresh process pays zero staging
            # compiles and restages of the same tier reuse the buffers
            if bucket is None:
                bucket = _env_flag("FLEET_BUCKET", False)
            cfg0 = bucket_config()
            if bucket and cfg0.enabled:
                prob, binfo = stage_problem_tiers(pt, cfg0)
                staged_cold = True
            else:
                prob = prepare_problem(pt)
    orig_prob = prob  # soft score is reported against the un-bonused problem

    # ---- shape bucketing (solver/buckets.py) -----------------------------
    # Round the churn-sensitive extents up to tiers so a fleet drifting a
    # few services reuses the compiled executable. A caller that staged a
    # pre-padded DeviceProblem (sched/tpu.py resident state) is honored
    # as-is: pad_problem_tiers is idempotent, so the staged object passes
    # through unchanged and re-solves never re-pad.
    if bucket is None:
        bucket = _env_flag("FLEET_BUCKET", False) or prob.S != pt.S
    # a resident staging carries the bucket config it was padded under;
    # honoring it keeps pad_problem_tiers idempotent even if the tier
    # ladder env knobs changed since cold staging
    cfg = resident.cfg if resident is not None else bucket_config()
    if bucket and cfg.enabled and not staged_cold:
        prob, binfo = pad_problem_tiers(prob, cfg)
    if binfo is not None:
        binfo.orig_S = pt.S   # a pre-padded staging reports the REAL rows
    bucketed = binfo is not None and prob.S != pt.S
    if resident_warm:
        # delta staging happened in ResidentProblem.apply_delta (donated
        # on-device merge); report it where stage_ms reports cold staging
        timings["delta_stage_ms"] = resident.consume_delta_ms()
    timings["stage_ms"] = (t() - t_start) * 1e3
    if staged_cold:
        _M_FRONTEND_MS.set(timings["stage_ms"], phase="stage")

    t_seed = t()
    warm = init_assignment is not None or resident_warm
    # Churn pre-repair mode: None -> FUSED into the anneal dispatch
    # (anneal.prerepair_state — no host work, no prerepair_ms timing);
    # True -> the legacy host repair.py pre-pass (kept for A/B and
    # debugging); False -> none (the anneal's targeted proposals alone).
    fused = warm and prerepair is None
    # a FACTORY, not a context instance: jax.transfer_guard is a one-shot
    # generator CM, and a sub-solve the gate rejects dispatches twice
    # (mini attempt, then the full fused path) — each under its own guard
    guard_ctx = (transfer_guard_ctx if resident_warm
                 else contextlib.nullcontext)
    def _legacy_host_prepass(seed_np: np.ndarray) -> np.ndarray:
        # the legacy host pre-repair (kept for A/B against the fused
        # prologue): relocate services stranded on dead/ineligible nodes.
        # Keep the result even when repair can't reach 0: it is never
        # worse than its input (repair.py backstop), and a partially-
        # fixed seed still saves the anneal sweeps. prerepair_ms is split
        # out so a reschedule artifact can say whether host pre-repair or
        # the device anneal ate the time (VERDICT r4 weak #1); the fused
        # path has no such phase by construction.
        t_pre = t()
        rows = np.arange(pt.S)
        stranded = ((~pt.node_valid[seed_np])
                    | (~pt.eligible[rows, seed_np]))
        if stranded.any():
            from .repair import repair as _host_repair
            seed_np = _host_repair(pt, seed_np, seed=seed).assignment
        timings["prerepair_ms"] = (t() - t_pre) * 1e3
        return seed_np

    if resident_warm:
        # seed already resident: the previous padded winner, phantoms
        # re-parked at delta time; nothing crosses the host boundary
        seed_assignment = resident.assignment
        t0 = min(t0, 0.1)  # warm start: refine, don't re-scramble
        if prerepair is True:
            # legacy host pre-pass requested (A/B): the seed deliberately
            # round-trips the host — fetch the real rows, repair, re-upload
            # (adopt_host counts the transfer)
            # np.array, not asarray: device_get of the resident slot is a
            # VIEW on the CPU backend and the slot is donated into the
            # next merge dispatch — the host pre-pass must own its copy
            seed_np = _legacy_host_prepass(np.array(
                jax.device_get(seed_assignment), dtype=np.int32,
                copy=True)[:pt.S])
            resident.adopt_host(seed_np, pt.node_valid, warm=True)
            seed_assignment = resident.assignment
    elif warm:
        seed_np = np.asarray(init_assignment, dtype=np.int32)
        if prerepair is True:
            seed_np = _legacy_host_prepass(seed_np)
        if bucketed:
            seed_np = pad_assignment(seed_np, prob.S, pt.node_valid)
        seed_assignment = jnp.asarray(seed_np, dtype=jnp.int32)
        t0 = min(t0, 0.1)  # warm start: refine, don't re-scramble
    else:
        if seed_impl is None:
            if jax.default_backend() == "cpu":
                # nobuild: auto-pick must never trigger a synchronous make
                # inside the timed solve; explicit seed_impl="native" may
                from ..native.lib import available_nobuild
                if available_nobuild():
                    # partitioned FFD past the crossover where the O(S*N/4)
                    # work cut beats the slicing overhead — measured r5 at
                    # 10k x 1k: 82.2 -> 21.8 ms at EQUAL soft (1.3527 vs
                    # 1.3521) and 0 violations (x2: 35.2 ms @ 1.3502, x8:
                    # 12.6 ms @ 1.3547 — x4 is the quality-neutral knee)
                    seed_impl = ("partitioned" if pt.S * pt.N >= 1_000_000
                                 else "native")
                else:
                    seed_impl = "scan"
            else:
                seed_impl = "batched"
        if seed_impl not in ("scan", "batched", "native", "partitioned"):
            raise ValueError(f"seed_impl must be 'scan', 'batched', "
                             f"'native', 'partitioned' or None, "
                             f"got {seed_impl!r}")
        if seed_impl in ("native", "partitioned"):
            # Host C++ FFD (whole-instance, or service-slices x disjoint
            # node subsets): feasible in tens of ms at 10k x 1k, so the
            # anneal only buys soft score (the CPU-fallback design point).
            try:
                if seed_impl == "partitioned":
                    from .greedy import partitioned_seed
                    host_assignment = partitioned_seed(pt, 4)
                else:
                    from ..native.lib import native_place
                    host_assignment, _ = native_place(
                        pt.demand, pt.capacity, pt.eligible, pt.node_valid,
                        pt.dep_depth, pt.port_ids, pt.volume_ids,
                        pt.anti_ids, strategy=pt.strategy.value)
                if bucketed:
                    host_assignment = pad_assignment(
                        host_assignment, prob.S, pt.node_valid)
                seed_assignment = jnp.asarray(host_assignment,
                                              dtype=jnp.int32)
            except (RuntimeError, OSError):
                # corrupt/stale .so: degrade to the device scan seed rather
                # than fail the solve (the .so existing was only a hint)
                log.warning("native seed unavailable at call time; "
                            "falling back to scan")
                seed_impl = "scan"
        if seed_impl not in ("native", "partitioned"):
            order_np = placement_order(
                pt.demand, pt.dep_depth,
                np.asarray(prob.conflict_ids)[: pt.S, :])
            if bucketed:
                # phantoms place last: zero demand + eligible everywhere
                # means the greedy scan parks them on any valid node
                order_np = np.concatenate(
                    [np.asarray(order_np),
                     np.arange(pt.S, prob.S, dtype=np.int64)])
            order = jnp.asarray(order_np)
            if seed_impl == "scan":
                seed_assignment = greedy_place(prob, order)
            else:
                seed_assignment = greedy_place_batched(prob, order,
                                                       batch=seed_batch,
                                                       rounds=seed_rounds)
        # no block here: the refine dispatch queues behind the seed on-device
        # (device impls), so seed_ms is dispatch time only and the device
        # runs back-to-back; the native impl is synchronous host work.
    # disjoint phases: the warm branch's host pre-repair is reported under
    # prerepair_ms, not double-counted into seed_ms
    timings["seed_ms"] = ((t() - t_seed) * 1e3
                          - timings.get("prerepair_ms", 0.0))

    if proposals_per_step is None:
        # derived from the PADDED row count: proposals_per_step is a static
        # jit argument, so deriving it from the exact S would recompile on
        # every fleet-size drift and defeat the bucketing (the clamps make
        # this a no-op at fleet scale). CPU sweep cost is ~linear in
        # proposals (no free width the way the MXU gives it): a 64-wide
        # sweep costs ~25 ms at 10k x 1k vs ~100 ms at the 256 TPU knee,
        # and with a feasible seed the sweeps only buy soft polish
        # (measured in VERDICT r2 item 5) — backend_proposals_per_step
        # holds the knee for this path AND the sub-solve's.
        from .anneal import backend_proposals_per_step
        proposals_per_step = backend_proposals_per_step(prob.S)
    # flight-deck buffer length: a STATIC of every refine/subsolve
    # executable (compiled in, like proposals_per_step), so the telemetry
    # rides the dispatch with zero extra compiles and zero host
    # transfers; FLEET_SOLVE_TRACE_BLOCKS=0 restores the pre-telemetry
    # program (the parity test's reference leg)
    trace_blocks = solve_trace_blocks()

    t_anneal = t()
    sharding = (NamedSharding(mesh, P(CHAIN_AXIS, None))
                if mesh is not None else None)
    # compile-event telemetry: the jit cache only grows when XLA compiled
    # a new variant of the fused pipeline, which is exactly the event an
    # operator watching solve latency needs to see (a recompile can turn a
    # 100 ms reschedule into seconds — VERDICT r4 weak #1)
    # fused pre-repair budget: a static bound the while_loop exits early
    # from; derived from the PADDED rows so it cannot break bucket reuse
    prerepair_moves = max(16, min(prob.S, 256)) if fused else 0
    # ---- churn-localized sub-solve plan (solver/subsolve.py) ------------
    # when the resident delta path knows the affected set and its
    # constraint closure is small, the anneal runs over a mini tier of
    # gathered rows instead of the full problem; the exact full-problem
    # gate below decides whether the localized result commits
    sub_plan = None
    if resident_warm and fused and adaptive and mesh is None:
        sub_plan = resident.take_active_plan()
    if binfo is not None:
        # hit = this process already ran the fused pipeline at these
        # jit-relevant extents, so the dispatch below will not recompile
        binfo.hit = record_bucket(
            (prob.S, prob.N, prob.G, prob.Gc, prob.T, prob.strategy,
             prob.max_skew, prob.conflict_ids.shape[1],
             prob.coloc_ids.shape[1], chains, steps,
             bool(warm and migration_weight > 0), adaptive,
             min(warm_block, anneal_block) if warm else anneal_block,
             proposals_per_step, fused, prerepair_moves,
             bool(resident_warm and adaptive and fused),
             prob.n_real is not None, trace_blocks,
             # plane layout is part of the executable identity: a packed
             # and a dense staging (or absent vs present preference) are
             # different treedefs/dtypes, hence different XLA programs
             str(prob.eligible.dtype), prob.preferred is not None,
             # a localized dispatch is its own executable, keyed by the
             # mini tier and compact id ladders (solver/subsolve.py)
             (sub_plan.tier, sub_plan.G_sub, sub_plan.Gc_sub)
             if sub_plan is not None else None))
        _M_BUCKET.inc(hit="true" if binfo.hit else "false")
        _M_PAD_WASTE.set(binfo.pad_waste)
    # the PRNG key is minted BEFORE the transfer guard arms: it is not a
    # problem tensor, and the guard's job is to prove the big (S, ·)
    # planes and the seed assignment never cross the host boundary
    key = jax.random.PRNGKey(seed)
    if resident_warm:
        t0_d, t1_d, mw_d = resident.warm_scalars(t0, t1, migration_weight)
    else:
        t0_d, t1_d, mw_d = t0, t1, migration_weight
    refine_kw = dict(
        chains=chains, steps=steps,
        warm=bool(warm and migration_weight > 0), adaptive=adaptive,
        anneal_block=min(warm_block, anneal_block) if warm else anneal_block,
        proposals_per_step=proposals_per_step, sharding=sharding,
        fused_prerepair=fused, prerepair_moves=prerepair_moves,
        # the resident delta path skips the 1-block soft polish when the
        # fused prologue already landed feasible: stickiness rejects
        # nearly all polish moves, so the sweep bought latency only. The
        # host warm path (and the legacy-prepass A/B leg) keeps its
        # 1-block polish (same results as r05).
        skip_feasible_polish=bool(resident_warm and adaptive and fused),
        trace_blocks=trace_blocks)
    cache_before = _refine._cache_size()
    sub_info = None
    sub_cache_before = 0
    if sub_plan is not None:
        from .anneal import backend_proposals_per_step
        from .subsolve import (record_outcome, record_subsolve_ms,
                               stage_subsolve, subsolve_cache_size,
                               subsolve_dispatch)
        sub_cache_before = subsolve_cache_size()
        t_sub = t()
        # small per-burst uploads (closure rows, compact ids, frozen
        # base) stage BEFORE the guard arms — the merge-upload discipline
        staged = stage_subsolve(resident, sub_plan)
        sub_props = backend_proposals_per_step(sub_plan.tier)
        with guard_ctx(), _dispatch_scope("subsolve"):
            (best_assignment, dstats, dsoft, sweeps_run, accepted,
             dtelem) = subsolve_dispatch(
                    prob, resident.assignment, staged, sub_plan, key,
                    t0_d, t1_d, mw_d, chains=chains, steps=steps,
                    block=min(warm_block, anneal_block),
                    proposals_per_step=sub_props,
                    trace_blocks=trace_blocks)
        if overlap_host_work is not None:
            # the gate decision below synchronizes with the in-flight
            # sub dispatch, so the overlapped host work must run NOW —
            # after it, the async window is gone
            t_ov = t()
            overlap_host_work()
            timings["overlap_host_ms"] = (t() - t_ov) * 1e3
            overlap_host_work = None
        # the exact full-problem gate rules: feasible commits the
        # scattered result; infeasible discards it and the full fused
        # path re-runs from the ORIGINAL seed (the kernel does not
        # donate, so the previous assignment — stranded rows intact, the
        # battle-tested prerepair shape — is still alive)
        sub_feasible = float(jax.device_get(dstats["total"])) == 0
        # disjoint phases: overlapped host work is reported under
        # overlap_host_ms, not double-counted into the sub-solve timing
        timings["subsolve_ms"] = ((t() - t_sub) * 1e3
                                  - timings.get("overlap_host_ms", 0.0))
        record_subsolve_ms(timings["subsolve_ms"])
        outcome = "localized" if sub_feasible else "fallback_infeasible"
        record_outcome(outcome)
        sub_info = {"rows": sub_plan.n_sub, "tier": sub_plan.tier,
                    "affected": sub_plan.affected, "outcome": outcome,
                    "ms": round(timings["subsolve_ms"], 2)}
        if sub_feasible:
            resident.adopt(best_assignment)
        else:
            sub_plan = None     # seed_assignment still holds the original
    if sub_plan is None:
        # the proof: under FLEET_TRANSFER_GUARD=disallow any host->device
        # transfer inside the warm dispatch raises (every input above is
        # already resident; statics hash, they don't transfer); off the
        # resident path the guard is a nullcontext
        with guard_ctx(), _dispatch_scope("refine"):
            (best_assignment, dstats, dsoft, sweeps_run, accepted,
             dtelem) = _refine(
                prob, seed_assignment, key, t0_d, t1_d, mw_d, **refine_kw)
        if resident is not None:
            # the padded winner stays on device as the next warm seed
            resident.adopt(best_assignment)
    compile_events = _refine._cache_size() - cache_before
    if sub_info is not None:
        from .subsolve import subsolve_cache_size
        compile_events += subsolve_cache_size() - sub_cache_before
    if overlap_host_work is not None:
        # async dispatch: the solve is in flight on device; do host work
        # (e.g. lower/ re-lowering of changed fleets) before blocking
        t_ov = t()
        overlap_host_work()
        timings["overlap_host_ms"] = (t() - t_ov) * 1e3
    # ONE transfer for everything the host decision needs — the
    # flight-deck telemetry rides it (no extra fetch, no extra dispatch)
    assignment, dstats, soft, sweeps_run, accepted, htelem = jax.device_get(
        (best_assignment, dstats, dsoft, sweeps_run, accepted, dtelem))
    # FORCE a host copy: on the CPU backend device_get returns a VIEW of
    # the device buffer, and the resident path DONATES that buffer into
    # the next burst's merge/sub-solve dispatch — without the copy every
    # retained SolveResult.assignment (scheduler slot, bench bookkeeping)
    # is clobbered in place when XLA reuses the storage (observed as
    # garbage node indices once the localized kernel aliased it to a
    # float scratch buffer)
    assignment = np.array(assignment, copy=True)
    # the padded winner, host side: the sub-solve mirror rides this fetch
    # (the result crossed the boundary anyway — no extra transfer)
    padded_host = assignment
    if bucketed:
        # phantom placements are an implementation detail of the padded
        # executable; no caller ever sees them
        assignment = assignment[: pt.S]
    soft = float(soft)
    accepted = int(accepted)
    timings["anneal_ms"] = (t() - t_anneal) * 1e3

    t_verify = t()
    # the numpy ground-truth path is entered only when the device solve
    # left violations and repair is needed
    if float(dstats["total"]) == 0:
        stats = {k: int(v) for k, v in dstats.items()}
        moves = 0
        pre_repair = 0
    else:
        stats = verify(pt, assignment)
        moves = 0
        pre_repair = int(stats["total"])
        if do_repair and stats["total"] > 0:
            rr: RepairResult = repair(pt, assignment)
            assignment, stats, moves = rr.assignment, rr.stats, rr.moves
            if resident is not None and moves:
                # the resident seed must track what the fleet actually
                # runs; a host repair rewrite is the rare re-upload the
                # host-transfer counter exists for
                resident.adopt_host(assignment, pt.node_valid,
                                    warm=resident_warm)
            # repair changed the winner: re-score its soft objective
            # (host-exact under bucketing — orig_prob may itself be a
            # pre-padded staging whose shape no longer matches)
            if not bucketed:
                soft = float(jax.device_get(
                    soft_score(orig_prob, jnp.asarray(assignment))))
    if bucketed:
        # report the REAL rows' soft score: the device number was computed
        # on the padded problem, whose /S mean denominators count phantoms
        soft = soft_score_host(pt, assignment)
    elif (resident_warm and int(sweeps_run) == 0
          and float(stats["total"]) == 0):
        # trusted 0-sweep exit (carried stats): the dispatch returned the
        # carried RANKING score, which includes the stickiness bonus —
        # recompute the un-bonused objective host-side (exact, and this
        # on-tier-unpadded corner is rare; the bucketed branch above
        # already does the same for the common path)
        soft = soft_score_host(pt, assignment)
    timings["verify_repair_ms"] = (t() - t_verify) * 1e3
    if resident is not None:
        # active-set bookkeeping (solver/subsolve.py): the mirror is what
        # the next burst's closure/frozen-base is computed against, and
        # feasibility is the frozen-base precondition. A host repair
        # rewrite already refreshed the mirror through adopt_host.
        resident.note_host_assignment(
            padded=None if moves else padded_host,
            feasible=stats["total"] == 0)
    timings["total_ms"] = (t() - t_start) * 1e3
    # -- flight-deck payload (docs/guide/10, "solver flight deck") ---------
    # accepted >= 0 distinguishes the adaptive dispatch (which carried a
    # real buffer) from the fixed-budget path's zero-filled treedef twin
    telemetry = None
    if trace_blocks > 0 and accepted >= 0:
        filled = int(htelem["filled"])
        rows = np.asarray(htelem["blocks"])[:filled]
        telemetry = {
            "schema": list(TRACE_COLS),
            "blocks": [[round(float(x), 6) for x in row] for row in rows],
            "trace_blocks": trace_blocks,
            "init": {"violations": float(htelem["init_violations"]),
                     "soft": round(float(htelem["init_soft"]), 6)},
            "prerepair_moves": int(htelem["prerepair_moves"]),
            "exit_sweep": int(sweeps_run),
            "path": ("subsolve" if sub_info is not None
                     and sub_info["outcome"] == "localized" else "full"),
        }
        if sub_info is not None:
            telemetry["subsolve"] = dict(sub_info)
        _record_solve_trace(telemetry, S=pt.S, N=prob.N,
                            warm=bool(warm), resident=bool(resident_warm),
                            violations=int(stats["total"]),
                            pre_repair=pre_repair,
                            total_ms=round(timings["total_ms"], 3))
    _M_SOLVES.inc(backend=jax.default_backend(),
                  warm="true" if warm else "false")
    _M_SOLVE_S.observe(timings["total_ms"] / 1e3)
    _M_SWEEPS.inc(int(sweeps_run))
    if accepted >= 0:
        _M_ACCEPTED.inc(accepted)
    if compile_events > 0:
        _M_COMPILES.inc(compile_events)
    _M_VIOL.set(int(stats["total"]))
    _M_PRE_VIOL.set(pre_repair)
    log.info("solve %s", kv(
        S=pt.S, N=prob.N, chains=chains, steps=steps,
        sweeps=int(sweeps_run),
        accepted=accepted if accepted >= 0 else None,
        compiles=compile_events or None,
        bucket=prob.S if bucketed else None,
        bucket_hit=(binfo.hit or None) if binfo is not None else None,
        violations=int(stats["total"]), pre_repair=pre_repair,
        repaired=moves or None, warm=warm or None,
        resident=resident_warm or None, fused=fused or None,
        sub=(f"{sub_info['rows']}/{sub_info['tier']}"
             f"({sub_info['outcome']})" if sub_info else None),
        **{k: f"{v:.1f}" for k, v in timings.items()}))
    return SolveResult(
        assignment=assignment, stats=stats, soft=soft,
        feasible=stats["total"] == 0, moves_repaired=moves,
        pre_repair_violations=pre_repair,
        timings_ms=timings, chains=chains, steps=int(sweeps_run),
        proposals_per_step=proposals_per_step,
        accepted_moves=accepted,
        bucket=binfo.to_dict() if binfo is not None else None,
        fused_prerepair=fused,
        subsolve=sub_info,
        telemetry=telemetry,
    )


def _record_solve_trace(payload: dict, **fields) -> None:
    """Record one solve's flight-deck telemetry as a flight-recorder span
    payload (kind="telemetry", rendered by `fleet solve trace`). No-op —
    one env lookup — when FLEET_TRACE_FILE is unset."""
    from ..obs.trace import (current_span_id, current_trace_id,
                             flight_recorder, new_span_id, new_trace_id,
                             record_span_event)
    if flight_recorder() is None:
        return
    record_span_event(
        "telemetry", "solve.trace", "fleetflow.solver",
        trace=current_trace_id() or new_trace_id(),
        span=current_span_id() or new_span_id(),
        fields={**fields, "telemetry": payload})
