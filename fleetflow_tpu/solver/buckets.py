"""Shape bucketing: the warm-path contract between fleet churn and XLA.

Every distinct (S, N, G, Gc, K, C) shape of a DeviceProblem is a distinct
XLA program: `_refine` (solver/api.py) is jitted with those extents baked
in as static/traced shapes, so a fleet drifting from 9,997 to 10,050
services — the normal churn/reschedule path — recompiles the whole fused
pipeline and pays the 4.3-5.5 s compile cliff for a 70 ms solve
(BENCH_r05). This module rounds the churn-sensitive extents UP to a
geometric tier ladder so every fleet size inside a tier reuses ONE
compiled executable:

  S   (service rows)        -> next tier (x``growth`` steps from ``minimum``)
  K   (conflict-id columns) -> next multiple of ``width_multiple``
  C   (coloc-id columns)    -> next multiple of ``width_multiple``
  G   (conflict-id count)   -> next tier (static: sizes the (N, G) tables)
  Gc  (coloc-id count)      -> next tier

N (node pool) is deliberately NOT bucketed: node inventories change by
operator action, not churn, and padding nodes would need phantom-capacity
semantics in every kernel. T is tied to N (node_topology defaults to
arange(N)) and follows it.

Padded service rows are PHANTOMS — the same construction the sharded
mega-solve uses (`pad_problem`, generalized here from solver/sharded.py):
zero demand, no conflict/coloc ids, no preference (the packed layout
keeps the plane absent; a present plane pads with zeros), eligible
everywhere (all-ones packed words).
A phantom parked on any *valid* node is provably inert:

  capacity     zero demand adds nothing to any load cell
  conflicts    no ids -> no (node, group) occupancy -> no pairs
  eligibility  eligible everywhere; seeds place phantoms on valid nodes
               and the anneal's W_ELIG (1e6) makes a move onto an invalid
               node unacceptable at any production temperature
  soft         zero demand/preference/coloc; only the padded-S mean
               denominators shift, so callers report the soft score of the
               REAL rows via `soft_score_host` on the original tensors

The one constraint phantoms are not inert for by construction is the
spread constraint (a parked phantom would count into per-domain totals),
so padded problems carry a traced ``n_real`` row count — the same mask the
sharded path threads statically — and the kernels exclude rows >= n_real
from topology/skew accounting. Bucketing therefore applies at
``max_skew > 0`` too (it was bypassed there before the mask existed).

Config: `bucket_config()` reads the FLEET_BUCKET* environment once per
call site; `FLEET_BUCKET=0` disables bucketing everywhere,
`FLEET_BUCKET_GROWTH` (default 1.25) and `FLEET_BUCKET_MIN` (default 64)
shape the tier ladder. docs/guide/11-performance.md covers tuning.
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

import numpy as np

__all__ = ["BucketConfig", "BucketInfo", "bucket_config", "bucket_size",
           "width_bucket", "subsolve_tier", "pad_problem",
           "pad_problem_tiers", "pad_assignment", "record_bucket",
           "soft_score_host", "stage_problem_tiers", "staging_arena_stats"]


@dataclass(frozen=True)
class BucketConfig:
    enabled: bool = True
    growth: float = 1.25     # geometric tier ratio for S / G / Gc
    minimum: int = 64        # first S tier; G/Gc ladder starts at 16
    width_multiple: int = 4  # K / C column rounding
    align: int = 8           # every S tier is a multiple of this (lanes)


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name, "").strip().lower()
    if not v:
        return default
    return v not in ("0", "false", "off", "no")


def bucket_config(default_enabled: bool = True) -> BucketConfig:
    """The process-wide bucketing knobs, read from the environment on each
    call (cheap; callers on hot paths hold the result)."""
    try:
        growth = float(os.environ.get("FLEET_BUCKET_GROWTH", "1.25"))
    except ValueError:
        growth = 1.25
    try:
        minimum = int(os.environ.get("FLEET_BUCKET_MIN", "64"))
    except ValueError:
        minimum = 64
    return BucketConfig(
        enabled=_env_flag("FLEET_BUCKET", default_enabled),
        growth=max(growth, 1.01),
        minimum=max(minimum, 8),
    )


def bucket_size(n: int, *, growth: float = 1.25, minimum: int = 64,
                align: int = 8) -> int:
    """Smallest tier >= n on the geometric ladder minimum, minimum*growth,
    minimum*growth^2, ... with every tier rounded up to a multiple of
    ``align``. bucket_size is idempotent: bucket_size(bucket_size(n)) ==
    bucket_size(n), which is what lets a pre-padded staging pass through
    `pad_problem_tiers` unchanged."""
    if n <= 0:
        return align
    tier = float(minimum)
    out = -((-minimum) // align) * align
    while out < n:
        tier *= growth
        out = -((-math.ceil(tier)) // align) * align  # ceil to align
    return out


def bucket_bounds(n: int, *, growth: float = 1.25, minimum: int = 64,
                  align: int = 8) -> tuple[int, int]:
    """(previous tier, tier) around n: the tier n pads up to, and the
    largest smaller tier (0 below the ladder). `fleet lint` FF014 uses the
    pair to say how far past a boundary a stage's row count sits."""
    upper = bucket_size(n, growth=growth, minimum=minimum, align=align)
    lower = 0
    tier = float(minimum)
    out = -((-minimum) // align) * align
    while out < upper:
        lower = out
        tier *= growth
        out = -((-math.ceil(tier)) // align) * align
    return lower, upper


def subsolve_tier(k: int, *, minimum: int = 256, maximum: int = 4096) -> int:
    """Mini tier for the active-set sub-problem's row count
    (solver/subsolve.py): the power-of-two ladder minimum, 2*minimum,
    4*minimum, ... capped at `maximum`. Bucketed for the same reason the
    full problem is — each distinct sub shape is its own XLA program, and
    churn closure sizes drift burst to burst — but on a coarser ladder:
    a handful of mini executables covers every localized solve. Returns
    the tier, or 0 when k exceeds `maximum` (the closure is too big to
    localize; the caller falls back to the full fused path)."""
    if k <= 0:
        return minimum
    tier = minimum
    while tier < k:
        tier *= 2
    return tier if tier <= maximum else 0


def width_bucket(k: int, multiple: int = 4) -> int:
    """Id-table column widths round to a small multiple: width drift (a
    service gaining a second port) must not recompile."""
    k = max(k, 1)
    return -((-k) // multiple) * multiple


@dataclass
class BucketInfo:
    """What padding was applied, for artifacts/metrics/SolveResult."""
    orig_S: int
    padded_S: int
    G: int
    Gc: int
    hit: bool = False           # this padded shape was already compiled-for

    @property
    def pad_waste(self) -> float:
        """Fraction of service rows that are phantoms."""
        return 1.0 - self.orig_S / self.padded_S if self.padded_S else 0.0

    def to_dict(self) -> dict:
        return {"orig_S": self.orig_S, "padded_S": self.padded_S,
                "pad_waste": round(self.pad_waste, 4), "hit": self.hit}


def _pad_rows(a, pad: int, fill):
    import jax.numpy as jnp
    widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, widths, constant_values=fill)


def _pad_cols(a, pad: int, fill):
    import jax.numpy as jnp
    return jnp.pad(a, [(0, 0), (0, pad)], constant_values=fill)


def _elig_fill(eligible):
    """Phantom-row fill for the eligibility plane: all-ones words when
    bit-packed (solver/problem.py packed layout), True when dense bool.
    Pad bits of a packed row are never read (gathers index columns < N)."""
    import jax.numpy as jnp
    return (np.uint32(0xFFFFFFFF) if eligible.dtype == jnp.uint32
            else True)


def pad_problem(prob, multiple: int):
    """Pad the service axis up to a multiple of ``multiple`` with phantom
    services (zero demand, no conflict/coloc ids, eligible everywhere, no
    preference): they sit wherever the annealer leaves them without
    touching any constraint or score. Returns (padded problem, original S)
    — slice the returned assignment back to [:orig_S].

    This is the sharded mega-solve's ragged-S entry point (S must divide
    over the mesh); `pad_problem_tiers` below is the bucketing entry point
    (S rounds to a reuse tier). Both build the same phantoms."""
    S = prob.S
    pad = (-S) % multiple
    if pad == 0:
        return prob, S
    kw = {}
    if prob.preferred is not None:   # absent plane stays absent
        kw["preferred"] = _pad_rows(prob.preferred, pad, 0.0)
    return dataclasses.replace(
        prob,
        demand=_pad_rows(prob.demand, pad, 0.0),
        conflict_ids=_pad_rows(prob.conflict_ids, pad, -1),
        coloc_ids=_pad_rows(prob.coloc_ids, pad, -1),
        eligible=_pad_rows(prob.eligible, pad, _elig_fill(prob.eligible)),
        S=S + pad, **kw,
    ), S


def pad_problem_tiers(prob, cfg: Optional[BucketConfig] = None):
    """Round a DeviceProblem up to its bucket: S to the tier ladder, the
    conflict/coloc id-table widths to ``width_multiple``, and the static
    G/Gc group counts to their own (smaller-based) tier ladder. Returns
    (padded problem, BucketInfo). Idempotent: a problem already sitting on
    its tiers comes back unchanged (same object), so staged re-use across
    re-solves never re-pads."""
    cfg = cfg or bucket_config()
    S_pad = bucket_size(prob.S, growth=cfg.growth, minimum=cfg.minimum,
                        align=cfg.align)
    K = prob.conflict_ids.shape[1]
    C = prob.coloc_ids.shape[1]
    K_pad = width_bucket(K, cfg.width_multiple)
    C_pad = width_bucket(C, cfg.width_multiple)
    # G/Gc ride a COARSER, power-of-two ladder: group counts drift with
    # fleet content (ports/volumes/colocations come and go service by
    # service), and any finer ladder crosses a G boundary — and recompiles
    # — while S sits comfortably in its tier. The cost of the headroom is
    # scatter-table memory ((N, G) int32), pennies next to a compile.
    G_pad = bucket_size(prob.G, growth=2.0, minimum=16, align=4)
    Gc_pad = bucket_size(prob.Gc, growth=2.0, minimum=4,
                         align=2) if prob.Gc > 0 else 0
    info = BucketInfo(orig_S=prob.S, padded_S=S_pad, G=G_pad, Gc=Gc_pad)
    if (S_pad == prob.S and K_pad == K and C_pad == C
            and G_pad == prob.G and Gc_pad == prob.Gc):
        return prob, info
    pad = S_pad - prob.S
    conflict_ids = prob.conflict_ids
    coloc_ids = prob.coloc_ids
    if K_pad > K:
        conflict_ids = _pad_cols(conflict_ids, K_pad - K, -1)
    if C_pad > C:
        coloc_ids = _pad_cols(coloc_ids, C_pad - C, -1)
    import jax.numpy as jnp
    # n_real marks rows >= it as phantoms — a TRACED scalar, so fleets
    # drifting within the tier reuse the compiled executable while the
    # kernels keep phantoms out of topology/skew accounting (what lets
    # bucketing apply at max_skew > 0). A pre-set n_real (re-padding an
    # already-resident problem) is preserved.
    n_real = (prob.n_real if prob.n_real is not None
              else jnp.asarray(prob.S, jnp.int32))
    kw = {}
    if prob.preferred is not None:   # absent plane stays absent
        kw["preferred"] = _pad_rows(prob.preferred, pad, 0.0)
    return dataclasses.replace(
        prob,
        demand=_pad_rows(prob.demand, pad, 0.0),
        conflict_ids=_pad_rows(conflict_ids, pad, -1),
        coloc_ids=_pad_rows(coloc_ids, pad, -1),
        eligible=_pad_rows(prob.eligible, pad, _elig_fill(prob.eligible)),
        S=S_pad, G=G_pad, Gc=Gc_pad, n_real=n_real, **kw,
    ), info


def pad_assignment(assignment: np.ndarray, padded_S: int,
                   node_valid: np.ndarray) -> np.ndarray:
    """Extend a real-row assignment with phantom placements on the first
    VALID node (phantoms on an invalid node would count as eligibility
    violations in the device stats — the one way a phantom can stop being
    inert)."""
    assignment = np.asarray(assignment, dtype=np.int32)
    pad = padded_S - assignment.shape[0]
    if pad <= 0:
        return assignment
    valid = np.flatnonzero(node_valid)
    fill = int(valid[0]) if valid.size else 0
    return np.concatenate(
        [assignment, np.full(pad, fill, dtype=np.int32)])


# -- compile-free padded staging -------------------------------------------
# pad_problem_tiers pads ON DEVICE: every plane pays a jnp.pad dispatch and
# — in a fresh process — a shape-specific XLA compile, which is why the
# cold_warm bench leg's stage_ms sat at ~667 ms while the actual bytes are
# a ~100 ms memcpy. stage_problem_tiers instead builds the PADDED planes on
# the host, in per-tier arena buffers reused across restages (the phantom
# region is written once per arena, not once per restage), and uploads
# them: staging becomes pure memcpy + device_put, no XLA ops at all.
# Constant (S, N) planes — eligible all-True, preferred absent — can
# additionally be served from a small immutable device-side cache, so a
# restage of the same tier re-uploads nothing for them.

_STAGE_LOCK = threading.Lock()          # arenas hand out shared buffers
_ARENAS: OrderedDict[tuple, list] = OrderedDict()   # key -> [array, rows]
_DEV_CONSTS: OrderedDict[tuple, object] = OrderedDict()
_DEV_CONST_CAP = 6                      # (S, N) planes; LRU beyond this


def _arena_cap_bytes() -> int:
    try:
        return int(float(os.environ.get("FLEET_STAGE_ARENA_MB", "")
                         or 512) * 1e6)
    except ValueError:
        return 512_000_000


def _arena_take_locked(name: str, shape: tuple, dtype, fill,
                       rows_written: int) -> np.ndarray:
    """A host buffer of `shape` whose rows >= rows_written hold `fill`;
    the caller overwrites rows [0:rows_written] (and owns the buffer until
    it releases _STAGE_LOCK). Reuse resets only the rows the previous
    staging dirtied beyond the new watermark."""
    key = (name, shape, np.dtype(dtype).str, repr(fill))
    ent = _ARENAS.get(key)
    if ent is None:
        arr = np.full(shape, fill, dtype=dtype)
        ent = _ARENAS[key] = [arr, 0]
        cap = _arena_cap_bytes()
        while len(_ARENAS) > 1 and \
                sum(e[0].nbytes for e in _ARENAS.values()) > cap:
            _ARENAS.popitem(last=False)
    else:
        _ARENAS.move_to_end(key)
        arr, dirty = ent
        if dirty > rows_written:
            arr[rows_written:dirty] = fill
    ent[1] = rows_written
    return ent[0]


def _device_const_locked(kind: str, shape: tuple, dtype, value,
                         device) -> object:
    """An immutable on-device constant plane, cached per shape/device.
    Rebuilt if a consumer deleted it (donation); callers that DONATE
    problem planes must not use this cache at all (a shared array donated
    by one staging would invalidate every other holder)."""
    import jax

    key = (kind, shape, None if device is None else repr(device))
    arr = _DEV_CONSTS.get(key)
    if arr is not None and not arr.is_deleted():
        _DEV_CONSTS.move_to_end(key)
        return arr
    host = _arena_take_locked(f"const:{kind}", shape, dtype, value, 0)
    arr = jax.device_put(host, device=device)
    _DEV_CONSTS[key] = arr
    while len(_DEV_CONSTS) > _DEV_CONST_CAP:
        _DEV_CONSTS.popitem(last=False)
    return arr


def staging_arena_stats() -> dict:
    with _STAGE_LOCK:
        return {
            "arenas": len(_ARENAS),
            "arena_bytes": int(sum(e[0].nbytes for e in _ARENAS.values())),
            "device_consts": len(_DEV_CONSTS),
        }


def stage_problem_tiers(pt, cfg: Optional[BucketConfig] = None,
                        device=None, reuse_device_constants: bool = True):
    """Stage a ProblemTensors DIRECTLY at its padded bucket shape.

    Equivalent to ``pad_problem_tiers(prepare_problem(pt), cfg)`` —
    bit-identical tensors, same statics — but compile-free: padded host
    planes are assembled in reusable per-tier arenas and uploaded with
    plain device_put (no jnp.pad / on-device fill ops, so a cold process
    pays zero staging compiles). The eligibility plane stages BIT-PACKED
    (solver/problem.py, 8x fewer arena/upload/sweep bytes; FLEET_PACKED=0
    restores dense bool), an absent preference stays absent (no zero
    plane at all), and the all-True eligible constant reuses an immutable
    device-side cache.

    Returns (DeviceProblem, BucketInfo). ``reuse_device_constants=False``
    opts out of the shared device cache — REQUIRED for stagings whose
    planes are later DONATED (the resident merge kernels), where a shared
    array would be invalidated under every other holder.
    """
    import jax
    import jax.numpy as jnp

    from .problem import (STRATEGY_CODES, DeviceProblem, _unify_conflict_ids,
                          pack_bool_rows, packed_enabled, packed_width,
                          record_plane_bytes)

    cfg = cfg or bucket_config()
    packed = packed_enabled()
    conflict = _unify_conflict_ids(pt)
    S, N = pt.S, pt.N
    K = conflict.shape[1]
    C = pt.coloc_ids.shape[1]
    G = max(int(conflict.max(initial=-1)) + 1, 1)
    Gc = int(pt.coloc_ids.max(initial=-1)) + 1
    T = int(pt.node_topology.max(initial=0)) + 1
    if cfg.enabled:
        S_pad = bucket_size(S, growth=cfg.growth, minimum=cfg.minimum,
                            align=cfg.align)
        K_pad = width_bucket(K, cfg.width_multiple)
        C_pad = width_bucket(C, cfg.width_multiple)
        G_pad = bucket_size(G, growth=2.0, minimum=16, align=4)
        Gc_pad = bucket_size(Gc, growth=2.0, minimum=4,
                             align=2) if Gc > 0 else 0
    else:
        S_pad, K_pad, C_pad, G_pad, Gc_pad = S, K, C, G, Gc
    info = BucketInfo(orig_S=S, padded_S=S_pad, G=G_pad, Gc=Gc_pad)

    def put(x):
        return jax.device_put(x, device=device)

    def put_arena(arr):
        # jax's CPU backend ZERO-COPIES device_put for large aligned
        # arrays (verified on jax 0.4.37): handing the shared arena
        # buffer straight to device_put would alias it into the returned
        # DeviceProblem, and the next restage of this tier would rewrite
        # a live staging's tensors in place. Upload a private copy — the
        # fresh buffer is then solely owned by (and may be aliased by)
        # the device array. One memcpy per plane; still no XLA ops. The
        # device-CONSTANT arenas below stay zero-copy: they are written
        # once at creation and never again.
        return jax.device_put(arr.copy(), device=device)

    R = np.asarray(pt.demand).shape[1]
    with _STAGE_LOCK:
        demand = _arena_take_locked("demand", (S_pad, R), np.float32, 0.0, S)
        demand[:S] = pt.demand
        conf = _arena_take_locked("conflict", (S_pad, K_pad), np.int32,
                                  -1, S)
        conf[:S, :K] = conflict
        if K_pad > K:
            conf[:S, K:] = -1
        coloc = _arena_take_locked("coloc", (S_pad, C_pad), np.int32, -1, S)
        coloc[:S, :C] = pt.coloc_ids
        if C_pad > C:
            coloc[:S, C:] = -1

        eligible_np = np.asarray(pt.eligible)
        all_eligible = bool(eligible_np.all())
        if packed:
            # bit-packed plane: 8x fewer bytes through the arena, the
            # upload, AND every anneal sweep (solver/problem.py). Phantom
            # rows (and the all-eligible constant) are all-ones words —
            # pad bits past N are never read.
            W = packed_width(N)
            ones = np.uint32(0xFFFFFFFF)
            if all_eligible and reuse_device_constants:
                eligible_arr = _device_const_locked(
                    "eligible_true_packed", (S_pad, W), np.uint32, ones,
                    device)
            else:
                elig = _arena_take_locked("eligible_packed", (S_pad, W),
                                          np.uint32, ones,
                                          0 if all_eligible else S)
                if not all_eligible:
                    elig[:S] = pack_bool_rows(eligible_np)
                eligible_arr = put_arena(elig)
        elif all_eligible and reuse_device_constants:
            eligible_arr = _device_const_locked("eligible_true",
                                                (S_pad, N), bool, True,
                                                device)
        else:
            elig = _arena_take_locked("eligible", (S_pad, N), bool, True,
                                      0 if all_eligible else S)
            if not all_eligible:
                elig[:S] = eligible_np
            eligible_arr = put_arena(elig)

        if pt.preferred is None:
            if packed:
                # absent by design: no zero plane is ever materialized —
                # the executables for this treedef carry no pref term
                preferred_arr = None
            elif reuse_device_constants:
                preferred_arr = _device_const_locked(
                    "preferred_zero", (S_pad, N), np.float32, 0.0, device)
            else:
                preferred_arr = put_arena(_arena_take_locked(
                    "preferred", (S_pad, N), np.float32, 0.0, 0))
        else:
            pref = _arena_take_locked("preferred", (S_pad, N), np.float32,
                                      0.0, S)
            pref[:S] = pt.preferred
            preferred_arr = put_arena(pref)

        prob = DeviceProblem(
            demand=put_arena(demand),
            capacity=put(np.asarray(pt.capacity, dtype=np.float32).copy()),
            conflict_ids=put_arena(conf),
            coloc_ids=put_arena(coloc),
            eligible=eligible_arr,
            node_valid=put(np.asarray(pt.node_valid, dtype=bool).copy()),
            node_topology=put(np.asarray(pt.node_topology,
                                         dtype=np.int32).copy()),
            preferred=preferred_arr,
            S=S_pad, N=N, G=G_pad, Gc=Gc_pad, T=T,
            strategy=STRATEGY_CODES[pt.strategy],
            max_skew=int(pt.max_skew),
            # same treedef as pad_problem_tiers(prepare_problem(pt)):
            # n_real traced whenever ANY extent padded, None on-tier
            n_real=(jnp.asarray(S, jnp.int32)
                    if (S_pad, K_pad, C_pad, G_pad, Gc_pad)
                    != (S, K, C, G, Gc) else None),
        )
    record_plane_bytes(prob)
    return prob, info


# -- bucket hit/miss telemetry ---------------------------------------------
# A "hit" means this process has already solved at a padded shape with the
# same jit-relevant extents, i.e. the fused pipeline will NOT recompile.
_seen_lock = threading.Lock()
_seen_buckets: set[tuple] = set()


def record_bucket(key: tuple) -> bool:
    """Record a padded-shape key; True when it was already seen (hit)."""
    with _seen_lock:
        hit = key in _seen_buckets
        _seen_buckets.add(key)
        return hit


# -- host-side exact soft score --------------------------------------------

def soft_score_host(pt, assignment: np.ndarray) -> float:
    """numpy mirror of kernels.soft_score against the ORIGINAL (unpadded)
    ProblemTensors: bucketed solves report the real rows' soft score, not
    the padded problem's (whose /S mean denominators include phantoms)."""
    from ..core.model import PlacementStrategy

    assignment = np.asarray(assignment)
    S, N = pt.S, pt.N
    load = np.zeros((N, pt.demand.shape[1]), dtype=np.float32)
    np.add.at(load, assignment, pt.demand.astype(np.float32))
    u = load / np.maximum(pt.capacity, 1e-6)
    usq = float((u * u).sum())
    denom = float(max(N, 1))
    if pt.strategy == PlacementStrategy.SPREAD_ACROSS_POOL:
        strat = usq / denom
    elif pt.strategy == PlacementStrategy.PACK_INTO_DEDICATED:
        strat = -usq / denom
    else:
        strat = float((assignment.astype(np.float32) / denom).mean())
    if pt.preferred is not None:
        pref = -float(pt.preferred[np.arange(S), assignment].mean())
    else:
        pref = 0.0
    coloc = 0.0
    Gc = int(pt.coloc_ids.max(initial=-1)) + 1
    if Gc > 0:
        valid = pt.coloc_ids >= 0
        counts = np.zeros((N, Gc), dtype=np.int64)
        rows = np.repeat(assignment, pt.coloc_ids.shape[1])[valid.ravel()]
        cols = pt.coloc_ids.ravel()[valid.ravel()]
        np.add.at(counts, (rows, cols), 1)
        c = counts.astype(np.float64)
        coloc = -float((c * (c - 1.0) / 2.0).sum()) / max(S, 1)
    return strat + pref + coloc
