"""Host-side exact repair + verification.

The deterministic backstop behind the zero-violation contract: the device
solver (greedy + annealing) lands feasible in practice, but the contract is
exact, so any residual violations are repaired here with vectorized numpy —
move each violating service to the best feasible node, smallest first, a
bounded number of rounds. Also home to `verify()`, the numpy ground-truth
violation accounting that tests use to cross-check the device kernels.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..lower.tensors import ProblemTensors

__all__ = ["verify", "repair", "RepairResult"]


def _group_counts(assignment: np.ndarray, ids: np.ndarray, N: int,
                  G: int) -> np.ndarray:
    valid = ids >= 0
    counts = np.zeros((N, G), dtype=np.int64)
    rows = np.repeat(assignment, ids.shape[1])[valid.ravel()]
    cols = ids.ravel()[valid.ravel()]
    np.add.at(counts, (rows, cols), 1)
    return counts


def _unified_ids(pt: ProblemTensors) -> np.ndarray:
    parts, offset = [], 0
    for arr in (pt.port_ids, pt.volume_ids, pt.anti_ids):
        parts.append(np.where(arr >= 0, arr + offset, -1))
        if arr.size:
            offset += int(arr.max(initial=-1)) + 1
    merged = np.concatenate(parts, axis=1)
    # dedupe within rows (mirrors problem._unify_conflict_ids): a repeated id
    # on one service is one constraint, not a self-conflict
    merged = -np.sort(-merged, axis=1)
    dup = np.zeros_like(merged, dtype=bool)
    dup[:, 1:] = (merged[:, 1:] == merged[:, :-1]) & (merged[:, 1:] >= 0)
    return np.where(dup, -1, merged)


def verify(pt: ProblemTensors, assignment: np.ndarray) -> dict:
    """Exact violation accounting on the host (numpy ground truth)."""
    S, N = pt.S, pt.N
    assignment = np.asarray(assignment)
    load = np.zeros((N, pt.demand.shape[1]), dtype=np.float64)
    np.add.at(load, assignment, pt.demand.astype(np.float64))
    cap_cells = int((load > pt.capacity * (1 + 1e-6)).sum())

    ids = _unified_ids(pt)
    G = int(ids.max(initial=-1)) + 1
    conflict_pairs = 0
    if G > 0:
        counts = _group_counts(assignment, ids, N, G)
        conflict_pairs = int((counts * (counts - 1) // 2).sum())

    elig = int((~pt.eligible[np.arange(S), assignment]).sum()
               + (~pt.node_valid[assignment]).sum())

    skew = 0
    if pt.max_skew > 0:
        per = np.bincount(pt.node_topology[assignment],
                          minlength=int(pt.node_topology.max()) + 1)
        skew = max(int(per.max() - per.min()) - pt.max_skew, 0)

    total = cap_cells + conflict_pairs + elig + skew
    return {"capacity": cap_cells, "conflicts": conflict_pairs,
            "eligibility": elig, "skew": skew, "total": total}


@dataclass
class RepairResult:
    assignment: np.ndarray
    moves: int
    stats: dict
    feasible: bool


def repair(pt: ProblemTensors, assignment: np.ndarray,
           max_rounds: int = 5) -> RepairResult:
    """Deterministically repair residual violations. Returns the repaired
    assignment (copy) and final stats; `feasible` is False when some
    violation could not be repaired (genuinely infeasible instances)."""
    S, N = pt.S, pt.N
    assignment = np.asarray(assignment).copy()
    ids = _unified_ids(pt)
    G = int(ids.max(initial=-1)) + 1
    demand = pt.demand.astype(np.float64)
    cap = pt.capacity.astype(np.float64)
    moves = 0

    for _ in range(max_rounds):
        load = np.zeros((N, demand.shape[1]), dtype=np.float64)
        np.add.at(load, assignment, demand)
        counts = (_group_counts(assignment, ids, N, G) if G > 0
                  else np.zeros((N, 1), dtype=np.int64))

        # --- collect violating services ---------------------------------
        bad = np.zeros(S, dtype=bool)
        # ineligible / invalid node
        bad |= ~pt.eligible[np.arange(S), assignment]
        bad |= ~pt.node_valid[assignment]
        # conflict groups: every service in an over-occupied (node, gid) cell
        # except the first keeper
        if G > 0:
            valid = ids >= 0
            svc_counts = np.where(
                valid, counts[assignment[:, None],
                              np.where(valid, ids, 0)], 0)
            in_conflict = (svc_counts > 1).any(axis=1)
            # keep one occupant per conflict cell: mark all, then unmark the
            # first occurrence per (node, gid)
            keeper = np.zeros(S, dtype=bool)
            seen: set = set()
            for s in range(S):
                cells = [(int(assignment[s]), int(g)) for g in ids[s] if g >= 0]
                if any(counts[c] > 1 for c in cells):
                    if all(c not in seen for c in cells):
                        keeper[s] = True
                        seen.update(cells)
            bad |= in_conflict & ~keeper
        # overloaded nodes: evict smallest services until the node fits
        over = (load > cap * (1 + 1e-6)).any(axis=1)
        for n in np.flatnonzero(over):
            members = np.flatnonzero((assignment == n) & ~bad)
            if members.size == 0:
                continue
            sizes = demand[members].sum(axis=1)
            for m in members[np.argsort(sizes)]:
                if not (load[n] > cap[n] * (1 + 1e-6)).any():
                    break
                bad[m] = True
                load[n] -= demand[m]

        if not bad.any():
            break

        # --- relocate, smallest first ------------------------------------
        # recompute load/counts excluding the evicted services
        load = np.zeros((N, demand.shape[1]), dtype=np.float64)
        np.add.at(load, assignment[~bad], demand[~bad])
        counts = (_group_counts(assignment[~bad], ids[~bad], N, G) if G > 0
                  else np.zeros((N, 1), dtype=np.int64))

        order = np.flatnonzero(bad)[np.argsort(demand[bad].sum(axis=1))]
        for s in order:
            fits = (load + demand[s] <= cap * (1 + 1e-6)).all(axis=1)
            ok = fits & pt.eligible[s] & pt.node_valid
            if G > 0:
                my = ids[s][ids[s] >= 0]
                if my.size:
                    ok &= (counts[:, my] == 0).all(axis=1)
            cand = np.flatnonzero(ok)
            if cand.size == 0:
                continue  # leave in place; next round may free capacity
            # balance: least-loaded feasible node
            util = (load[cand] / np.maximum(cap[cand], 1e-6)).max(axis=1)
            n = int(cand[np.argmin(util)])
            assignment[s] = n
            load[n] += demand[s]
            if G > 0 and (ids[s] >= 0).any():
                my = ids[s][ids[s] >= 0]
                counts[n, my] += 1
            moves += 1

    stats = verify(pt, assignment)
    return RepairResult(assignment=assignment, moves=moves, stats=stats,
                        feasible=stats["total"] == 0)
