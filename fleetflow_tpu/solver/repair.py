"""Host-side exact repair + verification.

The deterministic backstop behind the zero-violation contract: the device
solver (greedy + annealing) lands feasible in practice, but the contract is
exact, so any residual violations are repaired here with vectorized numpy —
move each violating service to the best feasible node, smallest first, a
bounded number of rounds. Also home to `verify()`, the numpy ground-truth
violation accounting that tests use to cross-check the device kernels.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

import numpy as np

from ..lower.tensors import ProblemTensors

__all__ = ["verify", "repair", "RepairResult"]


def _group_counts(assignment: np.ndarray, ids: np.ndarray, N: int,
                  G: int) -> np.ndarray:
    valid = ids >= 0
    counts = np.zeros((N, G), dtype=np.int64)
    rows = np.repeat(assignment, ids.shape[1])[valid.ravel()]
    cols = ids.ravel()[valid.ravel()]
    np.add.at(counts, (rows, cols), 1)
    return counts


def _unified_ids(pt: ProblemTensors) -> np.ndarray:
    parts, offset = [], 0
    for arr in (pt.port_ids, pt.volume_ids, pt.anti_ids):
        parts.append(np.where(arr >= 0, arr + offset, -1))
        if arr.size:
            offset += int(arr.max(initial=-1)) + 1
    merged = np.concatenate(parts, axis=1)
    # dedupe within rows (mirrors problem._unify_conflict_ids): a repeated id
    # on one service is one constraint, not a self-conflict
    merged = -np.sort(-merged, axis=1)
    dup = np.zeros_like(merged, dtype=bool)
    dup[:, 1:] = (merged[:, 1:] == merged[:, :-1]) & (merged[:, 1:] >= 0)
    return np.where(dup, -1, merged)


def verify(pt: ProblemTensors, assignment: np.ndarray) -> dict:
    """Exact violation accounting on the host (numpy ground truth)."""
    S, N = pt.S, pt.N
    assignment = np.asarray(assignment)
    load = np.zeros((N, pt.demand.shape[1]), dtype=np.float64)
    np.add.at(load, assignment, pt.demand.astype(np.float64))
    cap_cells = int((load > pt.capacity * (1 + 1e-6)).sum())

    ids = _unified_ids(pt)
    G = int(ids.max(initial=-1)) + 1
    conflict_pairs = 0
    if G > 0:
        counts = _group_counts(assignment, ids, N, G)
        conflict_pairs = int((counts * (counts - 1) // 2).sum())

    elig = int((~pt.eligible[np.arange(S), assignment]).sum()
               + (~pt.node_valid[assignment]).sum())

    skew = 0
    if pt.max_skew > 0:
        per = np.bincount(pt.node_topology[assignment],
                          minlength=int(pt.node_topology.max()) + 1)
        skew = max(int(per.max() - per.min()) - pt.max_skew, 0)

    total = cap_cells + conflict_pairs + elig + skew
    return {"capacity": cap_cells, "conflicts": conflict_pairs,
            "eligibility": elig, "skew": skew, "total": total}


@dataclass
class RepairResult:
    assignment: np.ndarray
    moves: int
    stats: dict
    feasible: bool


def repair(pt: ProblemTensors, assignment: np.ndarray,
           max_rounds: int = 8, seed: int = 0) -> RepairResult:
    """Repair residual violations (deterministic given `seed`). Returns the
    repaired assignment (copy) and final stats; `feasible` is False when some
    violation could not be repaired (genuinely infeasible instances).

    Mechanics: worklist relocation with one-level ejection chains, plus
    min-conflicts-style randomized escape — a service that keeps bouncing
    between the same contested nodes is sent to a random eligible node so
    deterministic ejection cycles (A evicts B evicts A…) break."""
    S, N = pt.S, pt.N
    original = np.asarray(assignment)
    assignment = original.copy()
    ids = _unified_ids(pt)
    G = int(ids.max(initial=-1)) + 1
    demand = pt.demand.astype(np.float64)
    cap = pt.capacity.astype(np.float64)
    moves = 0
    rng = np.random.default_rng(seed)
    bounce = np.zeros(S, dtype=np.int64)

    # conflict-id sets are built lazily and shared across rounds (`ids`
    # never changes): the worklist touches O(|bad| + evictees) services,
    # and materializing all S sets per round costs more than the whole
    # repair on warm churn fixes
    _id_cache: dict = {}

    def id_set(s: int) -> set:
        v = _id_cache.get(s)
        if v is None:
            row = ids[s]
            v = set(row[row >= 0].tolist()) if G > 0 else set()
            _id_cache[s] = v
        return v

    for _ in range(max_rounds):
        load = np.zeros((N, demand.shape[1]), dtype=np.float64)
        np.add.at(load, assignment, demand)
        counts = (_group_counts(assignment, ids, N, G) if G > 0
                  else np.zeros((N, 1), dtype=np.int64))

        # --- collect violating services ---------------------------------
        bad = np.zeros(S, dtype=bool)
        # ineligible / invalid node
        bad |= ~pt.eligible[np.arange(S), assignment]
        bad |= ~pt.node_valid[assignment]
        # conflict groups: every service in an over-occupied (node, gid) cell
        # except the first keeper
        if G > 0:
            valid = ids >= 0
            svc_counts = np.where(
                valid, counts[assignment[:, None],
                              np.where(valid, ids, 0)], 0)
            in_conflict = (svc_counts > 1).any(axis=1)
            # keep one occupant per conflict cell: mark all, then unmark the
            # first occurrence per (node, gid). Only conflicted rows can be
            # keepers, so iterate those (ascending, same first-wins order) —
            # a warm churn repair has ~|displaced| conflicted rows, and an
            # O(S) python loop here would dominate the whole repair.
            keeper = np.zeros(S, dtype=bool)
            seen: set = set()
            for s in np.flatnonzero(in_conflict):
                cells = [(int(assignment[s]), int(g)) for g in ids[s] if g >= 0]
                if any(counts[c] > 1 for c in cells):
                    if all(c not in seen for c in cells):
                        keeper[s] = True
                        seen.update(cells)
            bad |= in_conflict & ~keeper
        # overloaded nodes: evict smallest services until the node fits.
        # The per-service inner loop is replaced by a cumulative-sum scan:
        # evicting the smallest k members leaves load[n] - csum[k-1], so
        # the minimal k is the first index where every resource fits —
        # same eviction set and order as the sequential loop.
        over = (load > cap * (1 + 1e-6)).any(axis=1)
        for n in np.flatnonzero(over):
            members = np.flatnonzero((assignment == n) & ~bad)
            if members.size == 0:
                continue
            dm = demand[members]
            asc = np.argsort(dm.sum(axis=1))
            csum = np.cumsum(dm[asc], axis=0)
            fits_k = (load[n] - csum <= cap[n] * (1 + 1e-6)).all(axis=1)
            k = (int(np.argmax(fits_k)) + 1 if fits_k.any()
                 else members.size)
            bad[members[asc[:k]]] = True

        if not bad.any():
            break

        # --- relocate, smallest first ------------------------------------
        # load/counts excluding the evicted services: subtract the |bad|
        # rows' contributions instead of rebuilding from all S rows (a
        # warm churn repair has ~14 bad rows against 10k total)
        nbad = np.flatnonzero(bad)
        np.add.at(load, assignment[nbad], -demand[nbad])
        if G > 0:
            bad_ids = ids[nbad]
            bvalid = bad_ids >= 0
            np.add.at(counts,
                      (np.repeat(assignment[nbad], bad_ids.shape[1])[
                          bvalid.ravel()],
                       bad_ids.ravel()[bvalid.ravel()]), -1)
        else:
            counts = np.zeros((N, 1), dtype=np.int64)

        # Worklist relocation with one-level ejection chains: when a service
        # has no directly-feasible node, it may evict the services blocking
        # the least-contended node; evictees rejoin the queue. `detached`
        # marks queued services — their demand/conflicts are already out of
        # load/counts and they must not be seen (or evicted) as residents.
        # Bounded by a global move budget so pathological instances terminate.
        #
        # Node membership is LAZY: the worklist touches O(|bad| + evictees)
        # services, and materializing all N resident sets up-front (a 10k-
        # iteration Python loop) cost more than the whole repair on warm
        # churn fixes. Residents are grouped once with an argsort; a node's
        # set is built on first touch and kept current from then on.
        size = demand.sum(axis=1)
        _res_rows = np.flatnonzero(~bad)
        _res_order = _res_rows[np.argsort(assignment[_res_rows],
                                          kind="stable")]
        _res_nodes = assignment[_res_order]
        node_members: dict[int, set] = {}

        def members_of(n: int) -> set:
            s = node_members.get(n)
            if s is None:
                lo = int(np.searchsorted(_res_nodes, n, side="left"))
                hi = int(np.searchsorted(_res_nodes, n, side="right"))
                s = set(_res_order[lo:hi].tolist())
                node_members[n] = s
            return s

        detached = bad.copy()

        def plan_eviction(n: int, s: int) -> list | None:
            """Residents of n to evict so s fits (conflicts + capacity);
            None when even a full conflict eviction can't make room."""
            residents = members_of(n)
            evict = [r for r in residents
                     if id_set(s) & id_set(r)] if id_set(s) else []
            new_load = load[n] + demand[s] - demand[evict].sum(axis=0)
            rest = sorted((r for r in residents if r not in evict),
                          key=size.__getitem__)
            while (new_load > cap[n] * (1 + 1e-6)).any() and rest:
                r = rest.pop(0)
                evict.append(r)
                new_load -= demand[r]
            if (new_load > cap[n] * (1 + 1e-6)).any():
                return None
            return evict

        def detach(r: int, n: int) -> None:
            load[n] -= demand[r]
            if id_set(r):
                counts[n, list(id_set(r))] -= 1
            members_of(n).discard(r)
            detached[r] = True
            queue.append(r)

        queue = deque(np.flatnonzero(bad)[np.argsort(size[bad])].tolist())
        budget = 4 * S
        # True once any placement was NOT a direct feasible one (ejection
        # chain or randomized escape): those can strand or conflict, which
        # only the next round's full rescan catches
        evicted_any = False
        while queue and budget > 0:
            s = int(queue.popleft())
            budget -= 1
            bounce[s] += 1
            my = list(id_set(s))
            fits = (load + demand[s] <= cap * (1 + 1e-6)).all(axis=1)
            ok = fits & pt.eligible[s] & pt.node_valid
            if my:
                ok &= (counts[:, my] == 0).all(axis=1)
            cand = np.flatnonzero(ok)
            if cand.size:
                # balance: least-loaded feasible node (random when escaping
                # a bounce cycle); a direct placement ends the cycle, so the
                # counter resets
                if bounce[s] > 3:
                    n = int(rng.choice(cand))
                else:
                    util = (load[cand] / np.maximum(cap[cand], 1e-6)).max(axis=1)
                    n = int(cand[np.argmin(util)])
                bounce[s] = 0
            else:
                # any NON-direct placement forfeits the clean-round
                # shortcut below, even one that evicts nothing: a
                # randomized escape may land on an overloaded node the
                # next round's rescan must re-visit
                evicted_any = True
                elig = np.flatnonzero(pt.eligible[s] & pt.node_valid)
                if elig.size == 0:
                    continue  # truly no node: infeasible service
                if bounce[s] > 3:
                    # randomized escape: random eligible node, evict blockers
                    n = int(rng.choice(elig))
                    evict = plan_eviction(n, s) or [
                        r for r in members_of(n) if id_set(s) & id_set(r)]
                else:
                    # ejection: the eligible node whose blockers are cheapest
                    best = None
                    for n in elig:
                        ev = plan_eviction(int(n), s)
                        if ev is None:
                            continue
                        cost = size[ev].sum() if ev else 0.0
                        if best is None or cost < best[1]:
                            best = (int(n), cost, ev)
                    if best is None:
                        continue
                    n, _, evict = best
                for r in evict:
                    detach(r, n)
            assignment[s] = n
            load[n] += demand[s]
            if my:
                counts[n, my] += 1
            members_of(n).add(s)
            detached[s] = False
            moves += 1

        # Every evictee re-placed and every placement was DIRECT (checked
        # feasible against live load/counts, which direct placements keep
        # consistent): the next round's full rescan would find nothing.
        # Ejection chains and randomized escapes forfeit the shortcut —
        # they can strand or conflict, which the rescan exists to catch.
        # verify() below stays the ground truth either way.
        if not queue and not evicted_any and not detached.any():
            break

    stats = verify(pt, assignment)
    # Ejection leaves un-replaced evictees at stale nodes when the budget
    # exhausts; never return something worse than the input.
    if stats["total"] > 0:
        in_stats = verify(pt, original)
        if in_stats["total"] < stats["total"]:
            assignment, stats, moves = original.copy(), in_stats, 0
    return RepairResult(assignment=assignment, moves=moves, stats=stats,
                        feasible=stats["total"] == 0)
