"""Service-axis sharded annealing: the SPMD mega-solve.

Chain sharding (solver/api.py `mesh=`) is data parallelism — every device
holds the WHOLE problem. This module shards the PROBLEM itself over the
`svc` mesh axis (the domain analog of sequence/context parallelism): each
device owns S/D services — its slice of demand, conflict ids, eligibility
and preference matrices — while the per-node state (load, conflict-group
occupancy, colocation occupancy, topology counts) is replicated and kept
identical on every device by all-reducing each sweep's applied deltas.

Why it matters: the (S, ·) matrices dominate memory. The packed problem
layout (solver/problem.py) already cut the worst of it — eligibility is
bit-packed uint32 (~125 MB at 100k x 10k vs ~1 GB dense bool) and an
unused preference plane is absent instead of a 4 GB f32 zero fill — and
sharding S divides what remains by the mesh size; the sweep's hot path
then needs two collective patterns, both riding ICI:

  1. a `pmin` over the svc axis electing ONE winning move per target node
     globally (the feasibility-preserving winner-per-target rule must hold
     across shards, not per shard);
  2. `psum`s of the four applied state deltas (load, conflict occupancy,
     colocation occupancy, topology counts) so every device's replicated
     node state stays bit-identical.

Service ownership is disjoint, so the winner-per-service rule needs no
communication. The per-move cost delta mirrors anneal._proposal_delta term
for term (capacity overflow mass, conflicts, eligibility/validity, skew,
strategy soft rows, preference, colocation), so a legal sweep here is a
legal sweep there: a feasible chain stays feasible.

Entry points: `anneal_sharded(prob, init, key, mesh=...)` (hands back the
refined (S,) assignment; callers verify exactly on the host as
tests/test_sharded.py and __graft_entry__ do), and `shard_problem` to
pre-place a DeviceProblem's tensors on the mesh so repeated calls skip the
implicit reshard.
"""

from __future__ import annotations

import inspect
import os
from functools import lru_cache, partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map          # jax >= 0.8
except ImportError:                                  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from .anneal import (W_CAP, W_CONF, W_ELIG, _move_delta_core, _skew_pen,
                     violation_total_from_parts)
from .buckets import pad_problem
from .problem import DeviceProblem, eligible_lookup
from .resident import ResidentProblem, transfer_guard_ctx
from ..obs import get_logger, kv
from ..obs.metrics import REGISTRY

log = get_logger("solver.sharded")

# metric catalog: docs/guide/10-observability.md
_M_SHARDED = REGISTRY.counter(
    "fleet_solver_sharded_solves_total",
    "Pod-scale sharded solves by staging outcome: delta = warm re-solve "
    "from mesh-resident buffers, cold = full host staging",
    labels=("outcome",))
_M_SWAPS = REGISTRY.counter(
    "fleet_solver_tempering_swaps_total",
    "Parallel-tempering replica-exchange attempts by outcome",
    labels=("accepted",))
_M_SH_BYTES = REGISTRY.gauge(
    "fleet_solver_sharded_device_bytes",
    "Per-device bytes of the most recent sharded solve: problem tensors "
    "(service-axis shards + replicated node state) plus the anneal's "
    "chain/tempering working state")

# the replication-check kwarg was renamed across jax versions
_SM_KW = ("check_rep" if "check_rep" in inspect.signature(_shard_map).parameters
          else "check_vma" if "check_vma" in inspect.signature(_shard_map).parameters
          else None)


def shard_map(*args, **kw):
    if _SM_KW is not None:
        kw[_SM_KW] = False
    return _shard_map(*args, **kw)

__all__ = ["anneal_sharded", "pad_problem", "shard_problem",
           "per_device_bytes", "SVC_AXIS", "REPLICA_AXIS", "ShardedStats",
           "tempering_mesh", "tempering_swap_delta", "tempering_swap_accept",
           "ShardedResident", "solve_sharded", "sharded_route",
           "maybe_solve_sharded"]

SVC_AXIS = "svc"
REPLICA_AXIS = "replica"


def tempering_mesh(replicas: int = 1, svc_shards: Optional[int] = None,
                   devices=None) -> Mesh:
    """Build the (replica, svc) mesh the tempered sharded solve runs on:
    `replicas` independent annealing lanes, each sharding the service axis
    over `svc_shards` devices. With replicas=1 this degenerates to the
    plain service-axis sharded solve (no exchange rounds run)."""
    if devices is None:
        devices = jax.devices()
    replicas = max(int(replicas), 1)
    if svc_shards is None:
        svc_shards = max(len(devices) // replicas, 1)
    need = replicas * svc_shards
    if len(devices) < need:
        raise ValueError(f"tempering mesh needs {need} devices "
                         f"({replicas} replicas x {svc_shards} shards), "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(replicas, svc_shards)
    return Mesh(arr, (REPLICA_AXIS, SVC_AXIS))


def tempering_swap_delta(e_a, e_b, beta_a, beta_b):
    """Log acceptance ratio of exchanging the configurations of replicas a
    and b: (β_a − β_b)(E_a − E_b). Positive when the colder replica (larger
    β) would inherit the lower energy — the exchange that makes a bigger
    mesh a quality amplifier rather than just more lanes."""
    return (beta_a - beta_b) * (e_a - e_b)


def tempering_swap_accept(e_a, e_b, beta_a, beta_b, u):
    """Metropolis replica-exchange criterion: accept with probability
    min(1, exp((β_a − β_b)(E_a − E_b))) given `u` ~ Uniform[0, 1).

    Detailed balance holds by construction: p(swap)/p(unswap) equals the
    ratio of the joint Boltzmann weights, exp((β_a − β_b)(E_a − E_b)) —
    tests/test_sharded_resident.py checks the identity numerically. At
    equal temperatures the criterion always accepts (the swap is a
    distributional no-op); between lanes whose energy distributions
    coincide the acceptance fraction tends to ~50% as the β gap grows
    (only the favorable sign survives)."""
    return u < jnp.exp(jnp.minimum(
        tempering_swap_delta(e_a, e_b, beta_a, beta_b), 0.0))


class ShardedStats(NamedTuple):
    """Full return of anneal_sharded(..., return_stats=True): the winning
    padded assignment plus exact device-side stats (violation parts and
    soft recomputed from a scratch state rebuild of the winner, the same
    drift discipline as api._refine) and the tempering swap counters."""
    assignment: jax.Array       # (S,) i32, padded
    sweeps: jax.Array           # i32, sweeps actually run
    capacity: jax.Array         # f32, overloaded (node, resource) cells
    conflicts: jax.Array        # f32, same-node conflict pairs
    eligibility: jax.Array      # f32, services on ineligible/invalid nodes
    skew: jax.Array             # f32, excess spread over max_skew
    soft: jax.Array             # f32, soft score of the winner (padded rows)
    swap_attempts: jax.Array    # i32, replica-exchange attempts
    swap_accepts: jax.Array     # i32, accepted exchanges
    # flight-deck rows, (trace_blocks, len(SHARDED_TRACE_COLS)) f32,
    # replicated (every column is psum/pmin-derived, so the buffer is
    # identical on every device) — zero-length when trace_blocks=0 and
    # zero-FILLED on the fixed scan path (no block loop to observe)
    telemetry: jax.Array

    @property
    def violations(self):
        return self.capacity + self.conflicts + self.eligibility + self.skew


# per-block flight-deck schema of the sharded dispatch: the single-chip
# TRACE_COLS story minus the live-state column (the tempered loop's
# carried scalars are best-ever) plus the replica-exchange counters —
# "where did acceptance collapse" becomes "did the ladder stop mixing"
SHARDED_TRACE_COLS = ("sweep", "temperature", "best_violations",
                      "best_soft", "swap_attempts", "swap_accepts")

# pad_problem moved to solver/buckets.py (the bucketing module generalizes
# it: same phantom construction, plus tier ladders for S/G/Gc and id-table
# widths); re-exported via __all__ because the sharded entry points and
# their callers treat it as part of this module's API.


def shard_problem(prob: DeviceProblem, mesh: Mesh) -> DeviceProblem:
    """Pre-place the service-axis tensors over the mesh (S must divide
    evenly) and replicate the node-axis tensors, so repeated anneal_sharded
    calls on one problem skip the implicit reshard."""
    import dataclasses

    svc2 = NamedSharding(mesh, P(SVC_AXIS, None))
    rep = NamedSharding(mesh, P())
    kw = {}
    if prob.preferred is not None:   # absent plane: nothing to shard
        kw["preferred"] = jax.device_put(prob.preferred, svc2)
    return dataclasses.replace(
        prob,
        demand=jax.device_put(prob.demand, svc2),
        conflict_ids=jax.device_put(prob.conflict_ids, svc2),
        coloc_ids=jax.device_put(prob.coloc_ids, svc2),
        eligible=jax.device_put(prob.eligible, svc2),
        capacity=jax.device_put(prob.capacity, rep),
        node_valid=jax.device_put(prob.node_valid, rep),
        node_topology=jax.device_put(prob.node_topology, rep),
        **kw,
    )


def per_device_bytes(prob: DeviceProblem, *,
                     state: bool = False) -> dict[str, int]:
    """Bytes of each of `prob`'s tensors resident on ONE device.

    For a service-axis-sharded array each device holds an S/D slice; for a
    replicated array each device holds the full copy.  Summing the values
    gives the per-device staging footprint, which is what the module
    docstring's memory rationale claims scales ~1/D for the dominant (S, N)
    matrices — the evidence for that claim (VERDICT r4 weak #3) comes from
    comparing this across mesh sizes (tests/test_sharded.py) rather than
    asserting it.

    `state=True` additionally accounts the anneal's per-device WORKING
    state (`state_*` keys, computed from shapes — the buffers live only
    inside the dispatch): the carried replicated node state (load (N, R),
    conflict occupancy (N, G), colocation occupancy (N, Gc), topology
    counts (T,)) plus the two S/D assignment buffers (Metropolis carry +
    best-ever). Per-device state is the same on every lane of a tempered
    mesh (each lane is one more set of devices, not more bytes per
    device); the exchange rounds ppermute transient double-buffers of the
    same shapes on top. Without this the bench's per-device memory report
    undercounts — problem tensors alone are not what bounds the fleet
    shape on a chip."""
    import dataclasses

    out: dict[str, int] = {}
    s_loc = prob.S
    for f in dataclasses.fields(prob):
        v = getattr(prob, f.name)
        if not isinstance(v, jax.Array) or v.ndim == 0:
            continue
        shards = v.addressable_shards
        dev = shards[0].device
        out[f.name] = sum(s.data.nbytes for s in shards if s.device == dev)
        if f.name == "demand":
            s_loc = shards[0].data.shape[0]
    if state:
        R = prob.demand.shape[1]
        out["state_load"] = prob.N * R * 4
        out["state_used"] = prob.N * prob.G * 4
        out["state_coloc"] = prob.N * max(prob.Gc, 1) * 4
        out["state_topo"] = prob.T * 4
        out["state_assignment"] = s_loc * 4
        out["state_best_assignment"] = s_loc * 4
    return out


@partial(jax.jit, static_argnames=("steps", "proposals_per_step", "mesh",
                                   "adaptive", "block", "exchange_every",
                                   "return_sweeps", "return_stats",
                                   "trace_blocks"))
def anneal_sharded(prob: DeviceProblem, init_assignment: jax.Array,
                   key: jax.Array, steps: int = 64,
                   t0: float = 1.0, t1: float = 1e-3,
                   proposals_per_step: Optional[int] = None,
                   *, mesh: Mesh, adaptive: bool = False,
                   block: int = 16,
                   n_real=None,
                   ladder: float = 1.3,
                   exchange_every: int = 1,
                   return_sweeps: bool = False,
                   return_stats: bool = False,
                   trace_blocks: int = 0):
    """One annealing pass with the service axis sharded over `mesh`.

    init_assignment: (S,) int32 (replicated input; resharded internally).
    Returns the refined (S,) assignment. S must be divisible by the mesh
    size (pad_problem handles ragged S).  `return_sweeps=True` returns
    (assignment, sweeps_run) instead — sweeps_run is the sweep count the
    adaptive early exit actually executed (== steps when adaptive=False),
    so artifacts can report effort, not just latency (VERDICT r4 weak #3).
    `return_stats=True` returns a ShardedStats carrying exact device-side
    violation parts + soft of the winner (recomputed from a scratch state
    rebuild, the same float-drift discipline as api._refine) and the
    tempering swap counters.

    The returned assignment is the lexicographically best (violations,
    soft) state EVER VISITED, not the final Metropolis state (r5, same
    monotonicity contract as anneal.anneal_adaptive): each sweep scores
    the replicated state — capacity/conflict/skew violations and the
    strategy/coloc soft terms are local math on the replicated node
    state; the eligibility count and the two service-axis soft terms add
    two scalar psums per sweep, noise next to the sweep's four (N,·)
    state-delta psums. `adaptive=True` additionally runs in `block`-sweep
    chunks inside a lax.while_loop and exits at the first block boundary
    after any sweep visited a feasible state (any *replica* on a tempered
    mesh — the exit predicate is pmin'd across lanes so it stays uniform).

    `n_real` (TRACED — tier drift inside a shape bucket must not
    recompile, the same contract the resident path holds on one chip)
    marks rows >= n_real as pad_problem phantoms: they are excluded from
    topology counts, skew deltas, and the feasibility check, so padding
    cannot distort a spread constraint. None falls back to `prob.n_real`,
    then to "every row real".

    Parallel tempering: when `mesh` carries a REPLICA_AXIS (see
    `tempering_mesh`), each replica lane anneals the full problem at
    temperature `t(i) * ladder**lane` — lane 0 is the cold lane running
    the base schedule — and every `exchange_every` sweep-blocks
    neighboring lanes exchange their COMPLETE configurations (assignment
    shard + replicated node state) via `lax.ppermute` under the
    Metropolis swap criterion (`tempering_swap_accept`; even/odd pairing
    alternates per exchange round so the ladder mixes end to end). The
    final
    winner is the lexicographically best (violations, soft) state any
    lane ever visited, broadcast to every lane — adding devices along
    the replica axis buys solution QUALITY at equal wall-clock, not just
    divided memory."""
    D = mesh.shape[SVC_AXIS]
    has_rep = REPLICA_AXIS in mesh.shape
    n_rep = mesh.shape.get(REPLICA_AXIS, 1) if has_rep else 1
    S, N = prob.S, prob.N
    R = prob.demand.shape[1]
    Gc = max(prob.Gc, 1)
    T = prob.T
    assert S % D == 0, (f"S={S} must divide over {D} devices "
                        f"(use pad_problem first)")
    M = proposals_per_step or max(8, min(256, (S // D) // 2))
    if n_real is None:
        real_s = prob.n_real if prob.n_real is not None else S
    else:
        real_s = n_real
    decay = (t1 / t0) ** (1.0 / max(steps - 1, 1))
    lad = jnp.asarray(ladder, jnp.float32)

    def body(demand, conflict_ids, coloc_ids, eligible, preferred,
             capacity, node_valid, node_topology, assign, key):
        # shapes inside: demand (S/D, R), assign (S/D,), key replicated;
        # axis_index distinguishes the shard (and the replica lane)
        me = jax.lax.axis_index(SVC_AXIS)
        rep = (jax.lax.axis_index(REPLICA_AXIS) if has_rep
               else jnp.int32(0))
        # per-lane temperature multiplier: lane 0 is the cold lane on the
        # base schedule, hotter lanes explore basins the cold lane cannot
        lad_f = (lad ** rep.astype(jnp.float32) if has_rep
                 else jnp.float32(1.0))
        S_loc = assign.shape[0]
        # pad_problem phantoms (global row >= real_s) carry no topology
        # weight: a parked phantom must not relax or tighten a spread
        # constraint for the real services
        real = (me * S_loc + jnp.arange(S_loc)) < real_s

        # replicated node state built from ALL shards' assignments
        def build_state(assign):
            load = jnp.zeros((N, R), jnp.float32).at[assign].add(demand)
            cvalid = conflict_ids >= 0
            csafe = jnp.where(cvalid, conflict_ids, 0)
            used = jnp.zeros((N, prob.G), jnp.int32).at[
                jnp.broadcast_to(assign[:, None], csafe.shape), csafe].add(
                    cvalid.astype(jnp.int32))
            lvalid = coloc_ids >= 0
            lsafe = jnp.where(lvalid, coloc_ids, 0)
            coloc = jnp.zeros((N, Gc), jnp.int32).at[
                jnp.broadcast_to(assign[:, None], lsafe.shape), lsafe].add(
                    lvalid.astype(jnp.int32))
            topo = jnp.zeros((T,), jnp.int32).at[node_topology[assign]].add(
                real.astype(jnp.int32))
            return tuple(jax.lax.psum(x, SVC_AXIS)
                         for x in (load, used, coloc, topo))

        load0, used0, coloc0, topo0 = build_state(assign)

        def proposal_delta(load, used, coloc, topo, assign, s, b):
            """The SHARED per-move cost delta (anneal._move_delta_core) on
            shard-local gathers against the replicated node state — a
            legal sweep here is a legal sweep in the single-device anneal
            by construction, not by comment."""
            a = assign[s]
            elig_a = eligible_lookup(eligible, s, a) & node_valid[a]
            elig_b = eligible_lookup(eligible, s, b) & node_valid[b]
            d_pref = (jnp.float32(0.0) if preferred is None
                      else (preferred[s, a] - preferred[s, b]) / S)
            return _move_delta_core(
                prob, capacity=capacity, node_topology=node_topology,
                load=load, used=used, coloc=coloc, topo=topo,
                a=a, b=b, d=demand[s], ids=conflict_ids[s],
                cids=coloc_ids[s], elig_a=elig_a, elig_b=elig_b,
                d_pref=d_pref, r=real[s].astype(jnp.int32))

        def viol_total(assign, load, used, topo):
            """Exact hard-violation total: local math on the replicated
            node state + ONE scalar psum for the shard-local eligibility
            count (phantoms are eligible everywhere so the `real` mask is
            belt-and-braces)."""
            inel = ((~eligible_lookup(eligible, jnp.arange(S_loc), assign)
                     | ~node_valid[assign]) & real).sum()
            inel = jax.lax.psum(inel, SVC_AXIS)
            return violation_total_from_parts(prob, load, used, topo, inel)

        def soft_here(assign, load, coloc):
            """anneal.state_soft_score term for term from the replicated
            node state; the two service-axis terms (preference gather,
            strategy 2's index mean) psum their shard-local sums. Phantom
            rows contribute like any row — fine for its only use, a
            tie-break among equal-violation states."""
            u = load / jnp.maximum(capacity, 1e-6)
            usq = (u * u).sum()
            denom = jnp.float32(max(N, 1))
            s_denom = jnp.float32(max(S, 1))
            if prob.strategy == 0:
                strat = usq / denom
            elif prob.strategy == 1:
                strat = -usq / denom
            else:
                strat = jax.lax.psum(
                    (assign.astype(jnp.float32) / denom).sum(),
                    SVC_AXIS) / s_denom
            if preferred is None:   # absent plane: no zeros to stream
                pref = jnp.float32(0.0)
            else:
                pref = -jax.lax.psum(
                    preferred[jnp.arange(S_loc), assign].sum(),
                    SVC_AXIS) / s_denom
            if prob.Gc > 0:
                cc = coloc.astype(jnp.float32)
                col = -(cc * (cc - 1.0) / 2.0).sum() / s_denom
            else:
                col = jnp.float32(0.0)
            return strat + pref + col

        def energy(assign, load, used, coloc, topo):
            """The annealing-cost energy the exchange criterion samples:
            overflow mass, conflict pairs, ineligibility and skew at their
            sweep weights, plus the soft score — the same landscape the
            sweeps walk, so the swap criterion and the proposal criterion
            agree on what "better" means."""
            over = (jnp.maximum(load - capacity, 0.0)
                    / jnp.maximum(capacity, 1e-6)).sum() * W_CAP
            c = used.astype(jnp.float32)
            conf = (c * (c - 1.0) / 2.0).sum() * W_CONF
            inel = ((~eligible_lookup(eligible, jnp.arange(S_loc), assign)
                     | ~node_valid[assign]) & real).sum()
            inel = jax.lax.psum(inel, SVC_AXIS).astype(jnp.float32) * W_ELIG
            return (over + conf + inel + _skew_pen(prob, topo)
                    + soft_here(assign, load, coloc))

        def sweep(carry, i):
            (assign, load, used, coloc, topo, key,
             best_assign, best_viol, best_soft) = carry
            temp = t0 * decay ** i.astype(jnp.float32) * lad_f
            key = jax.random.fold_in(key, i)
            kk = jax.random.fold_in(key, me)   # decorrelate shards
            if has_rep:
                kk = jax.random.fold_in(kk, rep)   # ...and replica lanes
            ks, kb, ka, kt = jax.random.split(kk, 4)

            # targeted half: this shard's services on violating/invalid nodes
            over_node = (load > capacity * (1 + 1e-6)).any(-1)
            conf_node = ((used * (used - 1)).sum(-1) > 0)
            hot_node = over_node | conf_node
            svc_bad = (~eligible_lookup(eligible, jnp.arange(S_loc), assign)
                       | ~node_valid[assign])
            hot = hot_node[assign] | svc_bad
            logits = jnp.where(hot, 0.0, -30.0)
            s_tgt = jax.random.categorical(kt, logits, shape=(M,))
            s_uni = jax.random.randint(ks, (M,), 0, S_loc)
            half = M // 2
            s_idx = jnp.where(jnp.arange(M) < half, s_tgt, s_uni)
            b_idx = jax.random.randint(kb, (M,), 0, N)
            a_idx = assign[s_idx]

            delta = jax.vmap(lambda s, b: proposal_delta(
                load, used, coloc, topo, assign, s, b))(s_idx, b_idx)
            u = jax.random.uniform(ka, (M,))
            accept = ((delta < 0)
                      | (u < jnp.exp(-delta / jnp.maximum(temp, 1e-8)))) \
                & (a_idx != b_idx)

            order = jnp.arange(M, dtype=jnp.int32)
            winner = jnp.full((S_loc,), M, dtype=jnp.int32).at[s_idx].min(
                jnp.where(accept, order, M))
            cand = accept & (winner[s_idx] == order)

            # -- global winner-per-target-node election (collective #1) ----
            # rank = order + M * my_shard_index  (unique across the mesh)
            rank = jnp.where(cand, order + M * me, M * D)
            node_best = jnp.full((N,), M * D, jnp.int32).at[b_idx].min(rank)
            node_best = jax.lax.pmin(node_best, SVC_AXIS)
            applied = cand & (node_best[b_idx] == rank)

            w = applied.astype(jnp.float32)
            wi = applied.astype(jnp.int32)
            d = demand[s_idx]
            ids = conflict_ids[s_idx]
            vv = (ids >= 0).astype(jnp.int32) * wi[:, None]
            safe = jnp.where(ids >= 0, ids, 0)
            cids = coloc_ids[s_idx]
            lv = (cids >= 0).astype(jnp.int32) * wi[:, None]
            lsafe = jnp.where(cids >= 0, cids, 0)

            # -- replicated state update via psum of deltas (collective #2)
            dload = (jnp.zeros((N, R), jnp.float32)
                     .at[a_idx].add(-d * w[:, None])
                     .at[b_idx].add(d * w[:, None]))
            load = load + jax.lax.psum(dload, SVC_AXIS)
            a_rows = jnp.broadcast_to(a_idx[:, None], safe.shape)
            b_rows = jnp.broadcast_to(b_idx[:, None], safe.shape)
            dused = (jnp.zeros((N, prob.G), jnp.int32)
                     .at[a_rows, safe].add(-vv)
                     .at[b_rows, safe].add(vv))
            used = used + jax.lax.psum(dused, SVC_AXIS)
            al_rows = jnp.broadcast_to(a_idx[:, None], lsafe.shape)
            bl_rows = jnp.broadcast_to(b_idx[:, None], lsafe.shape)
            dcoloc = (jnp.zeros((N, Gc), jnp.int32)
                      .at[al_rows, lsafe].add(-lv)
                      .at[bl_rows, lsafe].add(lv))
            coloc = coloc + jax.lax.psum(dcoloc, SVC_AXIS)
            wr = wi * real[s_idx].astype(jnp.int32)
            dtopo = (jnp.zeros((T,), jnp.int32)
                     .at[node_topology[a_idx]].add(-wr)
                     .at[node_topology[b_idx]].add(wr))
            topo = topo + jax.lax.psum(dtopo, SVC_AXIS)

            # local assignment update (dump-row trick for losers)
            tgt = jnp.where(applied, s_idx, S_loc)
            assign = jnp.zeros((S_loc + 1,), jnp.int32).at[:S_loc].set(
                assign).at[tgt].set(b_idx.astype(jnp.int32))[:S_loc]

            # Best-ever tracking, lexicographic (violations, soft) — the
            # same monotonicity contract as the single-device anneal: a
            # sweep budget that ENDS on an uphill Metropolis state must
            # not discard a better state it walked through. Both scalars
            # are replicated (psums), so the update is identical on every
            # shard.
            vt = viol_total(assign, load, used, topo)
            sf = soft_here(assign, load, coloc)
            better = (vt < best_viol) | ((vt == best_viol) & (sf < best_soft))
            best_viol = jnp.where(better, vt, best_viol)
            best_soft = jnp.where(better, sf, best_soft)
            best_assign = jnp.where(better, assign, best_assign)
            return (assign, load, used, coloc, topo, key,
                    best_assign, best_viol, best_soft), None

        def exchange(assign, load, used, coloc, topo, key, b):
            """One replica-exchange round at block boundary `b` (even/odd
            pairing alternating with the round parity): neighboring lanes
            trade their COMPLETE configurations via lax.ppermute under the
            Metropolis swap criterion. Both partners of a pair fold the
            SAME key (the pair's low lane index) so the decision is
            symmetric without extra communication."""
            E = energy(assign, load, used, coloc, topo)
            # swap at the block's end temperature (clamped like the sweep
            # schedule); betas are per-lane, computable locally
            temp_b = t0 * decay ** jnp.minimum(
                (b + 1) * block - 1, steps - 1).astype(jnp.float32)

            def beta(rr):
                return 1.0 / jnp.maximum(
                    temp_b * lad ** rr.astype(jnp.float32), 1e-8)

            fwd = [(i, (i + 1) % n_rep) for i in range(n_rep)]
            bwd = [(i, (i - 1) % n_rep) for i in range(n_rep)]
            st = (assign, load, used, coloc, topo, E)
            below = jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, REPLICA_AXIS, fwd), st)
            above = jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, REPLICA_AXIS, bwd), st)

            # pairing parity advances per exchange ROUND, not per block:
            # tied to raw b, exchange_every=2 would pin every active
            # round to odd parity and a 2-lane ladder would never trade
            parity = (b // exchange_every) % 2
            kx = jax.random.fold_in(key, jnp.int32(0x7357))
            u_lo = jax.random.uniform(jax.random.fold_in(kx, rep))
            u_hi = jax.random.uniform(jax.random.fold_in(kx, rep - 1))
            is_lo = ((rep % 2) == parity) & (rep + 1 < n_rep)
            is_hi = (((rep + 1) % 2) == parity) & (rep >= 1)
            take_above = is_lo & tempering_swap_accept(
                E, above[5], beta(rep), beta(rep + 1), u_lo)
            take_below = is_hi & tempering_swap_accept(
                below[5], E, beta(rep - 1), beta(rep), u_hi)

            def sel(cur, ab, bel):
                return jnp.where(take_above, ab,
                                 jnp.where(take_below, bel, cur))

            out = tuple(sel(c, a2, b2)
                        for c, a2, b2 in zip(st[:5], above[:5], below[:5]))
            d_att = jax.lax.psum(is_lo.astype(jnp.int32), REPLICA_AXIS)
            d_acc = jax.lax.psum(take_above.astype(jnp.int32), REPLICA_AXIS)
            return out + (d_att, d_acc)

        viol0 = viol_total(assign, load0, used0, topo0)
        soft0 = soft_here(assign, load0, coloc0)
        carry0 = (assign, load0, used0, coloc0, topo0, key,
                  assign, viol0, soft0)
        zero_i = jnp.int32(0)
        n_blocks = -(-steps // block)
        # flight-deck buffer: one replicated f32 row per sweep-block
        # (every column below is psum/pmin-derived, hence identical on
        # all devices); rows past the static length drop
        telem0 = jnp.zeros((trace_blocks, len(SHARDED_TRACE_COLS)),
                           jnp.float32)

        def trace_row(telem, b, sweeps_f, bviol, bsoft, att, acc):
            if not trace_blocks:   # static: pre-telemetry program intact
                return telem
            row = jnp.stack([
                sweeps_f,
                # block-end temperature on the BASE (lane-0) schedule —
                # lane multipliers differ per replica and a replicated
                # output may not
                t0 * decay ** jnp.minimum(
                    (b + 1) * block - 1, steps - 1).astype(jnp.float32),
                bviol, bsoft,
                att.astype(jnp.float32), acc.astype(jnp.float32)])
            return telem.at[b].set(row, mode="drop")

        if not has_rep and not adaptive:
            # fixed scan path: no block loop to observe — the buffer
            # returns zero-filled (filled = 0 by the sweeps/block math)
            (_a, _l, _u, _c, _t, _k, best_assign, best_viol, best_soft), _ \
                = jax.lax.scan(sweep, carry0,
                               jnp.arange(steps, dtype=jnp.int32))
            sweeps_run = jnp.int32(steps)
            att = acc = zero_i
            telem = telem0
        elif not has_rep:
            def cond(carry):
                *_rest, b, done = carry
                return (~done) & (b < n_blocks)

            def blk(carry):
                (assign, load, used, coloc, topo, key,
                 best_assign, best_viol, best_soft, telem, b,
                 _done) = carry
                offsets = b * block + jnp.arange(block, dtype=jnp.int32)
                offsets = jnp.minimum(offsets, steps - 1)  # clamp schedule
                (assign, load, used, coloc, topo, key,
                 best_assign, best_viol, best_soft), _ = jax.lax.scan(
                    sweep, (assign, load, used, coloc, topo, key,
                            best_assign, best_viol, best_soft), offsets)
                telem = trace_row(
                    telem, b,
                    jnp.minimum((b + 1) * block, steps).astype(jnp.float32),
                    best_viol, best_soft, zero_i, zero_i)
                return (assign, load, used, coloc, topo, key,
                        best_assign, best_viol, best_soft, telem, b + 1,
                        best_viol == 0)

            (_a, _l, _u, _c, _t, _k, best_assign, best_viol, best_soft,
             telem, b_run, _done) = jax.lax.while_loop(
                cond, blk, carry0 + (telem0, zero_i, jnp.bool_(False)))
            sweeps_run = jnp.minimum(b_run * block, steps)
            att = acc = zero_i
        else:
            # tempered mesh: block loop + replica exchange at boundaries.
            # adaptive=False runs every block (the quality-curve config);
            # the exit predicate is pmin'd across lanes so every device
            # takes the same branch (a lane-local exit would deadlock the
            # collectives).
            def cond(carry):
                *_rest, b, done = carry
                return (~done) & (b < n_blocks)

            def blk(carry):
                (assign, load, used, coloc, topo, key, best_assign,
                 best_viol, best_soft, att, acc, telem, b, _done) = carry
                offsets = b * block + jnp.arange(block, dtype=jnp.int32)
                offsets = jnp.minimum(offsets, steps - 1)  # clamp schedule
                (assign, load, used, coloc, topo, key, best_assign,
                 best_viol, best_soft), _ = jax.lax.scan(
                    sweep, (assign, load, used, coloc, topo, key,
                            best_assign, best_viol, best_soft), offsets)
                if n_rep > 1:
                    ops = (assign, load, used, coloc, topo)
                    if exchange_every == 1:
                        out = exchange(*ops, key, b)
                    else:
                        # skip the WHOLE round (energy psum + both
                        # full-state ppermutes) on off blocks — the gate
                        # is replica-uniform (computed from the carried
                        # block index), so every lane takes the same
                        # branch and the collectives stay collective
                        out = jax.lax.cond(
                            (b % exchange_every) == (exchange_every - 1),
                            lambda o: exchange(*o, key, b),
                            lambda o: o + (zero_i, zero_i), ops)
                    (assign, load, used, coloc, topo, d_att, d_acc) = out
                    att = att + d_att
                    acc = acc + d_acc
                g_viol = jax.lax.pmin(best_viol, REPLICA_AXIS)
                # the lexicographic leader ACROSS lanes (one extra scalar
                # pmin per block): what the flight deck shows as "the
                # ladder's best so far"
                g_soft = jax.lax.pmin(
                    jnp.where(best_viol == g_viol, best_soft, jnp.inf),
                    REPLICA_AXIS)
                telem = trace_row(
                    telem, b,
                    jnp.minimum((b + 1) * block, steps).astype(jnp.float32),
                    g_viol, g_soft, att, acc)
                done = (g_viol == 0) if adaptive else jnp.bool_(False)
                return (assign, load, used, coloc, topo, key, best_assign,
                        best_viol, best_soft, att, acc, telem, b + 1, done)

            (_a, _l, _u, _c, _t, _k, best_assign, best_viol, best_soft,
             att, acc, telem, b_run, _done) = jax.lax.while_loop(
                cond, blk, carry0 + (zero_i, zero_i, telem0, zero_i,
                                     jnp.bool_(False)))
            sweeps_run = jnp.minimum(b_run * block, steps)
            if n_rep > 1:
                # global winner: the lexicographically best (violations,
                # soft) state any lane ever visited, broadcast to every
                # lane so the sharded output is replica-replicated
                g_viol = jax.lax.pmin(best_viol, REPLICA_AXIS)
                soft_m = jnp.where(best_viol == g_viol, best_soft, jnp.inf)
                g_soft = jax.lax.pmin(soft_m, REPLICA_AXIS)
                winner = (best_viol == g_viol) & (soft_m == g_soft)
                rank = jnp.where(winner, rep, n_rep)
                sel_rep = rep == jax.lax.pmin(rank, REPLICA_AXIS)
                best_assign = jax.lax.psum(
                    jnp.where(sel_rep, best_assign, 0), REPLICA_AXIS)
                best_viol, best_soft = g_viol, g_soft

        if return_stats:
            # exact stats of the WINNER from a scratch rebuild: the
            # carried float32 load drifts over thousands of scatter
            # updates, and the caller's repair decision must not trust
            # drifted state (the api._refine discipline)
            loadF, usedF, colocF, topoF = build_state(best_assign)
            capF = (loadF > capacity * (1 + 1e-6)).sum().astype(jnp.float32)
            cF = usedF.astype(jnp.float32)
            confF = (cF * (cF - 1.0) / 2.0).sum()
            inelF = jax.lax.psum(
                ((~eligible_lookup(eligible, jnp.arange(S_loc), best_assign)
                  | ~node_valid[best_assign]) & real).sum(),
                SVC_AXIS).astype(jnp.float32)
            if prob.max_skew > 0:
                skewF = jnp.maximum(
                    (topoF.max() - topoF.min()) - prob.max_skew, 0
                ).astype(jnp.float32)
            else:
                skewF = jnp.float32(0.0)
            softF = soft_here(best_assign, loadF, colocF)
        else:
            capF = confF = inelF = skewF = softF = jnp.float32(0.0)
        return (best_assign, sweeps_run, capF, confF, inelF, skewF,
                softF, att, acc, telem)

    # the preference plane may be ABSENT (packed layout): the shard_map
    # operand list — and the executable — then simply has no pref plane,
    # instead of streaming an all-zero (S/D, N) shard every sweep
    if prob.preferred is not None:
        sharded = shard_map(
            body, mesh=mesh,
            in_specs=(P(SVC_AXIS, None), P(SVC_AXIS, None),
                      P(SVC_AXIS, None), P(SVC_AXIS, None),
                      P(SVC_AXIS, None),
                      P(), P(), P(), P(SVC_AXIS), P()),
            out_specs=(P(SVC_AXIS), P(), P(), P(), P(), P(), P(), P(), P(),
                       P()))
        out = sharded(prob.demand, prob.conflict_ids, prob.coloc_ids,
                      prob.eligible, prob.preferred, prob.capacity,
                      prob.node_valid, prob.node_topology,
                      init_assignment.astype(jnp.int32), key)
    else:
        def body_nopref(demand, conflict_ids, coloc_ids, eligible,
                        capacity, node_valid, node_topology, assign, key):
            return body(demand, conflict_ids, coloc_ids, eligible, None,
                        capacity, node_valid, node_topology, assign, key)

        sharded = shard_map(
            body_nopref, mesh=mesh,
            in_specs=(P(SVC_AXIS, None), P(SVC_AXIS, None),
                      P(SVC_AXIS, None), P(SVC_AXIS, None),
                      P(), P(), P(), P(SVC_AXIS), P()),
            out_specs=(P(SVC_AXIS), P(), P(), P(), P(), P(), P(), P(), P(),
                       P()))
        out = sharded(prob.demand, prob.conflict_ids, prob.coloc_ids,
                      prob.eligible, prob.capacity,
                      prob.node_valid, prob.node_topology,
                      init_assignment.astype(jnp.int32), key)
    stats = ShardedStats(*out)
    if return_stats:
        return stats
    if return_sweeps:
        return stats.assignment, stats.sweeps
    return stats.assignment


# -- mesh-resident sharded state: the pod-scale warm path --------------------

@lru_cache(maxsize=8)
def _merge_fn_sharded(mesh: Mesh):
    """The donated delta-merge kernel for MESH-SHARDED resident state: the
    same semantics as resident._merge_fn, with explicit sharding
    constraints (SNIPPETS.md [1]-[3] pjit/donation/constraint patterns)
    pinning every output to its input layout — the donated (S, ·) shards
    are reused in place on their own devices and a warm re-solve never
    reshards or round-trips the host."""
    import dataclasses

    svc2 = NamedSharding(mesh, P(SVC_AXIS, None))
    svc1 = NamedSharding(mesh, P(SVC_AXIS))
    rep = NamedSharding(mesh, P())

    def merge(prob, assignment, node_valid, capacity, dem_idx, dem_val,
              elig_idx, elig_rows, n_real, *, has_demand, has_eligible):
        cst = jax.lax.with_sharding_constraint
        demand = (cst(prob.demand.at[dem_idx].set(dem_val, mode="drop"),
                      svc2)
                  if has_demand else prob.demand)
        eligible = (cst(prob.eligible.at[elig_idx].set(elig_rows,
                                                       mode="drop"), svc2)
                    if has_eligible else prob.eligible)
        # re-park phantom rows on a valid node (see resident._merge_fn)
        first_valid = jnp.argmax(node_valid).astype(jnp.int32)
        ar = jnp.arange(prob.S)
        assignment = cst(jnp.where(ar >= n_real, first_valid, assignment),
                         svc1)
        prob = dataclasses.replace(
            prob, demand=demand, eligible=eligible,
            node_valid=cst(node_valid, rep), capacity=cst(capacity, rep),
            n_real=n_real)
        return prob, assignment

    return jax.jit(merge, donate_argnums=(0, 1),
                   static_argnames=("has_demand", "has_eligible"))


class ShardedResident(ResidentProblem):
    """solver/resident.ResidentProblem generalized to a device mesh: the
    padded, bucketed problem lives mesh-sharded
    (`NamedSharding(mesh, P(SVC_AXIS, None))` for the (S, ·) planes,
    replicated node state) and the last assignment lives `P(SVC_AXIS)`
    across bursts. Churn merges through the donated sharded kernel above;
    the small per-burst uploads (masks, capacity, scatter rows) are
    committed replicated so the warm dispatch moves nothing implicitly —
    the PR-7 transfer-guard contract, now at pod scale."""

    # the SPMD anneal shards whole sweeps; churn-localized sub-solves are
    # a single-chip optimization (solver/subsolve.py)
    supports_subsolve = False

    def __init__(self, pt, *, mesh: Mesh, bucket: bool = True, cfg=None):
        self.mesh = mesh
        super().__init__(pt, bucket=bucket, cfg=cfg)

    def _expected_padded_S(self, pt) -> int:
        # the bucket tier, rounded up so it divides over the svc axis
        s = super()._expected_padded_S(pt)
        D = self.mesh.shape[SVC_AXIS]
        return s + (-s) % D

    def _staging_device(self):
        # stage on the host CPU backend: the XL (S, N) planes must never
        # materialize whole on accelerator 0 — a cold stage would OOM the
        # chip before the mesh ever divides the bytes. shard_problem then
        # commits each tensor straight to its NamedSharding, so every
        # device receives only its own slice.
        try:
            return jax.local_devices(backend="cpu")[0]
        except RuntimeError:                         # pragma: no cover
            return None                              # cpu backend disabled

    def cold_stage(self, pt) -> None:
        import dataclasses
        super().cold_stage(pt)
        D = self.mesh.shape[SVC_AXIS]
        prob, _ = pad_problem(self.prob, D)
        # n_real must be COMMITTED to the mesh: an uncommitted scalar
        # reshards at dispatch time, which the disallow guard (rightly)
        # reads as a transfer on the warm path
        prob = dataclasses.replace(prob, n_real=self._put_n_real())
        self.prob = shard_problem(prob, self.mesh)

    # -- staging hooks: everything lands committed on the mesh -------------

    def _merge(self):
        return _merge_fn_sharded(self.mesh)

    def _put_small(self, tree):
        return jax.device_put(tree, NamedSharding(self.mesh, P()))

    def _put_n_real(self):
        return jax.device_put(np.asarray(self.n_real, np.int32),
                              NamedSharding(self.mesh, P()))

    def _put_assignment(self, padded):
        return jax.device_put(np.asarray(padded, np.int32),
                              NamedSharding(self.mesh, P(SVC_AXIS)))

    def _stage_scalars(self, key):
        rep = NamedSharding(self.mesh, P())
        return tuple(jax.device_put(np.float32(v), rep) for v in key)


def _host_seed(pt, parts: int) -> np.ndarray:
    """Cold host seed for the sharded path: native FFD when the library is
    built (partitioned past the r5 crossover where whole-instance FFD
    dominates), else one minimal pass through the single-chip pipeline."""
    from ..native.lib import available_nobuild
    if available_nobuild():
        if pt.S * pt.N >= 1_000_000:
            from .greedy import partitioned_seed
            return partitioned_seed(pt, max(parts, 1))
        from ..native.lib import native_place
        seed, _ = native_place(pt.demand, pt.capacity, pt.eligible,
                               pt.node_valid, pt.dep_depth, pt.port_ids,
                               pt.volume_ids, pt.anti_ids,
                               strategy=pt.strategy.value)
        return np.asarray(seed, np.int32)
    # no native .so: the pure-host greedy (sched/host.py). NOT the
    # single-chip device pipeline — staging the whole un-sharded problem
    # on one device to produce a seed is exactly the footprint the
    # sharded path exists to avoid.
    from ..sched.host import greedy_host_place
    seed, _ = greedy_host_place(pt)
    return np.asarray(seed, np.int32)


def solve_sharded(pt, *, resident: ShardedResident,
                  resident_warm: bool = False,
                  init_assignment=None,
                  steps: int = 64, seed: int = 0,
                  t0: float = 1.0, t1: float = 1e-3,
                  adaptive: bool = True, block: int = 8,
                  proposals_per_step: Optional[int] = None,
                  ladder: Optional[float] = None,
                  exchange_every: Optional[int] = None,
                  do_repair: bool = True,
                  overlap_host_work=None):
    """Pod-scale end-to-end solve through the mesh-resident sharded path:
    the SPMD anneal (+ parallel tempering over the replica axis) with the
    api.solve contract — exact stats, host repair backstop, SolveResult.

    `resident_warm=True` seeds from the mesh-resident previous assignment
    (churn already merged via `ShardedResident.apply_delta`): nothing
    crosses the host boundary and the dispatch runs under
    FLEET_TRANSFER_GUARD=disallow when set, exactly like the single-chip
    resident path. Cold solves stage a host FFD seed. Tempering knobs:
    `ladder` (temperature ratio between neighboring lanes,
    FLEET_TEMPER_LADDER, default 1.3 — measured best of {1.3, 1.6, 2.0, 3.0} on the partitioned-seed curve) and `exchange_every` (sweep-blocks
    between exchange rounds, FLEET_TEMPER_EXCHANGE, default 1)."""
    import contextlib
    import time

    from .api import SolveResult
    from .buckets import soft_score_host
    from .repair import RepairResult, repair, verify

    t = time.perf_counter
    timings: dict = {}
    t_start = t()
    rp = resident
    mesh = rp.mesh
    prob = rp.prob
    D = mesh.shape[SVC_AXIS]
    n_rep = mesh.shape.get(REPLICA_AXIS, 1)
    if ladder is None:
        try:
            ladder = float(os.environ.get("FLEET_TEMPER_LADDER") or "1.3")
        except ValueError:
            ladder = 1.3
    if exchange_every is None:
        try:
            exchange_every = max(
                1, int(os.environ.get("FLEET_TEMPER_EXCHANGE") or "1"))
        except ValueError:
            exchange_every = 1
    warm = bool(resident_warm and rp.assignment is not None)
    if warm:
        timings["delta_stage_ms"] = rp.consume_delta_ms()
    timings["stage_ms"] = (t() - t_start) * 1e3

    t_seed = t()
    if warm:
        # seed already mesh-resident: the previous padded winner, phantoms
        # re-parked at delta time; nothing crosses the host boundary
        seed_assignment = rp.assignment
        t0 = min(t0, 0.1)   # warm start: refine, don't re-scramble
    else:
        if init_assignment is not None:
            seed_np = np.asarray(init_assignment, dtype=np.int32)
            t0 = min(t0, 0.1)   # host warm seed: same refine contract
        else:
            seed_np = _host_seed(pt, D)
        # adopt_host pads to the mesh tier and commits P(SVC_AXIS)
        rp.adopt_host(seed_np, pt.node_valid, warm=False)
        seed_assignment = rp.assignment
    timings["seed_ms"] = (t() - t_seed) * 1e3
    _M_SHARDED.inc(outcome="delta" if warm else "cold")

    t_anneal = t()
    t0_d, t1_d, lad_d = rp.warm_scalars(t0, t1, float(ladder))
    # the PRNG key is minted and committed BEFORE the guard arms: it is
    # not a problem tensor (same contract as api._solve)
    key = jax.device_put(jax.random.PRNGKey(seed),
                         NamedSharding(mesh, P()))
    from .anneal import solve_trace_blocks
    trace_blocks = solve_trace_blocks()
    guard = transfer_guard_ctx() if warm else contextlib.nullcontext()
    cache_before = anneal_sharded._cache_size()
    with guard:
        res = anneal_sharded(
            prob, seed_assignment, key, steps=steps, t0=t0_d, t1=t1_d,
            proposals_per_step=proposals_per_step, mesh=mesh,
            adaptive=adaptive, block=block, ladder=lad_d,
            exchange_every=exchange_every, return_stats=True,
            trace_blocks=trace_blocks)
    compile_events = anneal_sharded._cache_size() - cache_before
    # the padded winner stays mesh-resident as the next warm seed
    rp.adopt(res.assignment)
    if overlap_host_work is not None:
        t_ov = t()
        overlap_host_work()
        timings["overlap_host_ms"] = (t() - t_ov) * 1e3
    # ONE fetch for everything the host decision needs (the flight-deck
    # buffer rides it)
    (assignment, sweeps, capF, confF, inelF, skewF, _softF, att,
     acc, htelem) = jax.device_get(tuple(res))
    # FORCE a host copy before slicing: on the CPU backend device_get
    # returns a VIEW of the device buffer, and the padded winner was just
    # adopted as the mesh-resident seed (rp.adopt above) — the next warm
    # sharded dispatch DONATES that buffer, clobbering every retained
    # result in place (the same aliasing api._solve pins against)
    assignment = np.array(assignment, dtype=np.int32, copy=True)[: pt.S]
    timings["anneal_ms"] = (t() - t_anneal) * 1e3

    t_verify = t()
    moves = 0
    pre_repair = 0
    if float(capF + confF + inelF + skewF) == 0:
        stats = {"capacity": 0, "conflicts": 0, "eligibility": 0,
                 "skew": 0, "total": 0}
    else:
        stats = {k: int(v) for k, v in verify(pt, assignment).items()}
        pre_repair = int(stats["total"])
        if do_repair and stats["total"] > 0:
            rr: RepairResult = repair(pt, assignment)
            assignment, moves = rr.assignment, rr.moves
            stats = {k: int(v) for k, v in rr.stats.items()}
            if moves:
                # the resident seed must track what the fleet actually
                # runs; on the warm path this is the host-transfer event
                # the counter exists for
                rp.adopt_host(assignment, pt.node_valid, warm=warm)
    # the real rows' soft score (the device number counts phantoms in its
    # /S mean denominators)
    soft = soft_score_host(pt, assignment)
    timings["verify_repair_ms"] = (t() - t_verify) * 1e3
    timings["total_ms"] = (t() - t_start) * 1e3

    # the CORE solver families too, not just the sharded ones: above the
    # routing threshold these are the only solves a fleet runs, and the
    # guide/10 catalog ("violations of the most recent solve", chaos
    # monotonicity invariants) must keep reflecting them
    from . import api as _api
    _api._M_SOLVES.inc(backend=jax.default_backend(),
                       warm="true" if warm else "false")
    _api._M_SOLVE_S.observe(timings["total_ms"] / 1e3)
    _api._M_SWEEPS.inc(int(sweeps))
    if compile_events > 0:
        _api._M_COMPILES.inc(compile_events)
    _api._M_VIOL.set(int(stats["total"]))
    _api._M_PRE_VIOL.set(pre_repair)
    att, acc = int(att), int(acc)
    if att > 0:
        _M_SWAPS.inc(acc, accepted="true")
        _M_SWAPS.inc(att - acc, accepted="false")
    dev_bytes = per_device_bytes(prob, state=True)
    _M_SH_BYTES.set(float(sum(dev_bytes.values())))
    # flight-deck payload: the per-block rows of the sharded dispatch
    # (fleet solve trace renders them like the single-chip schema)
    telemetry = None
    if trace_blocks > 0:
        filled = min(-(-int(sweeps) // block) if block else 0,
                     trace_blocks)
        rows = np.asarray(htelem)[:filled]
        # a written row always has temperature > 0; all-zero rows are
        # the fixed scan path's unobserved buffer — drop, don't invent
        rows = rows[~np.all(rows == 0, axis=1)]
        telemetry = {
            "schema": list(SHARDED_TRACE_COLS),
            "blocks": [[round(float(x), 6) for x in row] for row in rows],
            "trace_blocks": trace_blocks,
            "exit_sweep": int(sweeps),
            "path": "sharded",
            "mesh": f"{n_rep}x{D}",
        }
        from .api import _record_solve_trace
        _record_solve_trace(telemetry, S=pt.S, N=pt.N, warm=warm,
                            resident=warm, violations=int(stats["total"]),
                            pre_repair=pre_repair,
                            total_ms=round(timings["total_ms"], 3))
    log.info("solve_sharded %s", kv(
        S=pt.S, N=pt.N, padded=prob.S, mesh=f"{n_rep}x{D}",
        sweeps=int(sweeps), swaps=f"{acc}/{att}" if att else None,
        compiles=compile_events or None,
        violations=int(stats["total"]), pre_repair=pre_repair,
        repaired=moves or None, warm=warm or None,
        **{k: f"{v:.1f}" for k, v in timings.items()}))
    return SolveResult(
        assignment=assignment, stats=stats, soft=float(soft),
        feasible=stats["total"] == 0, moves_repaired=moves,
        pre_repair_violations=pre_repair,
        timings_ms=timings, chains=n_rep, steps=int(sweeps),
        proposals_per_step=(proposals_per_step
                            or max(8, min(256, (prob.S // D) // 2))),
        accepted_moves=-1,
        bucket={"orig_S": pt.S, "padded_S": prob.S,
                "pad_waste": round(1.0 - pt.S / prob.S, 4),
                "hit": compile_events == 0},
        tempering={"replicas": n_rep, "ladder": float(ladder),
                   "exchange_every": int(exchange_every),
                   "swap_attempts": att, "swap_accepts": acc},
        telemetry=telemetry,
    )


# -- routing: when does a solve take the pod-scale path? ---------------------

def sharded_route(pt) -> Optional[Mesh]:
    """Decide whether `pt` takes the pod-scale sharded path, and on what
    mesh. `FLEET_SHARDED=0` disables, `=1` forces; otherwise instances
    with S*N >= FLEET_SHARDED_MIN_CELLS (default 5e7 — comfortably above
    the proven single-chip 10k x 1k point) route when >= 2 devices are
    visible. FLEET_SHARDED_REPLICAS picks the tempering lanes (default 2
    when the device count allows an even split, else 1); the remaining
    devices shard the service axis."""
    mode = os.environ.get("FLEET_SHARDED", "").strip().lower()
    if mode in ("0", "off", "false", "no"):
        return None
    force = mode in ("1", "on", "true", "yes", "force")
    try:
        thresh = int(os.environ.get("FLEET_SHARDED_MIN_CELLS")
                     or str(50_000_000))
    except ValueError:
        thresh = 50_000_000
    if not force and pt.S * pt.N < thresh:
        return None
    devs = jax.devices()
    if len(devs) < 2:
        return None
    try:
        want = int(os.environ.get("FLEET_SHARDED_REPLICAS") or "0")
    except ValueError:
        want = 0
    if want <= 0:
        replicas = 2 if len(devs) >= 4 else 1
    else:
        # an explicit replica count is honored up to the device count
        # (replicas=len(devs) means pure tempering, one-device lanes)
        replicas = min(want, len(devs))
        if replicas != want:
            log.warning("FLEET_SHARDED_REPLICAS=%d clamped to %d "
                        "(only %d devices visible)", want, replicas,
                        len(devs))
    return tempering_mesh(replicas, len(devs) // replicas, devices=devs)


# solve() kwargs the sharded path speaks; anything else pins the call to
# the single-chip pipeline (an explicit chains= or seed_impl, a custom
# mesh, ...) — a knob this path would silently drop must not route
_ROUTED_KW = {"steps", "seed", "init_assignment", "t0", "t1", "adaptive",
              "do_repair", "overlap_host_work",
              "prob", "resident", "mesh"}


def maybe_solve_sharded(pt, **kw):
    """api.solve's routing hook: above the pod-scale threshold (or under
    FLEET_SHARDED=1) solve through a transient mesh-resident staging.
    Returns None when the call stays on the single-chip path — explicit
    staging kwargs (prob/resident/mesh) and solver knobs the sharded path
    does not speak always stay put. The CP's TpuSolverScheduler routes
    itself (persistent per-stage ShardedResident slots); this hook covers
    direct library/bench calls."""
    if any(kw.get(k) is not None for k in ("prob", "resident", "mesh")):
        return None
    if not set(kw) <= _ROUTED_KW:
        return None
    mesh = sharded_route(pt)
    if mesh is None:
        return None
    rp = ShardedResident(pt, mesh=mesh)
    try:
        steps = kw.get("steps") or int(
            os.environ.get("FLEET_SHARDED_STEPS") or "64")
    except ValueError:
        steps = 64
    return solve_sharded(
        pt, resident=rp, steps=steps,
        seed=kw.get("seed", 0),
        init_assignment=kw.get("init_assignment"),
        t0=kw.get("t0", 1.0), t1=kw.get("t1", 1e-3),
        adaptive=kw.get("adaptive", True),
        do_repair=kw.get("do_repair", True),
        overlap_host_work=kw.get("overlap_host_work"))
