"""Service-axis sharded annealing: the SPMD mega-solve.

Chain sharding (solver/api.py `mesh=`) is data parallelism — every device
holds the WHOLE problem. This module shards the PROBLEM itself over the
`svc` mesh axis (the domain analog of sequence/context parallelism): each
device owns S/D services — its slice of demand, conflict ids, eligibility
and preference matrices — while the per-node state (load, conflict-group
occupancy, colocation occupancy, topology counts) is replicated and kept
identical on every device by all-reducing each sweep's applied deltas.

Why it matters: the (S, N) eligibility/preference matrices dominate memory
— at 100k services x 10k nodes they are ~1 GB each in bool/f32, past a
single chip's budget once chain state is added. Sharding S divides them by
the mesh size; the sweep's hot path then needs two collective patterns,
both riding ICI:

  1. a `pmin` over the svc axis electing ONE winning move per target node
     globally (the feasibility-preserving winner-per-target rule must hold
     across shards, not per shard);
  2. `psum`s of the four applied state deltas (load, conflict occupancy,
     colocation occupancy, topology counts) so every device's replicated
     node state stays bit-identical.

Service ownership is disjoint, so the winner-per-service rule needs no
communication. The per-move cost delta mirrors anneal._proposal_delta term
for term (capacity overflow mass, conflicts, eligibility/validity, skew,
strategy soft rows, preference, colocation), so a legal sweep here is a
legal sweep there: a feasible chain stays feasible.

Entry points: `anneal_sharded(prob, init, key, mesh=...)` (hands back the
refined (S,) assignment; callers verify exactly on the host as
tests/test_sharded.py and __graft_entry__ do), and `shard_problem` to
pre-place a DeviceProblem's tensors on the mesh so repeated calls skip the
implicit reshard.
"""

from __future__ import annotations

import inspect
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map          # jax >= 0.8
except ImportError:                                  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from .anneal import (W_CAP, W_CONF, W_ELIG, _overflow_mass, _skew_pen,
                     _soft_rows, violation_total_from_parts)
from .buckets import pad_problem
from .problem import DeviceProblem

# the replication-check kwarg was renamed across jax versions
_SM_KW = ("check_rep" if "check_rep" in inspect.signature(_shard_map).parameters
          else "check_vma" if "check_vma" in inspect.signature(_shard_map).parameters
          else None)


def shard_map(*args, **kw):
    if _SM_KW is not None:
        kw[_SM_KW] = False
    return _shard_map(*args, **kw)

__all__ = ["anneal_sharded", "pad_problem", "shard_problem",
           "per_device_bytes", "SVC_AXIS"]

SVC_AXIS = "svc"

# pad_problem moved to solver/buckets.py (the bucketing module generalizes
# it: same phantom construction, plus tier ladders for S/G/Gc and id-table
# widths); re-exported via __all__ because the sharded entry points and
# their callers treat it as part of this module's API.


def shard_problem(prob: DeviceProblem, mesh: Mesh) -> DeviceProblem:
    """Pre-place the service-axis tensors over the mesh (S must divide
    evenly) and replicate the node-axis tensors, so repeated anneal_sharded
    calls on one problem skip the implicit reshard."""
    import dataclasses

    svc2 = NamedSharding(mesh, P(SVC_AXIS, None))
    rep = NamedSharding(mesh, P())
    return dataclasses.replace(
        prob,
        demand=jax.device_put(prob.demand, svc2),
        conflict_ids=jax.device_put(prob.conflict_ids, svc2),
        coloc_ids=jax.device_put(prob.coloc_ids, svc2),
        eligible=jax.device_put(prob.eligible, svc2),
        preferred=jax.device_put(prob.preferred, svc2),
        capacity=jax.device_put(prob.capacity, rep),
        node_valid=jax.device_put(prob.node_valid, rep),
        node_topology=jax.device_put(prob.node_topology, rep),
    )


def per_device_bytes(prob: DeviceProblem) -> dict[str, int]:
    """Bytes of each of `prob`'s tensors resident on ONE device.

    For a service-axis-sharded array each device holds an S/D slice; for a
    replicated array each device holds the full copy.  Summing the values
    gives the per-device staging footprint, which is what the module
    docstring's memory rationale claims scales ~1/D for the dominant (S, N)
    matrices — the evidence for that claim (VERDICT r4 weak #3) comes from
    comparing this across mesh sizes (tests/test_sharded.py) rather than
    asserting it."""
    import dataclasses

    out: dict[str, int] = {}
    for f in dataclasses.fields(prob):
        v = getattr(prob, f.name)
        if not isinstance(v, jax.Array):
            continue
        shards = v.addressable_shards
        dev = shards[0].device
        out[f.name] = sum(s.data.nbytes for s in shards if s.device == dev)
    return out


@partial(jax.jit, static_argnames=("steps", "proposals_per_step", "mesh",
                                   "adaptive", "block", "n_real",
                                   "return_sweeps"))
def anneal_sharded(prob: DeviceProblem, init_assignment: jax.Array,
                   key: jax.Array, steps: int = 64,
                   t0: float = 1.0, t1: float = 1e-3,
                   proposals_per_step: Optional[int] = None,
                   *, mesh: Mesh, adaptive: bool = False,
                   block: int = 16,
                   n_real: Optional[int] = None,
                   return_sweeps: bool = False) -> jax.Array:
    """One annealing chain with the service axis sharded over `mesh`.

    init_assignment: (S,) int32 (replicated input; resharded internally).
    Returns the refined (S,) assignment. S must be divisible by the mesh
    size (pad_problem handles ragged S).  `return_sweeps=True` returns
    (assignment, sweeps_run) instead — sweeps_run is the sweep count the
    adaptive early exit actually executed (== steps when adaptive=False),
    so artifacts can report effort, not just latency (VERDICT r4 weak #3).

    The returned assignment is the lexicographically best (violations,
    soft) state EVER VISITED, not the final Metropolis state (r5, same
    monotonicity contract as anneal.anneal_adaptive): each sweep scores
    the replicated state — capacity/conflict/skew violations and the
    strategy/coloc soft terms are local math on the replicated node
    state; the eligibility count and the two service-axis soft terms add
    two scalar psums per sweep, noise next to the sweep's four (N,·)
    state-delta psums. `adaptive=True` additionally runs in `block`-sweep
    chunks inside a lax.while_loop and exits at the first block boundary
    after any sweep visited a feasible state.

    `n_real` (static) marks rows >= n_real as pad_problem phantoms: they
    are excluded from topology counts, skew deltas, and the feasibility
    check, so padding cannot distort a spread constraint."""
    D = mesh.shape[SVC_AXIS]
    S, N = prob.S, prob.N
    R = prob.demand.shape[1]
    Gc = max(prob.Gc, 1)
    T = prob.T
    assert S % D == 0, (f"S={S} must divide over {D} devices "
                        f"(use pad_problem first)")
    M = proposals_per_step or max(8, min(256, (S // D) // 2))
    real_s = S if n_real is None else n_real
    decay = (t1 / t0) ** (1.0 / max(steps - 1, 1))

    def body(demand, conflict_ids, coloc_ids, eligible, preferred,
             capacity, node_valid, node_topology, assign, key):
        # shapes inside: demand (S/D, R), assign (S/D,), key replicated;
        # axis_index distinguishes the shard
        me = jax.lax.axis_index(SVC_AXIS)
        S_loc = assign.shape[0]
        # pad_problem phantoms (global row >= real_s) carry no topology
        # weight: a parked phantom must not relax or tighten a spread
        # constraint for the real services
        real = (me * S_loc + jnp.arange(S_loc)) < real_s

        # replicated node state built from ALL shards' assignments
        def build_state(assign):
            load = jnp.zeros((N, R), jnp.float32).at[assign].add(demand)
            cvalid = conflict_ids >= 0
            csafe = jnp.where(cvalid, conflict_ids, 0)
            used = jnp.zeros((N, prob.G), jnp.int32).at[
                jnp.broadcast_to(assign[:, None], csafe.shape), csafe].add(
                    cvalid.astype(jnp.int32))
            lvalid = coloc_ids >= 0
            lsafe = jnp.where(lvalid, coloc_ids, 0)
            coloc = jnp.zeros((N, Gc), jnp.int32).at[
                jnp.broadcast_to(assign[:, None], lsafe.shape), lsafe].add(
                    lvalid.astype(jnp.int32))
            topo = jnp.zeros((T,), jnp.int32).at[node_topology[assign]].add(
                real.astype(jnp.int32))
            return tuple(jax.lax.psum(x, SVC_AXIS)
                         for x in (load, used, coloc, topo))

        load0, used0, coloc0, topo0 = build_state(assign)

        def proposal_delta(load, used, coloc, topo, assign, s, b):
            """anneal._proposal_delta term for term, on shard-local gathers
            against the replicated node state."""
            a = assign[s]
            d = demand[s]
            ids = conflict_ids[s]
            valid = ids >= 0
            safe = jnp.where(valid, ids, 0)
            cids = coloc_ids[s]
            lvalid = cids >= 0
            lsafe = jnp.where(lvalid, cids, 0)

            cap_a, cap_b = capacity[a], capacity[b]
            load_a, load_b = load[a], load[b]

            load_a2, load_b2 = load_a - d, load_b + d
            d_cap = (_overflow_mass(prob, load_a2, cap_a)
                     + _overflow_mass(prob, load_b2, cap_b)
                     - _overflow_mass(prob, load_a, cap_a)
                     - _overflow_mass(prob, load_b, cap_b)) * W_CAP

            conf_a = ((used[a, safe] - 1) * valid).sum()
            conf_b = (used[b, safe] * valid).sum()
            d_conf = (conf_b - conf_a).astype(jnp.float32) * W_CONF

            elig_a = eligible[s, a] & node_valid[a]
            elig_b = eligible[s, b] & node_valid[b]
            d_elig = (elig_a.astype(jnp.float32)
                      - elig_b.astype(jnp.float32)) * W_ELIG

            ta, tb = node_topology[a], node_topology[b]
            r = real[s].astype(jnp.int32)
            topo2 = topo.at[ta].add(-r).at[tb].add(r)
            d_skew = _skew_pen(prob, topo2) - _skew_pen(prob, topo)

            soft_before = _soft_rows(prob, jnp.stack([load_a, load_b]),
                                     jnp.stack([cap_a, cap_b]))
            soft_after = _soft_rows(prob, jnp.stack([load_a2, load_b2]),
                                    jnp.stack([cap_a, cap_b]))
            d_pref = (preferred[s, a] - preferred[s, b]) / S
            col_a = ((coloc[a, lsafe] - 1) * lvalid).sum()
            col_b = (coloc[b, lsafe] * lvalid).sum()
            d_coloc = (col_a - col_b).astype(jnp.float32) / max(S, 1)

            return (d_cap + d_conf + d_elig + d_skew
                    + (soft_after - soft_before) + d_pref + d_coloc)

        def viol_total(assign, load, used, topo):
            """Exact hard-violation total: local math on the replicated
            node state + ONE scalar psum for the shard-local eligibility
            count (phantoms are eligible everywhere so the `real` mask is
            belt-and-braces)."""
            inel = ((~eligible[jnp.arange(S_loc), assign]
                     | ~node_valid[assign]) & real).sum()
            inel = jax.lax.psum(inel, SVC_AXIS)
            return violation_total_from_parts(prob, load, used, topo, inel)

        def soft_here(assign, load, coloc):
            """anneal.state_soft_score term for term from the replicated
            node state; the two service-axis terms (preference gather,
            strategy 2's index mean) psum their shard-local sums. Phantom
            rows contribute like any row — fine for its only use, a
            tie-break among equal-violation states."""
            u = load / jnp.maximum(capacity, 1e-6)
            usq = (u * u).sum()
            denom = jnp.float32(max(N, 1))
            s_denom = jnp.float32(max(S, 1))
            if prob.strategy == 0:
                strat = usq / denom
            elif prob.strategy == 1:
                strat = -usq / denom
            else:
                strat = jax.lax.psum(
                    (assign.astype(jnp.float32) / denom).sum(),
                    SVC_AXIS) / s_denom
            pref = -jax.lax.psum(
                preferred[jnp.arange(S_loc), assign].sum(),
                SVC_AXIS) / s_denom
            if prob.Gc > 0:
                cc = coloc.astype(jnp.float32)
                col = -(cc * (cc - 1.0) / 2.0).sum() / s_denom
            else:
                col = jnp.float32(0.0)
            return strat + pref + col

        def sweep(carry, i):
            (assign, load, used, coloc, topo, key,
             best_assign, best_viol, best_soft) = carry
            temp = t0 * decay ** i.astype(jnp.float32)
            key = jax.random.fold_in(key, i)
            kk = jax.random.fold_in(key, me)   # decorrelate shards
            ks, kb, ka, kt = jax.random.split(kk, 4)

            # targeted half: this shard's services on violating/invalid nodes
            over_node = (load > capacity * (1 + 1e-6)).any(-1)
            conf_node = ((used * (used - 1)).sum(-1) > 0)
            hot_node = over_node | conf_node
            svc_bad = (~eligible[jnp.arange(S_loc), assign]
                       | ~node_valid[assign])
            hot = hot_node[assign] | svc_bad
            logits = jnp.where(hot, 0.0, -30.0)
            s_tgt = jax.random.categorical(kt, logits, shape=(M,))
            s_uni = jax.random.randint(ks, (M,), 0, S_loc)
            half = M // 2
            s_idx = jnp.where(jnp.arange(M) < half, s_tgt, s_uni)
            b_idx = jax.random.randint(kb, (M,), 0, N)
            a_idx = assign[s_idx]

            delta = jax.vmap(lambda s, b: proposal_delta(
                load, used, coloc, topo, assign, s, b))(s_idx, b_idx)
            u = jax.random.uniform(ka, (M,))
            accept = ((delta < 0)
                      | (u < jnp.exp(-delta / jnp.maximum(temp, 1e-8)))) \
                & (a_idx != b_idx)

            order = jnp.arange(M, dtype=jnp.int32)
            winner = jnp.full((S_loc,), M, dtype=jnp.int32).at[s_idx].min(
                jnp.where(accept, order, M))
            cand = accept & (winner[s_idx] == order)

            # -- global winner-per-target-node election (collective #1) ----
            # rank = order + M * my_shard_index  (unique across the mesh)
            rank = jnp.where(cand, order + M * me, M * D)
            node_best = jnp.full((N,), M * D, jnp.int32).at[b_idx].min(rank)
            node_best = jax.lax.pmin(node_best, SVC_AXIS)
            applied = cand & (node_best[b_idx] == rank)

            w = applied.astype(jnp.float32)
            wi = applied.astype(jnp.int32)
            d = demand[s_idx]
            ids = conflict_ids[s_idx]
            vv = (ids >= 0).astype(jnp.int32) * wi[:, None]
            safe = jnp.where(ids >= 0, ids, 0)
            cids = coloc_ids[s_idx]
            lv = (cids >= 0).astype(jnp.int32) * wi[:, None]
            lsafe = jnp.where(cids >= 0, cids, 0)

            # -- replicated state update via psum of deltas (collective #2)
            dload = (jnp.zeros((N, R), jnp.float32)
                     .at[a_idx].add(-d * w[:, None])
                     .at[b_idx].add(d * w[:, None]))
            load = load + jax.lax.psum(dload, SVC_AXIS)
            a_rows = jnp.broadcast_to(a_idx[:, None], safe.shape)
            b_rows = jnp.broadcast_to(b_idx[:, None], safe.shape)
            dused = (jnp.zeros((N, prob.G), jnp.int32)
                     .at[a_rows, safe].add(-vv)
                     .at[b_rows, safe].add(vv))
            used = used + jax.lax.psum(dused, SVC_AXIS)
            al_rows = jnp.broadcast_to(a_idx[:, None], lsafe.shape)
            bl_rows = jnp.broadcast_to(b_idx[:, None], lsafe.shape)
            dcoloc = (jnp.zeros((N, Gc), jnp.int32)
                      .at[al_rows, lsafe].add(-lv)
                      .at[bl_rows, lsafe].add(lv))
            coloc = coloc + jax.lax.psum(dcoloc, SVC_AXIS)
            wr = wi * real[s_idx].astype(jnp.int32)
            dtopo = (jnp.zeros((T,), jnp.int32)
                     .at[node_topology[a_idx]].add(-wr)
                     .at[node_topology[b_idx]].add(wr))
            topo = topo + jax.lax.psum(dtopo, SVC_AXIS)

            # local assignment update (dump-row trick for losers)
            tgt = jnp.where(applied, s_idx, S_loc)
            assign = jnp.zeros((S_loc + 1,), jnp.int32).at[:S_loc].set(
                assign).at[tgt].set(b_idx.astype(jnp.int32))[:S_loc]

            # Best-ever tracking, lexicographic (violations, soft) — the
            # same monotonicity contract as the single-device anneal: a
            # sweep budget that ENDS on an uphill Metropolis state must
            # not discard a better state it walked through. Both scalars
            # are replicated (psums), so the update is identical on every
            # shard.
            vt = viol_total(assign, load, used, topo)
            sf = soft_here(assign, load, coloc)
            better = (vt < best_viol) | ((vt == best_viol) & (sf < best_soft))
            best_viol = jnp.where(better, vt, best_viol)
            best_soft = jnp.where(better, sf, best_soft)
            best_assign = jnp.where(better, assign, best_assign)
            return (assign, load, used, coloc, topo, key,
                    best_assign, best_viol, best_soft), None

        viol0 = viol_total(assign, load0, used0, topo0)
        soft0 = soft_here(assign, load0, coloc0)
        carry0 = (assign, load0, used0, coloc0, topo0, key,
                  assign, viol0, soft0)

        if not adaptive:
            (_a, _l, _u, _c, _t, _k, best_assign, _bv, _bs), _ = \
                jax.lax.scan(sweep, carry0,
                             jnp.arange(steps, dtype=jnp.int32))
            return best_assign, jnp.int32(steps)

        n_blocks = -(-steps // block)

        def cond(carry):
            *_rest, b, done = carry
            return (~done) & (b < n_blocks)

        def blk(carry):
            (assign, load, used, coloc, topo, key,
             best_assign, best_viol, best_soft, b, _done) = carry
            offsets = b * block + jnp.arange(block, dtype=jnp.int32)
            offsets = jnp.minimum(offsets, steps - 1)   # clamp temp schedule
            (assign, load, used, coloc, topo, key,
             best_assign, best_viol, best_soft), _ = jax.lax.scan(
                sweep, (assign, load, used, coloc, topo, key,
                        best_assign, best_viol, best_soft), offsets)
            return (assign, load, used, coloc, topo, key,
                    best_assign, best_viol, best_soft, b + 1,
                    best_viol == 0)

        (_a, _l, _u, _c, _t, _k, best_assign, _bv, _bs, b_run,
         _done) = jax.lax.while_loop(
            cond, blk, carry0 + (jnp.int32(0), jnp.bool_(False)))
        return best_assign, jnp.minimum(b_run * block, steps)

    sharded = shard_map(
        body, mesh=mesh,
        in_specs=(P(SVC_AXIS, None), P(SVC_AXIS, None), P(SVC_AXIS, None),
                  P(SVC_AXIS, None), P(SVC_AXIS, None),
                  P(), P(), P(), P(SVC_AXIS), P()),
        out_specs=(P(SVC_AXIS), P()))
    assign, sweeps = sharded(prob.demand, prob.conflict_ids, prob.coloc_ids,
                             prob.eligible, prob.preferred, prob.capacity,
                             prob.node_valid, prob.node_topology,
                             init_assignment.astype(jnp.int32), key)
    return (assign, sweeps) if return_sweeps else assign
