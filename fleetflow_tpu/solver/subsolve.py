"""Active-set warm solves: churn-localized sub-problem annealing.

The warm path's remaining tax (BENCH_r08) is sweep cost whenever churn
actually needs annealing: a rolling-kill burst that moves 80 of 10k
services pays 5 FULL-problem sweeps (133 ms), and admission micro-solves
sweep all ~10.7k rows to place an 81-arrival batch (solve p99 218 ms vs
p50 52 ms). Steady-state churn is sparse — the rows that can possibly
move are the AFFECTED set (killed-node evictions, arrivals, demand and
eligibility drift) plus their constraint closure — so this module solves
exactly that set:

  ActiveIndex    host-side constraint index built once per resident
                 staging: unified conflict ids, coloc ids, dependency
                 adjacency and replica groups, each inverted id -> rows
  plan_active    the closure rule: affected rows ∪ rows sharing any
                 conflict/coloc id ∪ dependency neighbors ∪ replica
                 siblings, padded onto a mini tier ladder
                 (256/512/1024/... — buckets.subsolve_tier) so the
                 localized executable compiles once per tier
  subsolve       ONE jitted dispatch: gather the closure rows' planes
                 from the resident problem, seed the mini anneal's
                 carried state with the FROZEN remainder (load / conflict
                 occupancy / coloc occupancy / topology counts of every
                 untouched row — capacity is debited by what the frozen
                 fleet already consumes), run the fused pre-repair
                 prologue + adaptive anneal over the tiny planes (a sweep
                 over 512 rows streams ~20x fewer bytes than one over
                 10k), scatter the accepted rows back into the resident
                 assignment (donated in place), and compute EXACT
                 full-problem stats of the result as the acceptance gate

Correctness story: the frozen base makes every carried gradient exact
against the untouched fleet (frozen-frozen violations are zero because
the previous committed placement was feasible — a precondition the
planner checks), closure rows are visited in ascending row order so a
0-sweep feasible prologue exit commits the SAME relocations the full
fused prologue would, and regardless of what the mini anneal claims, the
dispatch's last act is `kernels.exact_stats_and_soft` on the full
problem: a gate-rejected sub-solve is DISCARDED and the full fused path
re-runs from the ORIGINAL seed (which is why the kernel never donates
the assignment — see the scatter note in the kernel body). Closures
above ``FLEET_SUBSOLVE_FRAC`` of the real rows (or past the tier
ladder) fall back up front.

Knobs: FLEET_SUBSOLVE=0 disables; FLEET_SUBSOLVE_FRAC (default 0.25) is
the closure cap as a fraction of real rows; FLEET_SUBSOLVE_MIN /
FLEET_SUBSOLVE_MAX (default 256 / 4096) bound the mini tier ladder.
Tuning + runbook: docs/guide/11-performance.md; metric catalog:
docs/guide/10-observability.md.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import lru_cache
from typing import Optional

import numpy as np

from .buckets import subsolve_tier, width_bucket
from ..obs import get_logger, kv
from ..obs.metrics import MS_BUCKETS, REGISTRY

log = get_logger("solver.subsolve")

__all__ = ["SubsolveConfig", "subsolve_config", "ActiveIndex", "ActivePlan",
           "plan_active", "stage_subsolve", "subsolve_dispatch",
           "subsolve_cache_size", "record_outcome"]

# metric catalog: docs/guide/10-observability.md
_M_SUB = REGISTRY.counter(
    "fleet_solver_subsolve_total",
    "Active-set sub-solve attempts by outcome: localized = mini anneal "
    "accepted by the exact full-problem gate, fallback_closure = closure "
    "exceeded the size cap, fallback_small = the problem is too small for "
    "a sub-problem to win, fallback_infeasible = the sub-solve landed "
    "infeasible and the full fused path re-ran",
    labels=("outcome",))
_M_SUB_ROWS = REGISTRY.gauge(
    "fleet_solver_subsolve_rows",
    "Closure size (real rows) of the most recent active-set sub-solve")
_M_SUB_TIER = REGISTRY.gauge(
    "fleet_solver_subsolve_tier",
    "Padded mini-tier of the most recent active-set sub-solve")
_M_SUB_MS = REGISTRY.histogram(
    "fleet_solver_subsolve_ms",
    "Wall milliseconds per localized sub-solve dispatch "
    "(staging + mini anneal + scatter + exact full-problem gate)",
    buckets=MS_BUCKETS)


def record_outcome(outcome: str) -> None:
    _M_SUB.inc(outcome=outcome)


# the outcome vocabulary the operator surfaces render
# (cp/admission.SUBSOLVE_OUTCOMES mirrors this list by name — the CP
# reads the counter through the registry so its status calls never
# import jax; tests pin the two lists equal)
SUB_OUTCOMES = ("localized", "fallback_closure", "fallback_small",
                "fallback_infeasible")


@dataclass(frozen=True)
class SubsolveConfig:
    enabled: bool = True
    frac: float = 0.25       # closure cap as a fraction of real rows
    min_tier: int = 256      # first mini tier
    max_tier: int = 4096     # largest mini tier (beyond: full path)


def subsolve_config(default_enabled: bool = True) -> SubsolveConfig:
    """Process-wide active-set knobs, read from the environment per call
    (cheap; hot callers hold the result)."""
    def _f(name, d):
        try:
            return float(os.environ.get(name, "") or d)
        except ValueError:
            return d
    v = os.environ.get("FLEET_SUBSOLVE", "").strip().lower()
    enabled = (default_enabled if not v
               else v not in ("0", "false", "off", "no"))
    return SubsolveConfig(
        enabled=enabled,
        frac=min(max(_f("FLEET_SUBSOLVE_FRAC", 0.25), 0.0), 1.0),
        min_tier=max(int(_f("FLEET_SUBSOLVE_MIN", 256)), 8),
        max_tier=max(int(_f("FLEET_SUBSOLVE_MAX", 4096)), 8),
    )


def _invert_ids(ids: np.ndarray):
    """CSR inversion of a (S, K) -1-padded id table: (uniq ids, offsets,
    rows) such that rows[offsets[i]:offsets[i+1]] carry uniq[i]."""
    mask = ids >= 0
    if not mask.any():
        return (np.empty(0, np.int64), np.zeros(1, np.int64),
                np.empty(0, np.int64))
    rows = np.nonzero(mask)[0]
    vals = ids[mask]
    order = np.argsort(vals, kind="stable")
    vals, rows = vals[order], rows[order]
    uniq, starts = np.unique(vals, return_index=True)
    offsets = np.append(starts, vals.size)
    return uniq, offsets, rows


class ActiveIndex:
    """Host constraint index over a resident staging's ProblemTensors:
    everything the closure rule needs to expand an affected set, built
    once per cold staging (O(S*K) numpy — the same order as staging
    itself) and reused every burst."""

    def __init__(self, pt):
        from .problem import _unify_conflict_ids
        self.pt = pt
        self.S = pt.S
        self.conflict = _unify_conflict_ids(pt)              # (S, K)
        self.coloc = np.asarray(pt.coloc_ids, dtype=np.int32)
        self._conf_inv = _invert_ids(self.conflict)
        self._coloc_inv = _invert_ids(self.coloc)
        self._dep = np.asarray(pt.dep_adj, dtype=bool)
        # replica groups: rows sharing a base service move together
        self._groups: dict[str, list[int]] = {}
        for i, base in enumerate(pt.replica_of or ()):
            self._groups.setdefault(base, []).append(i)

    @staticmethod
    def _rows_sharing(inv, ids: np.ndarray) -> np.ndarray:
        uniq, offs, rows = inv
        ids = np.unique(ids[ids >= 0])
        if not ids.size or not uniq.size:
            return np.empty(0, np.int64)
        pos = np.searchsorted(uniq, ids)
        pos = pos[pos < uniq.size]
        pos = pos[np.isin(uniq[pos], ids)]
        if not pos.size:
            return np.empty(0, np.int64)
        return np.concatenate([rows[offs[p]:offs[p + 1]] for p in pos])

    def closure(self, affected: np.ndarray) -> np.ndarray:
        """One-level constraint closure of `affected` (sorted, unique):
        rows sharing any conflict or coloc id, dependency neighbors
        (either direction), replica siblings. One level suffices for
        correctness — the frozen-base occupancy makes second-order
        interactions exact in the sub-problem — and keeps the closure
        from percolating to the whole fleet through id chains."""
        affected = np.unique(affected)
        inside = affected[affected < self.S]
        out = [affected]
        if inside.size:
            out.append(self._rows_sharing(self._conf_inv,
                                          self.conflict[inside].ravel()))
            out.append(self._rows_sharing(self._coloc_inv,
                                          self.coloc[inside].ravel()))
            if self._dep.size:
                nbr = (self._dep[inside].any(axis=0)
                       | self._dep[:, inside].any(axis=1))
                out.append(np.nonzero(nbr)[0])
            for i in inside:
                base = (self.pt.replica_of[i]
                        if i < len(self.pt.replica_of or ()) else None)
                if base is not None and base in self._groups:
                    out.append(np.asarray(self._groups[base]))
        return np.unique(np.concatenate(out)).astype(np.int64)

    def frozen_occupancy(self, ids: np.ndarray, inv, mirror: np.ndarray,
                         in_sub: np.ndarray, N: int) -> np.ndarray:
        """(N, len(ids)) int32 occupancy of the given ORIGINAL ids by
        frozen rows (carriers outside the closure), placed at their
        mirror nodes — the conflict/coloc base counts the mini anneal's
        carried state starts from."""
        out = np.zeros((N, max(len(ids), 1)), dtype=np.int32)
        uniq, offs, rows = inv
        if not uniq.size:
            return out
        pos = np.searchsorted(uniq, ids)
        for g, p in enumerate(pos):
            if p >= uniq.size or uniq[p] != ids[g]:
                continue
            carriers = rows[offs[p]:offs[p + 1]]
            carriers = carriers[~in_sub[carriers]]
            if carriers.size:
                np.add.at(out, (mirror[carriers], g), 1)
        return out


@dataclass
class ActivePlan:
    """A staged-on-host localized sub-problem, ready for ONE device
    dispatch. All arrays are small (O(tier) rows / O(N) node state) —
    the (S, ·) planes never leave the device; their closure rows are
    gathered inside the jitted kernel."""
    rows: np.ndarray          # (tier,) i32, pad slots = padded_S (dropped)
    n_sub: int                # real closure rows
    tier: int
    G_sub: int                # compact conflict-id count (padded ladder)
    Gc_sub: int               # compact coloc-id count (0 = none)
    sub_conflict: np.ndarray  # (tier, Kc) i32 compact-remapped, -1 pad
    sub_coloc: np.ndarray     # (tier, Cc) i32 compact-remapped, -1 pad
    load0: np.ndarray         # (N, R) f32 frozen load
    used0: np.ndarray         # (N, G_sub) i32 frozen conflict occupancy
    coloc0: np.ndarray        # (N, max(Gc_sub, 1)) i32 frozen coloc occ.
    topo0: np.ndarray         # (T,) i32 frozen topology counts
    affected: int = 0         # pre-closure affected rows (telemetry)


def plan_active(index: ActiveIndex, pt, mirror: np.ndarray, padded_S: int,
                T: int, pending_rows: np.ndarray,
                cfg: Optional[SubsolveConfig] = None,
                G_full: int = 1 << 30, Gc_full: int = 1 << 30
                ) -> tuple[Optional[ActivePlan], str]:
    """Build the localized sub-problem for the churn accumulated since
    the last solve. Returns (plan, outcome): plan None means the caller
    runs the full fused path, with `outcome` saying why (counted into
    fleet_solver_subsolve_total by the caller for fallbacks; "localized"
    is counted after the gate accepts).

    `mirror` is the host copy of the resident PADDED assignment as of the
    previous solve (phantom re-parks replayed); `pending_rows` the rows
    churn deltas touched (arrivals, tombstones, demand/eligibility
    drift, rows on capacity-shrunk nodes). Stranded rows (previous node
    now invalid or ineligible) are recomputed here from the post-delta
    tensors, so killed nodes need no separate bookkeeping."""
    cfg = cfg or subsolve_config()
    S = pt.S                         # real rows of the post-delta problem
    prev = mirror[:S]
    elig = np.asarray(pt.eligible)
    stranded = np.nonzero(~(np.asarray(pt.node_valid)[prev]
                            & elig[np.arange(S), prev]))[0]
    affected = np.unique(np.concatenate(
        [np.asarray(pending_rows, dtype=np.int64), stranded]))
    affected = affected[affected < S]
    if not affected.size:
        # nothing moved and nothing is stranded: the fused path's
        # 0-sweep exit is already optimal, and a 0-row sub-problem would
        # only add a gate pass
        return None, "fallback_small"
    rows = index.closure(affected)
    rows = rows[rows < S]
    k = int(rows.size)
    if k > max(cfg.frac * S, 1):
        return None, "fallback_closure"
    tier = subsolve_tier(k, minimum=cfg.min_tier, maximum=cfg.max_tier)
    if tier == 0:
        return None, "fallback_closure"
    if tier >= S:
        return None, "fallback_small"

    N = pt.N
    R = np.asarray(pt.demand).shape[1]
    in_sub = np.zeros(max(index.S, S), dtype=bool)
    in_sub[rows] = True

    # compact id spaces: only ids carried by closure rows exist in the
    # sub-problem; frozen carriers of those ids enter as base occupancy
    inside = rows[rows < index.S]
    conf_rows = (index.conflict[inside] if inside.size
                 else np.empty((0, index.conflict.shape[1]), np.int32))
    coloc_rows = (index.coloc[inside] if inside.size
                  else np.empty((0, index.coloc.shape[1]), np.int32))
    conf_ids = np.unique(conf_rows[conf_rows >= 0])
    coloc_ids = np.unique(coloc_rows[coloc_rows >= 0])
    # id-space sizes are pinned to the TIER (and the staging's full
    # G/Gc), NOT the closure content: a content-derived ladder recompiled
    # the mini executable whenever burst-to-burst id counts crossed a
    # step (measured: two ~1.4 s compiles inside a 16-burst churn loop).
    # One tier == one executable; a closure denser in ids than the tier
    # can hold is a (counted) fallback, not a compile
    G_sub = max(min(tier, G_full), 16)
    Gc_sub = 0 if Gc_full == 0 else max(min(tier // 4, Gc_full), 4)
    if len(conf_ids) > G_sub or len(coloc_ids) > Gc_sub:
        return None, "fallback_closure"

    Kc = width_bucket(index.conflict.shape[1], 4)
    Cc = width_bucket(index.coloc.shape[1], 4)
    sub_conflict = np.full((tier, Kc), -1, dtype=np.int32)
    sub_coloc = np.full((tier, Cc), -1, dtype=np.int32)
    if inside.size:
        remap = np.where(conf_rows >= 0,
                         np.searchsorted(conf_ids,
                                         np.where(conf_rows >= 0,
                                                  conf_rows, 0)), -1)
        at = np.nonzero(rows < index.S)[0]
        sub_conflict[at, :conf_rows.shape[1]] = remap
        if len(coloc_ids):
            cremap = np.where(coloc_rows >= 0,
                              np.searchsorted(coloc_ids,
                                              np.where(coloc_rows >= 0,
                                                       coloc_rows, 0)), -1)
            sub_coloc[at, :coloc_rows.shape[1]] = cremap

    # frozen remainder: load / occupancy / topology of every untouched
    # real row at its mirror node — the capacity debit and the exact
    # cross-boundary conflict/coloc/skew accounting in one state seed
    frozen = np.nonzero(~in_sub[:S])[0]
    load0 = np.zeros((N, R), dtype=np.float32)
    np.add.at(load0, prev[frozen],
              np.asarray(pt.demand, dtype=np.float32)[frozen])
    used0 = np.zeros((N, G_sub), dtype=np.int32)
    used0[:, : max(len(conf_ids), 1)] = index.frozen_occupancy(
        conf_ids, index._conf_inv, prev, in_sub, N) \
        if len(conf_ids) else 0
    coloc0 = np.zeros((N, max(Gc_sub, 1)), dtype=np.int32)
    if len(coloc_ids):
        coloc0[:, : len(coloc_ids)] = index.frozen_occupancy(
            coloc_ids, index._coloc_inv, prev, in_sub, N)
    topo0 = np.bincount(np.asarray(pt.node_topology)[prev[frozen]],
                        minlength=T).astype(np.int32)

    padded_rows = np.full(tier, padded_S, dtype=np.int32)
    padded_rows[:k] = rows            # ascending: prologue order matches
    plan = ActivePlan(
        rows=padded_rows, n_sub=k, tier=tier, G_sub=G_sub, Gc_sub=Gc_sub,
        sub_conflict=sub_conflict, sub_coloc=sub_coloc, load0=load0,
        used0=used0, coloc0=coloc0, topo0=topo0, affected=int(affected.size))
    log.debug("active-set plan %s", kv(affected=plan.affected, closure=k,
                                       tier=tier, G=G_sub, Gc=Gc_sub))
    return plan, "planned"


@lru_cache(maxsize=1)
def _subsolve_fn():
    """The localized gather -> mini-anneal -> scatter -> exact-gate
    kernel, built lazily (importing the planner never pays JAX startup).
    The resident assignment is read, not donated — see the scatter note
    in the kernel body for why the input must outlive the dispatch."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from .anneal import (anneal_adaptive_states, chain_states_from_assignment,
                         prerepair_state_counted)
    from .kernels import exact_stats_and_soft
    from .problem import DeviceProblem

    def subsolve(prob, assignment, rows, sub_conflict, sub_coloc, load0,
                 used0, coloc0, topo0, n_sub, key, t0, t1,
                 migration_weight, *, chains, steps, block,
                 proposals_per_step, prerepair_moves, Gc_sub,
                 trace_blocks=0):
        S_sub = rows.shape[0]
        rows_g = jnp.minimum(rows, prob.S - 1)   # clamp-safe gather index
        real = jnp.arange(S_sub) < n_sub
        demand_sub = jnp.where(real[:, None], prob.demand[rows_g], 0.0)
        if prob.eligible.dtype == jnp.uint32:
            elig_fill = jnp.uint32(0xFFFFFFFF)
        else:
            elig_fill = jnp.asarray(True)
        eligible_sub = jnp.where(real[:, None], prob.eligible[rows_g],
                                 elig_fill)
        pref_sub = None
        if prob.preferred is not None:
            pref_sub = jnp.where(real[:, None], prob.preferred[rows_g], 0.0)
        # phantom sub rows park on a valid node (inert: zero demand, no
        # ids, eligible everywhere — the bucket-phantom construction)
        park = jnp.argmax(prob.node_valid).astype(jnp.int32)
        seed_sub = jnp.where(real, assignment[rows_g], park).astype(jnp.int32)
        sub = DeviceProblem(
            demand=demand_sub, capacity=prob.capacity,
            conflict_ids=sub_conflict, coloc_ids=sub_coloc,
            eligible=eligible_sub, node_valid=prob.node_valid,
            node_topology=prob.node_topology,
            S=S_sub, N=prob.N, G=used0.shape[1], Gc=Gc_sub, T=prob.T,
            strategy=prob.strategy, max_skew=prob.max_skew,
            preferred=pref_sub, n_real=n_sub)
        # warm stickiness rides the sub proposal delta exactly as on the
        # full path: staying on the previous still-eligible node earns
        # migration_weight; churn-forced moves stay free
        sub_a = dataclasses.replace(
            sub, sticky_prev=seed_sub,
            sticky_w=jnp.asarray(migration_weight, jnp.float32))
        st0 = chain_states_from_assignment(
            sub_a, seed_sub, base=(load0, used0, coloc0, topo0))
        st0, prerepair_applied = prerepair_state_counted(
            sub_a, st0, prerepair_moves)
        init_states = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (chains,) + x.shape), st0)
        inits = jnp.broadcast_to(st0.assignment[None], (chains, S_sub))
        (best_assign_c, best_viol_c, best_soft_c, sweeps_run, accepted_c,
         telem) = anneal_adaptive_states(
                sub_a, inits, key, max_steps=steps, block=block,
                t0=t0, t1=t1, proposals_per_step=proposals_per_step,
                init_states=init_states, exit_on_feasible_init=True,
                trace_blocks=trace_blocks)
        accepted = accepted_c.sum()
        telem = dict(telem, prerepair_moves=prerepair_applied)
        # same lexicographic (violations, soft) rank as the full pipeline
        min_viol = best_viol_c.min()
        best = jnp.argmin(jnp.where(best_viol_c == min_viol,
                                    best_soft_c, jnp.inf))
        winner = best_assign_c[best]
        # scatter the accepted rows back into a FRESH assignment buffer;
        # pad slots carry prob.S and are dropped. The input is
        # deliberately NOT donated: (a) a gate-rejected sub-solve must
        # re-run the full fused path from the ORIGINAL seed — stranded
        # rows intact, the battle-tested prerepair path — so the old
        # buffer has to survive; (b) an (S,) i32 copy is ~40 KB at fleet
        # scale, noise next to the planes the merge kernel's donation
        # exists for; and (c) a donated-aliased executable of THIS kernel
        # deserialized from the persistent XLA compile cache corrupted
        # the output buffer (garbage node indices) — observed on
        # jax 0.4.x CPU, BENCH r09 bring-up
        new_assignment = assignment.at[rows].set(winner, mode="drop")
        # the acceptance gate: exact full-problem stats of the scattered
        # result — whatever the mini anneal believed, THIS decides
        stats, soft = exact_stats_and_soft(prob, new_assignment)
        return new_assignment, stats, soft, sweeps_run, accepted, telem

    return jax.jit(subsolve,
                   static_argnames=("chains", "steps", "block",
                                    "proposals_per_step",
                                    "prerepair_moves", "Gc_sub",
                                    "trace_blocks"))


def subsolve_cache_size() -> int:
    """Compiled-variant count of the localized kernel (compile-event
    telemetry: a new mini tier or id-ladder step is a compile)."""
    try:
        return _subsolve_fn()._cache_size()
    except Exception:                               # pragma: no cover
        return 0


def stage_subsolve(resident, plan: ActivePlan):
    """Device-stage a plan's small arrays (host -> device, BEFORE the
    transfer guard arms — the same discipline as the delta merge's
    uploads). Returns the positional args following (prob, assignment)."""
    import jax.numpy as jnp

    uploads = resident._put_small(
        (plan.rows, plan.sub_conflict, plan.sub_coloc, plan.load0,
         plan.used0, plan.coloc0, plan.topo0))
    return (*uploads, jnp.asarray(plan.n_sub, jnp.int32))


SUB_MAX_STEPS = 16   # mini-anneal sweep budget: a feasible closure exits
# in 0-2 sweeps (prerepair + targeted proposals over a tiny plane); one
# that hasn't converged by 16 is closure-starved and should bail to the
# full path instead of burning a full-problem budget on a lost cause


def subsolve_dispatch(prob, assignment, staged, plan: ActivePlan, key,
                      t0, t1, migration_weight, *, chains: int, steps: int,
                      block: int, proposals_per_step: int,
                      trace_blocks: int = 0):
    """Run the localized kernel (call under the transfer guard: every
    argument is already resident). Returns the device outputs
    (new_assignment, stats, soft, sweeps_run, accepted, telemetry)."""
    prerepair_moves = max(16, min(plan.tier, 256))
    _M_SUB_ROWS.set(plan.n_sub)
    _M_SUB_TIER.set(plan.tier)
    return _subsolve_fn()(
        prob, assignment, *staged, key, t0, t1, migration_weight,
        chains=chains, steps=min(steps, SUB_MAX_STEPS), block=block,
        proposals_per_step=proposals_per_step,
        prerepair_moves=prerepair_moves, Gc_sub=plan.Gc_sub,
        trace_blocks=trace_blocks)


def record_subsolve_ms(ms: float) -> None:
    _M_SUB_MS.observe(ms)
