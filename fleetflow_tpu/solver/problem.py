"""Device-resident problem representation.

Converts host ProblemTensors (numpy) into a pytree of jnp arrays shaped for
the solver kernels, staged onto the device once and reused across re-solves
(SURVEY.md section 7 hard part (d): keep host↔device transfers out of the
per-reschedule path).

Key transformation: the three anti-affinity families (host ports, exclusive
volumes, explicit anti-affinity groups) are unified into ONE conflict-id
space — a service carries up to K conflict ids (padded -1); two services
conflict iff they share any id and land on the same node. This keeps the
hot kernels free of per-family branching and avoids any S×S matrix: conflict
rows are computed on the fly from the (S, K) id table, so 10k×1k fits easily
in HBM (SURVEY.md hard part (b)).
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.model import PlacementStrategy
from ..lower.tensors import ProblemTensors
from ..obs.metrics import REGISTRY

__all__ = ["DeviceProblem", "STRATEGY_CODES", "prepare_problem",
           "PLANE_PACK", "packed_width", "packed_enabled", "pack_bool_rows",
           "eligible_lookup", "eligible_row", "eligible_rows",
           "record_plane_bytes"]

STRATEGY_CODES = {
    PlacementStrategy.SPREAD_ACROSS_POOL: 0,
    PlacementStrategy.PACK_INTO_DEDICATED: 1,
    PlacementStrategy.FILL_LOWEST: 2,
}

# -- packed problem planes ---------------------------------------------------
# The two dense (S, N) planes dominate problem memory AND the anneal's
# sweep bandwidth (~4.7 GiB at 100k x 10k; anneal_ms ~13 of 14.6 ms at
# 10k x 1k was plane reads, BENCH_r07_dev). The packed layout attacks both:
#
#   eligible   bit-packed (S, ceil(N/32)) uint32 — one bit per node, 8x
#              fewer bytes than the dense bool plane; the kernels unpack
#              with a shift/mask at each gather site (cheap ALU vs.
#              streamed bytes on both TPU and CPU)
#   preferred  ABSENT from the pytree (None) when no service scores nodes,
#              instead of a materialized 4*S*N zero plane every sweep then
#              streams; `prob.preferred is None` is a static treedef fact,
#              so each layout compiles its own executable variant
#
# Every eligibility read goes through eligible_lookup/eligible_row(s) below,
# which dispatch on dtype — the dense bool layout stays supported (the
# FLEET_PACKED=0 A/B and the packed-vs-unpacked parity property tests), but
# production staging is packed and `fleet audit kernels` pins the dtype so
# a dense (S, N) plane cannot silently reappear in a hot-path executable.

PLANE_PACK = 32  # bits per packed eligibility word


def packed_width(n: int) -> int:
    """Words per packed eligibility row: ceil(n / 32)."""
    return -(-max(int(n), 1) // PLANE_PACK)


def packed_enabled(default: bool = True) -> bool:
    """FLEET_PACKED gate (default on): bit-packed eligible plane + absent
    preferred plane at staging time."""
    v = os.environ.get("FLEET_PACKED", "").strip().lower()
    if not v:
        return default
    return v not in ("0", "false", "off", "no")


def pack_bool_rows(mask: np.ndarray) -> np.ndarray:
    """Host pack: (..., N) bool -> (..., ceil(N/32)) uint32, little-endian
    bit order (bit j of word w is column 32*w + j). Trailing pad bits of
    the last word are SET — never read (gathers index columns < N), and
    the all-ones convention makes the representation canonical: an
    all-True row packs to the same words as the staging arenas' constant
    0xFFFFFFFF fill, so bit-identical-tensor checks across staging paths
    stay meaningful."""
    mask = np.ascontiguousarray(np.asarray(mask, dtype=bool))
    N = mask.shape[-1]
    W = packed_width(N)
    b = np.packbits(mask, axis=-1, bitorder="little")
    pad = W * 4 - b.shape[-1]
    if pad:
        b = np.concatenate(
            [b, np.full(b.shape[:-1] + (pad,), 0xFF, np.uint8)], axis=-1)
    out = np.ascontiguousarray(b).view(np.uint32)
    rem = N % PLANE_PACK
    if rem:
        out[..., -1] |= np.uint32((0xFFFFFFFF << rem) & 0xFFFFFFFF)
    return out


def eligible_lookup(eligible: jax.Array, s, node) -> jax.Array:
    """eligible[s, node] as bool, for either plane layout: dense (S, N)
    bool, or bit-packed (S, ceil(N/32)) uint32 unpacked with shift/mask at
    the gather site. `s`/`node` broadcast like fancy indices."""
    if eligible.dtype != jnp.uint32:
        return eligible[s, node]
    node = jnp.asarray(node)
    word = eligible[s, node >> 5]
    return ((word >> (node & 31).astype(jnp.uint32))
            & jnp.uint32(1)).astype(bool)


def eligible_row(eligible: jax.Array, s, N: int) -> jax.Array:
    """One service's full (N,) eligibility row (dense or unpacked)."""
    if eligible.dtype != jnp.uint32:
        return eligible[s]
    cols = jnp.arange(N, dtype=jnp.int32)
    return eligible_lookup(eligible, s, cols)


def eligible_rows(eligible: jax.Array, svc: jax.Array, N: int) -> jax.Array:
    """(M, N) eligibility rows for a batch of services (dense or unpacked)."""
    if eligible.dtype != jnp.uint32:
        return eligible[svc]
    cols = jnp.arange(N, dtype=jnp.int32)
    return eligible_lookup(eligible, svc[:, None], cols[None, :])


# metric catalog: docs/guide/10-observability.md
_M_PLANE_BYTES = REGISTRY.gauge(
    "fleet_solver_plane_bytes",
    "Device bytes of the most recent staging's dense (S, N) problem "
    "planes, by plane and layout (packed=\"true\" = bit-packed eligibility "
    "/ absent preferred plane)",
    labels=("plane", "packed"))


def record_plane_bytes(prob: "DeviceProblem") -> None:
    """Report the staged plane footprint (solver/problem.py packed layout):
    what the memory math of docs/guide/11-performance.md claims, read off
    the actual staging."""
    e = prob.eligible
    _M_PLANE_BYTES.set(float(e.size) * e.dtype.itemsize, plane="eligible",
                       packed="true" if e.dtype == jnp.uint32 else "false")
    if prob.preferred is None:
        _M_PLANE_BYTES.set(0.0, plane="preferred", packed="true")
    else:
        p = prob.preferred
        _M_PLANE_BYTES.set(float(p.size) * p.dtype.itemsize,
                           plane="preferred", packed="false")


@jax.tree_util.register_dataclass
@dataclass
class DeviceProblem:
    """Pytree of device arrays + static metadata for the solver kernels."""
    demand: jax.Array          # (S, R) f32
    capacity: jax.Array        # (N, R) f32
    conflict_ids: jax.Array    # (S, K) i32, -1 pad (ports ∪ volumes ∪ anti)
    coloc_ids: jax.Array       # (S, C) i32, -1 pad
    # bit-packed (S, ceil(N/32)) uint32 (production layout; read through
    # eligible_lookup/eligible_row) or dense (S, N) bool (FLEET_PACKED=0)
    eligible: jax.Array
    node_valid: jax.Array      # (N,) bool
    node_topology: jax.Array   # (N,) i32 in [0, T)
    # static (not traced)
    S: int = field(metadata=dict(static=True))
    N: int = field(metadata=dict(static=True))
    G: int = field(metadata=dict(static=True))   # number of conflict ids
    Gc: int = field(metadata=dict(static=True))  # number of coloc ids (0 = none)
    T: int = field(metadata=dict(static=True))   # number of topology domains
    strategy: int = field(metadata=dict(static=True))
    max_skew: int = field(metadata=dict(static=True))
    # (S, N) f32 soft preference plane, or None when NO service scores
    # nodes — absent by design, not an all-zero plane every sweep then
    # streams (4*S*N bytes). Absence is a static treedef fact (`preferred
    # is None` == the has_preferred flag), so each layout is its own
    # compiled executable variant.
    preferred: Optional[jax.Array] = None
    # TRACED count of real (non-phantom) service rows, or None when every
    # row is real. Rows >= n_real are bucket-padding phantoms; the kernels
    # exclude them from topology/skew accounting (the sharded path threads
    # the same mask as a static `n_real` arg). Traced — not static — so a
    # fleet drifting 9,997 -> 10,050 inside one tier does NOT recompile.
    n_real: Optional[jax.Array] = None
    # warm-start migration stickiness, folded into the proposal delta and
    # the soft ranking ON THE FLY instead of materializing a bonused
    # (S, N) preferred plane (three full-plane passes, ~37 ms of the r05
    # warm dispatch at 10k x 1k). sticky_prev is the previous assignment
    # (S,) i32; sticky_w the per-service bonus (f32 scalar). The bonus
    # only anchors services whose previous node is still eligible+valid —
    # churn-forced moves stay free, same semantics as the old plane.
    sticky_prev: Optional[jax.Array] = None
    sticky_w: Optional[jax.Array] = None

    @property
    def has_preferred(self) -> bool:
        """Static: does a preference plane exist at all? (The absent-plane
        half of the packed layout — mirrors the merge kernel's
        has_demand/has_eligible static delta flags.)"""
        return self.preferred is not None

    @property
    def eligible_packed(self) -> bool:
        """Static: is the eligibility plane bit-packed uint32?"""
        return self.eligible.dtype == jnp.uint32


def _unify_conflict_ids(pt: ProblemTensors) -> np.ndarray:
    """Concatenate the three id families into one id space, compacting out
    unused slots per row."""
    parts = []
    offset = 0
    for arr in (pt.port_ids, pt.volume_ids, pt.anti_ids):
        shifted = np.where(arr >= 0, arr + offset, -1)
        if arr.size:
            offset += int(arr.max(initial=-1)) + 1
        parts.append(shifted)
    merged = np.concatenate(parts, axis=1)
    # dedupe within each row (a repeated id on one service is one constraint,
    # not a self-conflict): sort descending, blank repeats, then trim all-pad
    # columns
    merged = -np.sort(-merged, axis=1)
    dup = np.zeros_like(merged, dtype=bool)
    dup[:, 1:] = (merged[:, 1:] == merged[:, :-1]) & (merged[:, 1:] >= 0)
    merged = np.where(dup, -1, merged)
    merged = -np.sort(-merged, axis=1)
    keep = int((merged >= 0).sum(axis=1).max(initial=1))
    return merged[:, : max(keep, 1)].astype(np.int32)


def prepare_problem(pt: ProblemTensors,
                    device: Optional[Any] = None,
                    packed: Optional[bool] = None) -> DeviceProblem:
    """Stage a ProblemTensors onto the device (or default backend).

    `packed=None` defers to FLEET_PACKED (default on): the eligibility
    plane stages bit-packed uint32 and an absent preference stays absent
    (no zero plane); `packed=False` is the legacy dense layout, kept for
    the packed-vs-unpacked parity property tests and A/B debugging."""
    if packed is None:
        packed = packed_enabled()
    conflict = _unify_conflict_ids(pt)
    G = int(conflict.max(initial=-1)) + 1
    T = int(pt.node_topology.max(initial=0)) + 1

    put = partial(jax.device_put, device=device)
    # Degenerate (S, N) planes are common: no placement preferences -> no
    # `preferred` plane at all (packed) or an all-zero one (dense), no
    # eligibility restrictions -> an all-True `eligible`. On accelerators,
    # materialize constant planes as on-device XLA fills instead of
    # host->device uploads — over the axon tunnel (~12 MB/s measured r5)
    # uploading constant planes is seconds of pure waste per staging. On
    # CPU the "upload" is a memcpy while the fill pays a shape-specific
    # compile (~70 ms measured in the pipeline leg), so fills are
    # accelerator-only. Keyed on the platform the arrays actually land on —
    # an explicit `device` can differ from the default backend.
    use_fills = (device.platform if device is not None
                 else jax.default_backend()) != "cpu"
    fill_ctx = (jax.default_device(device) if device is not None
                else contextlib.nullcontext())
    with fill_ctx:
        if pt.preferred is None:
            preferred_arr = (None if packed else
                             (jnp.zeros((pt.S, pt.N), dtype=jnp.float32)
                              if use_fills else
                              put(np.zeros((pt.S, pt.N), dtype=np.float32))))
        else:
            preferred_arr = put(jnp.asarray(pt.preferred, dtype=jnp.float32))
        eligible_np = np.asarray(pt.eligible)
        all_eligible = bool(eligible_np.all())
        if packed:
            W = packed_width(pt.N)
            if use_fills and all_eligible:
                # all-ones fill: pad bits of the last word are set but
                # never read (gathers index columns < N only)
                eligible_arr = jnp.full((pt.S, W), np.uint32(0xFFFFFFFF),
                                        dtype=jnp.uint32)
            else:
                eligible_arr = put(pack_bool_rows(eligible_np))
        elif use_fills and all_eligible:
            eligible_arr = jnp.ones((pt.S, pt.N), dtype=bool)
        else:
            eligible_arr = put(jnp.asarray(pt.eligible))
    prob = DeviceProblem(
        demand=put(jnp.asarray(pt.demand, dtype=jnp.float32)),
        capacity=put(jnp.asarray(pt.capacity, dtype=jnp.float32)),
        conflict_ids=put(jnp.asarray(conflict)),
        coloc_ids=put(jnp.asarray(pt.coloc_ids, dtype=jnp.int32)),
        eligible=eligible_arr,
        node_valid=put(jnp.asarray(pt.node_valid)),
        node_topology=put(jnp.asarray(pt.node_topology, dtype=jnp.int32)),
        preferred=preferred_arr,
        S=pt.S, N=pt.N, G=max(G, 1),
        Gc=int(pt.coloc_ids.max(initial=-1)) + 1,
        T=T,
        strategy=STRATEGY_CODES[pt.strategy],
        max_skew=int(pt.max_skew),
    )
    record_plane_bytes(prob)
    return prob
