"""Device-resident problem representation.

Converts host ProblemTensors (numpy) into a pytree of jnp arrays shaped for
the solver kernels, staged onto the device once and reused across re-solves
(SURVEY.md section 7 hard part (d): keep host↔device transfers out of the
per-reschedule path).

Key transformation: the three anti-affinity families (host ports, exclusive
volumes, explicit anti-affinity groups) are unified into ONE conflict-id
space — a service carries up to K conflict ids (padded -1); two services
conflict iff they share any id and land on the same node. This keeps the
hot kernels free of per-family branching and avoids any S×S matrix: conflict
rows are computed on the fly from the (S, K) id table, so 10k×1k fits easily
in HBM (SURVEY.md hard part (b)).
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.model import PlacementStrategy
from ..lower.tensors import ProblemTensors

__all__ = ["DeviceProblem", "STRATEGY_CODES", "prepare_problem"]

STRATEGY_CODES = {
    PlacementStrategy.SPREAD_ACROSS_POOL: 0,
    PlacementStrategy.PACK_INTO_DEDICATED: 1,
    PlacementStrategy.FILL_LOWEST: 2,
}


@jax.tree_util.register_dataclass
@dataclass
class DeviceProblem:
    """Pytree of device arrays + static metadata for the solver kernels."""
    demand: jax.Array          # (S, R) f32
    capacity: jax.Array        # (N, R) f32
    conflict_ids: jax.Array    # (S, K) i32, -1 pad (ports ∪ volumes ∪ anti)
    coloc_ids: jax.Array       # (S, C) i32, -1 pad
    eligible: jax.Array        # (S, N) bool
    node_valid: jax.Array      # (N,) bool
    node_topology: jax.Array   # (N,) i32 in [0, T)
    preferred: jax.Array       # (S, N) f32 (zeros when unused)
    # static (not traced)
    S: int = field(metadata=dict(static=True))
    N: int = field(metadata=dict(static=True))
    G: int = field(metadata=dict(static=True))   # number of conflict ids
    Gc: int = field(metadata=dict(static=True))  # number of coloc ids (0 = none)
    T: int = field(metadata=dict(static=True))   # number of topology domains
    strategy: int = field(metadata=dict(static=True))
    max_skew: int = field(metadata=dict(static=True))
    # TRACED count of real (non-phantom) service rows, or None when every
    # row is real. Rows >= n_real are bucket-padding phantoms; the kernels
    # exclude them from topology/skew accounting (the sharded path threads
    # the same mask as a static `n_real` arg). Traced — not static — so a
    # fleet drifting 9,997 -> 10,050 inside one tier does NOT recompile.
    n_real: Optional[jax.Array] = None
    # warm-start migration stickiness, folded into the proposal delta and
    # the soft ranking ON THE FLY instead of materializing a bonused
    # (S, N) preferred plane (three full-plane passes, ~37 ms of the r05
    # warm dispatch at 10k x 1k). sticky_prev is the previous assignment
    # (S,) i32; sticky_w the per-service bonus (f32 scalar). The bonus
    # only anchors services whose previous node is still eligible+valid —
    # churn-forced moves stay free, same semantics as the old plane.
    sticky_prev: Optional[jax.Array] = None
    sticky_w: Optional[jax.Array] = None


def _unify_conflict_ids(pt: ProblemTensors) -> np.ndarray:
    """Concatenate the three id families into one id space, compacting out
    unused slots per row."""
    parts = []
    offset = 0
    for arr in (pt.port_ids, pt.volume_ids, pt.anti_ids):
        shifted = np.where(arr >= 0, arr + offset, -1)
        if arr.size:
            offset += int(arr.max(initial=-1)) + 1
        parts.append(shifted)
    merged = np.concatenate(parts, axis=1)
    # dedupe within each row (a repeated id on one service is one constraint,
    # not a self-conflict): sort descending, blank repeats, then trim all-pad
    # columns
    merged = -np.sort(-merged, axis=1)
    dup = np.zeros_like(merged, dtype=bool)
    dup[:, 1:] = (merged[:, 1:] == merged[:, :-1]) & (merged[:, 1:] >= 0)
    merged = np.where(dup, -1, merged)
    merged = -np.sort(-merged, axis=1)
    keep = int((merged >= 0).sum(axis=1).max(initial=1))
    return merged[:, : max(keep, 1)].astype(np.int32)


def prepare_problem(pt: ProblemTensors,
                    device: Optional[Any] = None) -> DeviceProblem:
    """Stage a ProblemTensors onto the device (or default backend)."""
    conflict = _unify_conflict_ids(pt)
    G = int(conflict.max(initial=-1)) + 1
    T = int(pt.node_topology.max(initial=0)) + 1

    put = partial(jax.device_put, device=device)
    # The two dense (S, N) planes dominate staging bytes (50 MB at 10k x 1k)
    # and the degenerate cases are common: no placement preferences -> an
    # all-zero `preferred`, no eligibility restrictions -> an all-True
    # `eligible`.  On accelerators, materialize those as on-device XLA
    # fills instead of host->device uploads — over the axon tunnel
    # (~12 MB/s measured r5) uploading constant planes is seconds of pure
    # waste per staging.  On CPU the "upload" is a memcpy (~10 ms) while
    # the fill pays a shape-specific compile (~70 ms measured in the
    # pipeline leg), so the fills are accelerator-only.
    # keyed on the platform the arrays actually land on — an explicit
    # `device` can differ from the default backend in either direction
    use_fills = (device.platform if device is not None
                 else jax.default_backend()) != "cpu"
    fill_ctx = (jax.default_device(device) if device is not None
                else contextlib.nullcontext())
    with fill_ctx:
        if pt.preferred is None:
            preferred_arr = (jnp.zeros((pt.S, pt.N), dtype=jnp.float32)
                             if use_fills else
                             put(np.zeros((pt.S, pt.N), dtype=np.float32)))
        else:
            preferred_arr = put(jnp.asarray(pt.preferred, dtype=jnp.float32))
        eligible_np = np.asarray(pt.eligible)
        if use_fills and eligible_np.all():
            eligible_arr = jnp.ones((pt.S, pt.N), dtype=bool)
        else:
            eligible_arr = put(jnp.asarray(pt.eligible))
    return DeviceProblem(
        demand=put(jnp.asarray(pt.demand, dtype=jnp.float32)),
        capacity=put(jnp.asarray(pt.capacity, dtype=jnp.float32)),
        conflict_ids=put(jnp.asarray(conflict)),
        coloc_ids=put(jnp.asarray(pt.coloc_ids, dtype=jnp.int32)),
        eligible=eligible_arr,
        node_valid=put(jnp.asarray(pt.node_valid)),
        node_topology=put(jnp.asarray(pt.node_topology, dtype=jnp.int32)),
        preferred=preferred_arr,
        S=pt.S, N=pt.N, G=max(G, 1),
        Gc=int(pt.coloc_ids.max(initial=-1)) + 1,
        T=T,
        strategy=STRATEGY_CODES[pt.strategy],
        max_skew=int(pt.max_skew),
    )
