"""Core solver kernels: feasibility, violation accounting, scoring.

These are the vmapped/jitted kernels the north star prescribes (BASELINE.json:
"a vmapped feasibility/scoring kernel"). All take a dense assignment vector
``assignment: (S,) int32`` (service → node) and the staged DeviceProblem, and
are pure — differentiable where meaningful, jit/vmap/shard_map friendly
everywhere (static shapes, no data-dependent control flow).

Violation semantics (the "zero constraint violations" contract):
  - capacity:   count of (node, resource) cells where load exceeds capacity
  - conflicts:  count of same-node pairs sharing a conflict id (host ports,
                exclusive volumes, explicit anti-affinity — unified id space)
  - eligibility: count of services placed on ineligible or invalid nodes
  - skew:       excess of (max - min) services per topology domain over
                max_skew, when a spread constraint is active

Soft score (lower is better) encodes the reference's placement strategies
(control-plane model.rs:68-75): spread_across_pool minimizes squared
utilization (load balancing), pack_into_dedicated maximizes it (bin
consolidation), fill_lowest prefers low node indices; plus preferred-label
affinity and colocation rewards.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .problem import DeviceProblem, eligible_lookup

__all__ = ["node_loads", "group_counts", "violation_stats", "total_violations",
           "soft_score", "total_cost", "exact_stats_and_soft",
           "real_row_weights", "W_HARD"]

W_HARD = 1e4  # weight of one hard violation vs the soft score range


def node_loads(prob: DeviceProblem, assignment: jax.Array) -> jax.Array:
    """(N, R) resource load per node under `assignment`."""
    return jnp.zeros((prob.N, prob.demand.shape[1]),
                     dtype=jnp.float32).at[assignment].add(prob.demand)


def group_counts(prob: DeviceProblem, assignment: jax.Array,
                 ids: jax.Array, G: int) -> jax.Array:
    """(N, G) count of services per (node, group-id). Padded (-1) slots are
    routed to id 0 with weight 0."""
    valid = ids >= 0
    safe_ids = jnp.where(valid, ids, 0)
    nodes = jnp.broadcast_to(assignment[:, None], ids.shape)
    return jnp.zeros((prob.N, G), dtype=jnp.int32).at[
        nodes, safe_ids].add(valid.astype(jnp.int32))


def _conflict_pairs(counts: jax.Array) -> jax.Array:
    """Sum over cells of C(count, 2) — number of conflicting same-node pairs."""
    c = counts.astype(jnp.float32)
    return (c * (c - 1.0) / 2.0).sum()


def real_row_weights(prob: DeviceProblem) -> jax.Array:
    """(S,) int32: 1 for real service rows, 0 for bucket-padding phantoms
    (rows >= prob.n_real). All-ones when the problem carries no phantom
    marker — the common exact-shape case pays nothing."""
    if prob.n_real is None:
        return jnp.ones(prob.S, dtype=jnp.int32)
    return (jnp.arange(prob.S) < prob.n_real).astype(jnp.int32)


def _skew_excess(prob: DeviceProblem, assignment: jax.Array) -> jax.Array:
    """relu((max - min services per topology domain) - max_skew); 0 when no
    spread constraint is active. Phantom rows carry no topology weight (a
    parked phantom must not relax or tighten a spread constraint)."""
    if prob.max_skew <= 0:
        return jnp.float32(0.0)
    topo = prob.node_topology[assignment]                       # (S,)
    per_domain = jnp.zeros(prob.T, dtype=jnp.int32).at[topo].add(
        real_row_weights(prob))
    skew = per_domain.max() - per_domain.min()
    return jnp.maximum(skew - prob.max_skew, 0).astype(jnp.float32)


@partial(jax.jit, static_argnames=())
def violation_stats(prob: DeviceProblem, assignment: jax.Array) -> dict:
    """Exact hard-violation accounting. Returns float32 scalars."""
    load = node_loads(prob, assignment)
    cap_cells = (load > prob.capacity * (1 + 1e-6)).sum().astype(jnp.float32)

    counts = group_counts(prob, assignment, prob.conflict_ids, prob.G)
    conflict_pairs = _conflict_pairs(counts)

    inelig = (~eligible_lookup(prob.eligible, jnp.arange(prob.S),
                               assignment)).sum()
    invalid = (~prob.node_valid[assignment]).sum()
    elig = (inelig + invalid).astype(jnp.float32)

    skew = _skew_excess(prob, assignment)
    return {
        "capacity": cap_cells,
        "conflicts": conflict_pairs,
        "eligibility": elig,
        "skew": skew,
        "total": cap_cells + conflict_pairs + elig + skew,
    }


def total_violations(prob: DeviceProblem, assignment: jax.Array) -> jax.Array:
    return violation_stats(prob, assignment)["total"]


def _utilization_sq(prob: DeviceProblem, load: jax.Array) -> jax.Array:
    u = load / jnp.maximum(prob.capacity, 1e-6)
    return (u * u).sum()


def soft_score(prob: DeviceProblem, assignment: jax.Array) -> jax.Array:
    """Strategy-dependent soft objective; lower is better. Bounded so W_HARD
    dominates any soft gradient."""
    load = node_loads(prob, assignment)
    usq = _utilization_sq(prob, load)
    denom = jnp.float32(max(prob.N, 1))
    if prob.strategy == 0:        # spread_across_pool: balance load
        strat = usq / denom
    elif prob.strategy == 1:      # pack_into_dedicated: concentrate load
        strat = -usq / denom
    else:                         # fill_lowest: prefer low node indices
        strat = (assignment.astype(jnp.float32) / denom).mean()

    if prob.preferred is None:
        pref = jnp.float32(0.0)   # absent plane: no zeros to stream
    else:
        pref = -prob.preferred[jnp.arange(prob.S), assignment].mean()

    # colocation reward: pairs sharing a coloc id on the same node
    if prob.Gc > 0:
        ccounts = group_counts(prob, assignment, prob.coloc_ids, prob.Gc)
        coloc = -_conflict_pairs(ccounts) / jnp.float32(max(prob.S, 1))
    else:
        coloc = jnp.float32(0.0)
    return strat + pref + coloc


def total_cost(prob: DeviceProblem, assignment: jax.Array) -> jax.Array:
    """Hard violations (dominant) + soft score: the annealing objective."""
    return W_HARD * total_violations(prob, assignment) + soft_score(prob, assignment)


def exact_stats_and_soft(prob: DeviceProblem,
                         assignment: jax.Array) -> tuple[dict, jax.Array]:
    """From-scratch (stats, soft) of one assignment — the acceptance gate
    both the fused pipeline's final rebuild and the active-set sub-solve
    (solver/subsolve.py) trust: whatever a cheaper carried/sub-problem path
    claims, the decision that commits a placement reads these numbers."""
    return violation_stats(prob, assignment), soft_score(prob, assignment)
