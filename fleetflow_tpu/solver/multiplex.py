"""Tenant multiplexer: batched same-tier warm solves in ONE dispatch.

Production for jax_graft means thousands of independent stages, not one
big one — and the tier ladder (solver/buckets.py) already forces
same-tier stage problems into identical padded shapes, which is exactly
the precondition for vmapping them into one batched dispatch. This
module stacks K same-tier resident-warm ``DeviceProblem`` stagings
(packed planes gain a leading lane axis, per-stage scalars become (K,)
vectors) and runs ONE vmapped fused-prerepair + adaptive anneal over
all K lanes:

    K x (dispatch + device_get + host gate)   ->   1 x (all of it)

Per-lane semantics are UNCHANGED: the vmapped pipeline is lane-wise the
same program as ``api._refine`` (jax batches the adaptive while_loop by
masking finished lanes, so each lane's proposal stream, early exit and
best-ever tracking are its own), each lane keeps its own PRNG key, its
own exact violation stats, its own acceptance gate and its own
flight-deck telemetry buffer (PR 15 schema, one buffer per lane). The
parity property test pins this: a lane's assignment is bit-identical to
a solo solve of the same stage with the same seed.

K is bucketed on a small power-of-two ladder (``mux_k``) so fleet-count
drift never recompiles: a batch of 5 pads to 8 by replicating lane 0
(padded lanes are discarded, counted on
``fleet_solver_mux_lanes_total{kind="pad"}``), and the executable
identity is (tier statics, ladder K) — the bench leg pins zero
recompiles across the whole tier x K grid after warm-up.

Lanes that cannot batch (singleton tier groups, host-warm stagings,
sharded residents) fall through to the serial ``api._solve`` path with
identical results; the multiplexer is a latency optimization, never a
semantics fork.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .anneal import TRACE_COLS, backend_proposals_per_step, solve_trace_blocks
from .api import DEFAULT_STEPS, SolveResult, _refine, _solve
from .buckets import soft_score_host
from .problem import DeviceProblem
from .repair import RepairResult, repair, verify
from .resident import ResidentProblem, transfer_guard_ctx
from ..lower.tensors import ProblemTensors
from ..obs import get_logger, kv
from ..obs.metrics import REGISTRY

log = get_logger("solver.mux")

__all__ = ["MuxEntry", "solve_multiplexed", "mux_k", "mux_cache_size",
           "stack_problems", "MUX_LADDER_MAX"]

# metric catalog: docs/guide/10-observability.md
_M_MUX_BATCHES = REGISTRY.counter(
    "fleet_solver_mux_batches_total",
    "Batched multiplexer dispatches by ladder lane count", labels=("k",))
_M_MUX_LANES = REGISTRY.counter(
    "fleet_solver_mux_lanes_total",
    "Multiplexer lanes by kind (stage = real stage solved in a batch, "
    "pad = ladder-padding replica, serial = mux-ineligible fallback)",
    labels=("kind",))
_M_MUX_STACK_MS = REGISTRY.gauge(
    "fleet_solver_mux_stack_ms",
    "Host+device time spent stacking the most recent mux batch")

# default ceiling of the lane ladder; FLEET_MUX_MAX overrides
MUX_LADDER_MAX = 16


def _ladder_max() -> int:
    import os
    try:
        return max(1, int(os.environ.get("FLEET_MUX_MAX") or MUX_LADDER_MAX))
    except ValueError:
        return MUX_LADDER_MAX


def mux_k(k: int, *, maximum: Optional[int] = None) -> int:
    """Round a lane count up to the power-of-two ladder (2, 4, 8, ...,
    FLEET_MUX_MAX). Like buckets.subsolve_tier for the mini-anneal, the
    ladder keeps the batched executable count logarithmic in fleet-count
    drift: K is a leading-axis extent, hence a recompile axis."""
    cap = _ladder_max() if maximum is None else maximum
    if k <= 1:
        return 1
    p = 2
    while p < k and p < cap:
        p *= 2
    return min(p, cap)


@dataclass
class MuxEntry:
    """One stage's slice of a batched solve: its problem tensors, its
    resident staging (device problem + committed assignment already on
    device), and its solve scalars — exactly what the serial resident-
    warm ``solve()`` call would take."""
    pt: ProblemTensors
    resident: ResidentProblem
    seed: int = 0
    t0: float = 1.0
    t1: float = 1e-3
    migration_weight: float = 0.5
    stage: Optional[str] = None     # caller's stage key (logging only)


def stack_problems(probs: list[DeviceProblem]) -> DeviceProblem:
    """Stack same-tier device problems along a new leading lane axis.
    The static fields are pytree aux data, so tree_map itself enforces
    the tier identity: mismatched statics are a treedef error, not a
    silent mis-batch. Leaves stack on device (no host transfer)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *probs)


@partial(jax.jit, static_argnames=("chains", "steps", "warm", "adaptive",
                                   "anneal_block", "proposals_per_step",
                                   "fused_prerepair", "prerepair_moves",
                                   "skip_feasible_polish", "trace_blocks"))
def _mux_refine(prob: DeviceProblem, seed_assignment: jax.Array,
                key: jax.Array, t0: jax.Array, t1: jax.Array,
                migration_weight: jax.Array, *,
                chains: int, steps: int, warm: bool, adaptive: bool = True,
                anneal_block: int = 1,
                proposals_per_step: Optional[int] = None,
                fused_prerepair: bool = True, prerepair_moves: int = 0,
                skip_feasible_polish: bool = True, trace_blocks: int = 0):
    """The batched fused pipeline: lane-wise ``api._refine`` under vmap.
    Inputs carry a leading (K,) lane axis (problem planes, seeds, PRNG
    keys, anneal scalars); outputs are the per-lane refine tuple with
    the same leading axis — winner (K, S), exact stats (K,) per
    component, soft (K,), sweeps (K,), accepted (K,), telemetry buffers
    (K, trace_blocks, cols). The inner jit inlines under the trace, so
    this is ONE XLA program per (tier statics, K)."""

    def lane(p, s, k, a, b, c):
        return _refine(p, s, k, a, b, c, chains=chains, steps=steps,
                       warm=warm, adaptive=adaptive,
                       anneal_block=anneal_block,
                       proposals_per_step=proposals_per_step,
                       sharding=None, fused_prerepair=fused_prerepair,
                       prerepair_moves=prerepair_moves,
                       skip_feasible_polish=skip_feasible_polish,
                       trace_blocks=trace_blocks)

    return jax.vmap(lane)(prob, seed_assignment, key, t0, t1,
                          migration_weight)


def mux_cache_size() -> int:
    """Compiled-variant count of the batched executable (the bench leg's
    recompile watch, like api._refine._cache_size for the serial path)."""
    return _mux_refine._cache_size()


def _eligible(e: MuxEntry) -> bool:
    rp = e.resident
    return (isinstance(rp, ResidentProblem)
            and getattr(rp, "mesh", None) is None
            and rp.assignment is not None)


def _tier_key(e: MuxEntry):
    """Group key: everything that feeds the executable identity. The
    leaf (shape, dtype) tuple covers S/N/G/Gc/T/widths/plane layout; the
    treedef covers the static fields and absent-plane structure."""
    prob = e.resident.prob
    leaves, treedef = jax.tree_util.tree_flatten(prob)
    shapes = tuple((x.shape, str(x.dtype)) for x in leaves)
    return (treedef, shapes, bool(e.migration_weight > 0))


def solve_multiplexed(entries: list[MuxEntry], *,
                      chains: Optional[int] = None,
                      steps: int = DEFAULT_STEPS,
                      anneal_block: int = 1,
                      warm_block: int = 1,
                      do_repair: bool = True) -> list[SolveResult]:
    """Solve a set of resident-warm stages, batching same-tier groups
    into single vmapped dispatches. Returns one SolveResult per entry,
    in entry order. Entries that cannot batch (singleton tier groups or
    mux-ineligible stagings) run through the serial ``api._solve`` warm
    path — same results, just without the shared dispatch."""
    if chains is None:
        chains = 1 if jax.default_backend() == "cpu" else 2

    results: list[Optional[SolveResult]] = [None] * len(entries)
    groups: dict = {}
    serial: list[int] = []
    for i, e in enumerate(entries):
        if _eligible(e):
            groups.setdefault(_tier_key(e), []).append(i)
        else:
            serial.append(i)

    for key, idxs in groups.items():
        if len(idxs) < 2:
            serial.extend(idxs)
            continue
        cap = _ladder_max()
        for at in range(0, len(idxs), cap):
            chunk = idxs[at:at + cap]
            _solve_batch(entries, chunk, results, chains=chains,
                         steps=steps, anneal_block=anneal_block,
                         warm_block=warm_block, do_repair=do_repair)

    for i in serial:
        e = entries[i]
        _M_MUX_LANES.inc(kind="serial")
        results[i] = _solve(
            e.pt, chains=chains, steps=steps, seed=e.seed,
            do_repair=do_repair, t0=e.t0, t1=e.t1,
            migration_weight=e.migration_weight,
            anneal_block=anneal_block, warm_block=warm_block,
            resident=e.resident if isinstance(e.resident, ResidentProblem)
            else None,
            resident_warm=_eligible(e),
            bucket=getattr(e.resident, "bucket", None))
    return results  # type: ignore[return-value]


def _solve_batch(entries: list[MuxEntry], idxs: list[int],
                 results: list, *, chains: int, steps: int,
                 anneal_block: int, warm_block: int,
                 do_repair: bool) -> None:
    t = time.perf_counter
    t_start = t()
    lanes = [entries[i] for i in idxs]
    K = len(lanes)
    Kp = mux_k(K)

    # ---- staging: everything host-touching happens BEFORE the guard ----
    # ladder padding replicates lane 0 (its result is discarded); the
    # replica shares lane 0's device buffers, so padding costs no memory
    # beyond the stacked copy every lane pays anyway
    def lane_at(j: int) -> MuxEntry:
        return lanes[j] if j < K else lanes[0]

    probs = [lane_at(j).resident.prob for j in range(Kp)]
    stacked = stack_problems(probs)
    seeds = jnp.stack([lane_at(j).resident.assignment for j in range(Kp)])
    keys = jnp.stack([jax.random.PRNGKey(lane_at(j).seed)
                      for j in range(Kp)])
    # warm scalars stage per lane through the resident's device cache
    # (the merge-upload discipline: scalars are resident before the
    # guard arms), then stack device-side into (K,) vectors
    scal = [lane_at(j).resident.warm_scalars(
        min(lane_at(j).t0, 0.1), lane_at(j).t1,
        lane_at(j).migration_weight) for j in range(Kp)]
    t0v = jnp.stack([s[0] for s in scal])
    t1v = jnp.stack([s[1] for s in scal])
    mwv = jnp.stack([s[2] for s in scal])

    prob0 = probs[0]
    warm = bool(lanes[0].migration_weight > 0)
    proposals = backend_proposals_per_step(prob0.S)
    prerepair_moves = max(16, min(prob0.S, 256))
    trace_blocks = solve_trace_blocks()
    refine_kw = dict(
        chains=chains, steps=steps, warm=warm, adaptive=True,
        anneal_block=min(warm_block, anneal_block),
        proposals_per_step=proposals, fused_prerepair=True,
        prerepair_moves=prerepair_moves, skip_feasible_polish=True,
        trace_blocks=trace_blocks)
    _M_MUX_STACK_MS.set((t() - t_start) * 1e3)

    cache_before = _mux_refine._cache_size()
    t_anneal = t()
    # the proof: under FLEET_TRANSFER_GUARD=disallow nothing inside the
    # batched dispatch crosses the host boundary — every lane's planes,
    # seed and scalars are already resident, statics hash
    with transfer_guard_ctx():
        (winners, dstats, dsoft, dsweeps, daccepted,
         dtelem) = _mux_refine(stacked, seeds, keys, t0v, t1v, mwv,
                               **refine_kw)
    compile_events = _mux_refine._cache_size() - cache_before
    # the padded winner stays on device as each lane's next warm seed
    # (lane slicing is a device op; padded replicas are never adopted)
    for j in range(K):
        lanes[j].resident.adopt(winners[j])
    # ONE transfer for every lane's host decision — the whole point
    (h_win, h_stats, h_soft, h_sweeps, h_acc, h_telem) = jax.device_get(
        (winners, dstats, dsoft, dsweeps, daccepted, dtelem))
    anneal_ms = (t() - t_anneal) * 1e3

    _M_MUX_BATCHES.inc(k=str(Kp))
    _M_MUX_LANES.inc(K, kind="stage")
    if Kp > K:
        _M_MUX_LANES.inc(Kp - K, kind="pad")
    from .api import _M_ACCEPTED, _M_COMPILES, _M_SOLVES, _M_SWEEPS
    if compile_events > 0:
        _M_COMPILES.inc(compile_events)

    for j in range(K):
        e = lanes[j]
        rp = e.resident
        prob = rp.prob
        # FORCE a host copy: device_get can return a view of a buffer
        # the resident path later donates (see api._solve)
        assignment = np.array(h_win[j], copy=True)
        padded_host = assignment
        bucketed = prob.S != e.pt.S
        if bucketed:
            assignment = assignment[: e.pt.S]
        stats_lane = {k: float(v[j]) for k, v in h_stats.items()}
        soft = float(h_soft[j])
        sweeps = int(h_sweeps[j])
        accepted = int(h_acc[j])
        moves = 0
        pre_repair = 0
        if stats_lane["total"] == 0:
            stats = {k: int(v) for k, v in stats_lane.items()}
        else:
            # per-lane exact gate, same as the serial path: verify on
            # host ground truth, repair backstop, resident re-upload
            stats = verify(e.pt, assignment)
            pre_repair = int(stats["total"])
            if do_repair and stats["total"] > 0:
                rr: RepairResult = repair(e.pt, assignment)
                assignment, stats, moves = rr.assignment, rr.stats, rr.moves
                if moves:
                    rp.adopt_host(assignment, e.pt.node_valid, warm=True)
        if bucketed or (sweeps == 0 and stats["total"] == 0):
            # padded-mean / stickiness-bonused device score: recompute
            # the un-bonused objective against the REAL rows host-side
            soft = soft_score_host(e.pt, assignment)
        rp.note_host_assignment(
            padded=None if moves else padded_host,
            feasible=stats["total"] == 0)
        telemetry = None
        if trace_blocks > 0 and accepted >= 0:
            filled = int(h_telem["filled"][j])
            rows = np.asarray(h_telem["blocks"][j])[:filled]
            telemetry = {
                "schema": list(TRACE_COLS),
                "blocks": [[round(float(x), 6) for x in row]
                           for row in rows],
                "trace_blocks": trace_blocks,
                "init": {
                    "violations": float(h_telem["init_violations"][j]),
                    "soft": round(float(h_telem["init_soft"][j]), 6)},
                "prerepair_moves": int(h_telem["prerepair_moves"][j]),
                "exit_sweep": sweeps,
                "path": "mux",
                "mux": {"k": Kp, "lane": j},
            }
        _M_SOLVES.inc(backend=jax.default_backend(), warm="true")
        _M_SWEEPS.inc(sweeps)
        if accepted >= 0:
            _M_ACCEPTED.inc(accepted)
        results[idxs[j]] = SolveResult(
            assignment=assignment, stats=stats, soft=soft,
            feasible=stats["total"] == 0, moves_repaired=moves,
            pre_repair_violations=pre_repair,
            timings_ms={"anneal_ms": anneal_ms, "mux_k": float(Kp),
                        "mux_lane": float(j)},
            chains=chains, steps=sweeps, proposals_per_step=proposals,
            accepted_moves=accepted, fused_prerepair=True,
            telemetry=telemetry)
    log.info("mux %s", kv(
        k=Kp, stages=K, tier=f"{prob0.S}x{prob0.N}",
        compiles=compile_events or None,
        ms=f"{anneal_ms:.1f}"))
