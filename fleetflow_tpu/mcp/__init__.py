"""MCP server (L6).

Analog of fleetflow-mcp (SURVEY.md §2.8): ~25 tools over stdio JSON-RPC —
local project tools (analyze/ps/up/down/logs/restart/validate/build/solve)
and CP tools (status/overview/projects/servers/stage status/redeploy/
restart/container logs/alerts/agents/tenant users).
"""

from .server import FleetMcpServer, serve_stdio

__all__ = ["FleetMcpServer", "serve_stdio"]
