"""MCP server: stdio JSON-RPC 2.0.

Analog of fleetflow-mcp lib.rs:146-1003 (rmcp #[tool_router]): implements
the Model Context Protocol handshake (initialize / tools/list / tools/call)
directly over stdio — no SDK dependency — and exposes the same tool
surface: local project tools against the loaded Flow + runtime backend,
and CP tools over the protocol client.

Every tool returns MCP `content: [{type: "text", text: ...}]` with JSON
payloads, matching how the reference's tools serialize results.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Callable, Optional

from ..core.errors import FlowError
from ..core.loader import load_project
from ..lower.tensors import lower_stage
from ..sched import pick_scheduler, place_with_fallback

__all__ = ["FleetMcpServer", "serve_stdio"]

PROTOCOL_VERSION = "2024-11-05"
SERVER_INFO = {"name": "fleetflow-tpu-mcp", "version": "0.1.0"}


def _tool(name: str, description: str, schema: Optional[dict] = None):
    def deco(fn):
        fn._mcp = {"name": name, "description": description,
                   "inputSchema": schema or {"type": "object",
                                             "properties": {}}}
        return fn
    return deco


def _text(payload: Any) -> dict:
    text = payload if isinstance(payload, str) else json.dumps(
        payload, indent=2, default=str)
    return {"content": [{"type": "text", "text": text}]}


_STAGE_SCHEMA = {"type": "object", "properties": {
    "stage": {"type": "string", "description": "stage name (default local)"}}}


class FleetMcpServer:
    def __init__(self, project_root: Optional[str] = None,
                 cp_endpoint: Optional[str] = None,
                 backend=None, cp_client=None):
        self.project_root = project_root
        self.cp_endpoint = cp_endpoint
        self._backend = backend
        self._cp = cp_client
        self.tools: dict[str, Callable] = {}
        for attr in dir(self):
            fn = getattr(self, attr)
            if callable(fn) and hasattr(fn, "_mcp"):
                self.tools[fn._mcp["name"]] = fn

    # ------------------------------------------------------------------
    # plumbing
    # ------------------------------------------------------------------

    def _flow(self, stage: Optional[str] = None):
        return load_project(stage=stage or "local", start=self.project_root)

    def backend(self):
        if self._backend is None:
            from ..runtime.backend import DockerCliBackend
            self._backend = DockerCliBackend()
        return self._backend

    def cp(self):
        if self._cp is None:
            from ..cli.client import CpClient
            self._cp = CpClient(self.cp_endpoint).connect()
        return self._cp

    def handle(self, msg: dict) -> Optional[dict]:
        """One JSON-RPC message -> response (None for notifications)."""
        mid = msg.get("id")
        method = msg.get("method", "")
        params = msg.get("params", {})
        if mid is None:
            return None   # notifications (initialized, cancelled) need no reply
        try:
            if method == "initialize":
                result = {"protocolVersion": PROTOCOL_VERSION,
                          "capabilities": {"tools": {}},
                          "serverInfo": SERVER_INFO}
            elif method == "tools/list":
                result = {"tools": [fn._mcp for fn in self.tools.values()]}
            elif method == "tools/call":
                name = params.get("name", "")
                fn = self.tools.get(name)
                if fn is None:
                    raise FlowError(f"unknown tool {name!r}")
                result = fn(**(params.get("arguments") or {}))
            elif method == "ping":
                result = {}
            else:
                return {"jsonrpc": "2.0", "id": mid,
                        "error": {"code": -32601,
                                  "message": f"method not found: {method}"}}
            return {"jsonrpc": "2.0", "id": mid, "result": result}
        except Exception as e:
            return {"jsonrpc": "2.0", "id": mid,
                    "result": {"content": [{"type": "text",
                                            "text": f"error: {e}"}],
                               "isError": True}}

    # ------------------------------------------------------------------
    # local tools (lib.rs:165-417)
    # ------------------------------------------------------------------

    @_tool("project_analyze", "Summarize the fleet project: services, "
           "stages, dependencies, resources", _STAGE_SCHEMA)
    def project_analyze(self, stage: str = "local") -> dict:
        flow = self._flow(stage)
        return _text({
            "project": flow.name,
            "stages": {name: {"services": st.services,
                              "servers": st.servers,
                              "backend": st.backend.value}
                       for name, st in flow.stages.items()},
            "services": {name: {
                "image": svc.image_name(),
                "depends_on": svc.depends_on,
                "ports": [f"{p.host}:{p.container}" for p in svc.ports],
                "resources": {"cpu": svc.resources.cpu,
                              "memory": svc.resources.memory}}
                for name, svc in flow.services.items()},
            "servers": sorted(flow.servers),
        })

    @_tool("fleet_ps", "List this project's containers", _STAGE_SCHEMA)
    def fleet_ps(self, stage: str = "local") -> dict:
        flow = self._flow(stage)
        infos = self.backend().list(label_filter={
            "fleetflow.project": flow.name, "fleetflow.stage": stage})
        return _text([{"name": i.name, "state": i.state, "health": i.health,
                       "image": i.image} for i in infos])

    @_tool("fleet_up", "Start a stage's services", _STAGE_SCHEMA)
    def fleet_up(self, stage: str = "local") -> dict:
        from ..runtime.engine import DeployEngine, DeployRequest
        flow = self._flow(stage)
        events: list[str] = []
        res = DeployEngine(self.backend()).execute(
            DeployRequest(flow=flow, stage_name=stage),
            on_event=lambda e: events.append(str(e)))
        return _text({"ok": res.ok, "deployed": res.deployed,
                      "failed": res.failed, "events": events[-20:]})

    @_tool("fleet_down", "Stop a stage", _STAGE_SCHEMA)
    def fleet_down(self, stage: str = "local") -> dict:
        from ..runtime.engine import DeployEngine
        flow = self._flow(stage)
        res = DeployEngine(self.backend()).down(flow, stage)
        return _text({"removed": res.removed})

    @_tool("fleet_logs", "Tail one service's container logs",
           {"type": "object", "properties": {
               "service": {"type": "string"},
               "stage": {"type": "string"},
               "tail": {"type": "integer"}},
            "required": ["service"]})
    def fleet_logs(self, service: str, stage: str = "local",
                   tail: int = 100) -> dict:
        from ..runtime.converter import container_name
        flow = self._flow(stage)
        return _text(self.backend().logs(
            container_name(flow.name, stage, service), tail=tail))

    @_tool("fleet_restart", "Restart one service's container",
           {"type": "object", "properties": {
               "service": {"type": "string"}, "stage": {"type": "string"}},
            "required": ["service"]})
    def fleet_restart(self, service: str, stage: str = "local") -> dict:
        from ..runtime.converter import container_name
        flow = self._flow(stage)
        cname = container_name(flow.name, stage, service)
        self.backend().restart(cname)
        return _text({"restarted": cname})

    @_tool("fleet_validate", "Validate config and placement feasibility")
    def fleet_validate(self) -> dict:
        flow = self._flow()
        out = {}
        for stage_name in sorted(flow.stages):
            try:
                pt = lower_stage(flow, stage_name)
                pl, _ = place_with_fallback(
                    pick_scheduler(pt.S, pt.N, prefer_tpu=False), pt)
                out[stage_name] = {"services": pt.S, "nodes": pt.N,
                                   "feasible": pl.feasible,
                                   "violations": pl.violations}
            except FlowError as e:
                out[stage_name] = {"error": str(e)}
        return _text(out)

    @_tool("fleet_build", "Build a service's image",
           {"type": "object", "properties": {
               "service": {"type": "string"}}, "required": ["service"]})
    def fleet_build(self, service: str) -> dict:
        from ..build import BuildResolver, ImageBuilder
        flow = self._flow()
        svc = flow.services.get(service)
        if svc is None or svc.build is None:
            raise FlowError(f"service {service!r} has no build config")
        resolved = BuildResolver(self.project_root or ".").resolve(svc)
        tag = ImageBuilder().build(resolved)
        return _text({"image": tag})

    @_tool("fleet_solve", "Solve a stage's placement on the TPU solver",
           {"type": "object", "properties": {
               "stage": {"type": "string"},
               "host_only": {"type": "boolean"}}})
    def fleet_solve(self, stage: str = "local",
                    host_only: bool = False) -> dict:
        flow = self._flow(stage)
        pt = lower_stage(flow, stage)
        pl, _ = place_with_fallback(
            pick_scheduler(pt.S, pt.N, prefer_tpu=not host_only), pt)
        return _text({"assignment": pl.assignment, "feasible": pl.feasible,
                      "violations": pl.violations, "source": pl.source,
                      "solve_ms": round(pl.solve_ms, 1)})

    # ------------------------------------------------------------------
    # CP tools (lib.rs:557-1003)
    # ------------------------------------------------------------------

    @_tool("cp_auth_status", "Check control-plane connectivity and auth")
    def cp_auth_status(self) -> dict:
        try:
            out = self.cp().request("health", "ping")
            return _text({"connected": True, "pong": out})
        except Exception as e:
            return _text({"connected": False, "error": str(e)})

    @_tool("cp_overview", "Cluster overview: servers, agents, alerts")
    def cp_overview(self) -> dict:
        return _text(self.cp().request("health", "overview"))

    @_tool("cp_projects", "List control-plane projects",
           {"type": "object", "properties": {"tenant": {"type": "string"}}})
    def cp_projects(self, tenant: Optional[str] = None) -> dict:
        return _text(self.cp().request("project", "list",
                                       {"tenant": tenant})["projects"])

    @_tool("cp_servers", "List registered servers with capacity/allocation")
    def cp_servers(self) -> dict:
        return _text(self.cp().request("server", "list")["servers"])

    @_tool("cp_alerts", "Active alerts (restart loops, unexpected stops, "
           "unhealthy containers, offline nodes)",
           {"type": "object", "properties": {"tenant": {"type": "string"}}})
    def cp_alerts(self, tenant: Optional[str] = None) -> dict:
        return _text(self.cp().request("health", "alerts",
                                       {"tenant": tenant})["alerts"])

    @_tool("cp_pools", "Worker pools with min/max and member servers")
    def cp_pools(self) -> dict:
        return _text(self.cp().request("server", "pool.list")["pools"])

    @_tool("cp_tenant_overview", "One tenant's projects/servers/alerts",
           {"type": "object", "properties": {"tenant": {"type": "string"}},
            "required": ["tenant"]})
    def cp_tenant_overview(self, tenant: str) -> dict:
        projects = self.cp().request("project", "list",
                                     {"tenant": tenant})["projects"]
        return _text({"tenant": tenant, "projects": projects})

    @_tool("cp_project_detail", "One project's record and stages "
           "(fleetflow_cp_project_detail)",
           {"type": "object", "properties": {"project": {"type": "string"},
                                             "tenant": {"type": "string"}},
            "required": ["project"]})
    def cp_project_detail(self, project: str, tenant: str = "default") -> dict:
        # without a tenant the handler defaults to 'default' and projects
        # in other tenants come back null even though cp_projects can list
        # them (ADVICE r2)
        rec = self.cp().request("project", "get",
                                {"name": project, "tenant": tenant})
        proj = rec.get("project")
        # stages are keyed by project ID, not the human name
        stages = (self.cp().request(
            "stage", "list", {"project": proj["id"]})["stages"]
            if proj else [])
        return _text({"project": proj, "stages": stages})

    @_tool("cp_stage_services", "Services registered under a stage "
           "(fleetflow_cp_stage_services)",
           {"type": "object", "properties": {"stage_id": {"type": "string"}},
            "required": ["stage_id"]})
    def cp_stage_services(self, stage_id: str) -> dict:
        return _text(self.cp().request("service", "list",
                                       {"stage": stage_id})["services"])

    @_tool("cp_stage_status", "Services/deployments/alerts of a stage",
           {"type": "object", "properties": {"stage_id": {"type": "string"}},
            "required": ["stage_id"]})
    def cp_stage_status(self, stage_id: str) -> dict:
        return _text(self.cp().request("stage", "status", {"stage": stage_id}))

    @_tool("cp_deployments", "Deployment history",
           {"type": "object", "properties": {"stage_id": {"type": "string"},
                                             "limit": {"type": "integer"}}})
    def cp_deployments(self, stage_id: Optional[str] = None,
                       limit: int = 20) -> dict:
        return _text(self.cp().request("deploy", "history",
                                       {"stage": stage_id,
                                        "limit": limit})["deployments"])

    @_tool("cp_service_restart", "Restart a container via its node agent",
           {"type": "object", "properties": {
               "server": {"type": "string"}, "container": {"type": "string"}},
            "required": ["server", "container"]})
    def cp_service_restart(self, server: str, container: str) -> dict:
        return _text(self.cp().request("service", "restart",
                                       {"server": server,
                                        "container": container}))

    @_tool("cp_container_logs", "Cached container logs from the log router",
           {"type": "object", "properties": {
               "server": {"type": "string"}, "container": {"type": "string"},
               "limit": {"type": "integer"}},
            "required": ["server", "container"]})
    def cp_container_logs(self, server: str, container: str,
                          limit: int = 50) -> dict:
        out = self.cp().request("container", "logs",
                                {"server": server, "container": container,
                                 "limit": limit})
        return _text([e["line"] for e in out["lines"]])

    @_tool("cp_containers", "Observed containers across the fleet",
           {"type": "object", "properties": {"server": {"type": "string"}}})
    def cp_containers(self, server: Optional[str] = None) -> dict:
        return _text(self.cp().request("container", "ps",
                                       {"server": server})["containers"])

    @_tool("cp_container_start", "Start a stopped container via its node "
           "agent (fleetflow_cp_container_start)",
           {"type": "object", "properties": {
               "server": {"type": "string"}, "container": {"type": "string"}},
            "required": ["server", "container"]})
    def cp_container_start(self, server: str, container: str) -> dict:
        return _text(self.cp().request("container", "start",
                                       {"server": server,
                                        "container": container}))

    @_tool("cp_container_stop", "Stop a running container via its node "
           "agent (fleetflow_cp_container_stop)",
           {"type": "object", "properties": {
               "server": {"type": "string"}, "container": {"type": "string"}},
            "required": ["server", "container"]})
    def cp_container_stop(self, server: str, container: str) -> dict:
        return _text(self.cp().request("container", "stop",
                                       {"server": server,
                                        "container": container}))

    @_tool("cp_container_restart", "Restart a container via its node "
           "agent (fleetflow_cp_container_restart)",
           {"type": "object", "properties": {
               "server": {"type": "string"}, "container": {"type": "string"}},
            "required": ["server", "container"]})
    def cp_container_restart(self, server: str, container: str) -> dict:
        return _text(self.cp().request("container", "restart",
                                       {"server": server,
                                        "container": container}))

    @_tool("cp_agents", "Connected node agents")
    def cp_agents(self) -> dict:
        return _text(self.cp().request("health", "overview")["agents"])

    @_tool("cp_tenant_users", "A tenant's users",
           {"type": "object", "properties": {"tenant": {"type": "string"}},
            "required": ["tenant"]})
    def cp_tenant_users(self, tenant: str) -> dict:
        return _text(self.cp().request("tenant", "user.list",
                                       {"tenant": tenant})["users"])

    @_tool("cp_placement_solve", "Solve placement for a flow stage against "
           "live CP inventory",
           {"type": "object", "properties": {"stage": {"type": "string"}},
            "required": ["stage"]})
    def cp_placement_solve(self, stage: str) -> dict:
        from ..core.serialize import flow_to_dict
        flow = self._flow(stage)
        return _text(self.cp().request("placement", "solve",
                                       {"flow": flow_to_dict(flow),
                                        "stage": stage}))

    @_tool("cp_placement_explain", "Why is a service on its node: per-node "
           "hard/soft breakdown of the stage's latest placement",
           {"type": "object", "properties": {
               "stage": {"type": "string",
                         "description": "stage key, <flow>/<stage>"},
               "service": {"type": "string"}},
            "required": ["stage", "service"]})
    def cp_placement_explain(self, stage: str, service: str) -> dict:
        return _text(self.cp().request("placement", "explain",
                                       {"stage": stage, "service": service}))

    @_tool("cp_redeploy", "Redeploy a stage through the control plane",
           {"type": "object", "properties": {"stage": {"type": "string"}},
            "required": ["stage"]})
    def cp_redeploy(self, stage: str) -> dict:
        from ..runtime.engine import DeployRequest
        flow = self._flow(stage)
        req = DeployRequest(flow=flow, stage_name=stage)
        return _text(self.cp().request("deploy", "execute",
                                       {"request": req.to_dict()},
                                       timeout=600))

    @_tool("cp_cost_summary", "Monthly cost total for a tenant "
           "(YYYY-MM month)",
           {"type": "object", "properties": {
               "month": {"type": "string"},
               "tenant": {"type": "string"}},
            "required": ["month"]})
    def cp_cost_summary(self, month: str, tenant: str = "default") -> dict:
        return _text(self.cp().request("cost", "summary",
                                       {"month": month, "tenant": tenant}))

    @_tool("cp_cost_list", "List recorded cost entries, optionally "
           "filtered by tenant and/or YYYY-MM month",
           {"type": "object", "properties": {
               "tenant": {"type": "string"},
               "month": {"type": "string"}}})
    def cp_cost_list(self, tenant: str = None, month: str = None) -> dict:
        return _text(self.cp().request("cost", "list",
                                       {"tenant": tenant, "month": month}))

    @_tool("cp_node_events", "Report a churn burst (nodes going offline/"
           "online) as ONE coalesced warm re-solve — maintenance windows "
           "should use this instead of N single node_event calls",
           {"type": "object", "properties": {
               "events": {"type": "array", "items": {
                   "type": "object", "properties": {
                       "slug": {"type": "string"},
                       "online": {"type": "boolean"}},
                   "required": ["slug", "online"]}}},
            "required": ["events"]})
    def cp_node_events(self, events: list) -> dict:
        return _text(self.cp().request("placement", "node_events",
                                       {"events": events}, timeout=120))

    @_tool("cp_server_cordon", "Cordon, uncordon, or drain a server "
           "(drain also warm-reschedules its services)",
           {"type": "object", "properties": {
               "slug": {"type": "string"},
               "action": {"type": "string",
                          "enum": ["cordon", "uncordon", "drain"]}},
            "required": ["slug", "action"]})
    def cp_server_cordon(self, slug: str, action: str) -> dict:
        if action not in ("cordon", "uncordon", "drain"):
            raise ValueError(f"unknown action {action!r}")
        return _text(self.cp().request("server", action, {"slug": slug},
                                       timeout=120))


def serve_stdio(project_root: Optional[str] = None,
                cp_endpoint: Optional[str] = None,
                stdin=None, stdout=None) -> None:
    """Line-delimited JSON-RPC over stdio (the MCP stdio transport)."""
    server = FleetMcpServer(project_root=project_root,
                            cp_endpoint=cp_endpoint)
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    for line in stdin:
        line = line.strip()
        if not line:
            continue
        try:
            msg = json.loads(line)
        except json.JSONDecodeError:
            continue
        resp = server.handle(msg)
        if resp is not None:
            stdout.write(json.dumps(resp) + "\n")
            stdout.flush()
