"""Worker-pool autoscaler: the elastic worker lifecycle.

Analog of the reference's worker provisioning + idle-shutdown pair
(scripts/spawn-build-worker.sh:1-30 spawns Sakura build workers;
scripts/idle-shutdown.sh:1-20 is a systemd timer that powers idle workers
off), folded into the control plane as a background reconciler over
WorkerPool records (model.rs:552-563 min/max):

- below `min_servers`: provision machines through the pool's cloud provider
  (the same ServerProvider path as server.provision) and register them into
  the pool.
- above `min_servers` with idle machines: deprovision the idle surplus,
  newest first, down to the floor ("idle" = online, schedulable, nothing
  allocated or reserved, no containers observed, and past a grace period).

One sweep is pure decision + provider calls with an injectable clock and
provider factory, so the whole policy is unit-testable without a cloud.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from .models import Server, ServerCapacity, WorkerPool
from ..core.model import ResourceSpec, ServerResource
from ..obs import get_logger, kv
from ..obs.metrics import REGISTRY

if TYPE_CHECKING:
    from .server import AppState

__all__ = ["Autoscaler", "ScaleAction"]

log = get_logger("cp.autoscaler")

# metric catalog: docs/guide/10-observability.md. The streaming-admission
# feedback signal (cp/admission.py pressure()): seconds the oldest queued
# admission request has waited when the signal is hot, 0 when drained —
# the input that makes the autoscaler provision on SOLVER pressure, not
# just idle counts.
_M_PRESSURE = REGISTRY.gauge(
    "fleet_autoscaler_pressure",
    "Admission queue pressure the autoscaler last planned against "
    "(oldest queued age in seconds; 0 = drained)")

IDLE_GRACE_S = 600.0     # idle-shutdown.sh waits ~10 min before poweroff
PROVISION_TIMEOUT_S = 900.0   # a machine that never came up is a zombie
OFFLINE_REAP_S = 900.0   # a worker offline this long is a corpse: reap the
                         # record (and any surviving VM) so the pool can
                         # replace it instead of counting it against max
# an offline node that still shows workload is given 4x the window for its
# stage to be redeployed elsewhere (which releases the allocations); past
# that the "workload" is bookkeeping residue on a dead machine and keeping
# the record would starve a capped pool below min forever
OFFLINE_BUSY_REAP_S = 4 * OFFLINE_REAP_S


@dataclass
class ScaleAction:
    pool: str
    kind: str               # "provision" | "deprovision"
    slug: str
    ok: bool = True
    error: str = ""


class Autoscaler:
    def __init__(self, state: "AppState", *, interval_s: float = 120.0,
                 idle_grace_s: float = IDLE_GRACE_S, clock=time.time,
                 pressure_source=None):
        self.state = state
        self.interval_s = interval_s
        self.idle_grace_s = idle_grace_s
        self.clock = clock
        # solver-pressure feedback (docs/guide/14-streaming-admission.md):
        # a callable returning cp/admission.py pressure() — defaults to
        # the AppState's admission controller when one is wired
        self.pressure_source = pressure_source
        self._task = None
        self._counter = 0
        # slug -> last time the worker had any workload (allocations or
        # observed containers). Maintained by the sweep itself: idleness is
        # about WORKLOAD, not liveness — a healthy agent heartbeats every
        # 30 s, so heartbeat recency would make idle shutdown unreachable.
        self._last_busy: dict[str, float] = {}

    # ------------------------------------------------------------------
    # decisions
    # ------------------------------------------------------------------

    def _pool_servers(self, pool: WorkerPool) -> list[Server]:
        # pool names are only unique per tenant
        return self.state.store.list(
            "servers", lambda s: s.pool == pool.name
            and s.tenant == pool.tenant)

    def _is_busy(self, s: Server) -> bool:
        alloc = s.allocated
        return bool(alloc.cpu > 0 or alloc.memory > 0 or alloc.disk > 0
                    or alloc.reserved_cpu > 0 or alloc.reserved_memory > 0
                    or alloc.reserved_disk > 0
                    or self.state.store.observed_on(s.slug))

    def _is_idle(self, s: Server) -> bool:
        """Idle = schedulable, no workload now, and no workload since the
        grace period started (tracked in _last_busy by the sweep)."""
        if not s.schedulable or self._is_busy(s):
            return False
        since = self._last_busy.get(s.slug, s.created_at)
        return self.clock() - since >= self.idle_grace_s

    def _pressure(self) -> dict:
        """The admission pressure signal this sweep plans against
        (cp/admission.py pressure()): {} when no source is wired."""
        src = self.pressure_source
        if src is None:
            adm = getattr(self.state, "admission", None)
            src = adm.pressure if adm is not None else None
        if src is None:
            return {}
        try:
            return src() or {}
        except Exception:
            log.exception("pressure source failed; planning without it")
            return {}

    def plan(self, pool: WorkerPool,
             pressure: Optional[dict] = None) -> tuple[int, list[Server]]:
        """(n_to_provision, servers_to_deprovision) for one pool.

        min_servers counts only ALIVE workers (online, or provisioning and
        younger than PROVISION_TIMEOUT_S): a pool whose machines died gets
        replacements, and a machine that never came up is reaped as a
        zombie rather than blocking replenishment forever.

        `pressure` is the streaming-admission feedback (cp/admission.py):
        SUSTAINED queue age or infeasible-parked arrivals mean the solver
        (or the fleet's capacity) is the bottleneck — provision one node
        per sweep beyond the floor and hold idle scale-down; a drained
        queue releases the hold so the normal idle-grace rules resume.
        The max_servers cap applies AFTER the pressure bump: pressure can
        never override the pool ceiling."""
        now = self.clock()
        servers = self._pool_servers(pool)
        zombies = [s for s in servers
                   if s.status == "provisioning"
                   and now - s.created_at >= PROVISION_TIMEOUT_S]
        def offline_age(s):
            return now - max(s.last_heartbeat, s.updated_at)

        corpses = [s for s in servers
                   if s.status == "offline"
                   and (offline_age(s) >= OFFLINE_BUSY_REAP_S
                        # a partitioned-but-working node still carries
                        # workload state: give its stages the longer window
                        or (offline_age(s) >= OFFLINE_REAP_S
                            and not self._is_busy(s)))]
        dead = zombies + corpses
        alive = [s for s in servers
                 if s.status == "online"
                 or (s.status == "provisioning" and s not in zombies)]
        need = max(pool.min_servers - len(alive), 0)
        pressurized = bool(pressure and pressure.get("sustained"))
        victims: list[Server] = list(dead)
        if (not pressurized and need == 0
                and len(alive) > pool.min_servers):
            # idle scale-down only when the admission queue is NOT under
            # sustained pressure: a hot queue means every node is about
            # to be needed, even one that looks idle this instant
            idle = [s for s in alive if self._is_idle(s)]
            # newest first: long-lived workers keep caches warm
            idle.sort(key=lambda s: s.created_at, reverse=True)
            surplus = len(alive) - pool.min_servers
            victims += idle[:surplus]
        if pressurized and need == 0:
            # solver pressure provisions ahead of the floor — one node
            # per sweep (a ratchet, not a thundering herd)
            need = 1
        # max_servers is a hard cap on provisioning (0 = uncapped); dead
        # records being reaped this sweep do not count against it —
        # applied LAST so neither the floor nor pressure can pierce it
        if pool.max_servers > 0:
            room = max(pool.max_servers - (len(servers) - len(dead)), 0)
            need = min(need, room)
        return need, victims

    # ------------------------------------------------------------------
    # one sweep
    # ------------------------------------------------------------------

    def run_sweep(self) -> list[ScaleAction]:
        actions: list[ScaleAction] = []
        pressure = self._pressure()
        _M_PRESSURE.set(float(pressure.get("oldest_age_s", 0.0))
                        if pressure.get("sustained") else 0.0)
        for pool in self.state.store.list("worker_pools"):
            provider_name = pool.preferred_labels.get(
                "provider", pool.required_labels.get("provider", ""))
            if not provider_name:
                continue   # pool without a provider is manually managed
            # refresh workload tracking BEFORE planning: busy workers get
            # their grace window restarted
            now = self.clock()
            for s in self._pool_servers(pool):
                if self._is_busy(s):
                    self._last_busy[s.slug] = now
            need, victims = self.plan(pool, pressure)
            inventory = None
            if victims:
                # one provider listing per pool, not per victim; a failed
                # listing SKIPS the deprovisions (deleting records without
                # deleting VMs would leak running, billing machines)
                try:
                    sp = self.state.server_provider_factory(provider_name)
                    inventory = {i.name: i for i in sp.list_servers()}
                except Exception as e:
                    log.error("provider list failed; deferring scale-down %s",
                              kv(pool=pool.name, error=e))
                    victims = []
                    # the plan assumed those victims were being reaped; with
                    # reaping deferred, re-clamp provisioning against the
                    # FULL record count so a capped pool cannot overshoot
                    if pool.max_servers > 0:
                        servers_now = len(self._pool_servers(pool))
                        need = min(need, max(pool.max_servers - servers_now, 0))
            for _ in range(need):
                actions.append(self._provision(pool, provider_name))
            for s in victims:
                actions.append(self._deprovision(pool, s, provider_name,
                                                 inventory))
        return actions

    def _provision(self, pool: WorkerPool, provider_name: str) -> ScaleAction:
        # slugs must be unique across daemon restarts (the counter resets):
        # probe the store until a free one is found
        while True:
            self._counter += 1
            slug = f"{pool.name}-w{self._counter}"
            if self.state.store.server_by_slug(slug) is None:
                break
        try:
            sp = self.state.server_provider_factory(provider_name)
            spec = ServerResource(name=slug, capacity=ResourceSpec())
            rec = self.state.store.create("servers", Server(
                tenant=pool.tenant, slug=slug, provider=provider_name,
                status="provisioning", pool=pool.name,
                capacity=ServerCapacity()))
            try:
                info = sp.create_server(spec)
            except Exception:
                self.state.store.delete("servers", rec.id)
                raise
            self.state.store.update("servers", rec.id,
                                    hostname=info.ip or "")
            log.info("scaled up %s", kv(pool=pool.name, slug=slug,
                                        provider=provider_name))
            return ScaleAction(pool.name, "provision", slug)
        except Exception as e:
            log.error("scale-up failed %s", kv(pool=pool.name, slug=slug,
                                               error=e))
            return ScaleAction(pool.name, "provision", slug, ok=False,
                               error=str(e))

    def _deprovision(self, pool: WorkerPool, s: Server,
                     provider_name: str,
                     inventory: Optional[dict] = None) -> ScaleAction:
        try:
            sp = self.state.server_provider_factory(provider_name)
            if inventory is None:
                inventory = {i.name: i for i in sp.list_servers()}
            match = inventory.get(s.slug)
            if match is not None and not sp.delete_server(match.id):
                return ScaleAction(pool.name, "deprovision", s.slug,
                                   ok=False, error="provider delete failed")
            self.state.store.delete("servers", s.id)
            self._last_busy.pop(s.slug, None)
            detector = getattr(self.state, "failure_detector", None)
            if detector is not None:
                # deliberate scale-down: stop tracking the lease (a dead
                # verdict for a deprovisioned worker would be noise)
                detector.forget(s.slug)
            self.state.placement.node_event(s.slug, online=False)
            log.info("scaled down %s", kv(pool=pool.name, slug=s.slug))
            return ScaleAction(pool.name, "deprovision", s.slug)
        except Exception as e:
            log.error("scale-down failed %s", kv(pool=pool.name, slug=s.slug,
                                                 error=e))
            return ScaleAction(pool.name, "deprovision", s.slug, ok=False,
                               error=str(e))

    # ------------------------------------------------------------------
    # background loop
    # ------------------------------------------------------------------

    async def run_loop(self) -> None:
        while True:
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, self.run_sweep)
            except Exception:
                log.exception("autoscaler sweep failed")
            await asyncio.sleep(self.interval_s)

    def spawn(self) -> None:
        self._task = asyncio.ensure_future(self.run_loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
