"""Control-plane replication: journal shipping, standby catch-up,
lease-based primary election, fencing.

The store (cp/store.py) already gives the CP a durable, replayable
journal; this module points it at OTHER PROCESSES. Borg runs an elected
Borgmaster with warm replicas holding a Paxos-replicated copy of the
cell state (Verma et al., EuroSys '15 §2.2); the same shape here rides
fleetflow's own pieces instead of a consensus library:

  journal shipping   every store mutation (including batched bursts)
                     streams to subscribed standbys as sequence-numbered
                     entries over the existing channel protocol
  gap detection      a standby applies entries at exactly seq+1; a skip
                     (slow-consumer eviction, missed frames) downgrades
                     it to snapshot catch-up — never silent divergence
  snapshot catch-up  a standby that joins late or falls behind installs
                     the primary's full snapshot (chunked under the
                     1 MiB frame cap), then resubscribes from its seq
  election           the ALIVE->SUSPECT->DEAD lease machine
                     (cp/failure_detector.py) pointed at the PRIMARY:
                     standbys ping it on an interval; a grace-expired
                     lease promotes the most-caught-up standby
  fencing            a monotonic epoch, bumped once per promotion and
                     stamped into every journal entry and agent command;
                     stale-epoch writes are refused at three doors (the
                     standby store, the replication channel, the agent)

Split-brain stance: with one standby (the supported topology) election
is trivially unique; with several, the primary gossips the ack table in
its ping replies so every standby knows who is most caught up, and only
the deterministic winner (highest acked seq, then lowest name) promotes.
Losing standbys stand down and keep re-dialing their configured primary
address — re-point them at the winner (config change, see the guide's
runbook); they do not discover its address on their own. A zombie
ex-primary that keeps running cannot damage the fleet: its epoch is
stale, so standbys refuse its journal and agents refuse its commands
(fleet_replication_fencing_rejections_total counts both).

Operator surface: `fleet cp replication status`, the replication block
in `fleet cp heal status`, and docs/guide/13-cp-replication.md (topology
+ the "my primary died" runbook).
"""

from __future__ import annotations

import asyncio
import json
import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from ..obs import get_logger, kv
from ..obs.metrics import REGISTRY
from .failure_detector import FailureDetector, LeaseConfig
from .store import ReplicationFenced, ReplicationGap, Store

log = get_logger("cp.replication")

__all__ = ["ReplicationConfig", "Replicator", "StandbyReplica",
           "StandbyRunner", "SNAPSHOT_CHUNK"]

# snapshot catch-up chunk size: comfortably under protocol.MAX_FRAME
# (1 MiB) after JSON string escaping overhead
SNAPSHOT_CHUNK = 256 * 1024

PRIMARY_SLUG = "primary"   # the one "agent" a standby's detector tracks

# metric catalog: docs/guide/13-cp-replication.md + 10-observability.md
_M_SHIPPED = REGISTRY.counter(
    "fleet_replication_entries_shipped_total",
    "Journal entries shipped to standbys (counted once per standby)")
_M_ACKED = REGISTRY.counter(
    "fleet_replication_entries_acked_total",
    "Journal entries acknowledged by standbys")
_M_LAG = REGISTRY.gauge(
    "fleet_replication_standby_lag",
    "Entries shipped but not yet acknowledged, by standby identity",
    labels=("standby",))
_M_FAILOVERS = REGISTRY.counter(
    "fleet_replication_failovers_total",
    "Standby promotions to primary (fencing epoch bumps)")
_M_CATCHUPS = REGISTRY.counter(
    "fleet_replication_snapshot_catchups_total",
    "Standby snapshot installs (bootstrap or stream-gap resync)")
_M_EPOCH = REGISTRY.gauge(
    "fleet_replication_epoch", "This CP's fencing epoch")
_M_ROLE = REGISTRY.gauge(
    "fleet_replication_role",
    "1 when this CP is the primary, 0 when a standby")


@dataclass
class ReplicationConfig:
    """Tuning knobs (docs/guide/13-cp-replication.md has sizing math).

    The election budget for a dead primary is `lease_s + grace_s` past
    the last successful ping; size `lease_s` >= 3x `ping_interval_s` so
    one dropped ping never starts the promotion clock."""
    ring_entries: int = 8192         # replayable backlog on the primary
    queue_batches: int = 4096        # per-standby send queue (batches)
    ping_interval_s: float = 2.0     # standby -> primary liveness probe
    lease_s: float = 10.0            # primary silence -> SUSPECT
    grace_s: float = 5.0             # suspect -> DEAD -> promote
    reconnect_backoff_s: float = 2.0


class _Standby:
    """Primary-side bookkeeping for one subscribed standby."""

    def __init__(self, conn, identity: str, queue_batches: int):
        self.conn = conn
        self.identity = identity
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=queue_batches)
        self.acked_seq = 0
        self.sent_seq = 0
        self.task: Optional[asyncio.Task] = None


class Replicator:
    """Primary-side journal shipper.

    Owns the store's `replication_sink`: every emitted entry lands in a
    bounded ring (the replayable backlog) and on each subscribed
    standby's send queue. The sink runs under the store lock — possibly
    on an executor thread — so it only buffers; the asyncio loop drains
    each standby's queue in order. A standby whose queue overflows has
    its queue cleared and keeps streaming: the seq gap it then observes
    downgrades it to snapshot catch-up (gap detection does the work a
    bespoke slow-consumer protocol would)."""

    def __init__(self, store: Store, *,
                 config: Optional[ReplicationConfig] = None,
                 loop: Optional[asyncio.AbstractEventLoop] = None):
        self.store = store
        self.config = config or ReplicationConfig()
        self._loop = loop
        self._ring: deque[tuple[int, str]] = deque(
            maxlen=self.config.ring_entries)
        # the sink runs under the STORE lock, possibly on an executor
        # thread, while attach/snapshot run on the asyncio loop — the
        # ring needs its own lock
        self._ring_lock = threading.Lock()
        self._standbys: dict[int, _Standby] = {}   # id(conn) -> state
        store.replication_sink = self._sink
        _M_EPOCH.set(store.epoch)
        _M_ROLE.set(1)

    # -- the store-lock side -------------------------------------------

    def _sink(self, entries: list[tuple[int, str]]) -> None:
        with self._ring_lock:
            self._ring.extend(entries)
        if not self._standbys:
            return
        loop = self._loop
        if loop is None:
            return
        loop.call_soon_threadsafe(self._fan_out, list(entries))

    # -- the asyncio side ----------------------------------------------

    def _fan_out(self, entries: list[tuple[int, str]]) -> None:
        for sb in list(self._standbys.values()):
            try:
                sb.queue.put_nowait(entries)
            except asyncio.QueueFull:
                # slow consumer: drop its backlog; the seq gap it sees
                # next forces a snapshot resync (never silent divergence)
                log.warning("standby send queue overflow %s",
                            kv(standby=sb.identity))
                while not sb.queue.empty():
                    sb.queue.get_nowait()

    async def _sender(self, sb: _Standby) -> None:
        try:
            while True:
                entries = await sb.queue.get()
                await sb.conn.send_event("replication", "append", {
                    "epoch": self.store.epoch,
                    "entries": entries,
                })
                sb.sent_seq = max(sb.sent_seq, entries[-1][0])
                _M_SHIPPED.inc(len(entries))
                _M_LAG.set(max(sb.sent_seq - sb.acked_seq, 0),
                           standby=sb.identity)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log.warning("standby stream ended %s",
                        kv(standby=sb.identity, error=e))
            self.detach(sb.conn)

    def attach(self, conn, identity: str, from_seq: int) -> dict:
        """`replication.subscribe`: register the connection as a standby
        sink. If `from_seq` is inside the ring window the backlog is
        queued and streaming begins; otherwise the standby must install
        a snapshot first (`snapshot_needed`)."""
        # lock order: the sink runs store-lock -> ring-lock, so NOTHING
        # here may touch the store while holding the ring lock (ABBA)
        store_seq, store_epoch = self.store.seq, self.store.epoch
        with self._ring_lock:
            ring_first = (self._ring[0][0] if self._ring
                          else store_seq + 1)
            if from_seq + 1 < ring_first:
                return {"snapshot_needed": True, "seq": store_seq,
                        "epoch": store_epoch}
            backlog = [(s, ln) for s, ln in self._ring if s > from_seq]
        sb = _Standby(conn, identity, self.config.queue_batches)
        sb.acked_seq = from_seq
        sb.sent_seq = from_seq
        if backlog:
            sb.queue.put_nowait(backlog)
        self._standbys[id(conn)] = sb
        sb.task = asyncio.ensure_future(self._sender(sb))
        log.info("standby subscribed %s", kv(
            standby=identity, from_seq=from_seq, backlog=len(backlog)))
        return {"subscribed": True, "seq": store_seq, "epoch": store_epoch}

    def detach(self, conn) -> None:
        sb = self._standbys.pop(id(conn), None)
        if sb is not None and sb.task is not None:
            sb.task.cancel()

    def ack(self, conn, seq: int) -> None:
        sb = self._standbys.get(id(conn))
        if sb is None:
            return
        newly = max(seq - sb.acked_seq, 0)
        sb.acked_seq = max(sb.acked_seq, seq)
        if newly:
            _M_ACKED.inc(newly)
        _M_LAG.set(max(sb.sent_seq - sb.acked_seq, 0), standby=sb.identity)

    # -- snapshot catch-up ---------------------------------------------

    def snapshot_chunks(self) -> tuple[dict, list[str]]:
        """Serialize the current snapshot into frame-safe chunks. Returns
        (meta, chunks); the standby fetches chunks by index and installs
        the reassembled document."""
        blob = json.dumps(self.store.snapshot_doc())
        chunks = [blob[i:i + SNAPSHOT_CHUNK]
                  for i in range(0, len(blob), SNAPSHOT_CHUNK)] or [""]
        meta = {"chunks": len(chunks), "bytes": len(blob),
                "seq": self.store.seq, "epoch": self.store.epoch}
        return meta, chunks

    # -- introspection --------------------------------------------------

    def status(self) -> dict:
        return {
            "role": "primary",
            "epoch": self.store.epoch,
            "seq": self.store.seq,
            "ring": {"entries": len(self._ring),
                     "first_seq": (self._ring[0][0]
                                   if self._ring else None)},  # benign race
            "standbys": [
                {"identity": sb.identity, "acked_seq": sb.acked_seq,
                 "sent_seq": sb.sent_seq,
                 "lag": max(sb.sent_seq - sb.acked_seq, 0)}
                for sb in sorted(self._standbys.values(),
                                 key=lambda s: s.identity)],
        }

    def max_lag(self) -> int:
        return max((sb.sent_seq - sb.acked_seq
                    for sb in self._standbys.values()), default=0)


class StandbyReplica:
    """Standby-side apply surface around a Store: stream entries in,
    detect gaps, install snapshots, promote. Transport-free so the chaos
    harness can drive it in-process and deterministically."""

    def __init__(self, store: Store):
        self.store = store
        self.applied = 0
        self.catchups = 0

    @property
    def last_seq(self) -> int:
        return self.store.seq

    def apply_lines(self, entries: list[tuple[int, str]]) -> int:
        """Apply shipped entries; raises ReplicationGap (resync needed)
        or ReplicationFenced (zombie writer) — both from the store."""
        n = self.store.apply_replicated(entries)
        self.applied += n
        return n

    def install(self, doc: dict) -> None:
        self.store.install_snapshot(doc)
        self.catchups += 1
        _M_CATCHUPS.inc()

    def promote(self) -> int:
        """Become the primary: bump the fencing epoch (journaled, so it
        replicates to any standby of OUR own) and flip the role gauges.
        The caller wires up the primary-side machinery (detector,
        reconverger, Replicator) around the promoted store."""
        epoch = self.store.bump_epoch()
        _M_FAILOVERS.inc()
        _M_EPOCH.set(epoch)
        _M_ROLE.set(1)
        log.warning("promoted to primary %s", kv(epoch=epoch,
                                                 seq=self.store.seq))
        return epoch


class StandbyRunner:
    """The standby's life: dial the primary, catch up, stream, watch the
    primary's lease, promote when it dies.

    The liveness signal is the standby's OWN FailureDetector tracking a
    single synthetic agent (the primary): every successful ping — and
    every applied append batch — renews the lease; a dropped connection
    fast-paths to SUSPECT exactly like an agent session loss. When the
    grace expires, the most-caught-up standby (by the ack table the
    primary gossips in ping replies) promotes; the rest stand down and
    keep re-dialing their CONFIGURED primary address — the operator
    re-points them at the winner (guide 13 runbook)."""

    def __init__(self, replica: StandbyReplica, host: str, port: int, *,
                 identity: str = "standby",
                 token: Optional[str] = None,
                 config: Optional[ReplicationConfig] = None,
                 on_promote: Optional[Callable[[], None]] = None,
                 clock=None):
        self.replica = replica
        self.host = host
        self.port = port
        self.identity = identity
        self.token = token
        self.config = config or ReplicationConfig()
        self.on_promote = on_promote
        self.detector = FailureDetector(
            LeaseConfig(lease_s=self.config.lease_s,
                        suspect_grace_s=self.config.grace_s),
            **({"clock": clock} if clock else {}))
        self.promoted = False
        self.conn = None
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()
        self._ack_table: dict[str, int] = {}
        _M_ROLE.set(0)

    # -- wiring ---------------------------------------------------------

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def run(self) -> None:
        while not self._stop.is_set() and not self.promoted:
            try:
                await self._session()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("standby session lost %s", kv(
                    primary=f"{self.host}:{self.port}", error=e))
            if self.promoted or self._stop.is_set():
                break
            # the dead session fast-paths the lease to SUSPECT; keep
            # sweeping while disconnected so grace expiry still promotes
            self.detector.observe_disconnect(PRIMARY_SLUG)
            deadline = (self.config.lease_s + self.config.grace_s
                        ) / max(self.config.ping_interval_s, 1e-9)
            for _ in range(int(deadline) + 2):
                if self._sweep_and_maybe_promote():
                    return
                try:
                    await asyncio.wait_for(
                        self._stop.wait(), self.config.ping_interval_s)
                    return
                except asyncio.TimeoutError:
                    pass
            await asyncio.sleep(self.config.reconnect_backoff_s)

    # -- one connected session -----------------------------------------

    async def _session(self) -> None:
        from .protocol import ProtocolClient
        conn, run_task = await ProtocolClient.connect(
            self.host, self.port, identity=self.identity, token=self.token,
            event_handlers={"replication": self._on_event})
        self.conn = conn
        try:
            self.detector.observe_heartbeat(PRIMARY_SLUG)
            sub = await conn.request("replication", "subscribe",
                                     {"from_seq": self.replica.last_seq,
                                      "identity": self.identity})
            if sub.get("snapshot_needed"):
                await self._catch_up(conn)
                sub = await conn.request(
                    "replication", "subscribe",
                    {"from_seq": self.replica.last_seq,
                     "identity": self.identity})
            if not sub.get("subscribed"):
                raise RuntimeError(f"subscribe refused: {sub}")
            log.info("streaming from primary %s", kv(
                primary=f"{self.host}:{self.port}",
                seq=self.replica.last_seq, epoch=sub.get("epoch")))
            while not self._stop.is_set():
                try:
                    pong = await conn.request(
                        "replication", "ping",
                        {"identity": self.identity,
                         "acked_seq": self.replica.last_seq},
                        timeout=self.config.ping_interval_s * 4)
                    self.detector.observe_heartbeat(PRIMARY_SLUG)
                    self._ack_table = {
                        s["identity"]: s["acked_seq"]
                        for s in pong.get("standbys", [])}
                except Exception:
                    # a failed ping is a missed heartbeat, nothing more:
                    # the lease machine decides when silence means death
                    pass
                if self._sweep_and_maybe_promote():
                    return
                if run_task.done():
                    raise RuntimeError("primary connection closed")
                try:
                    await asyncio.wait_for(self._stop.wait(),
                                           self.config.ping_interval_s)
                    return
                except asyncio.TimeoutError:
                    pass
        finally:
            self.conn = None
            await conn.close()
            run_task.cancel()

    async def _on_event(self, conn, method: str, payload: dict) -> None:
        if method != "append":
            return
        entries = [(int(s), ln) for s, ln in payload.get("entries", [])]
        try:
            self.replica.apply_lines(entries)
        except ReplicationGap:
            log.warning("stream gap; resyncing from snapshot %s",
                        kv(at_seq=self.replica.last_seq))
            await self._catch_up(conn)
        except ReplicationFenced as e:
            log.error("fenced append from stale primary %s", kv(error=e))
            return
        self.detector.observe_heartbeat(PRIMARY_SLUG)
        try:
            await conn.send_event("replication", "ack",
                                  {"seq": self.replica.last_seq})
        except Exception:
            pass   # the stream will resync on the next session

    async def _catch_up(self, conn) -> None:
        meta = await conn.request("replication", "snapshot", {})
        parts = []
        for i in range(int(meta["chunks"])):
            part = await conn.request("replication", "snapshot_chunk",
                                      {"chunk": i})
            parts.append(part["data"])
        self.replica.install(json.loads("".join(parts) or "{}"))
        log.info("snapshot installed %s", kv(
            seq=self.replica.last_seq, bytes=meta.get("bytes")))

    # -- election -------------------------------------------------------

    def _most_caught_up(self) -> bool:
        """Deterministic winner among the standbys the primary last
        gossiped: highest acked seq wins, ties break on lowest identity.
        An empty table (single-standby topology, or the primary died
        before ever gossiping) means we are the only candidate."""
        mine = self.replica.last_seq
        for ident, acked in sorted(self._ack_table.items()):
            if ident == self.identity:
                continue
            if acked > mine or (acked == mine and ident < self.identity):
                return False
        return True

    def _sweep_and_maybe_promote(self) -> bool:
        verdicts = self.detector.sweep()
        if not any(not v.online for v in verdicts):
            return False
        if not self._most_caught_up():
            log.info("primary dead but a peer standby is more caught up "
                     "%s", kv(mine=self.replica.last_seq,
                              table=dict(sorted(self._ack_table.items()))))
            return False
        self.promoted = True
        self.replica.promote()
        if self.on_promote is not None:
            self.on_promote()
        return True

    # -- introspection ---------------------------------------------------

    def status(self) -> dict:
        return {
            "role": "primary" if self.promoted else "standby",
            "primary": f"{self.host}:{self.port}",
            "epoch": self.replica.store.epoch,
            "seq": self.replica.last_seq,
            "applied": self.replica.applied,
            "snapshot_catchups": self.replica.catchups,
            "primary_lease": self.detector.status()["agents"].get(
                PRIMARY_SLUG),
        }
