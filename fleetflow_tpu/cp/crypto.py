"""Tenant secret encryption at rest.

Analog of controlplane crypto.rs:1-16: AES-256-GCM, wire format
base64(nonce ‖ ciphertext ‖ tag), master key from the
FLEETFLOW_MASTER_KEY env var as 64 hex chars. Uses the `cryptography`
package's AESGCM (the tag is appended to the ciphertext by the primitive,
matching the reference's layout).
"""

from __future__ import annotations

import base64
import os
import secrets as _secrets

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

from ..core.errors import ControlPlaneError

__all__ = ["SecretBox", "master_key_from_env", "generate_master_key"]

ENV_KEY = "FLEETFLOW_MASTER_KEY"
NONCE_LEN = 12


class CryptoError(ControlPlaneError):
    pass


def generate_master_key() -> str:
    return _secrets.token_hex(32)


def master_key_from_env() -> bytes:
    hexkey = os.environ.get(ENV_KEY, "")
    if len(hexkey) != 64:
        raise CryptoError(
            f"{ENV_KEY} must be 64 hex chars (32 bytes); got {len(hexkey)}")
    try:
        return bytes.fromhex(hexkey)
    except ValueError:
        raise CryptoError(f"{ENV_KEY} is not valid hex") from None


class SecretBox:
    def __init__(self, key: bytes):
        if len(key) != 32:
            raise CryptoError("AES-256-GCM key must be 32 bytes")
        self._aead = AESGCM(key)

    @classmethod
    def from_env(cls) -> "SecretBox":
        return cls(master_key_from_env())

    def encrypt(self, plaintext: str, aad: str = "") -> str:
        nonce = _secrets.token_bytes(NONCE_LEN)
        ct = self._aead.encrypt(nonce, plaintext.encode(),
                                aad.encode() or None)
        return base64.b64encode(nonce + ct).decode()

    def decrypt(self, token: str, aad: str = "") -> str:
        try:
            blob = base64.b64decode(token)
            nonce, ct = blob[:NONCE_LEN], blob[NONCE_LEN:]
            return self._aead.decrypt(nonce, ct, aad.encode() or None).decode()
        except Exception as e:
            raise CryptoError(f"decryption failed: {type(e).__name__}") from None
