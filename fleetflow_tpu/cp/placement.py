"""Placement service: the CP's solver front-end + reservation journal.

This is the component the reference lacks (its CP picks
`stage.servers.first`, handlers/deploy.rs:386-398, with fan-out "future
work"). Here the CP lowers the fleet against its *live* server inventory
(capacity minus committed+reserved allocations, label/pool eligibility,
cordon/drain masks) and solves on-device.

The reservation journal implements the 2-phase commit the reference sketches
in `ServerAllocated` (model.rs:421-427) and solves SURVEY.md hard part (c):
a solve RESERVES its assignment; the deploy either COMMITs (moving reserved
-> committed) or RELEASEs on failure. Concurrent re-solves see reserved
capacity as occupied, so racing placements can't double-book a node.

Churn handling (BASELINE config 5): `node_event` flips the validity bit and
triggers an incremental warm-start re-solve that moves only what churn
forces (solver migration stickiness).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..core.model import Flow, ResourceSpec, ServerLabels, ServerResource
from ..lower.tensors import ProblemTensors, lower_stage
from ..sched import (HostGreedyScheduler, Placement, TpuSolverScheduler,
                     place_with_fallback)
from .models import Server
from .store import Store

__all__ = ["PlacementService", "Reservation"]


@dataclass
class Reservation:
    id: str
    stage_key: str                      # "{project}/{stage}"
    demand_by_node: dict[str, np.ndarray]   # node slug -> (R,) reserved demand
    assignment: dict[str, str]
    committed: bool = False


def _server_to_resource(s: Server) -> ServerResource:
    return ServerResource(
        name=s.slug,
        capacity=ResourceSpec(cpu=s.capacity.cpu, memory=s.capacity.memory,
                              disk=s.capacity.disk),
        labels=ServerLabels(tier=s.labels.tier, region=s.labels.region,
                            clazz=s.labels.clazz, arch=s.labels.arch,
                            extra=dict(s.labels.extra)),
    )


class PlacementService:
    def __init__(self, store: Store, *, use_tpu: bool = False,
                 chains: int = 4, steps: int = 128):
        self.store = store
        self.use_tpu = use_tpu
        self._sched_tpu = TpuSolverScheduler(chains=chains, steps=steps)
        self._sched_host = HostGreedyScheduler()
        self._lock = threading.Lock()
        self._reservations: dict[str, Reservation] = {}   # in-flight only
        self._committed: dict[str, Reservation] = {}      # stage_key -> last
        self._ids = itertools.count(1)
        self._last: dict[str, tuple[ProblemTensors, Placement]] = {}

    # ------------------------------------------------------------------
    # inventory lowering
    # ------------------------------------------------------------------

    def _inventory(self, tenant: str,
                   slugs: Optional[list[str]] = None
                   ) -> tuple[list[ServerResource], np.ndarray]:
        """Live nodes + validity mask, with reserved+committed demand
        subtracted from capacity."""
        # a tenant sees its own servers plus the shared "default" pool;
        # "default" solves never touch tenant-dedicated capacity
        servers = self.store.list(
            "servers", lambda s: s.tenant in (tenant, "default")
            and (not slugs or s.slug in slugs))
        if not servers:
            raise ValueError(f"no servers registered for tenant {tenant!r}")
        reserved = self._reserved_by_node()
        nodes, valid = [], []
        for s in servers:
            res = _server_to_resource(s)
            alloc = np.array([s.allocated.cpu + s.allocated.reserved_cpu,
                              s.allocated.memory + s.allocated.reserved_memory,
                              s.allocated.disk + s.allocated.reserved_disk])
            alloc = alloc + reserved.get(s.slug, 0)
            cap = np.maximum(np.array(res.capacity.as_tuple()) - alloc, 0.0)
            res.capacity = ResourceSpec(cpu=float(cap[0]), memory=float(cap[1]),
                                        disk=float(cap[2]))
            nodes.append(res)
            valid.append(s.schedulable)
        return nodes, np.array(valid, dtype=bool)

    def _reserved_by_node(self) -> dict[str, np.ndarray]:
        out: dict[str, np.ndarray] = {}
        for r in self._reservations.values():
            if r.committed:
                continue
            for node, dem in r.demand_by_node.items():
                out[node] = out.get(node, 0) + dem
        return out

    # ------------------------------------------------------------------
    # solve + 2-phase reservation
    # ------------------------------------------------------------------

    def solve_stage(self, flow: Flow, stage_name: str, *,
                    tenant: str = "default",
                    reserve: bool = True) -> tuple[Placement, Optional[str]]:
        """Lower the stage against live inventory and solve; optionally open
        a reservation. Returns (placement, reservation_id)."""
        stage = flow.stage(stage_name)
        with self._lock:
            nodes, valid = self._inventory(tenant, stage.servers or None)
            # Config-declared labels back-fill: agents register slug +
            # capacity only, so live store records usually carry NO labels,
            # and a blank label passes every gate (_server_matches treats
            # tier=None as match-any, tensors.py) — a tier-gated stage
            # could silently place services on a declared-off-tier node
            # (found by the full-stack smoke: api landed on the standard
            # node).  Fill per FIELD: only fields the server API has not
            # set inherit the flow's declaration; API-set fields win.
            for n in nodes:
                decl = flow.servers.get(n.name)
                if decl is None:
                    continue
                d, got = decl.labels, n.labels
                n.labels = ServerLabels(
                    tier=got.tier if got.tier is not None else d.tier,
                    region=got.region if got.region is not None else d.region,
                    clazz=got.clazz if got.clazz is not None else d.clazz,
                    arch=got.arch if got.arch is not None else d.arch,
                    extra={**d.extra, **got.extra})
            pt = lower_stage(flow, stage_name, nodes=nodes)
            pt.node_valid &= valid
            key = f"{flow.name}/{stage_name}"
            prev = self._last.get(key)
            if self.use_tpu:
                warm = (prev is not None
                        and prev[0].S == pt.S and prev[0].N == pt.N)
                placement = self._sched_tpu.place(pt, warm_start=warm)
                if not placement.feasible and pt.relax_order:
                    placement, _ = place_with_fallback(
                        self._sched_tpu, pt, initial=placement)
            else:
                placement, _ = place_with_fallback(self._sched_host, pt)
            self._last[key] = (pt, placement)
            rid = None
            if reserve and placement.feasible:
                rid = self._reserve(key, pt, placement)
        return placement, rid

    def _reserve(self, key: str, pt: ProblemTensors,
                 placement: Placement) -> str:
        rid = f"rsv_{next(self._ids)}"
        demand_by_node: dict[str, np.ndarray] = {}
        for i, node in enumerate(placement.raw):
            slug = pt.node_names[int(node)]
            demand_by_node[slug] = (demand_by_node.get(slug, 0)
                                    + pt.demand[i].astype(np.float64))
        self._reservations[rid] = Reservation(
            id=rid, stage_key=key, demand_by_node=demand_by_node,
            assignment=dict(placement.assignment))
        return rid

    def _apply_allocation(self, r: Reservation, sign: float) -> None:
        for slug, dem in r.demand_by_node.items():
            s = self.store.server_by_slug(slug)
            if s is None:
                continue
            self.store.update("servers", s.id, allocated=type(s.allocated)(
                cpu=max(s.allocated.cpu + sign * float(dem[0]), 0.0),
                memory=max(s.allocated.memory + sign * float(dem[1]), 0.0),
                disk=max(s.allocated.disk + sign * float(dem[2]), 0.0),
                reserved_cpu=s.allocated.reserved_cpu,
                reserved_memory=s.allocated.reserved_memory,
                reserved_disk=s.allocated.reserved_disk,
            ))

    def commit(self, rid: str) -> bool:
        """Deploy succeeded: move reserved -> committed on the servers
        (2-phase step 2, model.rs:421-427). A redeploy of the same stage
        SUPERSEDES its previous commit — the old containers were stopped and
        replaced, so their allocation is returned first."""
        with self._lock:
            r = self._reservations.pop(rid, None)
            if r is None or r.committed:
                return False
            prev = self._committed.pop(r.stage_key, None)
            if prev is not None:
                self._apply_allocation(prev, -1.0)
            self._apply_allocation(r, +1.0)
            r.committed = True
            self._committed[r.stage_key] = r
            return True

    def release(self, rid: str, *, undo_commit: bool = False) -> bool:
        """Deploy failed or stage torn down: drop the reservation; with
        `undo_commit`, also return the stage's committed capacity."""
        with self._lock:
            r = self._reservations.pop(rid, None)
            if r is not None:
                return True
            if undo_commit:
                for key, c in list(self._committed.items()):
                    if c.id == rid:
                        self._apply_allocation(c, -1.0)
                        del self._committed[key]
                        return True
            return False

    def release_stage(self, stage_key: str) -> bool:
        """Stage torn down (`fleet down` on a remote stage): return its
        committed capacity."""
        with self._lock:
            c = self._committed.pop(stage_key, None)
            if c is None:
                return False
            self._apply_allocation(c, -1.0)
            return True

    def snapshot(self) -> dict[str, dict]:
        """Public view of the latest placement per stage (for REST/MCP)."""
        with self._lock:
            return {key: {"assignment": pl.assignment,
                          "feasible": pl.feasible,
                          "violations": pl.violations,
                          "source": pl.source,
                          "solve_ms": round(pl.solve_ms, 2)}
                    for key, (_pt, pl) in self._last.items()}

    # ------------------------------------------------------------------
    # streaming re-solve (BASELINE config 5)
    # ------------------------------------------------------------------

    def node_event(self, slug: str, *, online: bool) -> list[tuple[str, Placement]]:
        """Churn: flip the node's validity and warm-start re-solve every
        stage that had services there. Returns [(stage_key, new placement)].
        Device masks update as a small delta; the solver's migration
        stickiness keeps unaffected services in place."""
        return self.node_events([(slug, online)])

    def node_events(self, events: list[tuple[str, bool]]
                    ) -> list[tuple[str, Placement]]:
        """Coalesced churn (VERDICT r3 item 5): apply EVERY validity flip
        of a burst first, then warm re-solve each affected stage ONCE
        against the final mask — a 3-dead-1-revived burst costs one
        re-solve per stage, not four, and the solver sees the true final
        world instead of three intermediate ones (sequential re-solves can
        bounce services onto a node that the next event kills)."""
        for slug, online in events:
            s = self.store.server_by_slug(slug)
            if s is not None:
                self.store.update("servers", s.id,
                                  status="online" if online else "offline")
        moved: list[tuple[str, Placement]] = []
        with self._lock:
            for key, (pt, placement) in list(self._last.items()):
                needs_resolve = False
                flipped = False
                for slug, online in events:
                    if slug not in pt.node_names:
                        continue
                    j = pt.node_names.index(slug)
                    if bool(pt.node_valid[j]) == online:
                        continue
                    if not flipped:
                        pt.node_valid = pt.node_valid.copy()
                        flipped = True
                    pt.node_valid[j] = online
                    # a death with nothing placed on the node is a pure
                    # mask change; a death with services there forces a
                    # re-solve, and so does a REVIVE — the stage may be
                    # running degraded/infeasible on the shrunken pool and
                    # must get the chance to move back (the pre-coalescing
                    # behavior re-solved on every revive flip)
                    if online or np.any(np.asarray(placement.raw) == j):
                        needs_resolve = True
                if not needs_resolve:
                    continue
                if self.use_tpu:
                    new = self._sched_tpu.reschedule(pt)
                else:
                    new = self._sched_host.place(pt)
                if not new.feasible and pt.relax_order:
                    # a stage placed via declared relaxation must keep its
                    # relaxation through churn re-solves
                    sched = self._sched_tpu if self.use_tpu else self._sched_host
                    new, _ = place_with_fallback(sched, pt, initial=new)
                self._last[key] = (pt, new)
                moved.append((key, new))
        return moved
