"""Placement service: the CP's solver front-end + reservation journal.

This is the component the reference lacks (its CP picks
`stage.servers.first`, handlers/deploy.rs:386-398, with fan-out "future
work"). Here the CP lowers the fleet against its *live* server inventory
(capacity minus committed+reserved allocations, label/pool eligibility,
cordon/drain masks) and solves on-device.

The reservation journal implements the 2-phase commit the reference sketches
in `ServerAllocated` (model.rs:421-427) and solves SURVEY.md hard part (c):
a solve RESERVES its assignment; the deploy either COMMITs (moving reserved
-> committed) or RELEASEs on failure. Concurrent re-solves see reserved
capacity as occupied, so racing placements can't double-book a node.

Churn handling (BASELINE config 5): `node_event` flips the validity bit and
triggers an incremental warm-start re-solve that moves only what churn
forces (solver migration stickiness).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, replace as _dc_replace
from typing import Optional

import numpy as np

from ..core.model import Flow, ResourceSpec, ServerLabels, ServerResource
from ..lower.tensors import ProblemTensors, lower_stage
from ..obs import get_logger, kv
from ..obs.metrics import REGISTRY
from ..obs.slo import observe as slo_observe
from ..sched import (HostGreedyScheduler, Placement, TpuSolverScheduler,
                     level_schedule, place_with_fallback)
from .models import PlacementRecord, Server
from .store import Store

log = get_logger("cp.placement")

# metric catalog: docs/guide/10-observability.md. Churn re-solves that had
# to abandon the device solver (exception/timeout) for the greedy host
# path — self-healing must degrade, never stall (cp/reconverge.py).
_M_CHURN_FALLBACKS = REGISTRY.counter(
    "fleet_placement_churn_fallbacks_total",
    "Churn re-solves that fell back to the greedy host scheduler after a "
    "solver failure")

__all__ = ["PlacementService", "Reservation"]


@dataclass
class Reservation:
    id: str
    stage_key: str                      # "{project}/{stage}"
    demand_by_node: dict[str, np.ndarray]   # node slug -> (R,) reserved demand
    assignment: dict[str, str]
    committed: bool = False
    # churn reservations hold a displaced stage's NEW nodes between the
    # burst re-solve and the redeploy that re-commits it, so an admission
    # landing in that window cannot double-book them; superseded by the
    # stage's next solve/commit/release (never committed themselves)
    churn: bool = False


def _alloc_vector(s: Server) -> np.ndarray:
    """(R,) committed+reserved demand recorded on a server record — the ONE
    definition of 'how much of this node is spoken for' (used by admission
    inventory and churn capacity refresh alike)."""
    return np.array([s.allocated.cpu + s.allocated.reserved_cpu,
                     s.allocated.memory + s.allocated.reserved_memory,
                     s.allocated.disk + s.allocated.reserved_disk],
                    dtype=np.float64)


def _server_to_resource(s: Server) -> ServerResource:
    return ServerResource(
        name=s.slug,
        capacity=ResourceSpec(cpu=s.capacity.cpu, memory=s.capacity.memory,
                              disk=s.capacity.disk),
        labels=ServerLabels(tier=s.labels.tier, region=s.labels.region,
                            clazz=s.labels.clazz, arch=s.labels.arch,
                            extra=dict(s.labels.extra)),
    )


class PlacementService:
    def __init__(self, store: Store, *, use_tpu: bool = False,
                 chains=None, steps: int = 128):
        self.store = store
        self.use_tpu = use_tpu
        self._sched_tpu = TpuSolverScheduler(chains=chains, steps=steps)
        self._sched_host = HostGreedyScheduler()
        self._lock = threading.Lock()
        self._reservations: dict[str, Reservation] = {}   # in-flight only
        self._committed: dict[str, Reservation] = {}      # stage_key -> last
        self._ids = itertools.count(1)
        self._last: dict[str, tuple[ProblemTensors, Placement]] = {}
        # streaming-admission tombstones (cp/admission.py): rows kept in
        # the problem at zero demand so the padded shape tier survives a
        # departure, but masked OUT of every public assignment view —
        # a departed service must never look placed to invariants,
        # dashboards, or deploy fan-out
        self._masked: dict[str, frozenset] = {}
        # the committed book explains servers.allocated: rebuild it from
        # the store's placements table so a restarted (or promoted
        # standby, docs/guide/13-cp-replication.md) CP's next commit
        # SUPERSEDES the old allocation instead of stacking on top of it
        self._load_committed()

    # ------------------------------------------------------------------
    # committed-book persistence (crash/failover-safe capacity ledger)
    # ------------------------------------------------------------------

    def _load_committed(self) -> None:
        for rec in self.store.list("placements"):
            self._committed[rec.stage_key] = Reservation(
                id=f"rsv_{next(self._ids)}", stage_key=rec.stage_key,
                demand_by_node={slug: np.asarray(d, dtype=np.float64)
                                for slug, d in rec.demand_by_node.items()},
                assignment=dict(rec.assignment), committed=True)

    def _persist_committed(self, key: str) -> None:
        """Mirror the stage's committed reservation into the store (one
        row per stage, journaled and replicated). Caller holds the lock."""
        r = self._committed.get(key)
        rec = self.store.find_one("placements",
                                  lambda p: p.stage_key == key)
        if r is None:
            if rec is not None:
                self.store.delete("placements", rec.id)
            return
        attrs = dict(
            assignment=dict(r.assignment),
            demand_by_node={slug: [float(x) for x in np.asarray(d)]
                            for slug, d in r.demand_by_node.items()})
        if rec is None:
            self.store.create("placements",
                              PlacementRecord(stage_key=key, **attrs))
        else:
            self.store.update("placements", rec.id, **attrs)

    # ------------------------------------------------------------------
    # inventory lowering
    # ------------------------------------------------------------------

    def _inventory(self, tenant: str,
                   slugs: Optional[list[str]] = None,
                   exclude_demand: Optional[dict[str, np.ndarray]] = None,
                   ) -> tuple[list[ServerResource], np.ndarray]:
        """Live nodes + validity mask, with reserved+committed demand
        subtracted from capacity.  `exclude_demand` (slug -> (R,)) is
        demand attributed to the CALLING stage itself (e.g. its own churn
        hold) — excluded BEFORE the zero-clamp, so a deficit against a
        shrunken node cannot turn into phantom free capacity the way a
        post-clamp add-back would."""
        # a tenant sees its own servers plus the shared "default" pool;
        # "default" solves never touch tenant-dedicated capacity
        servers = self.store.list(
            "servers", lambda s: s.tenant in (tenant, "default")
            and (not slugs or s.slug in slugs))
        if not servers:
            raise ValueError(f"no servers registered for tenant {tenant!r}")
        reserved = self._reserved_by_node()
        nodes, valid = [], []
        for s in servers:
            res = _server_to_resource(s)
            alloc = _alloc_vector(s) + reserved.get(s.slug, 0)
            if exclude_demand:
                alloc = alloc - exclude_demand.get(s.slug, 0)
            cap = np.maximum(np.array(res.capacity.as_tuple()) - alloc, 0.0)
            res.capacity = ResourceSpec(cpu=float(cap[0]), memory=float(cap[1]),
                                        disk=float(cap[2]))
            nodes.append(res)
            valid.append(s.schedulable)
        return nodes, np.array(valid, dtype=bool)

    def _reserved_by_node(self) -> dict[str, np.ndarray]:
        out: dict[str, np.ndarray] = {}
        for r in self._reservations.values():
            if r.committed:
                continue
            for node, dem in r.demand_by_node.items():
                out[node] = out.get(node, 0) + dem
        return out

    # ------------------------------------------------------------------
    # solve + 2-phase reservation
    # ------------------------------------------------------------------

    def _apply_mask(self, key: str, placement: Placement) -> Placement:
        """Filter a stage's tombstoned (departed-but-row-retained) service
        names out of the public assignment. raw stays full-length — the
        solver's exact checker verifies every row, tombstones included."""
        mask = self._masked.get(key)
        if not mask:
            return placement
        return _dc_replace(placement, assignment={
            n: node for n, node in placement.assignment.items()
            if n not in mask})

    def solve_stage(self, flow: Flow, stage_name: str, *,
                    tenant: str = "default",
                    reserve: bool = True) -> tuple[Placement, Optional[str]]:
        """Lower the stage against live inventory and solve; optionally open
        a reservation. Returns (placement, reservation_id)."""
        stage = flow.stage(stage_name)
        key = f"{flow.name}/{stage_name}"
        with self._lock:
            # a full re-lower rebuilds the stage from the flow, which the
            # admission controller keeps tombstone-free
            self._masked.pop(key, None)
            # This stage's own churn hold is the placement this solve
            # supersedes, so it must not count against itself — but the
            # hold is only RELEASED when a real reservation replaces it
            # (_reserve): a reserve=False preview or an infeasible solve
            # must leave the double-book protection standing.
            own_churn: dict[str, np.ndarray] = {}
            for r in self._reservations.values():
                if r.churn and r.stage_key == key:
                    for slug, d in r.demand_by_node.items():
                        own_churn[slug] = own_churn.get(slug, 0) + d
            nodes, valid = self._inventory(tenant, stage.servers or None,
                                           exclude_demand=own_churn)
            # Config-declared labels back-fill: agents register slug +
            # capacity only, so live store records usually carry NO labels,
            # and a blank label passes every gate (_server_matches treats
            # tier=None as match-any, tensors.py) — a tier-gated stage
            # could silently place services on a declared-off-tier node
            # (found by the full-stack smoke: api landed on the standard
            # node).  Fill per FIELD: only fields the server API has not
            # set inherit the flow's declaration; API-set fields win.
            for n in nodes:
                decl = flow.servers.get(n.name)
                if decl is None:
                    continue
                d, got = decl.labels, n.labels
                n.labels = ServerLabels(
                    tier=got.tier if got.tier is not None else d.tier,
                    region=got.region if got.region is not None else d.region,
                    clazz=got.clazz if got.clazz is not None else d.clazz,
                    arch=got.arch if got.arch is not None else d.arch,
                    extra={**d.extra, **got.extra})
            pt = lower_stage(flow, stage_name, nodes=nodes)
            pt.node_valid &= valid
            prev = self._last.get(key)
            if self.use_tpu:
                warm = (prev is not None
                        and prev[0].S == pt.S and prev[0].N == pt.N)
                placement = self._sched_tpu.place(pt, warm_start=warm,
                                                  stage=key)
                if not placement.feasible and pt.relax_order:
                    placement, _ = place_with_fallback(
                        self._sched_tpu, pt, initial=placement,
                        place_kwargs={"stage": key})
            else:
                placement, _ = place_with_fallback(self._sched_host, pt)
            self._last[key] = (pt, placement)
            rid = None
            if reserve and placement.feasible:
                rid = self._reserve(key, pt, placement)
        return placement, rid

    def rehydrate(self, stage_key: str, flow: Flow,
                  tenant: str = "default") -> bool:
        """Failover/restart recovery: rebuild the stage's retained
        (problem, placement) entry by ADOPTING its committed assignment
        from the store's placements table — never by re-solving, which
        could silently diverge from what the fleet is actually running.
        Without this, a promoted standby's empty placement book would
        make every future churn re-solve skip the stage entirely
        (node_events only moves stages it has retained problems for).
        Returns False when there is nothing to adopt or the config has
        drifted past the record (the stage's next real solve rebuilds)."""
        rec = self.store.find_one("placements",
                                  lambda p: p.stage_key == stage_key)
        if rec is None:
            return False
        stage_name = stage_key.split("/", 1)[1]
        with self._lock:
            if stage_key in self._last:
                return True
            self._masked.pop(stage_key, None)   # flow carries no tombstones
            committed = self._committed.get(stage_key)
            # the committed demand is the stage's OWN load: exclude it
            # from inventory like solve_stage excludes its churn hold,
            # or the adopted placement double-counts itself
            exclude = dict(committed.demand_by_node) if committed else None
            nodes, valid = self._inventory(
                tenant, flow.stage(stage_name).servers or None,
                exclude_demand=exclude)
            pt = lower_stage(flow, stage_name, nodes=nodes)
            pt.node_valid &= valid
            node_idx = {n: i for i, n in enumerate(pt.node_names)}
            raw = np.zeros(pt.S, dtype=np.int64)
            for i, row in enumerate(pt.service_names):
                idx = node_idx.get(rec.assignment.get(row, ""), -1)
                if idx < 0:
                    return False   # drifted config/inventory: solve anew
                raw[i] = idx
            # the adopted rows prove their nodes were valid AT SOLVE
            # TIME: mark them valid in the retained problem even if the
            # node is offline in today's inventory, so the failure
            # detector's verdict flip registers as a CHANGE and triggers
            # the re-solve that moves the stage off the dead node
            pt.node_valid = pt.node_valid.copy()
            pt.node_valid[np.unique(raw)] = True
            self._last[stage_key] = (pt, Placement(
                assignment=dict(rec.assignment),
                levels=level_schedule(pt), feasible=True,
                source="rehydrated", raw=raw))
        log.info("placement rehydrated %s", kv(stage=stage_key,
                                               rows=pt.S))
        return True

    def admit_batch(self, stage_key: str, pt: ProblemTensors, delta=None,
                    *, tenant: str = "default", masked=None,
                    ) -> tuple[Placement, Optional[str], ProblemTensors]:
        """Streaming-admission micro-solve (cp/admission.py): solve a
        pre-built candidate problem — the stage's streaming pt with this
        batch's arrivals scattered in and departures tombstoned — warm
        through the resident delta path, and open a reservation for the
        whole batch. The candidate arrives in the delta shape
        (dataclasses.replace sharing every untouched tensor), so steady
        in-tier drift reuses ONE compiled executable and never crosses the
        host boundary.

        Unlike solve_stage, the stage's OWN standing demand (committed +
        in-flight) is excluded from capacity — its services are the ones
        being re-placed, and a stream that saw itself as load would choke
        on its own success. Returns (placement, reservation_id, pt_used);
        on an infeasible solve the retained (pt, placement) entry is left
        standing (the stage IS still feasibly placed without the batch)
        and reservation_id is None."""
        with self._lock:
            server_map = {s.slug: s for s in self.store.list("servers")}
            valid = np.array(
                [bool(server_map[slug].schedulable)
                 if slug in server_map else bool(pt.node_valid[j])
                 for j, slug in enumerate(pt.node_names)], dtype=bool)
            if not np.array_equal(valid, pt.node_valid):
                pt = _dc_replace(pt, node_valid=valid)
            pt = self._refresh_capacity(pt, stage_key,
                                        server_map=server_map)
            if delta is not None:
                # the delta always re-ships the small planes; keep them
                # coherent with the refreshed candidate
                delta.node_valid = pt.node_valid
                delta.capacity = pt.capacity
            degraded = False
            try:
                if self.use_tpu:
                    new = self._sched_tpu.reschedule(pt, delta=delta,
                                                     stage=stage_key)
                else:
                    new = self._sched_host.place(pt)
            except Exception as e:
                # same degradation contract as node_events: an admission
                # micro-solve must cost quality, not liveness
                _M_CHURN_FALLBACKS.inc()
                degraded = True
                log.error("admission solve failed; greedy host fallback %s",
                          kv(stage=stage_key, error=e))
                new = self._sched_host.place(pt)
            if not new.feasible and pt.relax_order:
                sched = (self._sched_host if degraded or not self.use_tpu
                         else self._sched_tpu)
                new, _ = place_with_fallback(
                    sched, pt, initial=new,
                    place_kwargs=({"stage": stage_key}
                                  if sched is self._sched_tpu else None))
            if not new.feasible:
                return self._apply_mask(stage_key, new), None, pt
            self._masked[stage_key] = frozenset(masked or ())
            new = self._apply_mask(stage_key, new)
            self._last[stage_key] = (pt, new)
            rid = self._reserve(stage_key, pt, new)
        return new, rid, pt

    @staticmethod
    def _demand_by_node(pt: ProblemTensors,
                        placement: Placement) -> dict[str, np.ndarray]:
        out: dict[str, np.ndarray] = {}
        for i, node in enumerate(placement.raw):
            dem = pt.demand[i]
            if not dem.any():
                continue    # zero-demand rows (admission tombstones)
                            # must not materialize per-node entries
            slug = pt.node_names[int(node)]
            out[slug] = out.get(slug, 0) + dem.astype(np.float64)
        return out

    def _drop_churn(self, key: str) -> None:
        """A stage's newly-created reservation (_reserve), a fresh
        commitment, or its teardown supersedes any churn reservation still
        holding its displaced placement.  Preview solves do NOT drop it —
        they add it back to their own inventory instead (solve_stage)."""
        for rid, r in list(self._reservations.items()):
            if r.churn and r.stage_key == key:
                del self._reservations[rid]

    def _reserve(self, key: str, pt: ProblemTensors,
                 placement: Placement) -> str:
        self._drop_churn(key)
        rid = f"rsv_{next(self._ids)}"
        self._reservations[rid] = Reservation(
            id=rid, stage_key=key,
            demand_by_node=self._demand_by_node(pt, placement),
            assignment=dict(placement.assignment))
        return rid

    def _apply_allocation(self, r: Reservation, sign: float) -> None:
        for slug, dem in r.demand_by_node.items():
            s = self.store.server_by_slug(slug)
            if s is None:
                continue
            self.store.update("servers", s.id, allocated=type(s.allocated)(
                cpu=max(s.allocated.cpu + sign * float(dem[0]), 0.0),
                memory=max(s.allocated.memory + sign * float(dem[1]), 0.0),
                disk=max(s.allocated.disk + sign * float(dem[2]), 0.0),
                reserved_cpu=s.allocated.reserved_cpu,
                reserved_memory=s.allocated.reserved_memory,
                reserved_disk=s.allocated.reserved_disk,
            ))

    def _apply_allocation_delta(self, prev: Reservation,
                                new: Reservation) -> None:
        """Supersede `prev` by `new` touching only the nodes whose demand
        actually CHANGED. Numerically identical to apply(prev, -1) +
        apply(new, +1), but a streaming micro-solve commit (one per drain
        tick, cp/admission.py) only moves a batch's worth of nodes —
        rewriting every server record of a 10k-service stage per commit
        was the admission bench's bottleneck, not the solve."""
        slugs = set(prev.demand_by_node) | set(new.demand_by_node)
        zero = np.zeros(3)
        for slug in slugs:
            d = (np.asarray(new.demand_by_node.get(slug, zero),
                            dtype=np.float64)
                 - np.asarray(prev.demand_by_node.get(slug, zero),
                              dtype=np.float64))
            if not d.any():
                continue
            s = self.store.server_by_slug(slug)
            if s is None:
                continue
            self.store.update("servers", s.id, allocated=type(s.allocated)(
                cpu=max(s.allocated.cpu + float(d[0]), 0.0),
                memory=max(s.allocated.memory + float(d[1]), 0.0),
                disk=max(s.allocated.disk + float(d[2]), 0.0),
                reserved_cpu=s.allocated.reserved_cpu,
                reserved_memory=s.allocated.reserved_memory,
                reserved_disk=s.allocated.reserved_disk,
            ))

    def commit(self, rid: str) -> bool:
        """Deploy succeeded: move reserved -> committed on the servers
        (2-phase step 2, model.rs:421-427). A redeploy of the same stage
        SUPERSEDES its previous commit — the old containers were stopped and
        replaced, so their allocation is returned first."""
        with self._lock:
            r = self._reservations.pop(rid, None)
            if r is None or r.committed:
                return False
            prev = self._committed.pop(r.stage_key, None)
            if prev is not None:
                self._apply_allocation_delta(prev, r)
            else:
                self._apply_allocation(r, +1.0)
            r.committed = True
            self._committed[r.stage_key] = r
            self._drop_churn(r.stage_key)   # commitment reflects reality now
            self._persist_committed(r.stage_key)
            return True

    def release(self, rid: str, *, undo_commit: bool = False) -> bool:
        """Deploy failed or stage torn down: drop the reservation; with
        `undo_commit`, also return the stage's committed capacity."""
        with self._lock:
            r = self._reservations.pop(rid, None)
            if r is not None:
                return True
            if undo_commit:
                for key, c in list(self._committed.items()):
                    if c.id == rid:
                        self._apply_allocation(c, -1.0)
                        del self._committed[key]
                        self._drop_churn(key)   # torn down: nothing to hold
                        self._persist_committed(key)
                        return True
            return False

    def commit_retained(self, stage_key: str) -> bool:
        """Adopt the stage's retained placement as its committed allocation
        — the reconverger's commit path (cp/reconverge.py): a churn
        re-solve's assignment was actually redeployed to the surviving
        agents, so the churn hold graduates to the commitment, superseding
        the pre-churn one (same supersede semantics as commit())."""
        with self._lock:
            entry = self._last.get(stage_key)
            if entry is None:
                return False
            pt, placement = entry
            if not placement.feasible:
                return False
            r = Reservation(
                id=f"rsv_{next(self._ids)}", stage_key=stage_key,
                demand_by_node=self._demand_by_node(pt, placement),
                assignment=dict(placement.assignment), committed=True)
            prev = self._committed.pop(stage_key, None)
            if prev is not None:
                self._apply_allocation(prev, -1.0)
            self._apply_allocation(r, +1.0)
            self._committed[stage_key] = r
            self._drop_churn(stage_key)
            self._persist_committed(stage_key)
            return True

    def release_stage(self, stage_key: str) -> bool:
        """Stage torn down (`fleet down` on a remote stage): return its
        committed capacity."""
        with self._lock:
            self._drop_churn(stage_key)
            c = self._committed.pop(stage_key, None)
            if c is None:
                return False
            self._apply_allocation(c, -1.0)
            self._persist_committed(stage_key)
            return True

    def _snapshot_locked(self) -> dict[str, dict]:
        return {key: {"assignment": pl.assignment,
                      "feasible": pl.feasible,
                      "violations": pl.violations,
                      "source": pl.source,
                      "solve_ms": round(pl.solve_ms, 2)}
                for key, (_pt, pl) in self._last.items()}

    def _reservations_locked(self) -> dict:
        def dem(d: dict[str, np.ndarray]) -> dict[str, list[float]]:
            return {slug: [round(float(x), 3)
                           for x in np.asarray(v, dtype=np.float64).ravel()]
                    for slug, v in d.items()}

        return {
            "in_flight": [
                {"id": r.id, "stage": r.stage_key, "churn": r.churn,
                 "demand_by_node": dem(r.demand_by_node)}
                for r in self._reservations.values()],
            "committed": [
                {"id": r.id, "stage": key,
                 "demand_by_node": dem(r.demand_by_node)}
                for key, r in self._committed.items()],
        }

    def snapshot(self) -> dict[str, dict]:
        """Public view of the latest placement per stage (for REST/MCP)."""
        with self._lock:
            return self._snapshot_locked()

    def solver_slots(self) -> dict:
        """Device slot-manager occupancy (sched/tpu.py slots_status):
        per-stage resident tier/bytes/idle/evictions plus the byte
        budget — the `fleet solve slots` payload."""
        return self._sched_tpu.slots_status()

    def retained(self, stage_key: str
                 ) -> Optional[tuple[ProblemTensors, Placement]]:
        """The retained (problem, placement) pair for a stage — what
        `explain` answers from. The chaos invariant checker re-verifies
        the final assignment against the solver's own exact checker
        (solver/repair.verify) through this accessor."""
        with self._lock:
            return self._last.get(stage_key)

    def reservations_snapshot(self) -> dict:
        """Public view of the 2-phase journal: in-flight reservations
        (including churn holds awaiting a redeploy) and committed
        allocations per stage — the operator's answer to "why is this
        node's capacity spoken for?"."""
        with self._lock:
            return self._reservations_locked()

    def placement_state(self) -> dict:
        """Both views under ONE lock acquisition, so a commit landing
        between them cannot make the dashboard render a placement with a
        contradictory journal (and a long solve is only waited out once)."""
        with self._lock:
            return {"stages": self._snapshot_locked(),
                    "reservations": self._reservations_locked()}

    def explain(self, stage_key: str, service: str, top_k: int = 5) -> dict:
        """Why is `service` where it is in `stage_key`'s latest placement?
        Per-node hard/soft breakdown from the retained (pt, placement) —
        solver/explain.py — answered from memory, no re-solve. Raises
        KeyError for an unknown stage or service."""
        from ..solver.explain import explain_assignment

        with self._lock:
            entry = self._last.get(stage_key)
            if entry is None:
                raise KeyError(
                    f"no retained placement for stage {stage_key!r}; "
                    f"known: {sorted(self._last)}")
            pt, placement = entry
            if placement.raw is not None:
                assignment = np.asarray(placement.raw)
            else:
                node_idx = {n: j for j, n in enumerate(pt.node_names)}
                assignment = np.array(
                    [node_idx[placement.assignment[nm]]
                     for nm in pt.service_names], dtype=np.int64)
            out = explain_assignment(pt, assignment, service, top_k=top_k)
            out["stage"] = stage_key
            out["source"] = placement.source
            return out

    # ------------------------------------------------------------------
    # streaming re-solve (BASELINE config 5)
    # ------------------------------------------------------------------

    def _stage_demand(self, key: str) -> dict[str, np.ndarray]:
        """Per-node demand currently attributed to stage `key`: its
        committed allocation plus any of its own IN-FLIGHT reservations
        (a churn re-solve racing the stage's deploy window must not
        double-count the stage against itself)."""
        out: dict[str, np.ndarray] = {}
        c = self._committed.get(key)
        if c is not None:
            for slug, d in c.demand_by_node.items():
                out[slug] = out.get(slug, 0) + d
        for r in self._reservations.values():
            if r.stage_key == key and not r.committed:
                for slug, d in r.demand_by_node.items():
                    out[slug] = out.get(slug, 0) + d
        return out

    def _refresh_capacity(self, pt: ProblemTensors, key: str,
                          overrides: Optional[dict[str, tuple]] = None,
                          server_map: Optional[dict[str, Server]] = None,
                          ) -> ProblemTensors:
        """Live per-node capacity for a churn re-solve of stage `key`:
        raw capacity minus committed allocations and in-flight
        reservations, plus this stage's OWN demand back (committed AND
        reserved — its services are the ones being re-placed).

        `overrides` maps stages already re-solved EARLIER IN THE SAME
        BURST to (their stage-demand snapshot, their new per-node demand):
        their store records still cite the pre-burst nodes, so without the
        substitution two stages displaced by one burst would each see the
        other at its old (dead) node and double-book the survivor.
        `server_map` (slug -> Server) avoids a per-node linear store scan
        when the caller already holds one.  Returns pt unchanged (same
        object, so device stagings keyed on identity stay warm) when
        nothing moved; otherwise a copy with fresh capacity."""
        own = self._stage_demand(key)
        reserved = self._reserved_by_node()
        other = [snap for okey, snap in (overrides or {}).items()
                 if okey != key]
        cap = pt.capacity.copy()
        for j, slug in enumerate(pt.node_names):
            s = (server_map.get(slug) if server_map is not None
                 else self.store.server_by_slug(slug))
            if s is None:
                continue
            alloc = (_alloc_vector(s) + reserved.get(slug, 0)
                     - own.get(slug, 0))
            for old_dem, new_dem in other:
                alloc = (alloc - old_dem.get(slug, 0)
                         + new_dem.get(slug, 0))
            raw = np.array([s.capacity.cpu, s.capacity.memory,
                            s.capacity.disk], dtype=np.float64)
            cap[j] = np.maximum(raw - alloc, 0.0)
        if np.array_equal(cap, pt.capacity):
            return pt
        return _dc_replace(pt, capacity=cap)

    def node_event(self, slug: str, *, online: bool) -> list[tuple[str, Placement]]:
        """Churn: flip the node's validity and warm-start re-solve every
        stage that had services there. Returns [(stage_key, new placement)].
        Device masks update as a small delta; the solver's migration
        stickiness keeps unaffected services in place."""
        return self.node_events([(slug, online)])

    def node_events(self, events: list[tuple[str, bool]]
                    ) -> list[tuple[str, Placement]]:
        """Coalesced churn (VERDICT r3 item 5): apply EVERY validity flip
        of a burst first, then warm re-solve each affected stage ONCE
        against the final mask — a 3-dead-1-revived burst costs one
        re-solve per stage, not four, and the solver sees the true final
        world instead of three intermediate ones (sequential re-solves can
        bounce services onto a node that the next event kills)."""
        for slug, online in events:
            s = self.store.server_by_slug(slug)
            if s is not None:
                self.store.update("servers", s.id,
                                  status="online" if online else "offline")
        moved: list[tuple[str, Placement]] = []
        # stages re-solved earlier in THIS burst -> (stage-demand snapshot,
        # new per-node demand), so later re-solves see them at their new
        # homes instead of their stale store records (double-booking the
        # survivor node)
        overrides: dict[str, tuple] = {}
        with self._lock:
            server_map = {s.slug: s for s in self.store.list("servers")}
            for key, (pt, placement) in list(self._last.items()):
                needs_resolve = False
                flipped = False
                for slug, online in events:
                    if slug not in pt.node_names:
                        continue
                    j = pt.node_names.index(slug)
                    if bool(pt.node_valid[j]) == online:
                        continue
                    if not flipped:
                        pt.node_valid = pt.node_valid.copy()
                        flipped = True
                    pt.node_valid[j] = online
                    # a death with nothing placed on the node is a pure
                    # mask change; a death with services there forces a
                    # re-solve, and so does a REVIVE — the stage may be
                    # running degraded/infeasible on the shrunken pool and
                    # must get the chance to move back (the pre-coalescing
                    # behavior re-solved on every revive flip)
                    if online or np.any(np.asarray(placement.raw) == j):
                        needs_resolve = True
                if not needs_resolve:
                    continue
                # Admission-during-churn (SURVEY hard part (c)): pt's
                # capacity is a snapshot from this stage's admission;
                # stages committed SINCE then have filled nodes pt still
                # sees as free, so a warm re-solve against the stale view
                # can double-book a node (each solve is self-consistent,
                # so no violation counter would ever say so). Rebuild
                # per-node capacity from live inventory, excluding this
                # stage's own commitment + in-flight reservations (its
                # services are the ones being re-placed) and substituting
                # burst-mates' already-re-solved positions.
                pt = self._refresh_capacity(pt, key, overrides, server_map)
                degraded = False
                t_solve = time.perf_counter()
                try:
                    if self.use_tpu:
                        # structured churn instead of a full re-staging:
                        # validity flips + refreshed capacity ride a
                        # ProblemDelta, which the scheduler merges into
                        # its device-resident problem when the bucket
                        # identity holds (solver/resident.py) — the
                        # (S, N) problem planes never re-cross the host
                        # boundary on a reconvergence burst. Content
                        # drift beyond the delta cold-stages safely.
                        from ..solver.resident import ProblemDelta
                        new = self._sched_tpu.reschedule(
                            pt, delta=ProblemDelta(node_valid=pt.node_valid,
                                                   capacity=pt.capacity),
                            stage=key)
                    else:
                        new = self._sched_host.place(pt)
                except Exception as e:
                    # graceful degradation: a churn re-solve is on the
                    # self-healing critical path — a solver crash/timeout
                    # must cost solution quality, not convergence. The
                    # greedy host path solves the same tensors.
                    _M_CHURN_FALLBACKS.inc()
                    degraded = True
                    log.error("churn solve failed; greedy host fallback %s",
                              kv(stage=key, error=e))
                    new = self._sched_host.place(pt)
                if not new.feasible and pt.relax_order:
                    # a stage placed via declared relaxation must keep its
                    # relaxation through churn re-solves (and a crashed
                    # device solver stays benched for the ladder too)
                    sched = (self._sched_host if degraded or not self.use_tpu
                             else self._sched_tpu)
                    new, _ = place_with_fallback(
                        sched, pt, initial=new,
                        place_kwargs=({"stage": key}
                                      if sched is self._sched_tpu else None))
                # the warm-reschedule latency SLO stream (obs/slo.py):
                # one sample per stage re-solve, relax-ladder included —
                # this IS the placement-p99-ms an operator declares
                slo_observe("placement_ms",
                            (time.perf_counter() - t_solve) * 1e3)
                # a streaming stage's tombstoned rows stay masked through
                # churn re-solves too
                new = self._apply_mask(key, new)
                self._last[key] = (pt, new)
                if new.feasible:
                    new_dem = self._demand_by_node(pt, new)
                    # hold the displaced stage's NEW nodes until its
                    # redeploy re-commits: an admission landing between
                    # the burst and the redeploy must not double-book
                    # them.  Reserve only the DELTA above the stage's
                    # still-standing demand (committed allocation AND any
                    # in-flight reservation of its own), so no service is
                    # counted twice.
                    self._drop_churn(key)
                    old = self._stage_demand(key)
                    delta = {}
                    for slug, d in new_dem.items():
                        extra = np.maximum(
                            np.asarray(d, dtype=np.float64)
                            - old.get(slug, 0), 0.0)
                        if extra.any():
                            delta[slug] = extra
                    if delta:
                        rid = f"rsv_{next(self._ids)}"
                        self._reservations[rid] = Reservation(
                            id=rid, stage_key=key, demand_by_node=delta,
                            assignment=dict(new.assignment), churn=True)
                    # snapshot AFTER the churn reservation exists: burst-
                    # mates' refreshes subtract this exact view and add
                    # new_dem, cancelling the reservation they also see
                    # in _reserved_by_node
                    overrides[key] = (self._stage_demand(key), new_dem)
                moved.append((key, new))
        return moved
