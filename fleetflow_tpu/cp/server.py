"""Control-plane server bootstrap.

Analog of controlplane server.rs:82-197: store connect -> auth select ->
AppState{store, auth, agent_registry, log_router, placement} -> register
channels -> mesh CA load/gen + per-boot server cert -> listen; a
CpServerHandle supports graceful shutdown (server.rs CpServerHandle).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import asyncio

from .admission import AdmissionConfig, AdmissionController
from .agent_registry import AgentRegistry
from .auth import Claims, NoAuth, make_provider
from .failure_detector import FailureDetector, LeaseConfig
from .log_router import LogRouter
from .placement import PlacementService
from .protocol import ProtocolServer
from .reconverge import ReconvergeConfig, Reconverger
from .replication import (ReplicationConfig, Replicator, StandbyReplica,
                          StandbyRunner)
from .shards import ShardTable, shards_from_env
from .store import Store
from ..obs import get_logger, kv

log = get_logger("cp.server")

__all__ = ["ServerConfig", "AppState", "CpServerHandle", "start"]


@dataclass
class ServerConfig:
    host: str = "127.0.0.1"
    port: int = 0                      # 0 = ephemeral (tests)
    name: str = "fleetflow-cp"
    db_path: Optional[str] = None      # None = in-memory (kv-mem analog)
    auth_kind: str = "none"            # none | token | jwks/auth0
    auth_secret: Optional[str] = None
    auth_jwks: Optional[str] = None    # JWKS url/path for kind=jwks
    auth_issuer: Optional[str] = None
    auth_audience: Optional[str] = None
    auth_client_id: Optional[str] = None   # OAuth client for device flow
    tls_dir: Optional[str] = None      # mesh-CA dir; None = plaintext
    use_tpu_solver: bool = False
    master_key_env: bool = False       # load SecretBox from env
    # self-healing (cp/failure_detector.py + cp/reconverge.py): lease-
    # based failure detection driving automatic re-solve + redeploy.
    # Tuning guidance: docs/guide/12-self-healing.md
    self_heal: bool = True
    lease_s: float = 90.0
    suspect_grace_s: float = 30.0
    heal_interval_s: float = 5.0
    heal_backoff_base_s: float = 2.0
    heal_backoff_max_s: float = 60.0
    heal_max_attempts: int = 5
    # replication (cp/replication.py, docs/guide/13-cp-replication.md).
    # A primary needs nothing: standbys dial in on the replication
    # channel. A standby sets `standby_of` to the primary's host:port;
    # it streams the journal, watches the primary's lease, and promotes
    # itself (epoch bump + fencing) when the lease dies.
    standby_of: Optional[str] = None
    standby_token: Optional[str] = None      # auth for the primary dial
    replication_ring: int = 8192             # replayable backlog entries
    standby_ping_interval_s: float = 2.0
    standby_lease_s: float = 10.0
    standby_grace_s: float = 5.0
    # streaming admission (cp/admission.py, docs/guide/14): continuous
    # arrivals/departures batched into bucketed micro-solves with
    # backpressure + tenant fairness; primaries only (a standby must not
    # admit — there is one writer per epoch)
    admission: bool = True
    admission_queue: int = 4096
    admission_batch: int = 128
    admission_shed_age_s: float = 120.0
    # rolling SLO objectives (obs/slo.py, docs/guide/10): objective
    # name -> threshold, e.g. {"placement-p99-ms": 50}. The engine is
    # built on primaries (and on promotion) even with no objectives —
    # `fleet slo status` then reports raw stream quantiles only.
    slo: Optional[dict] = None
    # fleet-horizon collector (obs/collector.py + obs/tsdb.py): the
    # cadence sampler feeding the in-process time-series store behind
    # `fleet top`, `fleet obs query/export` and the obs.query channel.
    # Primaries only (built again on promotion) — a standby's series
    # would be all zeros with no agents attached.
    collector: bool = True
    collector_interval_s: float = 5.0
    collector_capacity: int = 512          # samples retained per series
    collector_max_series: int = 4096       # series-cardinality cap
    # control-plane fan-out sharding (cp/shards.py, docs/guide/17):
    # agents are consistent-hashed onto this many worker shards; every
    # fan-out path (registry batches, log lanes, verdict coalescing)
    # runs shard-parallel. 0 = take FLEET_CP_SHARDS from the env
    # (default 4); 1 = effectively unsharded.
    cp_shards: int = 0


@dataclass
class AppState:
    """server.rs AppState:18-28 (+ the placement service)."""
    store: Store
    auth: object
    agent_registry: AgentRegistry
    log_router: LogRouter
    placement: PlacementService
    name: str = "fleetflow-cp"
    secret_box: Optional[object] = None
    dns_backend: Optional[object] = None
    backend_factory: Callable = None       # () -> ContainerBackend
    # name -> cloud ServerProvider (server.rs provision path; injectable
    # for tests, shells out to usacloud/aws otherwise)
    server_provider_factory: Callable = None
    ssh_runner: Callable = None            # injectable for deploy.run tests
    deploy_sleep: Callable[[float], None] = time.sleep
    started_at: float = field(default_factory=time.time)
    bg_tasks: set = field(default_factory=set)
    # chaos-harness injector when this state is driven by the chaos
    # runner (chaos/injector.py); None in production. An extension point:
    # anything holding AppState can consult the active fault set.
    chaos: Optional[object] = None
    # self-healing pair (None when self_heal is off): the lease-based
    # failure detector fed by agent heartbeats/disconnects, and the
    # reconverger that turns its verdicts into re-solves + redeploys
    failure_detector: Optional[FailureDetector] = None
    reconverger: Optional[Reconverger] = None
    # {"issuer", "client_id", "audience"} when the CP runs JwksAuth with a
    # device-flow-capable IdP; the dashboard's browser login uses it
    auth_idp: Optional[dict] = None
    # replication (docs/guide/13-cp-replication.md): "primary" serves
    # every channel and ships its journal through `replicator`;
    # "standby" refuses mutations + agent sessions until its
    # StandbyRunner promotes it
    replication_role: str = "primary"
    replicator: Optional[Replicator] = None
    standby: Optional[StandbyRunner] = None
    # streaming-admission controller (cp/admission.py); None on standbys
    # and when ServerConfig.admission is off. Its pressure() output is
    # the autoscaler's solver-pressure input.
    admission: Optional[AdmissionController] = None
    # rolling SLO engine (obs/slo.py); None on standbys. Installed as
    # the process default so the placement/admission/reconverge
    # observation points route to it.
    slo: Optional[object] = None
    # fleet-horizon collector (obs/collector.py); None on standbys and
    # when ServerConfig.collector is off. The obs.query channel and the
    # agent heartbeat handler both reach it through here.
    collector: Optional[object] = None


class CpServerHandle:
    def __init__(self, server: ProtocolServer, state: AppState,
                 host: str, port: int, ca: Optional["MeshCa"]):
        self.server = server
        self.state = state
        self.host = host
        self.port = port
        self.ca = ca

    @property
    def ca_pem(self) -> Optional[bytes]:
        return self.ca.ca_pem if self.ca else None

    async def stop(self) -> None:
        if self.state.standby is not None:
            self.state.standby.stop()
        if self.state.reconverger is not None:
            self.state.reconverger.stop()
        if self.state.admission is not None:
            self.state.admission.stop()
        if self.state.collector is not None:
            self.state.collector.stop()
        await self.server.stop()
        self.state.store.flush()


def _default_server_provider_factory(name: str, **kw):
    """Resolve a cloud ServerProvider by name (server_provider.rs enum
    dispatch). Shells out to the provider CLI; raises on unknown names."""
    if name == "sakura":
        from ..cloud.sakura import SakuraServerProvider
        return SakuraServerProvider(**kw)
    if name == "aws":
        from ..cloud.aws import AwsServerProvider
        return AwsServerProvider(**kw)
    raise ValueError(f"unknown server provider {name!r}")


def _default_backend_factory():
    """CP-local deploys (handlers/deploy.rs:470-507) use the local docker
    daemon when reachable, the in-memory mock otherwise (tests/dev)."""
    from ..runtime.backend import DockerCliBackend, MockBackend
    docker = DockerCliBackend()
    if docker.ping():
        return docker
    # dev mock: images materialize on pull, so deploys succeed end-to-end
    return MockBackend(auto_pull=True)


async def start(config: ServerConfig, *,
                backend_factory: Optional[Callable] = None,
                server_provider_factory: Optional[Callable] = None,
                ssh_runner: Optional[Callable] = None,
                deploy_sleep: Callable[[float], None] = time.sleep,
                ) -> CpServerHandle:
    """server.rs start:82-126."""
    store = Store(config.db_path)
    auth = make_provider(config.auth_kind, config.auth_secret,
                         jwks=config.auth_jwks, issuer=config.auth_issuer,
                         audience=config.auth_audience)

    secret_box = None
    if config.master_key_env:
        from .crypto import SecretBox
        secret_box = SecretBox.from_env()

    shard_table = ShardTable(config.cp_shards or shards_from_env())
    state = AppState(
        store=store,
        auth=auth,
        agent_registry=AgentRegistry(shard_table=shard_table),
        log_router=LogRouter(shard_table=shard_table),
        placement=PlacementService(store, use_tpu=config.use_tpu_solver),
        name=config.name,
        secret_box=secret_box,
        backend_factory=backend_factory or _default_backend_factory,
        server_provider_factory=(server_provider_factory
                                 or _default_server_provider_factory),
        ssh_runner=ssh_runner,
        deploy_sleep=deploy_sleep,
        auth_idp=({"issuer": config.auth_issuer,
                   "client_id": config.auth_client_id,
                   "audience": config.auth_audience}
                  if (config.auth_kind in ("jwks", "auth0")
                      and config.auth_issuer and config.auth_client_id)
                  else None),
    )

    def authenticate(identity: str, token: Optional[str]):
        """Returns the peer's Claims (stashed on the Connection for
        per-method permission checks, handlers._need_perm) or False.
        NoAuth returns True: no claims, handlers skip enforcement —
        the reference's NoAuth '(everything is the anonymous admin)'."""
        if isinstance(auth, NoAuth):
            return True
        try:
            claims: Claims = auth.verify(token)
            return claims if claims.sub else False
        except Exception:
            return False

    ca: Optional["MeshCa"] = None
    ssl_ctx = None
    if config.tls_dir:
        # lazy: cert.py needs the `cryptography` package, which plaintext
        # deployments (and the chaos harness) must not require
        from .cert import ensure_mesh_ca, server_ssl_context
        ca = ensure_mesh_ca(config.tls_dir)
        ssl_ctx = server_ssl_context(ca, common_name=config.name,
                                     work_dir=config.tls_dir)

    repl_config = ReplicationConfig(
        ring_entries=config.replication_ring,
        ping_interval_s=config.standby_ping_interval_s,
        lease_s=config.standby_lease_s,
        grace_s=config.standby_grace_s)

    if config.standby_of:
        # standby: stream the primary's journal, watch its lease, promote
        # on death. No self-heal machinery until promotion — a standby
        # must not issue verdicts about agents it doesn't serve.
        state.replication_role = "standby"
        host_s, _, port_s = config.standby_of.rpartition(":")
        state.standby = StandbyRunner(
            StandbyReplica(store), host_s, int(port_s),
            identity=config.name, token=config.standby_token,
            config=repl_config,
            on_promote=lambda: _promote(state, config, repl_config))
        state.standby.spawn()
    else:
        state.replicator = Replicator(
            store, config=repl_config, loop=asyncio.get_running_loop())
        state.agent_registry.epoch_source = lambda: store.epoch
        _build_slo(state, config)
        if config.self_heal:
            _build_self_heal(state, config)
        if config.admission:
            _build_admission(state, config)
        if config.collector:
            _build_collector(state, config)

    server = ProtocolServer(
        name=config.name, authenticate=authenticate, ssl_context=ssl_ctx,
        # the welcome frame advertises role + fencing epoch, so agents
        # and CLIs can spot a zombie ex-primary at the handshake
        welcome_extra=lambda: {"role": state.replication_role,
                               "epoch": store.epoch})
    from .handlers import register_all
    register_all(server, state)

    host, port = await server.start(config.host, config.port)
    log.info("listening %s", kv(
        host=host, port=port, name=config.name,
        role=state.replication_role,
        tls=bool(config.tls_dir), auth=config.auth_kind,
        db=config.db_path or ":memory:"))
    return CpServerHandle(server, state, host, port, ca)


def _build_self_heal(state: AppState, config: ServerConfig) -> None:
    """The self-healing pair + crash-recovery boot sequence, shared by
    primary start and standby promotion (crash-only design: recovery IS
    the boot path)."""
    state.failure_detector = FailureDetector(LeaseConfig(
        lease_s=config.lease_s,
        suspect_grace_s=config.suspect_grace_s))
    state.reconverger = Reconverger(
        state, state.failure_detector,
        config=ReconvergeConfig(
            interval_s=config.heal_interval_s,
            backoff_base_s=config.heal_backoff_base_s,
            backoff_max_s=config.heal_backoff_max_s,
            max_attempts=config.heal_max_attempts))
    # a restarted CP picks its convergence debt back up BEFORE any
    # agent reconnects
    state.reconverger.resume()
    # prime a lease for EVERY known server: an agent that died with the
    # old CP (or while it was down) never heartbeats the new one, and
    # without a lease its death would be invisible forever — its primed
    # lease expires to a DEAD verdict, the re-solve moves its stages,
    # and the stuck redelivery work is superseded. Live agents renew the
    # primed lease with their first heartbeat; servers with nothing
    # placed on them make the verdict a no-op.
    for s in state.store.list("servers"):
        state.failure_detector.prime(s.slug)
    state.reconverger.spawn()


def _build_slo(state: AppState, config: ServerConfig) -> None:
    """Rolling SLO engine (obs/slo.py), installed as the process default
    so the placement/admission/reconverge observation points feed it.
    Primaries only — a standby serves no traffic to measure."""
    from ..obs.slo import SloEngine, parse_slo_props, set_engine
    state.slo = set_engine(SloEngine(parse_slo_props(config.slo or {})))


def _build_admission(state: AppState, config: ServerConfig) -> None:
    """Streaming-admission controller + its background drain loop
    (primaries only: exactly one admission writer per epoch)."""
    state.admission = AdmissionController(
        state.placement,
        config=AdmissionConfig(max_queue=config.admission_queue,
                               batch_max=config.admission_batch,
                               shed_age_s=config.admission_shed_age_s),
        store=state.store)
    state.admission.spawn()


def collector_sources(state: AppState) -> list:
    """The CP's deep-gauge sources for the obs collector: callables
    run every sampling tick that read live subsystem state the registry
    scrape can't see (per-tenant queues, per-subscriber backlogs, slot
    byte accounting). Each both sets the registry gauges (so GET
    /metrics agrees) and RETURNS (name, labels, value, kind) entries —
    the chaos runner reuses these sources with registry=None, where the
    returned entries are the only way samples reach the capture (the
    process-global registry carries cross-test residue that must never
    leak into a pinned artifact). The collector dedups name+labels
    within a tick, so the double reporting never double-records."""
    from ..obs.collector import (_M_LOG_BACKLOG, _M_RECONV_DEBT,
                                 _M_RES_BUDGET, _M_TENANT_DEPTH,
                                 _M_TENANT_OLDEST)

    tenants_seen: set = set()

    def _slo(now):
        if state.slo is not None:
            state.slo.refresh()
        return ()

    def _admission(now):
        adm = state.admission
        if adm is None:
            return ()
        census = adm.queue_census()
        out = [("fleet_admission_queue_depth", {},
                float(census["queue_depth"])),
               ("fleet_admission_oldest_age_seconds", {},
                float(census["oldest_age_s"])),
               ("fleet_admission_parked", {}, float(census["parked"]))]
        live = set(census["tenants"])
        for tenant, row in census["tenants"].items():
            _M_TENANT_DEPTH.set(row["queued"], tenant=tenant)
            _M_TENANT_OLDEST.set(row["oldest_age_s"], tenant=tenant)
            out.append(("fleet_admission_tenant_queue_depth",
                        {"tenant": tenant}, float(row["queued"])))
            out.append(("fleet_admission_tenant_oldest_age_seconds",
                        {"tenant": tenant}, float(row["oldest_age_s"])))
        # a tenant whose queue drained must read 0, not freeze at its
        # last depth
        for tenant in tenants_seen - live:
            _M_TENANT_DEPTH.set(0, tenant=tenant)
            _M_TENANT_OLDEST.set(0.0, tenant=tenant)
            out.append(("fleet_admission_tenant_queue_depth",
                        {"tenant": tenant}, 0.0))
            out.append(("fleet_admission_tenant_oldest_age_seconds",
                        {"tenant": tenant}, 0.0))
        tenants_seen.update(live)
        return out

    def _log_router(now):
        total, subs = state.log_router.backlog()
        _M_LOG_BACKLOG.set(total)
        out = [("fleet_log_router_backlog_lines", {}, float(total))]
        # per-subscriber rows are TSDB-only: subscriber ids are
        # unbounded cardinality, so they must not become registry
        # label children
        for s in subs:
            out.append(("fleet_log_router_subscriber_backlog_lines",
                        {"subscriber": str(s["subscriber"])},
                        float(s["queued"])))
        return out

    def _reconverge(now):
        rec = state.reconverger
        if rec is None:
            return ()
        debt = rec.debt()
        _M_RECONV_DEBT.set(debt)
        return [("fleet_reconverge_redelivery_debt", {}, float(debt)),
                ("fleet_reconverge_parked_stages", {},
                 float(len(rec.parked_stage_keys())))]

    def _agents(now):
        return [("fleet_agents_connected", {},
                 float(len(state.agent_registry.list_connected()))),
                ("fleet_agent_commands_in_flight", {},
                 float(state.agent_registry.inflight()))]

    def _slots(now):
        slots = state.placement.solver_slots()
        _M_RES_BUDGET.set(slots["budget_bytes"])
        return [("fleet_sched_resident_budget_bytes", {},
                 float(slots["budget_bytes"])),
                ("fleet_sched_resident_bytes", {},
                 float(slots["resident_bytes"])),
                ("fleet_solver_resident_bytes_drift", {},
                 float(slots.get("bytes_drift", 0)))]

    def _shards(now):
        # per-shard occupancy + in-flight depth (cp/shards.py): shard
        # ids are a small fixed set, so the occupancy gauge also lives
        # in the registry; the in-flight split is TSDB-only like the
        # aggregate fleet_agent_commands_in_flight above
        out = []
        for row in state.agent_registry.shard_census():
            labels = {"shard": str(row["shard"])}
            out.append(("fleet_cp_shard_agents", labels,
                        float(row["agents"])))
            out.append(("fleet_cp_shard_inflight", labels,
                        float(row["inflight"])))
        return out

    return [_slo, _admission, _log_router, _reconverge, _agents, _slots,
            _shards]


def _build_collector(state: AppState, config: ServerConfig) -> None:
    """The fleet-horizon sampler (obs/collector.py): registry scrape +
    deep sources into the in-process TSDB, on the server's asyncio loop.
    Primaries only (rebuilt on promotion, like the SLO engine)."""
    from ..obs.collector import Collector
    from ..obs.tsdb import TimeSeriesDB
    tsdb = TimeSeriesDB(capacity_per_series=config.collector_capacity,
                        max_series=config.collector_max_series)
    collector = Collector(tsdb, interval_s=config.collector_interval_s)
    for src in collector_sources(state):
        collector.add_source(src)
    state.collector = collector
    collector.spawn()


def _promote(state: AppState, config: ServerConfig,
             repl_config: ReplicationConfig) -> None:
    """Standby -> primary flip (StandbyRunner.on_promote): open the
    gates, start shipping OUR journal to the next generation of
    standbys, and pick up the dead primary's convergence debt."""
    state.replication_role = "primary"
    state.replicator = Replicator(
        state.store, config=repl_config, loop=asyncio.get_running_loop())
    state.agent_registry.epoch_source = lambda: state.store.epoch
    _build_slo(state, config)
    if config.self_heal:
        _build_self_heal(state, config)
    if config.admission:
        # streams do not survive the dead primary (they are in-memory
        # batching state, not placement truth — that is journaled); a
        # client's next deploy.submit re-attaches
        _build_admission(state, config)
    if config.collector:
        # fresh horizon: the standby's (empty) store is replaced, not
        # merged — series begin at promotion, like the SLO windows
        _build_collector(state, config)
    log.warning("standby promoted: now serving as primary %s", kv(
        epoch=state.store.epoch, name=config.name))
