"""Channel handlers: the CP's RPC surface.

Analog of controlplane handlers/ (13 channels, handlers/mod.rs:21-35), all
shaped `method -> store/registry op -> payload`. The agent channel is the
duplex session (handlers/agent.rs): register-first enforcement, heartbeat /
alert / log / command_result events, CP->agent commands via AgentRegistry.

Every handler is a closure over AppState; `register_all` wires them into the
ProtocolServer.
"""

from __future__ import annotations

import asyncio
import time
from typing import TYPE_CHECKING

from ..core.serialize import flow_from_dict
from ..obs import get_logger, span
from ..obs.metrics import REGISTRY
from ..obs.trace import new_trace_id, use_trace
from ..runtime.engine import DeployEngine, DeployRequest
from .agent_registry import BUILD_TIMEOUT, DEPLOY_TIMEOUT
from .log_router import LogEntry, topic_for
from .models import (BuildJob, BuildStatus, CostEntry, Deployment,
                     DeploymentStatus, DnsRecord, ObservedContainer, Project,
                     Server, ServerCapacity, Tenant, TenantUser,
                     VolumeRecord, VolumeSnapshot, WorkerPool, now_ts)
from .protocol import Connection, ProtocolServer

if TYPE_CHECKING:
    from .server import AppState

__all__ = ["register_all", "check_all_servers", "dns_sync"]

_log = get_logger("cp.deploy")

# metric catalog: docs/guide/10-observability.md. Channel label only (the
# method vocabulary is open-ended via agent commands; channels are the
# fixed 15-way enum) — bounded cardinality by construction.
_M_REQUEST_S = REGISTRY.histogram(
    "fleet_cp_request_duration_seconds",
    "Channel RPC handler latency, by channel", labels=("channel",))
_M_REQUEST_ERRORS = REGISTRY.counter(
    "fleet_cp_request_errors_total",
    "Channel RPC handlers that raised, by channel", labels=("channel",))


def check_all_servers(state: "AppState") -> dict:
    """Bulk connectivity check shared by the server.check_all channel
    method and POST /api/health-check (web.rs /api/health-check): agent
    connected == online."""
    db = state.store
    statuses = {s.slug: ("online"
                         if state.agent_registry.is_connected(s.slug)
                         else "offline")
                for s in db.list("servers")}
    return {"updated": db.bulk_server_status(statuses),
            "statuses": statuses}


def dns_sync(state: "AppState") -> dict:
    """Push unsynced records through the cloud DNS adapter; without a
    backend they stay pending (never mark unsent records synced). Shared by
    the dns.sync channel method and POST /api/dns/sync."""
    db = state.store
    pending = db.list("dns_records", lambda r: not r.synced)
    if state.dns_backend is None:
        return {"synced": 0, "pending": len(pending),
                "error": "no DNS backend configured"}
    synced = 0
    for rec in pending:
        state.dns_backend.ensure_record(
            rec.zone, rec.name, rec.type, rec.content,
            ttl=rec.ttl, proxied=rec.proxied)
        db.update("dns_records", rec.id, synced=True)
        synced += 1
    return {"synced": synced}


def _require(payload: dict, *keys: str) -> list:
    missing = [k for k in keys if k not in payload]
    if missing:
        raise ValueError(f"missing fields: {missing}")
    return [payload[k] for k in keys]


# Per-method permission verbs (VERDICT r2 item 4: per-route claims
# enforcement, web.rs:140 / auth.rs Claims analog). A connection whose
# authenticate verdict attached Claims must hold `<verb>:<channel>` (or
# admin:all / `<verb>:*`) for each call; NoAuth connections carry no claims
# and skip enforcement ("everything is the anonymous admin"). The agent
# channel is NOT wrapped here (its register-first session protocol needs
# its own state), but it is no longer exempt from claims (ADVICE r3): when
# a connection carries Claims it must hold write:agent (or admin:all /
# write:*) for any agent-channel method or event — otherwise a read-only
# dashboard token could register as a node, forge heartbeats, and receive
# deploy fan-out payloads containing the full flow config.
#   - secret.get is deliberately NOT read-gated: it returns decrypted
#     secret material, which a read-only dashboard grant must not reach
#   - placement.solve is NOT read-gated: solve with reserve=true creates
#     a capacity reservation (state mutation under a read grant otherwise)
_READ_METHODS = frozenset({
    "get", "list", "history", "status", "overview", "summary", "alerts",
    "logs", "logs.live", "show", "snapshots", "ps", "pool.list",
    "user.list", "ping", "reservations", "metrics", "heal.status",
    "admit_status", "obs.query", "obs.series", "obs.export",
})
def _timed(channel: str, handler):
    """Wrap a channel handler with the request-latency histogram + error
    counter (web.rs would get this from tower middleware; here it's 8
    lines around every channel, the agent session included)."""

    async def timed(conn: Connection, method: str, p: dict):
        t0 = time.perf_counter()
        try:
            return await handler(conn, method, p)
        except Exception:
            _M_REQUEST_ERRORS.inc(channel=channel)
            raise
        finally:
            _M_REQUEST_S.observe(time.perf_counter() - t0, channel=channel)

    return timed


def _perm_wrap(channel: str, handler):
    """Wrap a channel handler with claims-based permission enforcement."""

    async def wrapped(conn: Connection, method: str, p: dict):
        claims = getattr(conn, "claims", None)
        if claims is not None:
            verb = "read" if method in _READ_METHODS else "write"
            perm = f"{verb}:{channel}"
            if not claims.has(perm):
                raise PermissionError(
                    f"missing permission {perm} (have: "
                    f"{', '.join(claims.permissions) or 'none'})")
        return await handler(conn, method, p)

    return wrapped


def _role_wrap(state: "AppState", channel: str, handler):
    """Standby gating (docs/guide/13-cp-replication.md): until promotion
    a standby answers reads (dashboards pointed at it see the replicated
    state) but refuses every mutation — there is exactly one writer per
    epoch, and it is not this process."""

    async def wrapped(conn: Connection, method: str, p: dict):
        if (state.replication_role != "primary"
                and method not in _READ_METHODS):
            raise ValueError(
                f"standby: not primary — {channel}.{method} must go to "
                f"the current primary (this CP will serve writes only "
                f"after promotion)")
        return await handler(conn, method, p)

    return wrapped


def register_all(server: ProtocolServer, state: "AppState") -> None:
    """handlers/mod.rs register_all:21-35."""
    for channel, factory in (
            ("tenant", _tenant), ("project", _project), ("stage", _stage),
            ("service", _service), ("container", _container),
            ("server", _server), ("health", _health), ("cost", _cost),
            ("dns", _dns), ("deploy", _deploy), ("volume", _volume),
            ("build", _build), ("placement", _placement)):
        server.register_channel(
            channel, _timed(channel, _role_wrap(
                state, channel, _perm_wrap(channel, factory(state)))))
    agent_handler, agent_events = _agent(state)
    server.register_channel("agent", _timed("agent", agent_handler),
                            agent_events)
    repl_handler, repl_events = _replication(state)
    server.register_channel(
        "replication", _timed("replication",
                              _perm_wrap("replication", repl_handler)),
        repl_events)
    server.on_disconnect = _on_disconnect(state)


# --------------------------------------------------------------------------
# simple CRUD channels
# --------------------------------------------------------------------------

def _tenant(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "create":
            (name,) = _require(p, "name")
            t = db.create("tenants", Tenant(
                name=name, display_name=p.get("display_name", name)))
            return {"tenant": t.public_dict()}
        if method == "list":
            return {"tenants": [t.public_dict() for t in db.list("tenants")]}
        if method == "get":
            t = db.tenant_by_name(p.get("name", ""))
            return {"tenant": t.public_dict() if t else None}
        if method == "delete":
            t = db.tenant_by_name(p.get("name", ""))
            return {"deleted": bool(t and db.delete("tenants", t.id))}
        if method == "secret.set":
            name, key, value = _require(p, "name", "key", "value")
            t = db.ensure_tenant(name)
            secrets = dict(t.secrets)
            secrets[key] = (state.secret_box.encrypt(value, aad=name)
                            if state.secret_box else value)
            db.update("tenants", t.id, secrets=secrets)
            return {"ok": True}
        if method == "secret.get":
            name, key = _require(p, "name", "key")
            t = db.tenant_by_name(name)
            if t is None or key not in t.secrets:
                return {"value": None}
            v = t.secrets[key]
            return {"value": state.secret_box.decrypt(v, aad=name)
                    if state.secret_box else v}
        if method == "user.add":
            tenant, email = _require(p, "tenant", "email")
            u = db.create("tenant_users", TenantUser(
                tenant=tenant, email=email, role=p.get("role", "member")))
            return {"user": u.to_dict()}
        if method == "user.list":
            return {"users": [u.to_dict()
                              for u in db.tenant_users(p.get("tenant", ""))]}
        if method == "user.remove":
            tenant, email = _require(p, "tenant", "email")
            u = db.user_by_email(tenant, email)
            return {"removed": bool(u and db.delete("tenant_users", u.id))}
        raise ValueError(f"unknown method tenant.{method}")
    return handle


def _project(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "create":
            (name,) = _require(p, "name")
            rec = db.create("projects", Project(
                tenant=p.get("tenant", "default"), name=name,
                description=p.get("description", "")))
            return {"project": rec.to_dict()}
        if method == "list":
            tenant = p.get("tenant")
            return {"projects": [r.to_dict() for r in db.list(
                "projects", lambda r: tenant is None or r.tenant == tenant)]}
        if method == "get":
            rec = db.project_by_name(p.get("tenant", "default"),
                                     p.get("name", ""))
            return {"project": rec.to_dict() if rec else None}
        if method == "delete":
            rec = db.project_by_name(p.get("tenant", "default"),
                                     p.get("name", ""))
            return {"deleted": bool(rec and db.delete("projects", rec.id))}
        raise ValueError(f"unknown method project.{method}")
    return handle


def _stage(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "list":
            project = p.get("project", "")
            return {"stages": [s.to_dict() for s in db.stages_of(project)]}
        if method == "ensure":
            project, name = _require(p, "project", "name")
            s = db.ensure_stage(project, name,
                                backend=p.get("backend", "docker"),
                                servers=p.get("servers", []))
            return {"stage": s.to_dict()}
        if method == "status":
            # aggregate: services + last deployment + active alerts
            sid = p.get("stage", "")
            services = [s.to_dict() for s in db.services_of(sid)]
            deps = db.deployment_history(stage=sid, limit=1)
            stage = db.get("stages", sid)
            alerts = []
            if stage is not None:
                alerts = [a.to_dict() for a in db.active_alerts()
                          if any(a.server == srv for srv in stage.servers)]
            return {"services": services,
                    "last_deployment": deps[0].public_dict() if deps else None,
                    "alerts": alerts}
        if method == "adopt":
            (sid,) = _require(p, "stage")
            s = db.adopt_stage(sid)
            return {"stage": s.to_dict() if s else None}
        if method == "delete":
            return {"deleted": db.delete("stages", p.get("stage", ""))}
        raise ValueError(f"unknown method stage.{method}")
    return handle


def _service(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "list":
            return {"services": [s.to_dict()
                                 for s in db.services_of(p.get("stage", ""))]}
        if method == "restart":
            server, container = _require(p, "server", "container")
            result = await state.agent_registry.send_command(
                server, "restart", {"container": container})
            return {"result": result}
        raise ValueError(f"unknown method service.{method}")
    return handle


def _container(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "ps":
            server = p.get("server")
            rows = (db.observed_on(server) if server
                    else db.list("observed_containers"))
            return {"containers": [r.to_dict() for r in rows]}
        if method == "logs":
            server, container = _require(p, "server", "container")
            entries = state.log_router.retained(
                topic_for(server, container), limit=p.get("limit"))
            return {"lines": [e.to_dict() for e in entries]}
        if method == "logs.live":
            # live container output fetched FROM the node (the retained
            # ring above only holds agent-published lines — deploy events,
            # alerts — not container stdout)
            server, container = _require(p, "server", "container")
            result = await state.agent_registry.send_command(
                server, "logs", {"container": container,
                                 "tail": p.get("tail"),
                                 "since": p.get("since")})
            return {"logs": result.get("logs", "")}
        if method in ("start", "stop", "restart"):
            # granular lifecycle (MCP cp_container_start/stop/restart):
            # routed to the owning node's agent
            server, container = _require(p, "server", "container")
            result = await state.agent_registry.send_command(
                server, method, {"container": container})
            return {"result": result}
        raise ValueError(f"unknown method container.{method}")
    return handle


def _server(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "register":
            (slug,) = _require(p, "slug")
            rec = db.register_server(
                slug, tenant=p.get("tenant", "default"),
                hostname=p.get("hostname", slug),
                provider=p.get("provider"))
            if "capacity" in p:
                cap = type(rec.capacity)(**p["capacity"])
                db.update("servers", rec.id, capacity=cap)
            if "labels" in p:
                # wire payloads say "class" (the to_dict form); the record
                # field is clazz (keyword-safe)
                raw = dict(p["labels"])
                if "class" in raw:
                    raw["clazz"] = raw.pop("class")
                lbl = type(rec.labels)(**raw)
                db.update("servers", rec.id, labels=lbl)
            return {"server": db.get("servers", rec.id).to_dict()}
        if method == "list":
            tenant = p.get("tenant")
            return {"servers": [s.to_dict() for s in db.list(
                "servers", lambda s: tenant is None or s.tenant == tenant)]}
        if method == "get":
            s = db.server_by_slug(p.get("slug", ""))
            return {"server": s.to_dict() if s else None}
        if method == "delete":
            s = db.server_by_slug(p.get("slug", ""))
            if s is not None:
                # evict any live agent session with the record: this is the
                # operator escape hatch when a slug is held by a session
                # that should not have it (the registry's anti-hijack fence
                # otherwise keeps refusing the legitimate agent)
                live = state.agent_registry.connection_of(s.slug)
                state.agent_registry.unregister(s.slug)
                if live is not None:
                    await live.close()
                if state.failure_detector is not None:
                    # deliberate removal, not a failure: no dead verdict
                    state.failure_detector.forget(s.slug)
            return {"deleted": bool(s and db.delete("servers", s.id))}
        if method in ("cordon", "uncordon", "drain"):
            s = db.server_by_slug(p.get("slug", ""))
            if s is None:
                return {"ok": False}
            new_state = {"cordon": "cordoned", "uncordon": "schedulable",
                         "drain": "draining"}[method]
            db.update("servers", s.id, scheduling_state=new_state)
            if method == "drain":
                state.placement.node_event(s.slug, online=False)
            return {"ok": True, "scheduling_state": new_state}
        if method == "ping":
            # single-server liveness (ServerCommands::Ping): round-trip
            # through the connected agent; offline agents answer here, not
            # with a timeout
            (slug,) = _require(p, "slug")
            if not state.agent_registry.is_connected(slug):
                return {"ok": False, "error": f"agent {slug!r} not connected"}
            result = await state.agent_registry.send_command(
                slug, "ping", {}, timeout=p.get("timeout", 10))
            return {"ok": True, "result": result}
        if method in ("boot", "shutdown"):
            # ServerCommands::{Boot,Shutdown}: power control through the
            # cloud ServerProvider (server.rs power on-off); CLI shellouts
            # run off-loop like provision/deprovision
            (slug,) = _require(p, "slug")
            s = db.server_by_slug(slug)
            if s is None:
                return {"ok": False, "error": f"no server {slug}"}
            if not s.provider:
                return {"ok": False,
                        "error": f"server {slug} has no provider; "
                                 f"cannot control power"}
            sp = state.server_provider_factory(
                s.provider, **p.get("provider_args", {}))
            loop = asyncio.get_running_loop()
            infos = await loop.run_in_executor(None, sp.list_servers)
            match = next((i for i in infos if i.name == slug), None)
            if match is None:
                return {"ok": False,
                        "error": f"provider has no instance named {slug}"}
            op = sp.power_on if method == "boot" else sp.power_off
            ok = await loop.run_in_executor(None, lambda: op(match.id))
            if ok and method == "shutdown":
                db.update("servers", s.id, status="offline")
                await loop.run_in_executor(
                    None, lambda: state.placement.node_event(slug,
                                                             online=False))
            return {"ok": bool(ok), "instance": match.id}
        if method == "check_all":
            return check_all_servers(state)
        if method == "provision":
            # server.rs provision: create the machine through the cloud
            # ServerProvider, then register it (status provisioning until
            # its agent connects). CLI shellouts run off-loop.
            slug, provider_name = _require(p, "slug", "provider")
            if db.server_by_slug(slug) is not None:
                raise ValueError(f"server {slug!r} already exists")
            from ..core.model import ResourceSpec, ServerResource
            cap = p.get("capacity", {})
            spec = ServerResource(
                name=slug,
                capacity=ResourceSpec(cpu=float(cap.get("cpu", 2)),
                                      memory=float(cap.get("memory", 4096)),
                                      disk=float(cap.get("disk", 40960))),
                plan=p.get("plan"))
            sp = state.server_provider_factory(
                provider_name, **p.get("provider_args", {}))
            # the record is created BEFORE the (slow, off-loop) cloud call:
            # it reserves the slug so a concurrent provision of the same
            # slug fails the exists-check above instead of double-creating
            # a billed instance; rolled back if the provider call fails
            rec = db.create("servers", Server(
                tenant=p.get("tenant", "default"), slug=slug,
                provider=provider_name, status="provisioning",
                capacity=ServerCapacity(cpu=spec.capacity.cpu,
                                        memory=spec.capacity.memory,
                                        disk=spec.capacity.disk)))
            loop = asyncio.get_running_loop()
            try:
                info = await loop.run_in_executor(
                    None, lambda: sp.create_server(spec))
            except Exception:
                db.delete("servers", rec.id)
                raise
            db.update("servers", rec.id, hostname=info.ip or "")
            return {"server": db.get("servers", rec.id).to_dict(),
                    "instance": {"id": info.id, "status": info.status,
                                 "ip": info.ip}}
        if method == "deprovision":
            (slug,) = _require(p, "slug")
            s = db.server_by_slug(slug)
            if s is None:
                return {"ok": False, "error": f"no server {slug}"}
            loop = asyncio.get_running_loop()
            if s.provider:
                sp = state.server_provider_factory(
                    s.provider, **p.get("provider_args", {}))
                infos = await loop.run_in_executor(None, sp.list_servers)
                match = next((i for i in infos if i.name == slug), None)
                if match is not None:
                    deleted = await loop.run_in_executor(
                        None, lambda: sp.delete_server(match.id))
                    if not deleted:
                        # keep the record: the cloud instance is still
                        # running (and billing); the operator can retry
                        return {"ok": False,
                                "error": f"provider failed to delete "
                                         f"{match.id}; server record kept"}
            db.delete("servers", s.id)
            # warm re-solve of affected stages runs off-loop (the JAX solve
            # would otherwise block every heartbeat/RPC for its duration)
            await loop.run_in_executor(
                None, lambda: state.placement.node_event(slug, online=False))
            return {"ok": True}
        if method == "pool.create":
            (name,) = _require(p, "name")
            mn = int(p.get("min_servers", 0))
            mx = int(p.get("max_servers", 0))
            if mn < 0 or mx < 0:
                raise ValueError("pool min/max must be >= 0")
            if mx and mn > mx:
                raise ValueError(f"pool min_servers {mn} > max_servers {mx}")
            pool = db.create("worker_pools", WorkerPool(
                tenant=p.get("tenant", "default"), name=name,
                required_labels=p.get("required_labels", {}),
                preferred_labels=p.get("preferred_labels", {}),
                min_servers=mn, max_servers=mx))
            return {"pool": pool.to_dict()}
        if method == "pool.list":
            return {"pools": [w.to_dict() for w in db.list("worker_pools")]}
        raise ValueError(f"unknown method server.{method}")
    return handle


def _health(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "ping":
            return {"pong": True, "ts": now_ts()}
        if method == "overview":
            servers = db.list("servers")
            online = [s for s in servers if s.status == "online"]
            return {
                "servers": len(servers),
                "online": len(online),
                "agents": state.agent_registry.list_connected(),
                "projects": len(db.list("projects")),
                "deployments": len(db.list("deployments")),
                "active_alerts": len(db.active_alerts()),
                # pointer, not payload: `fleet cp status` shows the series
                # count; the full registry rides health.metrics / /metrics
                "metrics": {"families": len(REGISTRY.names())},
            }
        if method == "alerts":
            return {"alerts": [a.to_dict()
                               for a in db.active_alerts(p.get("tenant"))]}
        if method == "metrics":
            # the same registry the daemon's GET /metrics serves, in JSON
            # (the channel face for `fleet cp metrics` / MCP consumers);
            # windowed SLO gauges recompute against NOW first, same as
            # the /metrics scrape (obs/slo.py refresh)
            if state.slo is not None:
                state.slo.refresh()
            return {"metrics": REGISTRY.snapshot()}
        if method == "slo.status":
            # rolling SLO engine (obs/slo.py): declared objectives vs
            # observed quantiles + fast/slow burn rates, rendered by
            # `fleet slo status`
            if state.slo is None:
                return {"enabled": False}
            return state.slo.status()
        if method == "solver.slots":
            # device slot-manager occupancy (sched/tpu.py): which stages
            # are resident, their bytes against the budget, and what was
            # evicted with a warm snapshot — `fleet solve slots`
            return {"enabled": True, **state.placement.solver_slots()}
        if method == "heal.status":
            # self-healing introspection (`fleet cp heal status`): lease
            # table, pending/parked convergence work, pass counters —
            # plus the replication block (role/epoch/standby lag) so one
            # command answers "who is primary and is the standby warm"
            out = ({"enabled": False} if state.reconverger is None
                   else {"enabled": True, **state.reconverger.status()})
            out["replication"] = _replication_status(state)
            # per-shard occupancy/in-flight (cp/shards.py) + the
            # reconverger's aggregate debt, so the shard rows answer
            # "which partition is behind" next to the work table
            out["shards"] = {
                "count": (state.agent_registry.shard_table.shards
                          if state.agent_registry.shard_table else 1),
                "census": state.agent_registry.shard_census(),
                "debt": (state.reconverger.debt()
                         if state.reconverger else 0)}
            return out
        if method in ("obs.query", "obs.series", "obs.export"):
            # TSDB channel face (obs/tsdb.py): the windowed store behind
            # `fleet top` / `fleet obs` — standby-safe reads (the standby
            # simply has no collector, so enabled=False)
            coll = state.collector
            if coll is None:
                return {"enabled": False}
            tsdb = coll.tsdb
            if method == "obs.series":
                return {"enabled": True, "series": [
                    {"name": s.name, "labels": s.labels_dict(),
                     "kind": s.kind}
                    for s in tsdb.match(p.get("name"), p.get("labels"))],
                    "stats": tsdb.stats()}
            if method == "obs.export":
                fmt = p.get("format", "openmetrics")
                if fmt == "jsonl":
                    return {"enabled": True, "format": fmt,
                            "text": tsdb.export_jsonl()}
                if fmt == "openmetrics":
                    return {"enabled": True, "format": fmt,
                            "text": tsdb.render_openmetrics()}
                raise ValueError(f"unknown export format {fmt!r}")
            window = float(p.get("window_s", 60.0))
            return {"enabled": True, "window_s": window,
                    "collector": coll.status(),
                    "series": tsdb.aggregate(
                        name=p.get("name"), labels=p.get("labels"),
                        window_s=window)}
        raise ValueError(f"unknown method health.{method}")
    return handle


def _cost(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "add":
            month, amount = _require(p, "month", "amount")
            rec = db.create("cost_entries", CostEntry(
                tenant=p.get("tenant", "default"), server=p.get("server", ""),
                provider=p.get("provider", ""), month=month,
                amount=float(amount), currency=p.get("currency", "USD")))
            return {"entry": rec.to_dict()}
        if method == "summary":
            (month,) = _require(p, "month")
            tenant = p.get("tenant", "default")
            return {"month": month, "tenant": tenant,
                    "total": state.store.monthly_cost(tenant, month)}
        if method == "list":
            tenant = p.get("tenant")
            month = p.get("month")
            rows = db.list("cost_entries",
                           lambda e: (tenant is None or e.tenant == tenant)
                           and (month is None or e.month == month))
            return {"entries": [e.to_dict() for e in rows]}
        raise ValueError(f"unknown method cost.{method}")
    return handle


def _dns(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "create":
            zone, name, content = _require(p, "zone", "name", "content")
            rec = db.create("dns_records", DnsRecord(
                tenant=p.get("tenant", "default"), zone=zone, name=name,
                type=p.get("record_type", "A"), content=content,
                ttl=p.get("ttl", 300), proxied=p.get("proxied", False)))
            return {"record": rec.to_dict()}
        if method == "list":
            zone = p.get("zone")
            return {"records": [r.to_dict() for r in db.list(
                "dns_records", lambda r: zone is None or r.zone == zone)]}
        if method == "delete":
            # by id, or by (zone, name) the way DnsCommands::Delete
            # addresses records (main.rs:441)
            rid = p.get("id", "")
            if not rid and p.get("zone") and p.get("name"):
                rec = db.find_one(
                    "dns_records",
                    lambda r: r.zone == p["zone"] and r.name == p["name"])
                rid = rec.id if rec else ""
            return {"deleted": db.delete("dns_records", rid)}
        if method == "sync":
            return dns_sync(state)
        raise ValueError(f"unknown method dns.{method}")
    return handle


# --------------------------------------------------------------------------
# deploy channel (handlers/deploy.rs)
# --------------------------------------------------------------------------

def _deploy(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "history":
            return {"deployments": [d.public_dict() for d in db.deployment_history(
                stage=p.get("stage"), limit=p.get("limit", 50))]}
        if method == "run":
            # legacy SSH remote-exec path (handlers/deploy.rs:24-252):
            # record the deployment, ssh to the stage's server, run a
            # remote `fleet deploy`, record the outcome. Kept for servers
            # that have no agent (the reference's Tailscale-SSH deploys);
            # agent-routed `execute` is the primary path.
            slug, project_path, stage_name = _require(
                p, "server", "path", "stage")
            srv = db.server_by_slug(slug)
            if srv is None:
                raise ValueError(f"no server {slug!r}")
            tenant = db.ensure_tenant(p.get("tenant", "default"))
            project = db.ensure_project(tenant.name,
                                        p.get("project", project_path))
            stage = db.ensure_stage(project.id, stage_name)
            dep = db.create("deployments", Deployment(
                tenant=tenant.name, project=project.id, stage=stage.id,
                status=DeploymentStatus.RUNNING.value))
            from ..cloud.ssh import SshTarget, exec_with_timeout
            from ..registry.deploy import remote_deploy_cmd
            cmd = remote_deploy_cmd(project_path, stage_name,
                                    p.get("fleet_bin", "fleet"))
            target = SshTarget(host=srv.hostname or slug,
                               user=p.get("ssh_user"))
            loop = asyncio.get_running_loop()
            try:
                out = await loop.run_in_executor(
                    None, lambda: exec_with_timeout(
                        target, cmd, timeout=DEPLOY_TIMEOUT,
                        runner=getattr(state, "ssh_runner", None)))
                db.finish_deployment(dep.id, DeploymentStatus.SUCCEEDED,
                                     log=out)
            except Exception as e:
                db.finish_deployment(dep.id, DeploymentStatus.FAILED,
                                     error=str(e))
                raise
            return {"deployment": db.get("deployments", dep.id).public_dict()}
        if method == "execute":
            return await execute_deploy(
                state, DeployRequest.from_dict(p["request"]),
                tenant_name=p.get("tenant", "default"))
        if method == "down":
            return await execute_down(
                state, DeployRequest.from_dict(p["request"]),
                tenant_name=p.get("tenant", "default"),
                remove=bool(p.get("remove", False)))
        if method == "submit":
            # streaming admission (cp/admission.py, docs/guide/14): enqueue
            # arrivals/departures for the continuous micro-solve pipeline
            # instead of forcing a full deploy per change. Backpressure
            # surfaces as AdmissionRejected — retryable; the message
            # carries (reason, retry_after_s) and rides the error frame.
            adm = getattr(state, "admission", None)
            if adm is None:
                raise ValueError(
                    "streaming admission is disabled on this CP "
                    "(`admission true` in the server config)")
            stage = p.get("stage")
            loop = asyncio.get_running_loop()
            if p.get("flow") and stage:
                # first submit for a stage may carry the flow to attach
                # (runs the baseline solve off-loop)
                flow = flow_from_dict(p["flow"])
                key = f"{flow.name}/{stage}"
                await loop.run_in_executor(
                    None, lambda: adm.attach(
                        flow, stage, tenant=p.get("tenant", "default")))
                stage = key
            return await loop.run_in_executor(
                None, lambda: adm.submit(
                    p.get("tenant", "default"),
                    arrivals=p.get("arrivals") or (),
                    departures=p.get("departures") or (),
                    stage=stage))
        if method == "admit_status":
            adm = getattr(state, "admission", None)
            if adm is None:
                return {"enabled": False}
            return await asyncio.get_running_loop().run_in_executor(
                None, adm.status)
        raise ValueError(f"unknown method deploy.{method}")
    return handle


async def execute_down(state: "AppState", req: DeployRequest,
                       tenant_name: str = "default",
                       remove: bool = False) -> dict:
    """CP-routed teardown: the complement of execute_deploy (the
    reference's down is local-only, commands/down.rs — but a stage
    deployed THROUGH the CP must be torn down through it too).

    Fan deploy.down out to every connected stage agent; a stage server
    WITHOUT a live agent counts as a FAILED node (its containers are still
    running — releasing capacity for them would let the next solve
    double-book the node when it reconnects). A stage whose servers were
    never agent-routed (the CP-local deploy fallback: last deployment has
    no placement) tears down on the CP host instead. Full-stage success
    returns committed capacity, marks services removed, and the whole
    teardown lands in the deployment history like any deploy."""
    db = state.store
    tenant = db.ensure_tenant(tenant_name)
    project = db.ensure_project(tenant.name, req.flow.name)
    stage_cfg = req.flow.stage(req.stage_name)
    stage = db.ensure_stage(project.id, req.stage_name)

    # quadlet/compose tear down whole-stage only (same semantics as the
    # local CLI path, which warns and drops -n); normalizing HERE keeps
    # the capacity-release decision below consistent with what the agents
    # actually did
    from ..core.model import Backend
    if stage_cfg.backend is not Backend.DOCKER and req.target_services:
        req.target_services = []

    # "down:*" marks a FULL-stage teardown record: the placement scan
    # below stops at the last successful one (a later redeploy starts the
    # stage's placement story over)
    dep = db.create("deployments", Deployment(
        tenant=tenant.name, project=project.id, stage=stage.id,
        status=DeploymentStatus.RUNNING.value,
        services=(["down:*"] if not req.target_services
                  else [f"down:{s}" for s in req.target_services])))

    # The placement record is the truth about WHERE the stage's containers
    # live (failed deploys record none, so the scan must span the FULL
    # history — a tail of failed redeploys must not flip the verdict, and
    # deployment_history's default limit would truncate it):
    #   - some deployment recorded a placement -> agent-routed: fan out to
    #     connected agents, and every PLACED node without a live agent
    #     blocks the teardown (its containers are still running; releasing
    #     capacity for them would double-book the node on reconnect). A
    #     declared-but-never-placed offline server blocks nothing.
    #   - no placement anywhere -> the stage only ever ran through the
    #     CP-local deploy fallback: tear down on the CP host, even if
    #     agents have connected since (they hold nothing of this stage).
    placed = None
    for d in reversed(db.list("deployments",
                              lambda d: d.stage == stage.id)):
        if d.id == dep.id:
            continue
        if ((d.services or [""])[0] == "down:*"
                and d.status == DeploymentStatus.SUCCEEDED.value):
            break         # fully torn down since; older placements are moot
        if d.placement:
            placed = d.placement
            break
    nodes: dict[str, object] = {}
    errors: list[str] = []
    try:
        if placed is not None:
            # fan out to every connected node that is declared OR holds
            # placed containers — a placed node edited OUT of the config
            # still runs this stage and must be torn down (or block the
            # release while unreachable)
            placed_nodes = sorted({n for n in placed.values()})
            relevant = sorted(set(stage_cfg.servers) | set(placed_nodes))
            targets = [s for s in relevant
                       if state.agent_registry.is_connected(s)]
            missing = [s for s in placed_nodes if s not in targets]
            if targets:
                results = await state.agent_registry.send_batch(
                    [(slug, "deploy.down",
                      {"request": req.to_dict(), "remove": remove})
                     for slug in targets], timeout=DEPLOY_TIMEOUT)
                nodes = {slug: (str(r) if isinstance(r, Exception) else r)
                         for slug, r in zip(targets, results)}
                errors = [s for s, r in zip(targets, results)
                          if isinstance(r, Exception)]
            for slug in missing:
                nodes[slug] = "agent not connected (containers may still " \
                              "be running; reconnect it and re-run down)"
            errors += missing
            if not nodes:
                raise ValueError(
                    f"no connected agents among stage servers "
                    f"{stage_cfg.servers} (the stage was agent-deployed; "
                    f"reconnect the agents to tear it down)")
        else:
            engine = DeployEngine(state.backend_factory(),
                                  sleep=state.deploy_sleep)
            res = await asyncio.get_running_loop().run_in_executor(
                None, lambda: engine.down(req.flow, req.stage_name,
                                          req.target_services or None))
            nodes = {"(cp-local)": {"removed": res.removed,
                                    "backend": "docker"}}

        ok = not errors
        if ok:
            if not req.target_services:
                # full-stage teardown: capacity back, every service marked
                state.placement.release_stage(
                    f"{req.flow.name}/{req.stage_name}")
                marked = stage_cfg.services
            else:
                # targeted: no capacity release (the stage still runs),
                # but the removed services must not show 'deployed'
                marked = req.target_services
            for svc in marked:
                db.upsert_service(stage.id, svc, status="removed")
        log = "\n".join(f"{slug}: {info}" for slug, info in nodes.items())
        db.finish_deployment(
            dep.id,
            DeploymentStatus.SUCCEEDED if ok else DeploymentStatus.FAILED,
            log=log, error="; ".join(errors) if errors else "")
        return {"ok": ok, "nodes": nodes, "failed_nodes": errors,
                "deployment": db.get("deployments", dep.id).public_dict()}
    except Exception as e:
        db.finish_deployment(dep.id, DeploymentStatus.FAILED, error=str(e))
        raise


async def execute_deploy(state: "AppState", req: DeployRequest,
                         tenant_name: str = "default") -> dict:
    """The deploy.execute path (handlers/deploy.rs:280-542), shared by the
    deploy channel and the web redeploy route: record the deployment (with
    the request, so redeploy can replay it), solve placement, fan out to
    every connected stage agent (or run CP-locally), finish the record.

    The whole path runs inside ONE trace: minted here (or adopted from the
    CLI's request), carried to every agent via DeployRequest.trace_id, so
    the CP span, each agent's engine spans, and all their log lines share
    a trace_id end to end."""
    req.trace_id = req.trace_id or new_trace_id()
    with use_trace(req.trace_id):
        with span(_log, "deploy.execute", project=req.flow.name,
                  stage=req.stage_name, tenant=tenant_name) as sp:
            return await _execute_deploy(state, req, tenant_name, sp)


async def _execute_deploy(state: "AppState", req: DeployRequest,
                          tenant_name: str, sp: dict) -> dict:
    db = state.store
    tenant = db.ensure_tenant(tenant_name)
    project = db.ensure_project(tenant.name, req.flow.name)
    stage_cfg = req.flow.stage(req.stage_name)
    # fail fast on statically-doomed flows BEFORE any record is created or
    # lowering begins: the lint structural rules (dependency cycles,
    # dangling depends_on / service references) prove the deploy cannot
    # succeed on ANY inventory, so the submit is rejected with coded
    # diagnostics in milliseconds. Inventory-dependent rules are NOT run
    # here — the CP solves against live agent inventory, not the flow's
    # declared servers.
    from ..lint import deploy_blockers
    blockers = deploy_blockers(req.flow, req.stage_name)
    if blockers:
        raise ValueError(
            "flow rejected by static analysis: "
            + "; ".join(f"{d.code}: {d.message}" for d in blockers))
    stage = db.ensure_stage(project.id, req.stage_name,
                            backend=stage_cfg.backend.value,
                            servers=stage_cfg.servers)
    # the stored request is a REPLAY TEMPLATE (stage_redeploy rebuilds it
    # via from_dict): the trace id must not ride along, or every future
    # redeploy would inherit this deploy's trace and `fleet events
    # --trace` would interleave operations that ran days apart
    stored_req = req.to_dict()
    stored_req.pop("trace_id", None)
    dep = db.create("deployments", Deployment(
        tenant=tenant.name, project=project.id, stage=stage.id,
        status=DeploymentStatus.RUNNING.value,
        services=[s.name for s in stage_cfg.resolved_services(req.flow)],
        request=stored_req))

    targets = [s for s in stage_cfg.servers
               if state.agent_registry.is_connected(s)]
    try:
        if targets:
            # Fan out to EVERY connected stage server concurrently —
            # the reference routes to .first() only and defers fan-out
            # (handlers/deploy.rs:386-398); the placement solve makes
            # per-node slices explicit, so we send each agent its own.
            placement, rid = await asyncio.get_running_loop(
                ).run_in_executor(None, lambda: state.placement
                                  .solve_stage(req.flow, req.stage_name,
                                               tenant=tenant.name))
            if not placement.feasible:
                raise ValueError(
                    f"placement infeasible: {placement.violations}")
            # batched shard-parallel fan-out (cp/shards.py): the deploy
            # engine hands the registry the whole per-node command set
            # and each shard lane pipelines its slice
            results = await state.agent_registry.send_batch(
                [(slug, "deploy.execute",
                  {"request": DeployRequest(
                      flow=req.flow, stage_name=req.stage_name,
                      target_services=req.target_services,
                      no_pull=req.no_pull, no_prune=req.no_prune,
                      node=slug, trace_id=req.trace_id).to_dict(),
                   "assignment": placement.assignment})
                 for slug in targets], timeout=DEPLOY_TIMEOUT)
            errors = [str(r) for r in results if isinstance(r, Exception)]
            if errors:
                if rid:
                    state.placement.release(rid)
                raise ValueError("; ".join(errors))
            if rid:
                state.placement.commit(rid)
            log = "\n".join(str(r) for r in results
                            if not isinstance(r, Exception))
            db.update("deployments", dep.id,
                      placement=placement.assignment)
        else:
            # CP-local execution (handlers/deploy.rs:470-507)
            engine = DeployEngine(state.backend_factory(),
                                  sleep=state.deploy_sleep)
            res = await asyncio.get_running_loop().run_in_executor(
                None, lambda: engine.execute(req))
            if not res.ok:
                raise ValueError(f"failed services: {res.failed}")
            log = f"deployed {len(res.deployed)} containers locally"
        for svc in (db.get("deployments", dep.id).services or []):
            db.upsert_service(stage.id, svc, status="deployed")
        db.finish_deployment(dep.id, DeploymentStatus.SUCCEEDED, log=log)
        sp["deployment"] = dep.id
        sp["agents"] = len(targets) or None
    except Exception as e:
        db.finish_deployment(dep.id, DeploymentStatus.FAILED,
                             error=str(e))
        raise
    return {"deployment": db.get("deployments", dep.id).public_dict()}


# --------------------------------------------------------------------------
# placement channel (TPU solver surface — no reference analog)
# --------------------------------------------------------------------------

def _placement(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        if method == "solve":
            flow = flow_from_dict(p["flow"])
            # executor: a fleet-scale solve must not stall heartbeats and
            # command_result traffic on the loop (PlacementService locks
            # with threading.Lock, so it is thread-safe)
            placement, rid = await asyncio.get_running_loop().run_in_executor(
                None, lambda: state.placement.solve_stage(
                    flow, p["stage"], tenant=p.get("tenant", "default"),
                    reserve=p.get("reserve", False)))
            return {"assignment": placement.assignment,
                    "feasible": placement.feasible,
                    "violations": placement.violations,
                    "source": placement.source,
                    "solve_ms": placement.solve_ms,
                    "reservation": rid}
        if method == "node_event":
            slug, online = _require(p, "slug", "online")
            moved = await asyncio.get_running_loop().run_in_executor(
                None, lambda: state.placement.node_event(
                    slug, online=bool(online)))
            return {"rescheduled": [
                {"stage": key, "assignment": pl.assignment,
                 "feasible": pl.feasible} for key, pl in moved]}
        if method == "node_events":
            # coalesced burst: [{"slug": ..., "online": bool}, ...] -> ONE
            # warm re-solve per affected stage against the final mask
            (raw,) = _require(p, "events")
            events = [(e["slug"], bool(e["online"])) for e in raw]
            moved = await asyncio.get_running_loop().run_in_executor(
                None, lambda: state.placement.node_events(events))
            return {"rescheduled": [
                {"stage": key, "assignment": pl.assignment,
                 "feasible": pl.feasible} for key, pl in moved]}
        if method == "commit":
            return {"ok": state.placement.commit(p.get("reservation", ""))}
        if method == "release":
            return {"ok": state.placement.release(p.get("reservation", ""))}
        if method == "explain":
            # why is this service on its node (solver/explain.py): answered
            # from the retained instance, but the lock may be held by a
            # fleet-scale solve — same off-loop rule
            stage, service = _require(p, "stage", "service")
            try:
                return await asyncio.get_running_loop().run_in_executor(
                    None, lambda: state.placement.explain(
                        stage, service, top_k=int(p.get("top_k", 5))))
            except KeyError as e:
                raise ValueError(str(e)) from None
        if method == "reservations":
            # executor: the snapshot takes the PlacementService lock, which
            # a fleet-scale solve can hold for its full duration — same
            # off-loop rule as solve/node_events above
            return await asyncio.get_running_loop().run_in_executor(
                None, state.placement.reservations_snapshot)
        raise ValueError(f"unknown method placement.{method}")
    return handle


# --------------------------------------------------------------------------
# volume / build channels
# --------------------------------------------------------------------------

def _volume(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "list":
            server = p.get("server")
            return {"volumes": [v.to_dict() for v in db.list(
                "volumes", lambda v: server is None or v.server == server)]}
        if method == "adopt":
            server, name = _require(p, "server", "name")
            v = db.find_one("volumes",
                            lambda r: r.server == server and r.name == name)
            if v is None:
                v = db.create("volumes", VolumeRecord(
                    tenant=p.get("tenant", "default"), server=server,
                    name=name, adopted=True))
            else:
                db.update("volumes", v.id, adopted=True)
            return {"volume": db.get("volumes", v.id).to_dict()}
        if method == "snapshot":
            (vol_id,) = _require(p, "volume")
            snap = db.create("volume_snapshots", VolumeSnapshot(
                volume=vol_id, label=p.get("label", "")))
            return {"snapshot": snap.to_dict()}
        if method == "snapshots":
            vol = p.get("volume")
            return {"snapshots": [s.to_dict() for s in db.list(
                "volume_snapshots", lambda s: vol is None or s.volume == vol)]}
        raise ValueError(f"unknown method volume.{method}")
    return handle


def _build(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        if method == "submit":
            repo, image_tag = _require(p, "repo", "image_tag")
            job = db.create("build_jobs", BuildJob(
                tenant=p.get("tenant", "default"), repo=repo,
                ref=p.get("ref", "main"), dockerfile=p.get("dockerfile"),
                context=p.get("context", "."), image_tag=image_tag,
                push=p.get("push", False)))
            # route to a connected build worker if any
            workers = state.agent_registry.list_connected()
            if workers:
                worker = workers[0]
                db.update("build_jobs", job.id,
                          status=BuildStatus.RUNNING.value, worker=worker)
                task = asyncio.ensure_future(_run_build(state, job.id, worker))
                state.bg_tasks.add(task)   # strong ref; loop refs are weak
                task.add_done_callback(state.bg_tasks.discard)
            return {"job": db.get("build_jobs", job.id).to_dict()}
        if method == "show":
            job = db.get("build_jobs", p.get("job", ""))
            return {"job": job.to_dict() if job else None}
        if method == "list":
            return {"jobs": [j.to_dict() for j in db.list("build_jobs")]}
        if method == "logs":
            job = db.get("build_jobs", p.get("job", ""))
            return {"log": job.log if job else ""}
        if method == "cancel":
            job = db.get("build_jobs", p.get("job", ""))
            if job and job.status in (BuildStatus.QUEUED.value,
                                      BuildStatus.RUNNING.value):
                db.update("build_jobs", job.id,
                          status=BuildStatus.CANCELLED.value)
                return {"cancelled": True}
            return {"cancelled": False}
        raise ValueError(f"unknown method build.{method}")
    return handle


async def _run_build(state: "AppState", job_id: str, worker: str) -> None:
    db = state.store
    job = db.get("build_jobs", job_id)
    try:
        result = await state.agent_registry.send_command(
            worker, "build", {
                "repo": job.repo, "ref": job.ref,
                "dockerfile": job.dockerfile, "context": job.context,
                "image_tag": job.image_tag, "push": job.push},
            timeout=BUILD_TIMEOUT)
        status, extra = BuildStatus.SUCCEEDED.value, {
            "log": str(result.get("log", ""))}
    except Exception as e:
        status, extra = BuildStatus.FAILED.value, {"error": str(e)}
    # a cancel that raced the build wins: don't resurrect a cancelled job
    if db.get("build_jobs", job_id).status == BuildStatus.CANCELLED.value:
        return
    db.update("build_jobs", job_id, status=status, finished_at=now_ts(),
              **extra)


# --------------------------------------------------------------------------
# agent channel (the duplex session, handlers/agent.rs)
# --------------------------------------------------------------------------

def _ingest_heartbeat_metrics(state: "AppState", slug: str, p: dict) -> None:
    """Fold a heartbeat's piggybacked metrics snapshot into the CP's
    TSDB as agent-labeled series (the fleet-wide half of `fleet top`).
    Malformed snapshots must never fail the heartbeat itself — liveness
    detection outranks telemetry."""
    snap = p.get("metrics")
    if not snap or state.collector is None:
        return
    try:
        state.collector.ingest_agent_snapshot(slug, snap)
    except Exception:
        _log.debug("heartbeat metrics ingest failed for %s", slug,
                   exc_info=True)


def _agent(state: "AppState"):
    registered: dict[int, str] = {}   # id(conn) -> slug
    state._agent_conn_slugs = registered

    def _check_agent_perm(conn: Connection) -> None:
        """ADVICE r3: the agent channel is machine-to-machine but not
        permission-free — a token-authenticated connection must hold
        write:agent to act as a node agent."""
        claims = getattr(conn, "claims", None)
        if claims is not None and not claims.has("write:agent"):
            raise PermissionError(
                "missing permission write:agent (have: "
                f"{', '.join(claims.permissions) or 'none'})")

    def _principal_of(conn: Connection) -> str:
        claims = getattr(conn, "claims", None)
        return getattr(claims, "sub", "") or conn.identity

    async def handle(conn: Connection, method: str, p: dict) -> dict:
        db = state.store
        _check_agent_perm(conn)
        if method == "register":
            if state.replication_role != "primary":
                # re-homing: the agent's rotation lands here while this
                # standby has not promoted — refuse so it keeps cycling
                # endpoints until it finds the (possibly new) primary
                raise ValueError(
                    "standby: not primary — register with the current "
                    "primary (agents rotate cp_endpoints automatically)")
            (slug,) = _require(p, "slug")
            state.agent_registry.register(slug, conn,
                                          principal=_principal_of(conn))
            registered[id(conn)] = slug
            db.register_server(slug, hostname=p.get("hostname", slug))
            db.heartbeat(slug, version=p.get("version", ""))
            if state.failure_detector is not None:
                state.failure_detector.observe_heartbeat(slug)
            if "capacity" in p:
                s = db.server_by_slug(slug)
                db.update("servers", s.id,
                          capacity=type(s.capacity)(**p["capacity"]))
            return {"registered": True, "server": state.name}
        # register-first enforcement (handlers/agent.rs:28-63)
        if id(conn) not in registered:
            raise PermissionError("agent must register before other methods")
        slug = registered[id(conn)]
        if method == "heartbeat":
            db.heartbeat(slug, version=p.get("version", ""))
            if state.failure_detector is not None:
                state.failure_detector.observe_heartbeat(slug)
            _ingest_heartbeat_metrics(state, slug, p)
            return {"ok": True}
        raise ValueError(f"unknown method agent.{method}")

    async def events(conn: Connection, method: str, p: dict) -> None:
        db = state.store
        try:
            _check_agent_perm(conn)
        except PermissionError:
            return  # events carry no response channel: drop silently
        slug = registered.get(id(conn))
        if slug is None:
            return  # events from unregistered connections are dropped
        if method == "heartbeat":
            db.heartbeat(slug, version=p.get("version", ""))
            if state.failure_detector is not None:
                state.failure_detector.observe_heartbeat(slug)
            _ingest_heartbeat_metrics(state, slug, p)
        elif method == "alert":
            kind = p.get("kind", "unknown")
            if p.get("resolved"):
                db.resolve_alert(slug, p.get("container", ""), kind)
            else:
                db.upsert_alert(slug, p.get("container", ""), kind,
                                p.get("message", ""))
        elif method == "command_result":
            rid = p.get("request_id")
            if rid:
                state.agent_registry.resolve_result(rid, p)
        elif method == "log":
            state.log_router.publish(LogEntry(
                topic=topic_for(slug, p.get("container", "?")),
                line=p.get("line", ""), level=p.get("level", "info")))
        elif method == "inventory":
            rows = [ObservedContainer(
                server=slug, name=r.get("name", ""), image=r.get("image", ""),
                state=r.get("state", ""), health=r.get("health"),
                restart_count=r.get("restart_count", 0),
                project=r.get("project"), stage=r.get("stage"),
                service=r.get("service"), runtime=r.get("runtime", "docker"))
                for r in p.get("containers", [])]
            db.replace_observed(slug, rows)

    return handle, events


# --------------------------------------------------------------------------
# replication channel (journal shipping to standbys, cp/replication.py)
# --------------------------------------------------------------------------

def _replication_status(state: "AppState") -> dict:
    if state.replicator is not None:
        return state.replicator.status()
    if state.standby is not None:
        return state.standby.status()
    return {"role": state.replication_role,
            "epoch": state.store.epoch, "seq": state.store.seq}


def _replication(state: "AppState"):
    async def handle(conn: Connection, method: str, p: dict) -> dict:
        if method == "status":
            return _replication_status(state)
        if method == "append":
            # the push face is first of all a fencing door: a zombie
            # ex-primary that reconnects and tries to keep shipping its
            # journal is refused by epoch before anything is applied
            epoch = int(p.get("epoch", 0))
            if epoch < state.store.epoch:
                from .store import _M_FENCING
                _M_FENCING.inc(side="cp")
                raise ValueError(
                    f"fenced: entry epoch {epoch} < current epoch "
                    f"{state.store.epoch} — stale primary")
            if state.replication_role == "primary":
                raise ValueError(
                    "this CP is the primary; it does not accept "
                    "replication appends (possible split brain)")
            entries = [(int(s), ln) for s, ln in p.get("entries", [])]
            applied = state.store.apply_replicated(entries)
            return {"applied": applied, "seq": state.store.seq}
        if state.replication_role != "primary" or state.replicator is None:
            raise ValueError(
                f"standby: replication.{method} is served by the primary")
        repl = state.replicator
        if method == "ping":
            # the standby's liveness probe doubles as its ack + the
            # gossip ride-along: the reply carries the full ack table so
            # every standby can rank itself for election
            repl.ack(conn, int(p.get("acked_seq", 0)))
            st = repl.status()
            return {"pong": True, "epoch": st["epoch"], "seq": st["seq"],
                    "standbys": st["standbys"]}
        if method == "subscribe":
            return repl.attach(conn, str(p.get("identity", conn.identity)),
                               int(p.get("from_seq", 0)))
        if method == "snapshot":
            meta, chunks = repl.snapshot_chunks()
            conn._snapshot_chunks = chunks   # per-connection stash
            return meta
        if method == "snapshot_chunk":
            chunks = getattr(conn, "_snapshot_chunks", None)
            if chunks is None:
                raise ValueError("no snapshot in progress; call "
                                 "replication.snapshot first")
            i = int(p.get("chunk", 0))
            data = chunks[i]
            if i == len(chunks) - 1:
                # last chunk served: drop the stash — the connection
                # lives on for streaming and must not pin a full copy
                # of fleet state until disconnect
                conn._snapshot_chunks = None
            return {"data": data}
        raise ValueError(f"unknown method replication.{method}")

    async def events(conn: Connection, method: str, p: dict) -> None:
        if method == "ack" and state.replicator is not None:
            state.replicator.ack(conn, int(p.get("seq", 0)))

    return handle, events


def _on_disconnect(state: "AppState"):
    async def on_disconnect(conn: Connection) -> None:
        if state.replicator is not None:
            state.replicator.detach(conn)   # no-op for non-standby conns
        registered: dict[int, str] = getattr(state, "_agent_conn_slugs", {})
        slug = registered.pop(id(conn), None)
        if slug is not None:
            state.agent_registry.unregister(slug, conn)
            # fast reconnect: a newer session may already own the slug
            # (agent_registry.rs:51-53) — don't mark a live agent offline
            if not state.agent_registry.is_connected(slug):
                s = state.store.server_by_slug(slug)
                if s is not None:
                    state.store.update("servers", s.id, status="offline")
                if state.failure_detector is not None:
                    # fast-path ALIVE -> SUSPECT: the lease's renewals came
                    # over this (now dead) session. The grace window still
                    # absorbs a quick reconnect before any verdict fires.
                    state.failure_detector.observe_disconnect(slug)
    return on_disconnect
