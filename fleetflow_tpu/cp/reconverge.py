"""Reconverger: dead-node verdicts -> warm re-solves -> actual redeploys.

Before this module, the self-healing story stopped half-way: the failure
path recorded heartbeats (store), the health checker could flip a server
offline, and `placement.node_events` would even compute a new assignment —
but nothing DELIVERED that assignment to the surviving agents. A killed
node stranded its services until an operator redeployed by hand. The
reconverger closes the loop (crash-only design: recovery IS the normal
code path):

  FailureDetector.sweep() -> LeaseEvents (dead / node-online verdicts)
      -> placement.node_events(coalesced burst)   one warm re-solve/stage
         (on the TPU scheduler the burst rides a structured ProblemDelta
         into the device-resident problem — solver/resident.py — so a
         reconvergence re-solve never re-uploads the problem tensors;
         `fleet cp heal status` reports the delta/cold staging counts)
      -> redelivery: DeployRequest per surviving node via
         AgentRegistry.send_command, with
           * per-work idempotency keys (agent/agent.py dedupes a replay
             after reconnect, so at-least-once delivery is safe)
           * bounded-retry exponential backoff + jitter on retryable
             failures (core.errors.AgentUnreachable)
           * one trace_id spanning detection -> re-solve -> redeploy
             (flight-recorder correlation, obs/trace.py)
      -> placement.commit_retained on success + a Deployment record
         (the placement record keeps `fleet down`'s node scan truthful)

Infeasible re-solves and exhausted retries PARK the stage: a ParkedWork
record (persisted through the store journal, so a CP restart resumes
convergence instead of forgetting it) retried on the next node-online
verdict. Solver failures during the re-solve degrade to the greedy host
path inside placement.node_events — healing never stalls on the device.

The loop is step-driven with an injectable monotonic clock: production
runs `spawn()` (asyncio task, `interval_s` cadence); the chaos harness
calls `await step()` from its replay loop on the virtual clock, which is
what makes `rolling-kill-selfheal` a deterministic, digest-reproducible
scenario.
"""

from __future__ import annotations

import asyncio
import itertools
import random
import time
import uuid
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Optional

from ..core.errors import AgentCommandError, AgentUnreachable
from ..obs import get_logger, kv, span
from ..obs.metrics import REGISTRY
from ..obs.slo import observe as slo_observe
from ..obs.trace import new_trace_id, use_trace
from ..runtime.engine import DeployRequest
from .agent_registry import DEPLOY_TIMEOUT
from .failure_detector import FailureDetector, LeaseEvent
from .models import Deployment, DeploymentStatus, ParkedWork

if TYPE_CHECKING:
    from .server import AppState

log = get_logger("cp.reconverge")

__all__ = ["ReconvergeConfig", "Reconverger"]

# metric catalog: docs/guide/10-observability.md
_M_RECONVERGE_S = REGISTRY.histogram(
    "fleet_reconverge_duration_seconds",
    "Verdict-handling pass wall time: coalesced churn re-solve + "
    "redelivery fan-out")
_M_REDELIVERIES = REGISTRY.counter(
    "fleet_reconverge_redeliveries_total",
    "Self-heal deploy redeliveries, by outcome", labels=("outcome",))
_M_PARKED = REGISTRY.gauge(
    "fleet_reconverge_parked",
    "Stages parked by the reconverger (infeasible or retries exhausted), "
    "awaiting a node-online verdict")


@dataclass
class ReconvergeConfig:
    """Backoff/parking knobs (docs/guide/12-self-healing.md)."""
    interval_s: float = 5.0          # background loop cadence
    backoff_base_s: float = 2.0      # first retry delay
    backoff_max_s: float = 60.0      # delay ceiling
    max_attempts: int = 5            # then the stage parks


@dataclass
class _Work:
    """One stage's convergence debt: redeliver its retained placement, or
    (parked) wait for capacity to return."""
    stage_key: str
    idempotency_key: str
    trace_id: str
    attempt: int = 0
    next_try_at: float = 0.0
    parked: bool = False
    reason: str = ""
    last_error: str = ""
    # when the VERDICT that opened this debt fired (engine clock; None =
    # unstamped — 0.0 is a legitimate reading on a virtual clock):
    # retire-on-success observes clock() - verdict_at into the heal_s
    # SLO stream — the verdict→converged time-to-heal (obs/slo.py).
    # Superseding work (a fresh burst re-solve for a still-open stage)
    # inherits the ORIGINAL stamp: the operator's question is "how long
    # was the stage degraded", not "how long did the last attempt take".
    verdict_at: Optional[float] = None


class Reconverger:
    def __init__(self, state: "AppState", detector: FailureDetector, *,
                 config: Optional[ReconvergeConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        self.state = state
        self.detector = detector
        self.config = config or ReconvergeConfig()
        self.clock = clock
        # jitter source: seeded by the chaos harness so retry timing is
        # replay-deterministic; fresh entropy in production
        self.rng = rng or random.Random()
        self._work: dict[str, _Work] = {}
        self._gen = itertools.count(1)
        # per-process nonce in every idempotency key: the counter restarts
        # with the CP, and a restarted CP's key "g1" must not collide with
        # an entry still live in an agent's dedupe window (the agent would
        # answer a DIFFERENT assignment's redelivery from the cache)
        self._key_nonce = uuid.uuid4().hex[:8]
        self._task: Optional[asyncio.Task] = None
        self.stats = {"verdicts_dead": 0, "verdicts_online": 0,
                      "resolves": 0, "redeliveries_ok": 0,
                      "redeliveries_retried": 0, "parked": 0, "resumed": 0,
                      "rebuilt_solves": 0}

    # ------------------------------------------------------------------
    # persistence (crash-restart resume)
    # ------------------------------------------------------------------

    def resume(self) -> int:
        """Reload convergence debt a previous CP process left in the
        store: parked stages stay parked; in-flight redelivery work
        retries immediately (the restart may BE the reason it never
        finished). Called once at server start — and again on standby
        promotion, where "previous process" is the dead primary and the
        store contents arrived via replication."""
        n = 0
        for rec in self.state.store.list("parked_work"):
            if rec.stage_key in self._work:
                continue
            self._work[rec.stage_key] = _Work(
                stage_key=rec.stage_key,
                idempotency_key=f"heal-{rec.stage_key}-r{rec.id}",
                trace_id=new_trace_id(), attempt=rec.attempt,
                next_try_at=self.clock(), parked=rec.parked,
                reason=rec.reason or "resumed", last_error=rec.detail,
                # the original verdict died with the predecessor; the
                # resumed heal clock starts here (undercounts across a
                # failover rather than inventing a cross-process stamp)
                verdict_at=self.clock())
            n += 1
        if n:
            self.stats["resumed"] += n
            log.info("resumed convergence backlog %s", kv(stages=n))
        self._rehydrate_placements()
        self._set_parked_gauge()
        return n

    def _rehydrate_placements(self) -> None:
        """Rebuild the placement book from replicated records: every
        committed stage gets its running assignment re-adopted as the
        retained placement (PlacementService.rehydrate). Without this a
        freshly promoted/restarted CP cannot re-place those stages when
        their nodes die later — node_events only moves stages it holds
        retained problems for."""
        placement = self.state.placement
        rehydrate = getattr(placement, "rehydrate", None)
        if rehydrate is None:   # minimal placement fake (unit tests)
            return
        n = 0
        for rec in self.state.store.list("placements"):
            if placement.retained(rec.stage_key) is not None:
                continue
            req, tenant = self._template(rec.stage_key)
            if req is None:
                continue
            try:
                if rehydrate(rec.stage_key, req.flow, tenant=tenant):
                    n += 1
            except Exception:
                log.exception("placement rehydration failed %s",
                              kv(stage=rec.stage_key))
        if n:
            self.stats["rehydrated"] = self.stats.get("rehydrated", 0) + n
            log.info("placement book rehydrated %s", kv(stages=n))

    def _persist(self, w: _Work) -> None:
        db = self.state.store
        rec = db.find_one("parked_work",
                          lambda r: r.stage_key == w.stage_key)
        attrs = dict(reason=w.reason, parked=w.parked, attempt=w.attempt,
                     detail=w.last_error[:500])
        if rec is None:
            db.create("parked_work", ParkedWork(stage_key=w.stage_key,
                                                **attrs))
        else:
            db.update("parked_work", rec.id, **attrs)

    def _unpersist(self, stage_key: str) -> None:
        db = self.state.store
        rec = db.find_one("parked_work",
                          lambda r: r.stage_key == stage_key)
        if rec is not None:
            db.delete("parked_work", rec.id)

    def _set_parked_gauge(self) -> None:
        _M_PARKED.set(sum(1 for w in self._work.values() if w.parked))

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def has_work(self) -> bool:
        """Non-parked redelivery debt outstanding (the chaos settle loop
        keeps advancing the clock until this drains)."""
        return any(not w.parked for w in self._work.values())

    def parked_stage_keys(self) -> list[str]:
        return sorted(k for k, w in self._work.items() if w.parked)

    def pending_stage_keys(self) -> list[str]:
        """Stages with ACTIVE redelivery debt (not parked) — what the
        chaos liveness invariant requires to be empty after settle."""
        return sorted(k for k, w in self._work.items() if not w.parked)

    def debt(self) -> int:
        """Count of stages with active (non-parked) redelivery work —
        the collector's deep gauge (fleet_reconverge_redelivery_debt).
        A plain dict scan; safe from the sampler cadence."""
        return sum(1 for w in self._work.values() if not w.parked)

    def status(self) -> dict:
        """`fleet cp heal status` payload."""
        now = self.clock()
        return {
            "detector": self.detector.status(),
            "config": {"interval_s": self.config.interval_s,
                       "backoff_base_s": self.config.backoff_base_s,
                       "backoff_max_s": self.config.backoff_max_s,
                       "max_attempts": self.config.max_attempts},
            "work": [{"stage": w.stage_key, "parked": w.parked,
                      "attempt": w.attempt, "reason": w.reason,
                      "retry_in_s": (None if w.parked else
                                     round(max(w.next_try_at - now, 0), 3)),
                      "last_error": w.last_error[:200]}
                     for _, w in sorted(self._work.items())],
            "stats": dict(self.stats),
            # how the churn re-solves behind the verdicts were staged:
            # delta = merged into the device-resident problem (the
            # sub-10ms warm path, docs/guide/11-performance.md), cold =
            # full host restaging (content drift / first solve). Host-path
            # CPs report zeros — the TPU scheduler owns these counters.
            "resident": self._resident_stats(),
        }

    @staticmethod
    def _resident_stats() -> dict:
        from ..obs.metrics import REGISTRY
        from .admission import subsolve_outcomes
        reuse = REGISTRY.get("fleet_solver_resident_reuse_total")
        xfers = REGISTRY.get("fleet_solver_host_transfers_total")
        return {
            "delta_reuse": int(reuse.value(outcome="delta")) if reuse else 0,
            "cold_stagings": int(reuse.value(outcome="cold")) if reuse else 0,
            "host_transfers": int(xfers.value()) if xfers else 0,
            # active-set dispatch outcomes (solver/subsolve.py): the heal
            # path's churn re-solves are exactly what it localizes
            "subsolve": subsolve_outcomes(),
        }

    # ------------------------------------------------------------------
    # the convergence step
    # ------------------------------------------------------------------

    async def step(self, drive: bool = True) -> dict:
        """One pass: sweep the detector, turn verdicts into a coalesced
        churn burst, enqueue/park per-stage work, then drive every due
        redelivery. Returns a deterministic summary (the chaos runner
        logs it into the replayable event log).

        `drive=False` stops after the verdict/bookkeeping half — the
        chaos harness uses it to kill a primary BETWEEN enqueuing
        redelivery work and delivering it (the mid-redelivery crash
        window the cp-failover scenario must cover)."""
        summary = {"dead": [], "online": [], "resolved": [],
                   "redelivered": [], "retried": [], "parked": []}
        events = self.detector.sweep()
        if events:
            try:
                await self._handle_verdicts(events, summary)
            except Exception:
                # verdicts were requeued by _handle_verdicts; the step
                # itself survives (the loop's next pass retries them)
                log.exception("verdict handling failed; will retry")
                summary["dead"], summary["online"] = [], []
                summary["resolved"] = []
        if drive:
            await self._drive_due(summary)
        return summary

    async def _handle_verdicts(self, events: list[LeaseEvent],
                               summary: dict) -> None:
        dead = [e.slug for e in events if not e.online]
        online = [e.slug for e in events if e.online]
        self.stats["verdicts_dead"] += len(dead)
        self.stats["verdicts_online"] += len(online)
        summary["dead"] = dead
        summary["online"] = online
        trace_id = new_trace_id()
        t0 = time.perf_counter()
        with use_trace(trace_id):
            with span(log, "reconverge", dead=",".join(dead) or None,
                      online=",".join(online) or None) as sp:
                burst = [(e.slug, e.online) for e in events]
                try:
                    # the warm re-solve runs off-loop: heartbeats and
                    # command_result traffic must keep flowing while JAX
                    # works
                    moved = await asyncio.get_running_loop(
                        ).run_in_executor(
                            None,
                            lambda: self.state.placement.node_events(burst))
                except Exception:
                    # the verdicts are NOT consumed: requeue so the next
                    # step retries them (placement.node_events already
                    # degrades to the host path internally; reaching here
                    # means something worse — but never lose a verdict)
                    self.detector.requeue(events)
                    raise
                self.stats["resolves"] += len(moved)
                sp["stages"] = len(moved) or None
                for key, placement in moved:
                    summary["resolved"].append(
                        {"stage": key, "feasible": placement.feasible})
                    # per-stage isolation: a store/persist hiccup on one
                    # stage must not abort the loop — the verdicts were
                    # already consumed by sweep(), so any stage skipped
                    # here would lose its redelivery work forever
                    try:
                        if placement.feasible:
                            self._enqueue(key, trace_id)
                        else:
                            self._park(
                                self._work.get(key)
                                or _Work(stage_key=key,
                                         idempotency_key=self._next_key(key),
                                         trace_id=trace_id,
                                         verdict_at=self.clock()),
                                "infeasible",
                                f"violations={placement.violations}")
                            summary["parked"].append(key)
                    except Exception:
                        log.exception("work bookkeeping failed %s",
                                      kv(stage=key))
                if online:
                    # returned capacity: wake every parked stage the burst
                    # re-solve didn't already reach — its full redeploy
                    # solves fresh against the grown inventory
                    touched = {key for key, _ in moved}
                    for key in self.parked_stage_keys():
                        if key not in touched:
                            try:
                                self._unpark(key, trace_id)
                            except Exception:
                                log.exception("unpark failed %s",
                                              kv(stage=key))
        _M_RECONVERGE_S.observe(time.perf_counter() - t0)

    def _next_key(self, stage_key: str) -> str:
        return f"heal-{stage_key}-{self._key_nonce}-g{next(self._gen)}"

    def _enqueue(self, stage_key: str, trace_id: str) -> None:
        """New feasible assignment for a stage: (re)start its redelivery
        work. A fresh assignment supersedes older debt — and gets a fresh
        idempotency key, because the PAYLOAD changed (dedupe must only
        ever suppress replays of the same assignment)."""
        prev = self._work.get(stage_key)
        w = _Work(stage_key=stage_key,
                  idempotency_key=self._next_key(stage_key),
                  trace_id=trace_id, next_try_at=self.clock(),
                  reason="redeliver",
                  # time-to-heal runs from the FIRST verdict that opened
                  # this stage's still-unhealed debt
                  verdict_at=(prev.verdict_at
                              if prev is not None
                              and prev.verdict_at is not None
                              else self.clock()))
        self._work[stage_key] = w
        self._persist(w)
        self._set_parked_gauge()

    def _unpark(self, stage_key: str, trace_id: str) -> None:
        w = self._work.get(stage_key)
        if w is None or not w.parked:
            return
        w.parked = False
        w.attempt = 0
        w.trace_id = trace_id
        w.reason = "unparked"
        # the payload the redelivery will carry is whatever the fresh
        # re-solve produced, not what was parked: a stale (or empty —
        # the infeasible-park placeholder's) key must never ride along,
        # or a timeout retry would lose its dedupe protection
        w.idempotency_key = self._next_key(stage_key)
        w.next_try_at = self.clock()
        self._persist(w)
        self._set_parked_gauge()
        log.info("unparked %s", kv(stage=stage_key))

    def _park(self, w: _Work, reason: str, detail: str = "") -> None:
        w.parked = True
        w.reason = reason
        w.last_error = detail
        self._work[w.stage_key] = w
        self.stats["parked"] += 1
        _M_REDELIVERIES.inc(outcome="parked")
        self._persist(w)
        self._set_parked_gauge()
        log.warning("parked %s", kv(stage=w.stage_key, reason=reason,
                                    detail=detail or None))

    def _retry(self, w: _Work, summary: dict, error: str) -> None:
        w.attempt += 1
        w.last_error = error
        if w.attempt >= self.config.max_attempts:
            self._park(w, "retries-exhausted", error)
            summary["parked"].append(w.stage_key)
            return
        base = min(self.config.backoff_max_s,
                   self.config.backoff_base_s * (2 ** (w.attempt - 1)))
        # full-jitter-lite: 75-125% of the exponential step, so a burst of
        # displaced stages doesn't hammer the surviving agents in lockstep
        w.next_try_at = self.clock() + base * (0.75 + 0.5 * self.rng.random())
        self.stats["redeliveries_retried"] += 1
        _M_REDELIVERIES.inc(outcome="retry")
        self._persist(w)
        summary["retried"].append(w.stage_key)
        log.info("redelivery retry scheduled %s", kv(
            stage=w.stage_key, attempt=w.attempt,
            delay_s=round(w.next_try_at - self.clock(), 2), error=error))

    async def _drive_due(self, summary: dict) -> None:
        now = self.clock()
        due = [w for _, w in sorted(self._work.items())
               if not w.parked and w.next_try_at <= now]
        for w in due:
            with use_trace(w.trace_id):
                try:
                    ok = await self._redeliver(w)
                except AgentCommandError as e:
                    if e.retryable:
                        self._retry(w, summary, str(e))
                    else:
                        # the agent ran the deploy and failed it: retrying
                        # verbatim reruns the failure — park for operator
                        # attention / the next topology change
                        self._park(w, "deploy-failed", str(e))
                        summary["parked"].append(w.stage_key)
                    continue
                except Exception as e:  # solver/store surprises: retry
                    self._retry(w, summary, f"{type(e).__name__}: {e}")
                    continue
            if ok:
                summary["redelivered"].append(w.stage_key)

    # ------------------------------------------------------------------
    # redelivery
    # ------------------------------------------------------------------

    def _template(self, stage_key: str
                  ) -> tuple[Optional[DeployRequest], str]:
        """The stage's replay template: the newest deployment record that
        stored its request (execute_deploy does; so do our own heal
        records). Returns (request, tenant)."""
        project_name, _, stage_name = stage_key.partition("/")
        for d in reversed(self.state.store.list("deployments")):
            req = d.request
            if (req and req.get("stage_name") == stage_name
                    and (req.get("flow") or {}).get("name") == project_name):
                return DeployRequest.from_dict(dict(req)), d.tenant
        return None, "default"

    async def _redeliver(self, w: _Work) -> bool:
        """Push the stage's retained assignment to its surviving nodes.
        True on full success (work retired); raises AgentCommandError on
        per-node failure (classified by the caller)."""
        key = w.stage_key
        entry = self.state.placement.retained(key)
        if entry is None:
            # No retained placement for in-flight work means THIS process
            # never solved the stage: the work was inherited from a dead
            # predecessor (CP restart, or a standby promoted mid-
            # redelivery). Rebuild the retry state from replicated
            # records: a fresh solve from the stored deployment template
            # repopulates the retained entry, and the redelivery proceeds
            # as if the solve had happened here. Only when there is no
            # template either is the stage truly gone.
            entry = await self._rebuild_retained(w)
            if entry is None:
                return False
        _pt, placement = entry
        if not placement.feasible:
            self._park(w, "infeasible",
                       f"violations={placement.violations}")
            return False
        req, tenant = self._template(key)
        if req is None:
            self._park(w, "no-template",
                       "no stored deployment request to replay")
            return False
        assignment = dict(placement.assignment)
        targets = sorted({node for node in assignment.values()})
        registry = self.state.agent_registry
        absent = [t for t in targets if not registry.is_connected(t)]
        if absent:
            raise AgentUnreachable(
                f"assigned nodes not connected: {absent}",
                reason="not-connected")
        with span(log, "heal.redeliver", stage=key,
                  nodes=",".join(targets), attempt=w.attempt) as sp:
            # one BATCH to the registry (not one awaited future per
            # node): each target rides its owning shard's bounded
            # pipeline lane — cp/shards.py — and the per-command metric
            # labels + fencing epoch are resolved once for the batch
            results = await registry.send_batch(
                [(slug, "deploy.execute",
                  {"request": DeployRequest(
                      flow=req.flow, stage_name=req.stage_name,
                      no_pull=req.no_pull, no_prune=req.no_prune,
                      node=slug, trace_id=w.trace_id).to_dict(),
                   "assignment": assignment,
                   "idempotency_key": w.idempotency_key})
                 for slug in targets], timeout=DEPLOY_TIMEOUT)
            failures = [r for r in results if isinstance(r, Exception)]
            if failures:
                # prefer the retryable classification: if ANY node failed
                # retryably the whole redelivery is worth retrying (the
                # idempotency key makes re-sending to the ok nodes safe)
                retryable = [f for f in failures
                             if getattr(f, "retryable", False)]
                raise (retryable[0] if retryable else failures[0])
            self.state.placement.commit_retained(key)
            self._record_deployment(key, tenant, req, assignment, targets)
            sp["nodes_ok"] = len(targets)
        self.stats["redeliveries_ok"] += 1
        _M_REDELIVERIES.inc(outcome="ok")
        if w.verdict_at is not None:
            # verdict → converged, on the engine clock (virtual in
            # chaos): the heal-p99-s SLO stream (obs/slo.py)
            slo_observe("heal_s", max(self.clock() - w.verdict_at, 0.0))
        self._retire(w)
        log.info("stage reconverged %s", kv(stage=key,
                                            nodes=",".join(targets)))
        return True

    async def _rebuild_retained(self, w: _Work):
        """Failover/restart path: re-solve the stage from its stored
        deployment template so redelivery has a placement to carry.
        Returns the retained (pt, placement) entry, or None after
        retiring/parking the work."""
        key = w.stage_key
        req, tenant = self._template(key)
        if req is None:
            # stage torn down / never solved anywhere: nothing to converge
            self._retire(w)
            return None
        solve = getattr(self.state.placement, "solve_stage", None)
        if solve is None:   # minimal placement fake (unit tests)
            self._retire(w)
            return None
        with span(log, "heal.rebuild", stage=key, attempt=w.attempt):
            # reserve=False: commit_retained books the capacity when the
            # redelivery lands, same as the node_events churn path
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: solve(req.flow, req.stage_name,
                                    tenant=tenant, reserve=False))
        self.stats["rebuilt_solves"] += 1
        log.info("retained placement rebuilt from template %s",
                 kv(stage=key))
        return self.state.placement.retained(key)

    def _retire(self, w: _Work) -> None:
        self._work.pop(w.stage_key, None)
        self._unpersist(w.stage_key)
        self._set_parked_gauge()

    def _record_deployment(self, stage_key: str, tenant_name: str,
                           req: DeployRequest, assignment: dict,
                           targets: list[str]) -> None:
        """The heal lands in deployment history like any deploy — and
        records its placement, which `fleet down`'s node scan treats as
        the truth about WHERE containers live (handlers.execute_down)."""
        db = self.state.store
        tenant = db.ensure_tenant(tenant_name)
        project = db.ensure_project(tenant.name, req.flow.name)
        stage_cfg = req.flow.stage(req.stage_name)
        stage = db.ensure_stage(project.id, req.stage_name)
        stored_req = req.to_dict()
        stored_req.pop("trace_id", None)
        stored_req.pop("node", None)
        dep = db.create("deployments", Deployment(
            tenant=tenant.name, project=project.id, stage=stage.id,
            status=DeploymentStatus.RUNNING.value,
            services=[s.name for s in stage_cfg.resolved_services(req.flow)],
            placement=assignment, request=stored_req))
        db.finish_deployment(dep.id, DeploymentStatus.SUCCEEDED,
                             log=f"self-heal redeploy to "
                                 f"{', '.join(targets)}")
        for svc in dep.services or []:
            db.upsert_service(stage.id, svc, status="deployed")

    # ------------------------------------------------------------------
    # background loop (production)
    # ------------------------------------------------------------------

    async def run_loop(self) -> None:
        while True:
            try:
                await self.step()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("reconverge step failed")
            await asyncio.sleep(self.config.interval_s)

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run_loop())
        return self._task

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
