"""Streaming admission: continuous service arrivals/departures as bucketed
micro-solves with backpressure, tenant fairness, and autoscaler feedback.

Placement used to be burst-driven (deploy commands, coalesced reconvergence
bursts). Serving millions of users means a *continuous* stream of service
arrivals and departures (ROADMAP item 5), and PRs 7-8 built exactly the
substrate that makes a streaming steady state cheap: device-resident
problems whose churn arrives as donated `ProblemDelta` merges, padded onto
`solver/buckets.py` shape tiers so in-tier drift reuses ONE compiled
executable. This module is the serving-stack front half — the continuous
batcher in front of that warm solve path:

  submit()    bounded, per-tenant FIFO sub-queues. Depth and age
              watermarks implement BACKPRESSURE: past the depth bound the
              policy either SHEDS (a structured, retryable
              `AdmissionRejected` the client backs off on) or PARKS
              (accepted, deferred until the queue drains); requests that
              out-age the age watermark are shed by the drain loop so the
              queue can never grow a stale tail.
  step()      one drain pass: a DEFICIT-ROUND-ROBIN scan over the tenant
              sub-queues builds one bucketed micro-batch (weighted max-min
              fairness — an arrival storm from one tenant cannot starve
              the others), the batch folds into the stage's streaming
              problem (tombstoned departures, row-reusing arrivals), and
              ONE micro-solve rides the resident delta path through
              `PlacementService.admit_batch`, committed as ONE reservation.
  pressure()  the autoscaler feedback signal (cp/autoscaler.py): sustained
              queue age or infeasible-parked arrivals mean the SOLVER is
              the bottleneck or the fleet is full — provision nodes; a
              drained queue releases the hold so idle scale-down resumes.

The streaming problem shape (why steady state is zero-recompile,
zero-host-transfer):

  * a DEPARTURE tombstones its row in place — demand zeroed by a
    `ProblemDelta` row scatter; the row index goes on a free list. The
    (S, N) planes never reshape, so the padded tier (and the compiled
    executable) survives.
  * an ARRIVAL first reuses a free tombstone row (same-shape scatter), and
    only appends a fresh row — activating an on-device phantom row via the
    delta's `n_real` bump — when the free list is empty. At steady state
    (arrivals ~ departures) rows recirculate and S is constant.
  * streamed services must be SIMPLE: resources + optional node
    eligibility, one replica, no ports/volumes/anti-affinity/colocation/
    dependencies — exactly the churn the delta path can express
    (solver/resident.py `_arrivals_compatible`). Richer services go through
    the full deploy path (`deploy.execute`), which re-lowers and
    cold-stages honestly.
  * when the row count would cross its shape tier and tombstones exist,
    the stream COMPACTS (drops tombstone rows and cold-restages once) —
    amortized, counted, and absent at steady state.

Determinism contract (pinned by tests/test_admission.py and the chaos
`arrival-storm` scenario): events fold into the streaming problem in
submission order within each tenant, and a micro-solve is a pure function
of the resulting problem content — so replaying a stream through any batch
chunking commits the same final placement as one equivalent batch solve.

Metric catalog: docs/guide/10-observability.md. Knobs + runbook:
docs/guide/14-streaming-admission.md.
"""

from __future__ import annotations

import asyncio
import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field, replace as _dc_replace
from typing import Callable, Optional

import numpy as np

from ..core.errors import ControlPlaneError
from ..core.model import Flow, ResourceSpec, Service
from ..obs import get_logger, kv
from ..obs.metrics import REGISTRY
from ..obs.slo import observe as slo_observe

# the active-set dispatch vocabulary (solver/subsolve.py); read via the
# registry so a host-path CP's status call never imports jax
SUBSOLVE_OUTCOMES = ("localized", "fallback_closure", "fallback_small",
                     "fallback_infeasible")


def subsolve_outcomes() -> dict:
    m = REGISTRY.get("fleet_solver_subsolve_total")
    return {o: (int(m.value(outcome=o)) if m is not None else 0)
            for o in SUBSOLVE_OUTCOMES}

log = get_logger("cp.admission")

__all__ = ["AdmissionConfig", "AdmissionController", "AdmissionRejected",
           "AdmissionRequest"]

_M_DEPTH = REGISTRY.gauge(
    "fleet_admission_queue_depth",
    "Service arrivals/departures queued for admission across all tenants")
_M_OLDEST = REGISTRY.gauge(
    "fleet_admission_oldest_age_seconds",
    "Age of the oldest queued admission request")
_M_BATCH = REGISTRY.histogram(
    "fleet_admission_batch_size",
    "Events folded into one admission micro-solve",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
_M_BATCH_AGE = REGISTRY.histogram(
    "fleet_admission_batch_age_seconds",
    "Age of the oldest event in a micro-batch at solve time")
_M_WAIT = REGISTRY.histogram(
    "fleet_admission_wait_seconds",
    "Per-request admission latency: submit to committed placement")
_M_ADMITTED = REGISTRY.counter(
    "fleet_admission_admitted_total",
    "Service arrivals committed into a placement, by tenant",
    labels=("tenant",))
_M_DEPARTED = REGISTRY.counter(
    "fleet_admission_departed_total",
    "Service departures committed out of a placement, by tenant",
    labels=("tenant",))
_M_SHEDS = REGISTRY.counter(
    "fleet_admission_sheds_total",
    "Admission requests shed by backpressure, by reason "
    "(depth = queue bound hit at submit, age = out-aged the watermark)",
    labels=("reason",))
_M_PARKED = REGISTRY.counter(
    "fleet_admission_parked_total",
    "Arrivals parked (accepted but deferred: infeasible micro-solve or "
    "park-on-full policy)")
_M_UNPARKED = REGISTRY.counter(
    "fleet_admission_unparked_total",
    "Parked arrivals re-queued after capacity freed up")
_M_QUOTA_PARKED = REGISTRY.counter(
    "fleet_admission_quota_parked_total",
    "Arrivals parked by a per-tenant hard quota cap, by tenant (accepted "
    "but deferred until the tenant's live+queued count drops under its cap)",
    labels=("tenant",))
_M_SOLVES = REGISTRY.counter(
    "fleet_admission_solves_total",
    "Admission micro-solves, by outcome",
    labels=("outcome",))
_M_RATE = REGISTRY.gauge(
    "fleet_admission_placements_per_s",
    "Sustained admission throughput over the most recent drain window "
    "(committed arrivals per wall-clock second of micro-solving)")
_M_DEBT = REGISTRY.gauge(
    "fleet_admission_fairness_debt",
    "Deficit-round-robin credit per tenant (requests the tenant may pop "
    "before yielding the drain to the next tenant)",
    labels=("tenant",))
_M_PHASE = REGISTRY.histogram(
    "fleet_admission_solve_phase_ms",
    "Wall milliseconds per admission drain phase: drain = parked "
    "retry + age shed + DRR batch pop, fold = candidate delta-problem "
    "build (+compaction), solve = resident micro-solve(s), commit = "
    "reservation commit + row bookkeeping — the p99-vs-p50 breakdown "
    "the solve-tail hunt needs",
    labels=("phase",),
    buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500))


class AdmissionRejected(ControlPlaneError):
    """Backpressure: the admission queue refused this submit. RETRYABLE —
    the client should back off `retry_after_s` and resubmit; `reason` is a
    short stable token (queue-depth | age) for metrics and log labels."""

    retryable = True

    def __init__(self, message: str, *, reason: str = "queue-depth",
                 retry_after_s: float = 1.0):
        super().__init__(f"admission rejected ({reason}, "
                         f"retry_after_s={retry_after_s:g}): {message}")
        self.reason = reason
        self.retry_after_s = retry_after_s


@dataclass
class AdmissionConfig:
    max_queue: int = 4096        # depth watermark: bound on queued requests
    shed_age_s: float = 120.0    # age watermark: queued longer is shed
    on_full: str = "shed"        # shed | park (policy at the depth bound)
    batch_max: int = 128         # events per micro-solve (delta scatter tier)
    quantum: float = 8.0         # DRR credit per unit weight per visit
    tenant_weights: dict[str, float] = field(default_factory=dict)
    # per-tenant HARD caps on streamed arrivals: live + queued + parked
    # may never exceed the cap. Overflow arrivals PARK with reason
    # "quota" (accepted, deferred — not shed: the client did nothing
    # wrong, the tenant is at its purchased ceiling) and re-queue only
    # when departures open headroom. Absent tenant = uncapped.
    tenant_caps: dict[str, int] = field(default_factory=dict)
    # autoscaler feedback: queue age that counts as solver pressure, and
    # how long it must persist before the autoscaler provisions on it
    pressure_age_s: float = 5.0
    pressure_sustain_s: float = 15.0
    # parked arrivals retry when capacity frees (a departure commits or a
    # stream re-syncs); 0 disables parking retry entirely
    drain_interval_s: float = 0.5


@dataclass
class AdmissionRequest:
    """One queued arrival or departure. `state` is the census the chaos
    `admission-converged` invariant audits: every request must end
    terminal (placed | departed | parked | shed | cancelled), never lost."""
    id: str
    tenant: str
    kind: str                    # arrival | departure
    name: str
    stage_key: str
    submitted_at: float
    seq: int
    service: Optional[Service] = None
    demand: Optional[np.ndarray] = None        # (R,) arrival demand
    eligible_nodes: Optional[list[str]] = None
    state: str = "queued"
    done_at: Optional[float] = None
    # why a parked request is parked: capacity (infeasible micro-solve),
    # depth (on_full="park" policy), quota (tenant hard cap). Drives the
    # retry policy: quota parks wait for tenant headroom, not capacity
    park_reason: Optional[str] = None

    TERMINAL = frozenset({"placed", "departed", "parked", "shed",
                          "cancelled"})


@dataclass
class _Stream:
    """Per-stage streaming problem state: the canonical row book the
    micro-solves fold into."""
    key: str
    flow: Flow
    stage_name: str
    tenant: str
    pt: object                              # lower.tensors.ProblemTensors
    row_of: dict[str, int] = field(default_factory=dict)   # live name -> row
    tombstones: set[str] = field(default_factory=set)      # masked names
    free_rows: list[int] = field(default_factory=list)     # reusable rows
    streamed: dict[str, int] = field(default_factory=dict)  # name -> seq
    owner: dict[str, str] = field(default_factory=dict)     # name -> tenant


def _simple_reject(svc: Service) -> Optional[str]:
    """Why `svc` cannot ride the streaming delta path (None = it can).
    Mirrors solver/resident._arrivals_compatible: appended rows must bring
    no hard-constraint ids, no dependencies, one replica."""
    if svc.ports:
        return "ports"
    if svc.volumes:
        return "volumes"
    if svc.anti_affinity:
        return "anti_affinity"
    if svc.colocate_with:
        return "colocate_with"
    if svc.depends_on:
        return "depends_on"
    if svc.replicas != 1:
        return f"replicas={svc.replicas}"
    return None


class AdmissionController:
    """The continuous batcher in front of the warm solve path (module
    docstring). Thread-safe; the clock is injectable (time.monotonic in
    production, the chaos VirtualClock in replay) so every watermark and
    wait is exact arithmetic on whichever clock drives the world."""

    def __init__(self, placement, *, clock: Callable[[], float] = time.monotonic,
                 config: Optional[AdmissionConfig] = None, store=None):
        self.placement = placement
        self.clock = clock
        self.cfg = config or AdmissionConfig()
        # journal parked arrivals into this cp/store.py Store (table
        # "admission_parked") so accepted-but-deferred work replicates to
        # standbys and survives a CP failover; None = in-memory only
        self._store = store
        self._lock = threading.Lock()
        self._queues: dict[str, deque[AdmissionRequest]] = {}
        self._deficit: dict[str, float] = {}
        self._rr: list[str] = []          # persistent tenant rotation
        self._rr_idx = 0
        self._parked: list[AdmissionRequest] = []
        self._park_epoch = 0              # capacity epoch parked waits on
        self._capacity_epoch = 0          # bumps when capacity frees up
        self._streams: dict[str, _Stream] = {}
        self._ids = itertools.count(1)
        self._seq = itertools.count(1)
        self.requests: dict[str, AdmissionRequest] = {}
        # per-tenant completed admission waits (the admission-fair
        # invariant's evidence); bounded so a long-lived CP cannot grow it
        self.wait_samples: dict[str, deque[float]] = {}
        self._pressure_since: Optional[float] = None
        # last computed pressure view, readable WITHOUT the controller
        # lock: a drain pass holds the lock for the whole micro-solve,
        # and the autoscaler's feedback must not block on solver wall
        # time (stale by at most one drain tick)
        self._pressure_snapshot: dict = {"queue_depth": 0,
                                         "oldest_age_s": 0.0, "parked": 0,
                                         "parked_quota": 0,
                                         "sustained": False,
                                         "drained": True}
        self.stats = {"admitted": 0, "departed": 0, "sheds": 0,
                      "parked": 0, "unparked": 0, "solves": 0,
                      "compactions": 0, "batches": 0, "quota_parked": 0,
                      "restored": 0}
        # wall-ms of the most recent drain pass, by phase (drain / fold /
        # solve / commit) — surfaced through deploy.admit_status so a
        # p99 solve tail can be attributed to a phase without a profiler
        self.last_phase_ms: dict[str, float] = {}
        # per-micro-solve wall-ms samples (bounded): the solve TAIL is a
        # first-class operator number — `fleet admit status` reports the
        # p50/p99 and the bench's BENCH_ADMIT_ASSERT bounds their ratio
        # so a re-grown tail fails CI instead of hiding in an average
        self.solve_ms_samples: deque[float] = deque(maxlen=4096)
        self._task = None
        self._restore_parked()

    # ------------------------------------------------------------------
    # parked-arrival journal (store table "admission_parked")
    # ------------------------------------------------------------------

    def _journal_park(self, r: AdmissionRequest, reason: str) -> None:
        """Persist a park transition. create() overwrites by id, so a
        re-park of a retried arrival just refreshes its record."""
        r.park_reason = reason
        if self._store is None or r.service is None:
            return
        from .models import ParkedArrival
        svc = r.service
        spec = {"name": svc.name, "image": svc.image,
                "version": svc.version, "cpu": svc.resources.cpu,
                "memory": svc.resources.memory, "disk": svc.resources.disk,
                "labels": dict(svc.labels or {})}
        self._store.create("admission_parked", ParkedArrival(
            id=r.id, tenant=r.tenant, name=r.name, stage_key=r.stage_key,
            submitted_at=r.submitted_at, seq=r.seq, reason=reason,
            spec=spec, eligible_nodes=list(r.eligible_nodes or [])))

    def _unjournal_park(self, r: AdmissionRequest) -> None:
        """A parked arrival re-queued or went terminal: drop its record
        (idempotent — restores and in-memory controllers both land here)."""
        if self._store is not None:
            self._store.delete("admission_parked", r.id)

    def _restore_parked(self) -> None:
        """Rebuild the parked set from the journal (CP failover/restart):
        the promoted primary re-parks what the dead one accepted. Restored
        requests keep their original seq so retry order is preserved, and
        the id/seq counters advance past them so new submits cannot
        collide. They retry as soon as capacity first moves — exactly the
        contract they parked under."""
        if self._store is None:
            return
        rows = self._store.list("admission_parked")
        if not rows:
            return
        max_seq = max_id = 0
        for rec in sorted(rows, key=lambda rec: rec.seq):
            svc = self.make_arrival(dict(rec.spec))
            r = AdmissionRequest(
                id=rec.id, tenant=rec.tenant, kind="arrival", name=rec.name,
                stage_key=rec.stage_key, submitted_at=rec.submitted_at,
                seq=rec.seq, service=svc,
                demand=np.array(svc.resources.as_tuple(), dtype=np.float64),
                eligible_nodes=list(rec.eligible_nodes) or None,
                state="parked", park_reason=rec.reason or "capacity")
            self._parked.append(r)
            self.requests[r.id] = r
            max_seq = max(max_seq, int(rec.seq))
            try:
                max_id = max(max_id, int(str(rec.id).rsplit("_", 1)[1]))
            except (IndexError, ValueError):
                pass
        self._ids = itertools.count(max_id + 1)
        self._seq = itertools.count(max_seq + 1)
        self.stats["restored"] += len(rows)
        log.info("admission parked restored %s",
                 kv(restored=len(rows), max_seq=max_seq))

    # ------------------------------------------------------------------
    # per-tenant hard quota caps
    # ------------------------------------------------------------------

    def _tenant_inflight(self, tenant: str) -> int:
        """Streamed services a cap must count: live + queued arrivals +
        parked arrivals. Departures never count — they only free."""
        live = sum(1 for s in self._streams.values()
                   for t in s.owner.values() if t == tenant)
        queued = sum(1 for r in (self._queues.get(tenant) or ())
                     if r.kind == "arrival")
        parked = sum(1 for r in self._parked
                     if r.tenant == tenant and r.kind == "arrival")
        return live + queued + parked

    def _quota_headroom(self, tenant: str) -> Optional[int]:
        """Remaining arrivals the tenant's hard cap admits right now
        (None = uncapped; may be negative when departures lag)."""
        cap = self.cfg.tenant_caps.get(tenant)
        if cap is None:
            return None
        return int(cap) - self._tenant_inflight(tenant)

    # ------------------------------------------------------------------
    # stage attachment
    # ------------------------------------------------------------------

    def attach(self, flow: Flow, stage_name: str, *,
               tenant: str = "default") -> str:
        """Register a stage as streaming-managed. The stage must have (or
        gets) a committed baseline placement: micro-solves are deltas
        against it. Returns the stage key."""
        key = f"{flow.name}/{stage_name}"
        with self._lock:
            if key in self._streams:
                return key
        entry = self.placement.retained(key)
        if entry is None:
            placement, rid = self.placement.solve_stage(
                flow, stage_name, tenant=tenant)
            if not placement.feasible:
                raise ControlPlaneError(
                    f"cannot attach {key}: baseline placement infeasible "
                    f"({placement.violations} violations)")
            if rid:
                self.placement.commit(rid)
            entry = self.placement.retained(key)
        pt, _ = entry
        with self._lock:
            self._streams[key] = _Stream(
                key=key, flow=flow, stage_name=stage_name, tenant=tenant,
                pt=pt, row_of={n: i for i, n in enumerate(pt.service_names)})
        log.info("admission stream attached %s", kv(stage=key, rows=pt.S))
        return key

    def _stream_for(self, stage: Optional[str]) -> _Stream:
        if stage is not None:
            s = self._streams.get(stage)
            if s is None:
                raise ValueError(
                    f"stage {stage!r} is not admission-managed; attached: "
                    f"{sorted(self._streams)}")
            return s
        if len(self._streams) == 1:
            return next(iter(self._streams.values()))
        raise ValueError(
            f"stage required ({len(self._streams)} streams attached: "
            f"{sorted(self._streams)})")

    def _resync(self, stream: _Stream) -> None:
        """Another solve path replaced the stage's retained problem:
        adopt it as the new streaming baseline. A flow re-lower (redeploy,
        full re-solve) carries no tombstone rows — the controller keeps
        the flow compacted — so the book resets; but a CHURN re-solve
        (placement.node_events) reuses the streaming pt's rows, so any
        tombstone names still present must CARRY OVER: wiping them would
        unmask departed services in the next committed view and leak
        their rows forever."""
        entry = self.placement.retained(stream.key)
        if entry is None or entry[0] is stream.pt:
            return
        pt = entry[0]
        idx = {n: i for i, n in enumerate(pt.service_names)}
        carried = {n: idx[n] for n in stream.tombstones if n in idx}
        stream.pt = pt
        stream.row_of = {n: i for n, i in idx.items() if n not in carried}
        stream.tombstones = set(carried)
        stream.free_rows = sorted(carried.values())
        self._capacity_epoch += 1       # the world changed under us:
        log.debug("admission stream resynced %s",    # parked get a retry
                  kv(stage=stream.key, rows=pt.S,
                     carried_tombstones=len(carried)))

    # ------------------------------------------------------------------
    # submit (backpressure front door)
    # ------------------------------------------------------------------

    def make_arrival(self, spec: dict) -> Service:
        """Build a streamed Service from a wire spec: {name, image?,
        version?, cpu?, memory?, disk?, eligible_nodes?, labels?}."""
        return Service(
            name=str(spec["name"]),
            image=spec.get("image") or "app",
            version=spec.get("version") or "latest",
            resources=ResourceSpec(cpu=float(spec.get("cpu", 0.1)),
                                   memory=float(spec.get("memory", 64.0)),
                                   disk=float(spec.get("disk", 0.0))),
            labels=dict(spec.get("labels") or {}),
        )

    def submit(self, tenant: str, arrivals=(), departures=(), *,
               stage: Optional[str] = None) -> dict:
        """Enqueue a batch of arrivals (Service or wire spec dicts) and
        departures (service names). Atomic: validates everything first,
        then enqueues everything — a bad entry rejects the whole submit
        with ValueError; backpressure rejects it with AdmissionRejected
        (retryable). Returns {accepted, queued, stage}."""
        now = self.clock()
        with self._lock:
            stream = self._stream_for(stage)
            self._resync(stream)
            svcs: list[Service] = []
            queued_names = {r.name for q in self._queues.values() for r in q
                            if r.kind == "arrival"
                            and r.stage_key == stream.key}
            for a in arrivals:
                svc = a if isinstance(a, Service) else self.make_arrival(a)
                why = _simple_reject(svc)
                if why is not None:
                    raise ValueError(
                        f"arrival {svc.name!r} is not streamable ({why}): "
                        f"constrained services deploy via deploy.execute "
                        f"(docs/guide/14-streaming-admission.md)")
                if (svc.name in stream.row_of and svc.name not in
                        stream.tombstones) or svc.name in queued_names:
                    raise ValueError(
                        f"arrival {svc.name!r} already live or queued in "
                        f"{stream.key}")
                if svc.name in {s.name for s in svcs}:
                    raise ValueError(f"duplicate arrival {svc.name!r}")
                svcs.append(svc)
            deps: list[str] = []
            pending_deps = {r.name for q in self._queues.values() for r in q
                            if r.kind == "departure"
                            and r.stage_key == stream.key}
            for name in departures:
                name = str(name)
                if name in pending_deps or name in deps:
                    # a doubled departure would tombstone one row twice
                    # (double free-list entry -> one row handed to two
                    # arrivals); draining is idempotent, not cumulative
                    raise ValueError(
                        f"departure {name!r} is already pending in "
                        f"{stream.key}")
                if name not in stream.streamed:
                    # a base-flow service may carry constraint ids (or
                    # replica rows) the tombstone row would keep
                    # occupying — route its teardown through deploy.down
                    base = stream.flow.services.get(name)
                    if base is not None and _simple_reject(base):
                        raise ValueError(
                            f"departure {name!r} is a constrained base "
                            f"service; tear it down via deploy.down")
                live = (name in stream.row_of
                        and name not in stream.tombstones)
                queued = name in queued_names or any(
                    s.name == name for s in svcs)
                parked = any(r.name == name and r.stage_key == stream.key
                             for r in self._parked)
                if not (live or queued or parked):
                    raise ValueError(
                        f"departure {name!r}: no such live, queued or "
                        f"parked service in {stream.key}")
                deps.append(name)

            # tenant hard quota (policy, not backpressure): arrivals past
            # the cap's headroom PARK with reason "quota" — accepted and
            # journaled, deferred until this tenant's own departures open
            # headroom. Split BEFORE the depth watermark so a capped
            # tenant's overflow never occupies (or sheds against) the
            # shared queue bound
            quota_overflow: list[Service] = []
            headroom = self._quota_headroom(tenant)
            if headroom is not None and svcs and len(svcs) > max(headroom, 0):
                keep = max(headroom, 0)
                quota_overflow = svcs[keep:]
                svcs = svcs[:keep]

            # depth watermark (backpressure). Pure-departure submits are
            # exempt: they only ever FREE capacity — refusing them at a
            # full queue would turn transient backpressure into a stall
            # (deps are naturally bounded by the live set, so the
            # exemption cannot grow the queue without bound)
            depth = sum(len(q) for q in self._queues.values())
            incoming = len(svcs) + len(deps)
            if svcs and depth + incoming > self.cfg.max_queue:
                if self.cfg.on_full == "park":
                    result = self._park_on_full(stream, tenant, svcs, deps,
                                                now)
                else:
                    _M_SHEDS.inc(len(svcs), reason="depth")
                    self.stats["sheds"] += len(svcs)
                    raise AdmissionRejected(
                        f"queue depth {depth}+{incoming} exceeds "
                        f"{self.cfg.max_queue}", reason="queue-depth",
                        retry_after_s=max(self.cfg.drain_interval_s * 2,
                                          1.0))
            else:
                accepted = self._enqueue(stream, tenant, svcs, deps, now)
                result = {"accepted": accepted,
                          "queued": depth + incoming,
                          "stage": stream.key}
            if quota_overflow:
                ids = self._park_quota(stream, tenant, quota_overflow, now)
                result["accepted"] = list(result["accepted"]) + ids
                result["parked"] = result.get("parked", 0) + len(ids)
                result["quota_parked"] = len(ids)
            self._update_pressure(now)
            self._set_queue_gauges(now)
            return result

    def _enqueue(self, stream: _Stream, tenant: str, svcs: list[Service],
                 deps: list[str], now: float) -> list[str]:
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = deque()
            self._deficit[tenant] = 0.0
            self._rr.append(tenant)
        accepted = []
        for svc in svcs:
            r = AdmissionRequest(
                id=f"adm_{next(self._ids)}", tenant=tenant, kind="arrival",
                name=svc.name, stage_key=stream.key, submitted_at=now,
                seq=next(self._seq), service=svc,
                demand=np.array(svc.resources.as_tuple(), dtype=np.float64))
            q.append(r)
            self.requests[r.id] = r
            accepted.append(r.id)
        for name in deps:
            r = AdmissionRequest(
                id=f"adm_{next(self._ids)}", tenant=tenant,
                kind="departure", name=name, stage_key=stream.key,
                submitted_at=now, seq=next(self._seq))
            q.append(r)
            self.requests[r.id] = r
            accepted.append(r.id)
        return accepted

    def _park_on_full(self, stream: _Stream, tenant: str,
                      svcs: list[Service], deps: list[str],
                      now: float) -> dict:
        """on_full="park": accept but defer the arrivals past the depth
        bound (departures always enqueue — they free capacity)."""
        accepted = self._enqueue(stream, tenant, [], deps, now)
        for svc in svcs:
            r = AdmissionRequest(
                id=f"adm_{next(self._ids)}", tenant=tenant, kind="arrival",
                name=svc.name, stage_key=stream.key, submitted_at=now,
                seq=next(self._seq), service=svc,
                demand=np.array(svc.resources.as_tuple(), dtype=np.float64),
                state="parked")
            self.requests[r.id] = r
            self._parked.append(r)
            self._journal_park(r, "depth")
            accepted.append(r.id)
        n = len(svcs)
        if n:
            _M_PARKED.inc(n)
            self.stats["parked"] += n
        self._update_pressure(now)
        self._set_queue_gauges(now)
        return {"accepted": accepted, "queued": len(svcs) + len(deps),
                "stage": stream.key, "parked": n}

    def _park_quota(self, stream: _Stream, tenant: str,
                    svcs: list[Service], now: float) -> list[str]:
        """Park arrivals a tenant hard cap refused headroom for. Accepted
        (ids returned, journaled) but deferred: they re-queue only once
        the tenant's own live+queued count drops under its cap."""
        ids = []
        for svc in svcs:
            r = AdmissionRequest(
                id=f"adm_{next(self._ids)}", tenant=tenant, kind="arrival",
                name=svc.name, stage_key=stream.key, submitted_at=now,
                seq=next(self._seq), service=svc,
                demand=np.array(svc.resources.as_tuple(), dtype=np.float64),
                state="parked")
            self.requests[r.id] = r
            self._parked.append(r)
            self._journal_park(r, "quota")
            ids.append(r.id)
        n = len(svcs)
        _M_PARKED.inc(n)
        _M_QUOTA_PARKED.inc(n, tenant=tenant)
        self.stats["parked"] += n
        self.stats["quota_parked"] += n
        log.info("admission quota parked %s", kv(
            tenant=tenant, arrivals=n,
            cap=self.cfg.tenant_caps.get(tenant)))
        return ids

    # ------------------------------------------------------------------
    # deficit round robin (weighted tenant fairness)
    # ------------------------------------------------------------------

    def _weight(self, tenant: str) -> float:
        return max(float(self.cfg.tenant_weights.get(tenant, 1.0)), 1e-6)

    def _next_batch(self) -> list[AdmissionRequest]:
        """One DRR scan: each non-empty tenant queue earns quantum*weight
        credit per visit and pops whole requests against it — weighted
        max-min fair service, so a flooding tenant drains at its weight's
        share while light tenants drain completely."""
        batch: list[AdmissionRequest] = []
        if not self._rr:
            return batch
        n = len(self._rr)
        idle_visits = 0
        i = self._rr_idx
        while len(batch) < self.cfg.batch_max and idle_visits < n:
            tenant = self._rr[i % n]
            i += 1
            q = self._queues.get(tenant)
            if not q:
                self._deficit[tenant] = 0.0
                idle_visits += 1
                continue
            self._deficit[tenant] = (self._deficit.get(tenant, 0.0)
                                     + self.cfg.quantum
                                     * self._weight(tenant))
            popped = False
            while (q and self._deficit[tenant] >= 1.0
                   and len(batch) < self.cfg.batch_max):
                batch.append(q.popleft())
                self._deficit[tenant] -= 1.0
                popped = True
            if not q:
                self._deficit[tenant] = 0.0
            idle_visits = 0 if popped else idle_visits + 1
        self._rr_idx = i % n
        for tenant in self._rr:
            _M_DEBT.set(self._deficit.get(tenant, 0.0), tenant=tenant)
        return batch

    # ------------------------------------------------------------------
    # the drain pass
    # ------------------------------------------------------------------

    def has_work(self) -> bool:
        with self._lock:
            # parked arrivals whose capacity epoch moved are pending a
            # retry — real work; parked-with-unchanged-epoch is not (no
            # hot loop on a standing infeasibility)
            return (any(self._queues.values())
                    or (bool(self._parked)
                        and self._park_epoch != self._capacity_epoch))

    def step(self, now: Optional[float] = None) -> dict:
        """One drain pass: retry parked if capacity moved, shed the aged
        tail, pop one DRR batch, fold + micro-solve + commit per stage.
        Returns a summary for callers that narrate (chaos runner, tests)."""
        with self._lock:
            now = self.clock() if now is None else now
            t_drain = time.perf_counter()
            self._retry_parked()
            self._shed_aged(now)
            batch = self._next_batch()
            drain_ms = (time.perf_counter() - t_drain) * 1e3
            summary = {"batch": len(batch), "placed": [], "departed": [],
                       "parked": [], "stages": [], "violations": 0,
                       "solve_ms": 0.0, "shed": 0,
                       "phase_ms": {"drain": drain_ms, "fold": 0.0,
                                    "solve": 0.0, "commit": 0.0}}
            if not batch:
                self._update_pressure(now)
                self._set_queue_gauges(now)
                return summary
            self.stats["batches"] += 1
            _M_BATCH.observe(len(batch))
            _M_BATCH_AGE.observe(now - min(r.submitted_at for r in batch))
            by_stage: dict[str, list[AdmissionRequest]] = {}
            for r in batch:
                by_stage.setdefault(r.stage_key, []).append(r)
            for key in sorted(by_stage):
                stream = self._streams[key]
                self._resync(stream)
                out = self._micro_solve(stream, by_stage[key], now)
                summary["placed"] += out["placed"]
                summary["departed"] += out["departed"]
                summary["parked"] += out["parked"]
                summary["violations"] = max(summary["violations"],
                                            out["violations"])
                summary["solve_ms"] += out["solve_ms"]
                for ph, ms in out.get("phase_ms", {}).items():
                    summary["phase_ms"][ph] += ms
                if out["placed"] or out["departed"]:
                    summary["stages"].append(key)
            for ph, ms in summary["phase_ms"].items():
                _M_PHASE.observe(ms, phase=ph)
                self.last_phase_ms[ph] = round(ms, 3)
            self._update_pressure(now)
            self._set_queue_gauges(now)
            return summary

    def _shed_aged(self, now: float) -> None:
        """Age watermark: a queued request older than shed_age_s is shed
        (terminal, counted) — the queue can never grow a stale tail the
        client believes is still pending. Departures are exempt: they
        only ever FREE capacity and must eventually apply."""
        if self.cfg.shed_age_s <= 0:
            return
        for tenant in sorted(self._queues):
            q = self._queues[tenant]
            keep: deque[AdmissionRequest] = deque()
            for r in q:
                # quota-marked arrivals are exempt: their age is the cap
                # wait the controller itself imposed when it ACCEPTED
                # them — shedding them on requeue would betray that
                if (r.kind == "arrival" and r.park_reason != "quota"
                        and now - r.submitted_at > self.cfg.shed_age_s):
                    r.state, r.done_at = "shed", now
                    _M_SHEDS.inc(reason="age")
                    self.stats["sheds"] += 1
                else:
                    keep.append(r)
            self._queues[tenant] = keep

    def _retry_parked(self) -> None:
        """Parked arrivals re-queue (front, original order) once capacity
        has plausibly moved: a departure committed or a stream resynced
        since the park. Epoch-gated so an infeasible arrival cannot
        hot-loop a solve every drain pass. Quota parks additionally need
        tenant HEADROOM — a capacity epoch bump from some other tenant's
        departure must not tunnel a capped tenant past its cap — and a
        request whose stage is not (yet) re-attached stays parked, so a
        freshly promoted CP cannot KeyError a restored arrival."""
        if not self._parked or self._park_epoch == self._capacity_epoch:
            return
        self._park_epoch = self._capacity_epoch
        parked, self._parked = self._parked, []
        # headroom with the parked set swapped OUT: cap - (live + queued).
        # Every arrival we keep or requeue re-occupies one slot below.
        headroom: dict[str, Optional[int]] = {
            t: self._quota_headroom(t)
            for t in {r.tenant for r in parked}}
        requeue: list[AdmissionRequest] = []
        for r in sorted(parked, key=lambda r: r.seq):
            if r.stage_key not in self._streams:
                self._parked.append(r)
                if headroom.get(r.tenant) is not None:
                    headroom[r.tenant] -= 1
                continue
            h = headroom.get(r.tenant)
            if r.park_reason == "quota" and h is not None and h <= 0:
                self._parked.append(r)
                continue
            if h is not None:
                headroom[r.tenant] = h - 1
            requeue.append(r)
        for r in sorted(requeue, key=lambda r: r.seq, reverse=True):
            r.state = "queued"
            # a quota park KEEPS its marker through the requeue: its wait
            # includes policy-imposed cap time, which must not pollute
            # the fairness/SLO wait surfaces when it finally places
            if r.park_reason != "quota":
                r.park_reason = None
            self._unjournal_park(r)
            q = self._queues.get(r.tenant)
            if q is None:
                q = self._queues[r.tenant] = deque()
                self._deficit[r.tenant] = 0.0
                self._rr.append(r.tenant)
            q.appendleft(r)
        n = len(requeue)
        if n:
            _M_UNPARKED.inc(n)
            self.stats["unparked"] += n

    # ------------------------------------------------------------------
    # folding a batch into the streaming problem
    # ------------------------------------------------------------------

    def _fold(self, stream: _Stream, events: list[AdmissionRequest]):
        """Fold events (submission order) into a CANDIDATE problem built
        from the stream's current pt by dataclasses.replace — the delta
        shape the resident staging recognizes. Returns (pt2, delta,
        row_plan) without mutating the stream; commit applies row_plan."""
        import dataclasses as _dc

        from ..solver.resident import ProblemDelta

        pt = stream.pt
        S, N = pt.S, pt.N
        R = pt.demand.shape[1]
        events = sorted(events, key=lambda r: r.seq)
        free = list(stream.free_rows)
        appended: list[AdmissionRequest] = []
        # (row, request, departed name the row previously carried)
        reuse: list[tuple[int, AdmissionRequest, str]] = []
        tomb_rows: list[tuple[int, str]] = []
        cancelled: list[AdmissionRequest] = []
        placed_in_batch: dict[str, AdmissionRequest] = {}
        for r in events:
            if r.kind == "arrival":
                if free:
                    row = free.pop(0)
                    reuse.append((row, r, pt.service_names[row]))
                else:
                    appended.append(r)
                placed_in_batch[r.name] = r
            else:
                if r.name in placed_in_batch:
                    # departure of an arrival in the SAME batch: both
                    # cancel out before ever touching the problem
                    a = placed_in_batch.pop(r.name)
                    if a in appended:
                        appended.remove(a)
                    else:
                        for j, (row, req, _old) in enumerate(reuse):
                            if req is a:
                                free.insert(0, row)
                                del reuse[j]
                                break
                    cancelled.append(a)
                    cancelled.append(r)
                    continue
                if any(name == r.name for _row, name in tomb_rows):
                    # doubled departure (validation guards this; a race
                    # must still never double-free the row)
                    cancelled.append(r)
                    continue
                row = stream.row_of[r.name]
                tomb_rows.append((row, r.name))
                free.append(row)

        k_app = len(appended)
        S2 = S + k_app
        names = list(pt.service_names)
        if k_app:
            demand = np.vstack([pt.demand,
                                np.zeros((k_app, R), dtype=pt.demand.dtype)])
            eligible = np.vstack([pt.eligible,
                                  np.zeros((k_app, N), dtype=bool)])
            dep_adj = np.zeros((S2, S2), dtype=bool)
            dep_adj[:S, :S] = pt.dep_adj
            dep_depth = np.concatenate(
                [pt.dep_depth, np.zeros(k_app, dtype=pt.dep_depth.dtype)])
            ids = {}
            for f in ("port_ids", "volume_ids", "anti_ids", "coloc_ids"):
                old = getattr(pt, f)
                ids[f] = np.vstack([old, np.full((k_app, old.shape[1]), -1,
                                                 dtype=old.dtype)])
            replica_of = list(pt.replica_of) + [r.name for r in appended]
        else:
            demand = pt.demand.copy()
            eligible = pt.eligible.copy() if reuse else pt.eligible
            dep_adj, dep_depth = pt.dep_adj, pt.dep_depth
            ids = {f: getattr(pt, f) for f in
                   ("port_ids", "volume_ids", "anti_ids", "coloc_ids")}
            replica_of = pt.replica_of

        changed_rows: list[int] = []
        elig_rows: list[int] = []
        node_index = {n: j for j, n in enumerate(pt.node_names)}

        def elig_mask(r: AdmissionRequest) -> np.ndarray:
            if not r.eligible_nodes:
                return np.ones(N, dtype=bool)
            mask = np.zeros(N, dtype=bool)
            for n in r.eligible_nodes:
                j = node_index.get(n)
                if j is not None:
                    mask[j] = True
            return mask

        for row, name in tomb_rows:
            demand[row] = 0.0
            changed_rows.append(row)
        for row, r, _old in reuse:
            demand[row] = r.demand
            eligible[row] = elig_mask(r)
            names[row] = r.name
            changed_rows.append(row)
            elig_rows.append(row)
        for j, r in enumerate(appended):
            row = S + j
            demand[row] = r.demand
            eligible[row] = elig_mask(r)
            names.append(r.name)
            changed_rows.append(row)
            elig_rows.append(row)

        if not changed_rows and not cancelled:
            return None, None, None
        rows = np.asarray(sorted(set(changed_rows)), dtype=np.int32)
        erows = np.asarray(sorted(set(elig_rows)), dtype=np.int32)
        # always carry BOTH scatter planes (possibly empty): one static
        # (has_demand, has_eligible) combination means one merge-kernel
        # executable at steady state (solver/resident._merge_fn statics)
        delta = ProblemDelta(
            demand_rows=(rows, demand[rows]),
            eligible_rows=(erows, eligible[erows]),
            n_real=S2 if k_app else None)
        pt2 = _dc.replace(pt, demand=demand, eligible=eligible,
                          dep_adj=dep_adj, dep_depth=dep_depth,
                          service_names=names, replica_of=replica_of,
                          **ids)
        plan = {"appended": appended, "reuse": reuse,
                "tomb_rows": tomb_rows, "free": free,
                "cancelled": cancelled,
                "events": [r for r in events if r not in cancelled]}
        return pt2, delta, plan

    def _should_compact(self, stream: _Stream, n_new: int) -> bool:
        """Compact (drop tombstone rows, cold-restage once) before a
        growth that would cross the padded shape tier while reclaimable
        rows exist — trading one counted restage for keeping the steady
        state inside one executable."""
        if not stream.free_rows:
            return False
        from ..solver.buckets import bucket_config, bucket_size
        cfg = bucket_config()
        if not cfg.enabled:
            return len(stream.free_rows) * 4 >= stream.pt.S
        cur = bucket_size(stream.pt.S, growth=cfg.growth,
                          minimum=cfg.minimum, align=cfg.align)
        grown = bucket_size(stream.pt.S + n_new, growth=cfg.growth,
                            minimum=cfg.minimum, align=cfg.align)
        return grown != cur

    def _compact(self, stream: _Stream) -> None:
        """Drop the reclaimable tombstone rows (exactly the free list:
        every tombstoned-but-not-reused row) from the streaming problem.
        The next solve cold-stages (new shapes) — amortized and counted."""
        pt = stream.pt
        drop = set(stream.free_rows)
        keep = np.asarray([i for i in range(pt.S) if i not in drop],
                          dtype=np.int64)
        names = [pt.service_names[i] for i in keep]
        stream.pt = _dc_replace(
            pt,
            demand=pt.demand[keep],
            eligible=pt.eligible[keep],
            dep_adj=pt.dep_adj[np.ix_(keep, keep)],
            dep_depth=pt.dep_depth[keep],
            port_ids=pt.port_ids[keep],
            volume_ids=pt.volume_ids[keep],
            anti_ids=pt.anti_ids[keep],
            coloc_ids=pt.coloc_ids[keep],
            service_names=names,
            replica_of=[pt.replica_of[i] for i in keep]
            if pt.replica_of else pt.replica_of)
        stream.row_of = {n: i for i, n in enumerate(names)}
        stream.tombstones = set()
        stream.free_rows = []
        self.stats["compactions"] += 1
        log.info("admission stream compacted %s",
                 kv(stage=stream.key, dropped=len(drop), rows=len(keep)))

    def _micro_solve(self, stream: _Stream, events: list[AdmissionRequest],
                     now: float) -> dict:
        """One bucketed micro-solve: fold the events, solve through the
        resident delta path, commit as ONE reservation. Infeasible:
        departures re-apply alone (they strictly free capacity) and the
        arrivals PARK for retry when capacity moves."""
        out = {"placed": [], "departed": [], "parked": [], "violations": 0,
               "solve_ms": 0.0,
               "phase_ms": {"fold": 0.0, "solve": 0.0, "commit": 0.0}}
        # a departure whose arrival has not landed yet: cancel a PARKED
        # arrival in place, defer one still queued (its arrival sits ahead
        # of it in FIFO order, so the retry resolves next pass)
        batch_arrivals = {r.name for r in events if r.kind == "arrival"}
        kept: list[AdmissionRequest] = []
        for r in sorted(events, key=lambda r: r.seq):
            if (r.kind == "departure" and r.name not in stream.row_of
                    and r.name not in batch_arrivals):
                parked = next(
                    (p for p in self._parked
                     if p.name == r.name and p.stage_key == stream.key),
                    None)
                if parked is not None:
                    self._parked.remove(parked)
                    parked.state, parked.done_at = "cancelled", now
                    self._unjournal_park(parked)
                    r.state, r.done_at = "departed", now
                    out["departed"].append(r.name)
                elif any(q2.name == r.name and q2.kind == "arrival"
                         for q in self._queues.values() for q2 in q):
                    # its arrival is still queued behind it: retry next
                    # pass (FIFO guarantees the arrival pops first)
                    self._queues[r.tenant].appendleft(r)
                else:
                    # target is gone (already departed, shed, or never
                    # existed): the goal state holds — terminal, not a
                    # forever-spinning requeue
                    r.state, r.done_at = "cancelled", now
                continue
            kept.append(r)
        events = kept
        if not events:
            return out
        t_fold = time.perf_counter()
        n_app = sum(1 for r in events if r.kind == "arrival")
        if self._should_compact(stream, max(n_app - len(stream.free_rows),
                                            0)):
            self._compact(stream)
        folded = self._fold(stream, events)
        out["phase_ms"]["fold"] += (time.perf_counter() - t_fold) * 1e3
        pt2, delta, plan = folded
        if plan is None:
            return out
        for r in plan["cancelled"]:
            r.state = "cancelled" if r.kind == "arrival" else "departed"
            r.done_at = now
        if not plan["events"]:
            return out

        t0 = time.perf_counter()
        masked = (stream.tombstones
                  | {name for _row, name in plan["tomb_rows"]})
        placement, rid, pt_used = self.placement.admit_batch(
            stream.key, pt2, delta, tenant=stream.tenant, masked=masked)
        wall_ms = (time.perf_counter() - t0) * 1e3
        out["solve_ms"] = wall_ms
        out["phase_ms"]["solve"] += wall_ms
        # ONE sample per micro-solve: the p50/p99 surface measures the
        # solver tail, not how many stage streams a drain batch fanned to
        self.solve_ms_samples.append(wall_ms)
        slo_observe("admission_solve_ms", wall_ms)
        out["violations"] = placement.violations
        self.stats["solves"] += 1

        if placement.feasible and rid:
            t_commit = time.perf_counter()
            self.placement.commit(rid)
            _M_SOLVES.inc(outcome="committed")
            self._commit_plan(stream, pt_used, plan, now, out)
            out["phase_ms"]["commit"] += \
                (time.perf_counter() - t_commit) * 1e3
            if wall_ms > 0:
                _M_RATE.set(len(out["placed"]) / (wall_ms / 1e3))
            return out

        _M_SOLVES.inc(outcome="infeasible")
        if rid:
            self.placement.release(rid)
        arrivals = [r for r in plan["events"] if r.kind == "arrival"]
        departures = [r for r in plan["events"] if r.kind == "departure"]
        for r in arrivals:
            r.state = "parked"
            self._parked.append(r)
            self._journal_park(r, "capacity")
        if arrivals:
            _M_PARKED.inc(len(arrivals))
            self.stats["parked"] += len(arrivals)
            log.warning("admission parked %s", kv(
                stage=stream.key, arrivals=len(arrivals),
                violations=placement.violations))
        out["parked"] = [r.name for r in arrivals]
        if departures:
            # strictly capacity-freeing — re-fold without the arrivals
            t_fold = time.perf_counter()
            pt3, delta3, plan3 = self._fold(stream, departures)
            out["phase_ms"]["fold"] += (time.perf_counter() - t_fold) * 1e3
            if plan3 is not None and plan3["events"]:
                masked3 = (stream.tombstones
                           | {n for _row, n in plan3["tomb_rows"]})
                t_solve = time.perf_counter()
                placement3, rid3, pt_used3 = self.placement.admit_batch(
                    stream.key, pt3, delta3, tenant=stream.tenant,
                    masked=masked3)
                solve3_ms = (time.perf_counter() - t_solve) * 1e3
                out["phase_ms"]["solve"] += solve3_ms
                self.solve_ms_samples.append(solve3_ms)
                slo_observe("admission_solve_ms", solve3_ms)
                if placement3.feasible and rid3:
                    t_commit = time.perf_counter()
                    self.placement.commit(rid3)
                    _M_SOLVES.inc(outcome="committed")
                    self._commit_plan(stream, pt_used3, plan3, now, out)
                    out["phase_ms"]["commit"] += \
                        (time.perf_counter() - t_commit) * 1e3
                    return out
                if rid3:
                    self.placement.release(rid3)
                # cannot even apply departures: requeue them untouched
                for r in sorted(departures, key=lambda r: r.seq,
                                reverse=True):
                    self._queues[r.tenant].appendleft(r)
        return out

    def _commit_plan(self, stream: _Stream, pt_used, plan: dict,
                     now: float, out: dict) -> None:
        """The micro-solve committed: apply the row plan to the stream
        book and the flow (so redeploys/teardowns see streamed truth),
        mark the requests terminal, record waits."""
        stream.pt = pt_used
        stage = stream.flow.stage(stream.stage_name)
        freed_capacity = False
        for row, name in plan["tomb_rows"]:
            stream.tombstones.add(name)
            del stream.row_of[name]
            stream.streamed.pop(name, None)
            tenant = stream.owner.pop(name, None)
            if name in stream.flow.services:
                del stream.flow.services[name]
            if name in stage.services:
                stage.services.remove(name)
            freed_capacity = True
            if tenant is not None:
                _M_DEPARTED.inc(tenant=tenant)
        stream.free_rows = plan["free"]
        for row, r, old_name in plan["reuse"]:
            # the row was renamed by _fold: its previous (departed)
            # occupant leaves the tombstone mask with it
            stream.tombstones.discard(old_name)
            stream.row_of[r.name] = row
        for j, r in enumerate(plan["appended"]):
            stream.row_of[r.name] = stream.pt.S - len(plan["appended"]) + j
        for r in plan["events"]:
            if r.kind == "arrival":
                r.state, r.done_at = "placed", now
                stream.streamed[r.name] = r.seq
                stream.owner[r.name] = r.tenant
                stream.flow.services[r.name] = r.service
                stage.services.append(r.name)
                _M_ADMITTED.inc(tenant=r.tenant)
                self.stats["admitted"] += 1
                if r.park_reason != "quota":
                    # quota-parked waits are policy (the tenant sat at
                    # its purchased cap), not scheduler service time —
                    # they must not pollute the fairness percentiles or
                    # the admission-wait SLO stream
                    _M_WAIT.observe(now - r.submitted_at)
                    samples = self.wait_samples.setdefault(
                        r.tenant, deque(maxlen=4096))
                    samples.append(now - r.submitted_at)
                    # admission-wait SLO stream: submit → committed
                    # placement on the engine's clock (virtual in chaos)
                    slo_observe("admission_wait_s", now - r.submitted_at)
                out["placed"].append(r.name)
            else:
                r.state, r.done_at = "departed", now
                self.stats["departed"] += 1
                out["departed"].append(r.name)
        if freed_capacity:
            self._capacity_epoch += 1

    # ------------------------------------------------------------------
    # feedback + introspection
    # ------------------------------------------------------------------

    def _queue_ages(self, now: float) -> tuple[int, float]:
        depth, oldest = 0, 0.0
        for q in self._queues.values():
            depth += len(q)
            if q:
                oldest = max(oldest, now - q[0].submitted_at)
        return depth, oldest

    def _update_pressure(self, now: float) -> None:
        depth, oldest = self._queue_ages(now)
        # quota parks are EXCLUDED from pressure: provisioning nodes
        # cannot raise a tenant's purchased cap, so counting them would
        # hold the autoscaler hot (and block idle scale-down) forever
        hard_parked = sum(1 for r in self._parked
                          if r.park_reason != "quota")
        hot = (depth > 0 and oldest >= self.cfg.pressure_age_s) \
            or bool(hard_parked)
        if hot:
            if self._pressure_since is None:
                self._pressure_since = now
        else:
            self._pressure_since = None
        self._pressure_snapshot = {
            "queue_depth": depth,
            "oldest_age_s": round(oldest, 3),
            "parked": len(self._parked),
            "parked_quota": len(self._parked) - hard_parked,
            "sustained": (self._pressure_since is not None
                          and now - self._pressure_since
                          >= self.cfg.pressure_sustain_s),
            "drained": depth == 0 and hard_parked == 0}

    def _set_queue_gauges(self, now: float) -> None:
        depth, oldest = self._queue_ages(now)
        _M_DEPTH.set(depth)
        _M_OLDEST.set(oldest)

    def pressure(self) -> dict:
        """The autoscaler's solver-pressure input (cp/autoscaler.py):
        sustained queue age or infeasible-parked arrivals say 'provision';
        a drained queue says 'normal idle rules apply'. Lock-free read of
        the last submit/step's snapshot — the feedback must not block on
        a drain pass's solver wall time."""
        return dict(self._pressure_snapshot)

    def live_names(self, stage_key: str) -> list[str]:
        """Currently-live streamed services of a stage (the chaos
        admission-converged invariant cross-checks these against the
        committed placement)."""
        with self._lock:
            stream = self._streams.get(stage_key)
            if stream is None:
                return []
            return sorted(stream.streamed)

    def streamed_names(self, tenant: str,
                       stage: Optional[str] = None) -> list[str]:
        """Live streamed services owned by `tenant`, oldest first — what
        a departure generator drains. Names with a departure already
        queued are excluded: draining is idempotent, not cumulative."""
        with self._lock:
            pending = {r.name for q in self._queues.values() for r in q
                       if r.kind == "departure"}
            out = []
            for key, stream in sorted(self._streams.items()):
                if stage is not None and key != stage:
                    continue
                out += [(seq, n) for n, seq in stream.streamed.items()
                        if stream.owner.get(n) == tenant
                        and n not in pending]
            return [n for _seq, n in sorted(out)]

    def queue_census(self) -> dict:
        """Per-tenant (queued, oldest_age_s) plus totals — the cheap
        slice of status() the obs collector deep-samples every tick:
        no percentile math, no stream walk, one short lock hold."""
        with self._lock:
            now = self.clock()
            depth, oldest = self._queue_ages(now)
            tenants = {t: {"queued": len(q),
                           "oldest_age_s": now - q[0].submitted_at}
                       for t, q in self._queues.items() if q}
            return {"queue_depth": depth, "oldest_age_s": oldest,
                    "parked": len(self._parked), "tenants": tenants}

    def status(self) -> dict:
        """The `fleet admit status` / deploy.admit_status payload."""
        with self._lock:
            now = self.clock()
            depth, oldest = self._queue_ages(now)
            tenants = {}
            for tenant in sorted(set(self._rr) | set(self.wait_samples)
                                 | set(self.cfg.tenant_caps)
                                 | {r.tenant for r in self._parked}):
                q = self._queues.get(tenant) or ()
                waits = self.wait_samples.get(tenant) or ()
                cap = self.cfg.tenant_caps.get(tenant)
                tenants[tenant] = {
                    "queued": len(q),
                    "oldest_age_s": round(now - q[0].submitted_at, 3)
                    if q else 0.0,
                    "weight": self._weight(tenant),
                    "deficit": round(self._deficit.get(tenant, 0.0), 2),
                    "wait_p50_s": round(float(np.percentile(
                        list(waits), 50)), 3) if waits else None,
                    "wait_p99_s": round(float(np.percentile(
                        list(waits), 99)), 3) if waits else None,
                    # hard-quota surface (`fleet admit status`): usage is
                    # everything the cap counts — live + queued + parked
                    "live": sum(1 for s in self._streams.values()
                                for t in s.owner.values() if t == tenant),
                    "usage": self._tenant_inflight(tenant),
                    "cap": cap,
                    "parked_quota": sum(
                        1 for r in self._parked
                        if r.tenant == tenant
                        and r.park_reason == "quota"),
                }
            streams = {key: {"rows": s.pt.S,
                             "live_streamed": len(s.streamed),
                             "tombstones": len(s.tombstones),
                             "free_rows": len(s.free_rows)}
                       for key, s in sorted(self._streams.items())}
            return {"enabled": True,
                    "queue_depth": depth,
                    "oldest_age_s": round(oldest, 3),
                    "parked": len(self._parked),
                    "parked_quota": sum(1 for r in self._parked
                                        if r.park_reason == "quota"),
                    "tenants": tenants,
                    "streams": streams,
                    "pressure": {
                        "sustained": (self._pressure_since is not None
                                      and now - self._pressure_since
                                      >= self.cfg.pressure_sustain_s),
                        "since_s": round(now - self._pressure_since, 3)
                        if self._pressure_since is not None else None},
                    "stats": dict(self.stats),
                    # last non-empty drain pass, by phase — attribute a
                    # p99 solve tail without attaching a profiler
                    "solve_phases_ms": dict(self.last_phase_ms),
                    # the micro-solve tail over the sample window: the
                    # number the active-set path (solver/subsolve.py)
                    # exists to keep flat
                    "solve_ms_p50": round(float(np.percentile(
                        list(self.solve_ms_samples), 50)), 2)
                    if self.solve_ms_samples else None,
                    "solve_ms_p99": round(float(np.percentile(
                        list(self.solve_ms_samples), 99)), 2)
                    if self.solve_ms_samples else None,
                    # how the micro-solves were dispatched (the metrics
                    # existed; this is where operators actually look):
                    # localized = active-set mini anneal committed by the
                    # exact gate, fallback_* = the full path ran and why
                    "subsolve": subsolve_outcomes(),
                    "config": {"max_queue": self.cfg.max_queue,
                               "shed_age_s": self.cfg.shed_age_s,
                               "on_full": self.cfg.on_full,
                               "batch_max": self.cfg.batch_max,
                               "quantum": self.cfg.quantum,
                               "weights": dict(self.cfg.tenant_weights),
                               "tenant_caps": dict(self.cfg.tenant_caps)}}

    # ------------------------------------------------------------------
    # background drain loop (production; chaos/bench call step() directly)
    # ------------------------------------------------------------------

    async def run_loop(self) -> None:
        while True:
            try:
                if self.has_work():
                    await asyncio.get_running_loop().run_in_executor(
                        None, self.step)
            except Exception:
                log.exception("admission drain pass failed")
            await asyncio.sleep(self.cfg.drain_interval_s)

    def spawn(self) -> None:
        self._task = asyncio.ensure_future(self.run_loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
