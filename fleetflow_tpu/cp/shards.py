"""Control-plane shard table: consistent-hash partitioning of agents.

One asyncio process terminates every agent session (ROADMAP item 3), and
at 10k agents the flat fan-out paths — registry command delivery, log
routing, failure-detector sweeps — are the throughput ceiling. This
module is the partitioning substrate they all share: a consistent-hash
ring mapping agent slug -> shard id, so each CP worker shard owns a
stable subset of the fleet (its registry partition, its command
pipeline lane, its log-routing lane, its verdict-coalescing bucket).

Consistent hashing (Karger et al., STOC '97) rather than `hash(slug) %
n` for two reasons that matter operationally:

  * stability under resize — changing `FLEET_CP_SHARDS` moves only
    ~1/n of the fleet's agents to new shards, so a resize on a live CP
    invalidates the minimum of shard-local state (pipeline lanes,
    coalesced verdict buckets), not the whole table;
  * determinism across processes — Python's builtin `hash()` is
    randomized per process (PYTHONHASHSEED), which would scatter agents
    differently on every CP restart and make chaos schedules
    unreplayable. The ring hashes with blake2b, stable everywhere.

Rebalancing needs NO new persistent state: the mapping is pure
(slug, shard_count) -> shard, so after a resize the new table is fully
determined by the already-journaled server/lease records — `resize()`
just recounts which live slugs moved and lets the owners (registry,
log router, detector) re-bucket lazily on next touch.

Tuning `FLEET_CP_SHARDS`: docs/guide/17-cp-sharding.md.
"""

from __future__ import annotations

import bisect
import hashlib
import os
from typing import Iterable, Optional

from ..obs import get_logger, kv
from ..obs.metrics import MS_BUCKETS, REGISTRY

log = get_logger("cp.shards")

__all__ = ["ShardTable", "DEFAULT_SHARDS", "shards_from_env"]

# Default worker-shard count. Sized for one CP process: shards are
# asyncio task lanes, not OS threads, so the sweet spot tracks the
# per-shard pipeline depth (see PER_SHARD_CONCURRENCY in
# agent_registry.py), not core count.
DEFAULT_SHARDS = 4

# virtual nodes per shard — enough that the largest shard carries at
# most a few percent more agents than the mean at 10k agents
VNODES = 64

# metric catalog: docs/guide/10-observability.md
_M_SHARD_AGENTS = REGISTRY.gauge(
    "fleet_cp_shard_agents",
    "Agents owned per CP worker shard (consistent-hash partition size)",
    labels=("shard",))
_M_FANOUT_MS = REGISTRY.histogram(
    "fleet_cp_shard_fanout_ms",
    "Per-shard command-batch pipeline wall ms (send_batch lanes)",
    labels=("shard",), buckets=MS_BUCKETS)
_M_REBALANCES = REGISTRY.counter(
    "fleet_cp_shard_rebalances_total",
    "Shard-table resizes (FLEET_CP_SHARDS changes); each moves ~1/n "
    "of the fleet's slugs")
_M_LOG_DROPPED = REGISTRY.counter(
    "fleet_cp_shard_log_dropped_total",
    "Log lines dropped from full subscriber lanes, by publisher shard",
    labels=("shard",))


def shards_from_env(default: int = DEFAULT_SHARDS) -> int:
    """Parse FLEET_CP_SHARDS; bad/absent values fall back to `default`.
    0 or 1 means unsharded (one lane owns everything)."""
    raw = os.environ.get("FLEET_CP_SHARDS", "")
    try:
        n = int(raw)
    except ValueError:
        return default
    return n if n >= 1 else default


def _hash64(key: str) -> int:
    # blake2b is the stdlib's fastest keyed-size hash; 8 bytes is plenty
    # of ring resolution for <=64 shards * 64 vnodes
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")


class ShardTable:
    """Immutable-feeling consistent-hash ring with in-place resize.

    Not thread-locked: mutation (`resize`) happens only from the CP's
    event loop / chaos runner; `shard_of` is a pure read over tuples,
    safe from executor threads mid-resize because the ring is swapped
    atomically (single attribute rebind).
    """

    def __init__(self, shards: Optional[int] = None):
        n = shards if shards is not None else shards_from_env()
        self._ring_keys: tuple[int, ...] = ()
        self._ring_vals: tuple[int, ...] = ()
        self.shards = 0
        self._build(max(1, n))

    def _build(self, n: int) -> None:
        points = []
        for shard in range(n):
            for v in range(VNODES):
                points.append((_hash64(f"shard-{shard}:vn-{v}"), shard))
        points.sort()
        self._ring_keys = tuple(p[0] for p in points)
        self._ring_vals = tuple(p[1] for p in points)
        self.shards = n

    # ------------------------------------------------------------------
    def shard_of(self, slug: str) -> int:
        """slug -> owning shard id (0..shards-1); pure and stable."""
        if self.shards <= 1:
            return 0
        i = bisect.bisect_right(self._ring_keys, _hash64(slug))
        if i == len(self._ring_keys):
            i = 0
        return self._ring_vals[i]

    def partition(self, slugs: Iterable[str]) -> dict[int, list[str]]:
        """Bucket slugs by owning shard (buckets keyed 0..shards-1, all
        present even when empty — callers iterate lanes, not agents)."""
        out: dict[int, list[str]] = {s: [] for s in range(self.shards)}
        for slug in slugs:
            out[self.shard_of(slug)].append(slug)
        return out

    def resize(self, n: int, live_slugs: Iterable[str] = ()) -> int:
        """Rebuild the ring for `n` shards; returns how many of
        `live_slugs` changed owner. No persistent state is touched —
        the live slugs come from the journaled server/lease tables and
        their new owners are recomputed lazily by each subsystem."""
        n = max(1, n)
        if n == self.shards:
            return 0
        slugs = list(live_slugs)
        before = {s: self.shard_of(s) for s in slugs}
        self._build(n)
        moved = sum(1 for s in slugs if self.shard_of(s) != before[s])
        _M_REBALANCES.inc()
        log.info("shard table resized %s", kv(
            shards=n, moved=moved, live=len(slugs)))
        return moved

    # ------------------------------------------------------------------
    # instrumentation hooks (shared by registry / log router / detector)
    # ------------------------------------------------------------------

    def observe_fanout_ms(self, shard: int, ms: float) -> None:
        _M_FANOUT_MS.observe(ms, shard=str(shard))

    def set_shard_agents(self, census: dict[int, int]) -> None:
        for shard in range(self.shards):
            _M_SHARD_AGENTS.set(census.get(shard, 0), shard=str(shard))

    def count_log_drop(self, shard: int) -> None:
        _M_LOG_DROPPED.inc(shard=str(shard))
