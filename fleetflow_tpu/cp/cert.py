"""Mesh CA: private certificate authority for control-plane TLS.

Analog of controlplane cert.rs (MeshCa from club-unison): generate and
persist a private CA (key file 0600), issue a per-boot server certificate
with SANs, and hand agents/CLI the CA public cert for pinning
(TrustAnchors::Custom in the reference; `ssl.SSLContext.load_verify_locations`
here). Client code trusts ONLY this CA — never the system roots — which is
the pinning property the reference relies on (cp_client.rs:105).
"""

from __future__ import annotations

import datetime
import ipaddress
import os
from pathlib import Path
from typing import Optional

import ssl

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

__all__ = ["MeshCa", "ensure_mesh_ca", "server_ssl_context",
           "client_ssl_context"]

CA_CN = "fleetflow-tpu mesh ca"
_ONE_DAY = datetime.timedelta(days=1)


class MeshCa:
    def __init__(self, key, cert: x509.Certificate):
        self.key = key
        self.cert = cert

    # -- persistence --------------------------------------------------------
    @classmethod
    def generate(cls) -> "MeshCa":
        key = ec.generate_private_key(ec.SECP256R1())
        name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, CA_CN)])
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (x509.CertificateBuilder()
                .subject_name(name).issuer_name(name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - _ONE_DAY)
                .not_valid_after(now + datetime.timedelta(days=3650))
                .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                               critical=True)
                .sign(key, hashes.SHA256()))
        return cls(key, cert)

    def save(self, dir_path: str) -> None:
        d = Path(dir_path)
        d.mkdir(parents=True, exist_ok=True)
        key_pem = self.key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption())
        key_file = d / "ca.key"
        key_file.write_bytes(key_pem)
        os.chmod(key_file, 0o600)           # cert.rs: key file 0600
        (d / "ca.pem").write_bytes(
            self.cert.public_bytes(serialization.Encoding.PEM))

    @classmethod
    def load(cls, dir_path: str) -> Optional["MeshCa"]:
        d = Path(dir_path)
        key_file, cert_file = d / "ca.key", d / "ca.pem"
        if not (key_file.exists() and cert_file.exists()):
            return None
        key = serialization.load_pem_private_key(key_file.read_bytes(), None)
        cert = x509.load_pem_x509_certificate(cert_file.read_bytes())
        return cls(key, cert)

    @property
    def ca_pem(self) -> bytes:
        return self.cert.public_bytes(serialization.Encoding.PEM)

    # -- issuance -----------------------------------------------------------
    def issue_server_cert(self, common_name: str,
                          sans: list[str]) -> tuple[bytes, bytes]:
        """Per-boot server cert with SANs (cert.rs issue_server_cert).
        Returns (key_pem, cert_pem)."""
        key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        alt_names: list[x509.GeneralName] = []
        for san in sans:
            try:
                alt_names.append(x509.IPAddress(ipaddress.ip_address(san)))
            except ValueError:
                alt_names.append(x509.DNSName(san))
        cert = (x509.CertificateBuilder()
                .subject_name(x509.Name([
                    x509.NameAttribute(NameOID.COMMON_NAME, common_name)]))
                .issuer_name(self.cert.subject)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - _ONE_DAY)
                .not_valid_after(now + datetime.timedelta(days=90))
                .add_extension(x509.SubjectAlternativeName(alt_names),
                               critical=False)
                .sign(self.key, hashes.SHA256()))
        key_pem = key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption())
        cert_pem = cert.public_bytes(serialization.Encoding.PEM)
        return key_pem, cert_pem


def ensure_mesh_ca(dir_path: str) -> MeshCa:
    """Load-or-generate (cert.rs ensure_mesh_ca:36)."""
    ca = MeshCa.load(dir_path)
    if ca is None:
        ca = MeshCa.generate()
        ca.save(dir_path)
    return ca


def server_ssl_context(ca: MeshCa, common_name: str = "cp",
                       sans: Optional[list[str]] = None,
                       work_dir: Optional[str] = None) -> ssl.SSLContext:
    """TLS context for the CP listener with a freshly issued cert."""
    import tempfile
    key_pem, cert_pem = ca.issue_server_cert(
        common_name, sans or ["localhost", "127.0.0.1", "::1"])
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    d = Path(work_dir) if work_dir else Path(tempfile.mkdtemp(prefix="ffcp-"))
    d.mkdir(parents=True, exist_ok=True)
    key_f, cert_f = d / "server.key", d / "server.pem"
    key_f.write_bytes(key_pem)
    os.chmod(key_f, 0o600)
    cert_f.write_bytes(cert_pem)
    ctx.load_cert_chain(str(cert_f), str(key_f))
    return ctx


def client_ssl_context(ca_pem: bytes) -> ssl.SSLContext:
    """Client context pinned to the mesh CA only (cp_client.rs:105)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_verify_locations(cadata=ca_pem.decode())
    ctx.check_hostname = False          # identity = CA pinning, like the ref
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx
