"""10k-agent control-plane fan-out bench (ISSUE 19).

Simulates a fleet of agents against a REAL AgentRegistry + FailureDetector
(no solver, no sockets): each simulated agent acks a command after a small
wire latency (loop.call_later), so the measured quantity is the CP-side
delivery machinery — task scheduling, correlation futures, shard pipeline
lanes — under a realistic ack delay, not localhost TCP noise.

Three measured legs, each sharded-vs-unsharded:

  * fanout — registry command fan-out to every agent. The unsharded
    baseline is the serial one-await-per-command loop (the reference's
    sequential per-service round-trip, engine.rs:157-167 — the same
    baseline the headline solve leg compares against); the sharded number
    is `send_batch` pipelining PER_SHARD_CONCURRENCY commands per shard
    lane. Reported as p50/p99 wall ms over rounds + sends/s throughput.
  * redeliver — the same fan-out with deploy.execute-shaped payloads (the
    reconverger's redelivery storm after a node death).
  * sweep — FailureDetector sweep cost at N and 10N leases with a FIXED
    expired count: the scan engine (use_heap=False) pays O(agents) per
    sweep, the heap engine O(expired) — the 10N/N cost ratio is the
    sublinearity evidence, and both engines must emit identical verdicts
    on the same expiry schedule.

BENCH_AGENTS_ASSERT=1 turns the acceptance contract into hard failures:
sharded fan-out/redelivery throughput >= 5x serial at 10k agents (2x at
BENCH_SMALL scale, where fixed per-round overhead is a larger slice),
send_batch metric coalescing held (label lookups < items), heap sweep
cost sublinear in fleet size, and verdict parity between sweep engines.

Knobs: BENCH_AGENTS_WIRE_MS (simulated ack latency, default 0.2),
BENCH_AGENTS_ROUNDS (batched rounds, default 5), FLEET_CP_SHARDS.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import sys
import time
from typing import Optional

from .agent_registry import AgentRegistry
from .failure_detector import DEAD, FailureDetector, LeaseConfig
from .shards import ShardTable, shards_from_env

__all__ = ["agents_scenario"]


class _SimAgentConn:
    """A simulated agent session: every command envelope is acked
    `wire_s` later via the registry's normal command_result correlation
    path (resolve_result), so the future plumbing under test is exactly
    production's."""

    def __init__(self, registry: AgentRegistry, wire_s: float):
        self._registry = registry
        self._wire_s = wire_s
        self._closed = False

    async def send_event(self, channel: str, method: str,
                         payload: Optional[dict] = None) -> None:
        rid = (payload or {}).get("request_id")
        if rid is None:
            return
        asyncio.get_running_loop().call_later(
            self._wire_s, self._registry.resolve_result, rid,
            {"result": {"ok": True}})


def _pct(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    ys = sorted(xs)
    i = min(len(ys) - 1, int(round(q / 100.0 * (len(ys) - 1))))
    return ys[i]


async def _fanout_leg(n_agents: int, shards: int, wire_s: float,
                      rounds: int, payload: Optional[dict],
                      serial_sample: int) -> dict:
    registry = AgentRegistry(shard_table=ShardTable(shards))
    slugs = [f"sim-{i:05d}" for i in range(n_agents)]
    for slug in slugs:
        registry.register(slug, _SimAgentConn(registry, wire_s))

    # serial baseline over a sample (throughput is per-item, so a sample
    # measures it; the full serial loop at 10k x wire would dominate the
    # bench's wall time for no extra information)
    sample = slugs[:serial_sample]
    t0 = time.perf_counter()
    for slug in sample:
        await registry.send_command(slug, "bench.ping", payload, timeout=30)
    serial_s = time.perf_counter() - t0
    serial_rate = len(sample) / serial_s

    items = [(slug, "bench.ping", payload) for slug in slugs]
    round_ms: list[float] = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        results = await registry.send_batch(items, timeout=30)
        round_ms.append((time.perf_counter() - t0) * 1e3)
        errs = sum(1 for r in results if isinstance(r, BaseException))
        assert errs == 0, f"{errs} batch sends failed"
    p50 = _pct(round_ms, 50)
    stats = dict(registry.last_batch_stats)
    return {
        "agents": n_agents,
        "shards": shards,
        "serial_sample": len(sample),
        "serial_rate_per_s": round(serial_rate, 1),
        "serial_extrapolated_ms": round(n_agents / serial_rate * 1e3, 1),
        "batch_rounds": rounds,
        "batch_p50_ms": round(p50, 1),
        "batch_p99_ms": round(_pct(round_ms, 99), 1),
        "batch_rate_per_s": round(n_agents / (p50 / 1e3), 1),
        "speedup_vs_serial": round((n_agents / (p50 / 1e3)) / serial_rate,
                                   1),
        "last_batch_stats": stats,
        "round_ms": [round(x, 1) for x in round_ms],
    }


def _sweep_leg(n: int, expired: int) -> dict:
    """Sweep cost scan-vs-heap at `n` and 10*`n` leases, fixed `expired`
    count. The steady-state sweep (nothing due) is the cost that runs
    every reconverge tick — scan pays the full-table walk there, heap
    pays only the pop-nothing check — and the expiry batch pins verdict
    parity between the engines."""
    cfg = LeaseConfig(lease_s=90.0, suspect_grace_s=30.0)
    iters = 10

    def build(n_leases: int, use_heap: bool):
        box = [1000.0]
        det = FailureDetector(cfg, clock=lambda: box[0], use_heap=use_heap)
        for i in range(n_leases):
            det.observe_heartbeat(f"lease-{i:06d}")
        det.sweep()
        return det, box

    def steady_ms(det) -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            det.sweep()
        return (time.perf_counter() - t0) * 1e3 / iters

    out: dict = {"leases": n, "expired": expired, "engines": {}}
    verdicts: dict[str, list[str]] = {}
    for use_heap in (False, True):
        name = "heap" if use_heap else "scan"
        det, box = build(n, use_heap)
        at_n = steady_ms(det)
        # expire a fixed batch: disconnect -> grace elapses -> DEAD
        for i in range(expired):
            det.observe_disconnect(f"lease-{i:06d}")
        box[0] += cfg.suspect_grace_s + 1
        t0 = time.perf_counter()
        evs = det.sweep()
        expiry_ms = (time.perf_counter() - t0) * 1e3
        verdicts[name] = sorted(e.slug for e in evs if e.state == DEAD)
        det10, _ = build(10 * n, use_heap)
        at_10n = steady_ms(det10)
        out["engines"][name] = {
            "steady_ms_at_n": round(at_n, 3),
            "steady_ms_at_10n": round(at_10n, 3),
            "scale_10n_over_n": round(at_10n / max(at_n, 1e-6), 2),
            "expiry_batch_ms": round(expiry_ms, 3),
            "expiry_verdicts": len(verdicts[name]),
        }
    out["verdict_parity"] = verdicts["scan"] == verdicts["heap"]
    return out


async def _run(small: bool) -> dict:
    n_agents = 1000 if small else 10000
    shards = shards_from_env()
    wire_s = float(os.environ.get("BENCH_AGENTS_WIRE_MS", "0.2")) / 1e3
    rounds = int(os.environ.get("BENCH_AGENTS_ROUNDS", "5"))
    serial_sample = min(n_agents, 1000 if small else 2000)
    deploy_payload = {
        "request": {"fleet": "bench", "stage": "prod", "services": 3,
                    "idempotency_key": "bench-redeliver"},
        "assignment": {"svc-a": "n1", "svc-b": "n2", "svc-c": "n3"},
    }
    fanout = await _fanout_leg(n_agents, shards, wire_s, rounds,
                               None, serial_sample)
    redeliver = await _fanout_leg(n_agents, shards, wire_s, rounds,
                                  deploy_payload, serial_sample)
    return {"agents": n_agents, "shards": shards,
            "wire_ms": wire_s * 1e3,
            "fanout": fanout, "redeliver": redeliver}


def agents_scenario(small: bool) -> dict:
    """Entry point for bench.py's `agents` leg (and the CI smoke step)."""
    # the expiry batch transitions log at info/warning; a bench leg must
    # not spray hundreds of lease lines to stderr
    lease_log = logging.getLogger("fleetflow.cp.lease")
    prev_level = lease_log.level
    lease_log.setLevel(logging.ERROR)
    try:
        out = asyncio.run(_run(small))
        out["sweep"] = _sweep_leg(n=1000 if small else 10000,
                                  expired=50)
    finally:
        lease_log.setLevel(prev_level)
    if os.environ.get("BENCH_AGENTS_ASSERT", "").lower() in \
            ("1", "true", "on", "yes"):
        _assert_agents(out, small)
    return out


def _assert_agents(out: dict, small: bool) -> None:
    """BENCH_AGENTS_ASSERT=1: the ISSUE 19 acceptance contract."""
    need = 2.0 if small else 5.0
    breaches = []
    for leg in ("fanout", "redeliver"):
        r = out[leg]
        if r["speedup_vs_serial"] < need:
            breaches.append(
                f"{leg}: sharded batch {r['batch_rate_per_s']:.0f}/s is "
                f"only {r['speedup_vs_serial']:.1f}x the serial baseline "
                f"{r['serial_rate_per_s']:.0f}/s (need >= {need:.0f}x)")
        stats = r["last_batch_stats"]
        if not (0 < stats["label_lookups"] < stats["items"]):
            breaches.append(
                f"{leg}: per-command metric lookups not coalesced "
                f"({stats['label_lookups']} lookups for "
                f"{stats['items']} items)")
        if stats["epoch_lookups"] > 1:
            breaches.append(f"{leg}: fencing epoch resolved "
                            f"{stats['epoch_lookups']}x per batch")
    sweep = out["sweep"]
    heap = sweep["engines"]["heap"]
    scan = sweep["engines"]["scan"]
    # sublinear: 10x the fleet must NOT cost ~10x the sweep. Slack for
    # timer noise on the sub-ms heap sweeps.
    if heap["steady_ms_at_10n"] > 3 * heap["steady_ms_at_n"] + 0.5:
        breaches.append(
            f"heap sweep not sublinear: {heap['steady_ms_at_n']:.3f} ms "
            f"at n -> {heap['steady_ms_at_10n']:.3f} ms at 10n")
    if heap["steady_ms_at_10n"] > scan["steady_ms_at_10n"]:
        breaches.append(
            f"heap sweep ({heap['steady_ms_at_10n']:.3f} ms) no cheaper "
            f"than scan ({scan['steady_ms_at_10n']:.3f} ms) at 10n")
    if not sweep["verdict_parity"]:
        breaches.append("scan and heap sweeps emitted different verdict "
                        "sets on the same expiry schedule")
    if heap["expiry_verdicts"] != sweep["expired"]:
        breaches.append(
            f"heap sweep emitted {heap['expiry_verdicts']} verdicts for "
            f"{sweep['expired']} expired leases")
    if breaches:
        print(json.dumps({"agents_assert": "FAIL", "breaches": breaches}),
              file=sys.stderr, flush=True)
        sys.exit(1)
