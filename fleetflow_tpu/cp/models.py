"""Control-plane records.

Analog of fleetflow-controlplane model.rs (SURVEY.md §2.4): tenants, users,
projects, stages, services, servers (labels/capacity/allocation/scheduling
state), worker pools, deployments, alerts, observed containers, volumes +
snapshots, build jobs, cost entries, DNS records. Placement policy types are
shared with the config layer (core.model), since this build surfaces them in
stage config too.

Records serialize with dataclasses.asdict-style plain dicts via `to_dict`/
`from_dict` so they ride the wire protocol and the store's JSON snapshots.
"""

from __future__ import annotations

import enum
import time
import uuid
from dataclasses import asdict, dataclass, field, fields
from typing import Optional

from ..core.model import PlacementPolicy, ResourceSpec  # noqa: F401  (re-export)

__all__ = [
    "now_ts", "new_id", "Record", "Tenant", "TenantRole", "TenantUser",
    "Project", "StageRecord", "ServiceRecord", "SchedulingState",
    "DesiredState", "ServerLabelsRec", "ServerCapacity", "ServerAllocated",
    "Server", "WorkerPool", "DeploymentStatus", "Deployment", "AlertKind",
    "Alert", "ObservedContainer", "VolumeRecord", "VolumeSnapshot",
    "BuildStatus", "BuildJob", "CostEntry", "DnsRecord", "ParkedWork",
    "PlacementRecord",
]


def now_ts() -> float:
    return time.time()


def new_id(prefix: str) -> str:
    return f"{prefix}_{uuid.uuid4().hex[:12]}"


@dataclass
class Record:
    """Base: id + timestamps; subclasses add their fields. Timestamps
    are assigned by the Store on create/update (from its injectable
    clock — the chaos harness runs stores on virtual time); a caller
    that pre-sets created_at explicitly keeps it."""
    id: str = ""
    created_at: float = 0.0
    updated_at: float = 0.0

    def to_dict(self) -> dict:
        d = asdict(self)
        for k, v in list(d.items()):
            if isinstance(v, enum.Enum):
                d[k] = v.value
        return d

    @classmethod
    def from_dict(cls, d: dict):
        known = {f.name: f for f in fields(cls)}
        kwargs = {}
        for k, v in d.items():
            if k not in known:
                continue
            t = known[k].type
            # enum-typed fields round-trip from their value strings
            kwargs[k] = v
        obj = cls(**kwargs)
        obj._coerce()
        return obj

    def _coerce(self) -> None:
        pass


# --------------------------------------------------------------------------
# Tenancy (model.rs:18,111,143)
# --------------------------------------------------------------------------

@dataclass
class Tenant(Record):
    name: str = ""
    display_name: str = ""
    secrets: dict[str, str] = field(default_factory=dict)  # name -> ciphertext

    def public_dict(self) -> dict:
        """API/listing payload: to_dict minus the secrets map. Without a
        master key the stored values are plaintext, and even ciphertext
        must not be reachable under a read grant (the same invariant that
        keeps secret.get write-gated). Persistence keeps to_dict."""
        d = self.to_dict()
        d.pop("secrets", None)
        return d


class TenantRole(str, enum.Enum):
    OWNER = "owner"
    ADMIN = "admin"
    MEMBER = "member"
    VIEWER = "viewer"


@dataclass
class TenantUser(Record):
    tenant: str = ""
    email: str = ""
    role: str = TenantRole.MEMBER.value

    def can_write(self) -> bool:
        return self.role in (TenantRole.OWNER.value, TenantRole.ADMIN.value,
                             TenantRole.MEMBER.value)

    def can_admin(self) -> bool:
        return self.role in (TenantRole.OWNER.value, TenantRole.ADMIN.value)


# --------------------------------------------------------------------------
# Project / stage / service (model.rs:215,240,331)
# --------------------------------------------------------------------------

@dataclass
class Project(Record):
    tenant: str = ""
    name: str = ""
    description: str = ""


@dataclass
class StageRecord(Record):
    project: str = ""               # project id
    name: str = ""
    backend: str = "docker"
    servers: list[str] = field(default_factory=list)
    placement: Optional[dict] = None   # serialized PlacementPolicy
    adopted: bool = False              # stage adoption flow (db.rs:480)


@dataclass
class ServiceRecord(Record):
    stage: str = ""                 # stage id
    name: str = ""
    image: str = ""
    status: str = "unknown"
    desired_replicas: int = 1


# --------------------------------------------------------------------------
# Servers / pools (model.rs:395-563)
# --------------------------------------------------------------------------

class SchedulingState(str, enum.Enum):
    """model.rs:435-442."""
    SCHEDULABLE = "schedulable"
    CORDONED = "cordoned"
    DRAINING = "draining"


class DesiredState(str, enum.Enum):
    """model.rs:446."""
    ACTIVE = "active"
    STOPPED = "stopped"
    TERMINATED = "terminated"


@dataclass
class ServerLabelsRec:
    """model.rs:400."""
    tier: Optional[str] = None
    region: Optional[str] = None
    clazz: Optional[str] = None
    arch: Optional[str] = None
    extra: dict[str, str] = field(default_factory=dict)


@dataclass
class ServerCapacity:
    """model.rs:415 — cpu cores, memory MiB, disk MiB."""
    cpu: float = 2.0
    memory: float = 4096.0
    disk: float = 40960.0


@dataclass
class ServerAllocated:
    """Two-phase commit/release of reserved resources (model.rs:421-427):
    `reserved` holds in-flight placements until the deploy confirms, then
    moves into `committed`. The reservation journal in placement.py is the
    authoritative racing-re-solve guard (SURVEY.md hard part (c))."""
    cpu: float = 0.0
    memory: float = 0.0
    disk: float = 0.0
    reserved_cpu: float = 0.0
    reserved_memory: float = 0.0
    reserved_disk: float = 0.0


@dataclass
class Server(Record):
    tenant: str = ""
    slug: str = ""
    hostname: str = ""
    provider: Optional[str] = None
    status: str = "unknown"         # online|offline|unknown
    agent_version: str = ""
    last_heartbeat: float = 0.0
    labels: ServerLabelsRec = field(default_factory=ServerLabelsRec)
    capacity: ServerCapacity = field(default_factory=ServerCapacity)
    allocated: ServerAllocated = field(default_factory=ServerAllocated)
    scheduling_state: str = SchedulingState.SCHEDULABLE.value
    desired_state: str = DesiredState.ACTIVE.value
    pool: Optional[str] = None

    def to_dict(self) -> dict:
        d = super().to_dict()
        # wire parity with the reference model.rs ("class", a Rust keyword
        # there and a Python keyword here — stored as clazz on both sides)
        lbl = d.get("labels") or {}
        if "clazz" in lbl:
            lbl["class"] = lbl.pop("clazz")
        return d

    def _coerce(self) -> None:
        if isinstance(self.labels, dict):
            if "class" in self.labels:
                self.labels["clazz"] = self.labels.pop("class")
            self.labels = ServerLabelsRec(**self.labels)
        if isinstance(self.capacity, dict):
            self.capacity = ServerCapacity(**self.capacity)
        if isinstance(self.allocated, dict):
            self.allocated = ServerAllocated(**self.allocated)

    @property
    def schedulable(self) -> bool:
        return (self.scheduling_state == SchedulingState.SCHEDULABLE.value
                and self.status == "online")


@dataclass
class WorkerPool(Record):
    """model.rs:552-563."""
    tenant: str = ""
    name: str = ""
    required_labels: dict[str, str] = field(default_factory=dict)
    preferred_labels: dict[str, str] = field(default_factory=dict)
    min_servers: int = 0
    max_servers: int = 0


@dataclass
class ParkedWork(Record):
    """Self-healing backlog entry (cp/reconverge.py): a stage the
    reconverger could not converge yet. `parked=True` means blocked on
    capacity (infeasible re-solve, exhausted retries) and retried on the
    next node-online verdict; `parked=False` is in-flight redelivery work
    persisted so a CP restart resumes it instead of forgetting it."""
    stage_key: str = ""              # "{project}/{stage}"
    reason: str = ""                 # infeasible|retries-exhausted|...
    parked: bool = True
    attempt: int = 0
    detail: str = ""


@dataclass
class ParkedArrival(Record):
    """A parked ADMISSION arrival (cp/admission.py): accepted by submit()
    but deferred — an infeasible micro-solve, the park-on-full depth
    policy, or a per-tenant hard quota cap. Journaled so accepted-but-
    deferred work survives a CP failover: the promoted primary re-parks
    these from the replicated store instead of silently forgetting work
    the client was told was accepted. Distinct from ParkedWork, which is
    the reconverger's per-STAGE backlog; this is per-REQUEST admission
    state. `spec` is the make_arrival wire dict the service rebuilds
    from; `seq` preserves submission order across the restore."""
    tenant: str = ""
    name: str = ""                   # streamed service name
    stage_key: str = ""              # "{flow}/{stage}"
    submitted_at: float = 0.0        # admission-clock submit time
    seq: int = 0                     # controller submission sequence
    reason: str = "capacity"         # capacity | depth | quota
    spec: dict = field(default_factory=dict)
    eligible_nodes: list = field(default_factory=list)


@dataclass
class PlacementRecord(Record):
    """A stage's COMMITTED placement (cp/placement.py): the assignment the
    fleet actually runs and the per-node demand it books. Persisted so a
    restarted or promoted CP rebuilds its capacity ledger from the store
    instead of double-counting the next commit — the in-memory `_committed`
    map alone dies with the process, but the `servers.allocated` numbers it
    explains do not."""
    stage_key: str = ""                              # "{project}/{stage}"
    assignment: dict[str, str] = field(default_factory=dict)  # row -> slug
    # slug -> [cpu, memory, disk] booked by this placement
    demand_by_node: dict[str, list[float]] = field(default_factory=dict)


# --------------------------------------------------------------------------
# Deployments (model.rs:639)
# --------------------------------------------------------------------------

class DeploymentStatus(str, enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"


@dataclass
class Deployment(Record):
    tenant: str = ""
    project: str = ""
    stage: str = ""
    status: str = DeploymentStatus.PENDING.value
    services: list[str] = field(default_factory=list)
    server: Optional[str] = None
    log: str = ""
    error: str = ""
    placement: Optional[dict] = None   # assignment snapshot
    # the serialized DeployRequest that produced this deployment, kept so
    # redeploy (web.rs api_stage_redeploy analog) can re-execute without
    # access to the project's config tree
    request: Optional[dict] = None
    finished_at: float = 0.0

    def public_dict(self) -> dict:
        """API/listing payload: to_dict minus the stored request — the
        whole flow config would otherwise ride along in every 50-entry
        history response. Persistence keeps to_dict (the request must
        survive restarts for redeploy)."""
        d = self.to_dict()
        d.pop("request", None)
        return d


# --------------------------------------------------------------------------
# Alerts / observation (model.rs:168,373)
# --------------------------------------------------------------------------

class AlertKind(str, enum.Enum):
    RESTART_LOOP = "restart_loop"
    UNEXPECTED_STOP = "unexpected_stop"
    UNHEALTHY = "unhealthy"
    NODE_OFFLINE = "node_offline"


@dataclass
class Alert(Record):
    tenant: str = ""
    server: str = ""
    container: str = ""
    kind: str = ""
    message: str = ""
    active: bool = True
    resolved_at: float = 0.0


@dataclass
class ObservedContainer(Record):
    """Desired-vs-observed reconciliation input (model.rs:373)."""
    server: str = ""
    name: str = ""
    image: str = ""
    state: str = ""
    health: Optional[str] = None
    restart_count: int = 0
    project: Optional[str] = None   # fleetflow label attribution
    stage: Optional[str] = None
    service: Optional[str] = None
    runtime: str = "docker"         # docker | podman | podman-rootless


# --------------------------------------------------------------------------
# Volumes (model.rs:743,793)
# --------------------------------------------------------------------------

@dataclass
class VolumeRecord(Record):
    tenant: str = ""
    server: str = ""
    name: str = ""
    driver: str = "local"
    size_mb: float = 0.0
    adopted: bool = False


@dataclass
class VolumeSnapshot(Record):
    volume: str = ""
    label: str = ""
    size_mb: float = 0.0


# --------------------------------------------------------------------------
# Builds (model.rs:881)
# --------------------------------------------------------------------------

class BuildStatus(str, enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    CANCELLED = "cancelled"


@dataclass
class BuildJob(Record):
    tenant: str = ""
    repo: str = ""
    ref: str = "main"
    dockerfile: Optional[str] = None
    context: str = "."
    image_tag: str = ""
    push: bool = False
    status: str = BuildStatus.QUEUED.value
    worker: Optional[str] = None
    log: str = ""
    error: str = ""
    finished_at: float = 0.0


# --------------------------------------------------------------------------
# Cost / DNS (model.rs:579,611)
# --------------------------------------------------------------------------

@dataclass
class CostEntry(Record):
    tenant: str = ""
    server: str = ""
    provider: str = ""
    month: str = ""                 # "2026-07"
    amount: float = 0.0
    currency: str = "USD"


@dataclass
class DnsRecord(Record):
    tenant: str = ""
    zone: str = ""
    name: str = ""
    type: str = "A"
    content: str = ""
    ttl: int = 300
    proxied: bool = False
    synced: bool = False
