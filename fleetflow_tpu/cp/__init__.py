"""Control plane (L3): the distributed brain.

Analog of fleetflow-controlplane (SURVEY.md §2.4): a store, channel-based
wire protocol, agent registry with request-id correlation, log router,
auth, mesh CA, secret crypto, and 13 channel handlers — plus the piece the
reference doesn't have: a placement service that runs the TPU solver and a
streaming re-solver that reacts to node churn (BASELINE config 5).

Transport: the reference rides club-unison (QUIC + mTLS with a private
MeshCa). Here the control RPC is asyncio TCP with length-prefixed JSON
frames, optionally wrapped in TLS from the same private-CA scheme
(cp/cert.py); the data plane (the solve itself) is JAX collectives on the
device mesh, not host RPC.
"""

from .server import AppState, CpServerHandle, ServerConfig, start
from .store import Store

__all__ = ["start", "AppState", "CpServerHandle", "ServerConfig", "Store"]
