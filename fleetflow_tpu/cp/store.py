"""Control-plane store.

Analog of the reference's SurrealDB data layer (controlplane db.rs, 3,421
LoC of async CRUD over ~14 tables). The reference runs embedded `kv-mem`
for tests and RocksDB-backed SurrealDB in production (db.rs:41,76); here the
store keeps the same test-vs-durable split with no external database
process: in-memory tables, plus — when a path is given — an append-only
JSON-lines journal with periodic compaction into a snapshot file (the
LSM-ish shape RocksDB gives the reference).

Durability model (VERDICT r2 item 3: mutations must not rewrite the whole
database): every create/update/delete appends ONE journal line
(`{"op": "put"|"del", "t": table, ...}`), O(record) not O(database);
when the journal passes `journal_max_bytes` or `journal_max_entries` the
store compacts: full snapshot via tmp+rename, then journal truncate.
Recovery loads the snapshot and replays the journal; replaying a journal
that was already folded into the snapshot (crash between snapshot rename
and truncate) is idempotent — puts overwrite with identical rows, deletes
of absent rows are no-ops. A torn final line (crash mid-append) is
detected and dropped. Writes are flushed to the OS on every append;
`fsync=True` (or `FLEET_STORE_FSYNC=1`, honored by every construction
site) additionally fsyncs each append and crash-orders compaction — the
snapshot bytes and directory entry reach disk before the journal is
truncated — matching the reference's RocksDB WAL guarantee at a
throughput cost.


Thread-safe: one RLock guards all tables (handler tasks run on one asyncio
loop, but the REST surface and background checkers may call from executor
threads).
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import Callable, Optional, TypeVar

from .models import (Alert, BuildJob, CostEntry, Deployment, DeploymentStatus,
                     DnsRecord, ObservedContainer, ParkedWork, Project, Record,
                     Server, ServiceRecord, StageRecord, Tenant, TenantUser,
                     VolumeRecord, VolumeSnapshot, WorkerPool, new_id, now_ts)
from ..obs.metrics import REGISTRY

__all__ = ["Store"]

# metric catalog: docs/guide/10-observability.md. Counted via the store's
# own mutation-observer hook so the change-data-capture path and the
# metrics path can never disagree about what a mutation is.
_M_STORE_OPS = REGISTRY.counter(
    "fleet_store_ops_total", "Store mutations by table and op (put/del)",
    labels=("table", "op"))
_M_HEARTBEATS = REGISTRY.counter(
    "fleet_heartbeats_total", "Agent heartbeats recorded")
_M_COMPACTIONS = REGISTRY.counter(
    "fleet_store_compactions_total", "Journal compactions (snapshot writes)")


def _count_op(op: str, table: str, _payload: object) -> None:
    _M_STORE_OPS.inc(table=table, op=op)

R = TypeVar("R", bound=Record)

_TABLES: dict[str, type] = {
    "tenants": Tenant, "tenant_users": TenantUser, "projects": Project,
    "stages": StageRecord, "services": ServiceRecord, "servers": Server,
    "worker_pools": WorkerPool, "deployments": Deployment, "alerts": Alert,
    "observed_containers": ObservedContainer, "volumes": VolumeRecord,
    "volume_snapshots": VolumeSnapshot, "build_jobs": BuildJob,
    "cost_entries": CostEntry, "dns_records": DnsRecord,
    "parked_work": ParkedWork,
}


class Store:
    def __init__(self, path: Optional[str] = None, *,
                 journal_max_bytes: int = 4 * 1024 * 1024,
                 journal_max_entries: int = 20_000,
                 fsync: Optional[bool] = None,
                 clock: Callable[[], float] = now_ts):
        self._lock = threading.RLock()
        # record timestamps come from this clock (create/update/heartbeat
        # /finish/resolve stamps): wall time in production, the virtual
        # clock in the chaos harness — so record ages are deterministic
        # under replay instead of depending on real elapsed time
        self._clock = clock
        self._tables: dict[str, dict[str, Record]] = {t: {} for t in _TABLES}
        self._path = Path(path) if path else None
        self._journal_path = (self._path.with_name(self._path.name + ".journal")
                              if self._path else None)
        self._journal_max_bytes = journal_max_bytes
        self._journal_max_entries = journal_max_entries
        if fsync is None:   # FLEET_STORE_FSYNC=1 opts any deployment in
            fsync = os.environ.get("FLEET_STORE_FSYNC", "").strip().lower() \
                in ("1", "true", "yes", "on")
        self._fsync = fsync
        self._journal_file = None          # lazily-opened append handle
        self._journal_bytes = 0
        self._journal_entries = 0
        self._compactions = 0
        self._batch_depth = 0
        self._batch_buf: list[str] = []
        # mutation observers: fn(op, table, rec_or_id) called under the
        # store lock AFTER each create/update/delete. This is the
        # change-data-capture hook the chaos harness builds its causal
        # event log on; it doubles as a general extension point (metrics,
        # cache invalidation). Observers must be fast and must not
        # re-enter the store's mutators.
        self._observers: list[Callable[[str, str, object], None]] = [_count_op]
        if self._path and self._path.exists():
            self._load()
        if self._journal_path and self._journal_path.exists():
            self._replay_journal()
            # fold the surviving journal into a fresh snapshot so repeated
            # crash/restart cycles cannot grow an unbounded replay tail
            self.flush()

    @classmethod
    def connect_memory(cls) -> "Store":
        """Test constructor (db.rs connect_memory:76)."""
        return cls(path=None)

    def subscribe(self, fn: Callable[[str, str, object], None]) -> None:
        """Register a mutation observer: fn("put"|"del", table, rec|id)."""
        with self._lock:
            self._observers.append(fn)

    def unsubscribe(self, fn: Callable[[str, str, object], None]) -> None:
        with self._lock:
            if fn in self._observers:
                self._observers.remove(fn)

    def _notify(self, op: str, table: str, payload: object) -> None:
        for fn in self._observers:
            fn(op, table, payload)

    # ------------------------------------------------------------------
    # generic CRUD
    # ------------------------------------------------------------------

    def create(self, table: str, rec: R) -> R:
        with self._lock:
            if not rec.id:
                rec.id = new_id(table.rstrip("s"))
            rec.created_at = rec.created_at or self._clock()
            rec.updated_at = self._clock()
            self._tables[table][rec.id] = rec
            self._log_put(table, rec)
            self._notify("put", table, rec)
            return rec

    def get(self, table: str, rec_id: str) -> Optional[Record]:
        with self._lock:
            return self._tables[table].get(rec_id)

    def update(self, table: str, rec_id: str, **changes) -> Optional[Record]:
        with self._lock:
            rec = self._tables[table].get(rec_id)
            if rec is None:
                return None
            for k, v in changes.items():
                setattr(rec, k, v)
            rec.updated_at = self._clock()
            self._log_put(table, rec)
            self._notify("put", table, rec)
            return rec

    def delete(self, table: str, rec_id: str) -> bool:
        with self._lock:
            gone = self._tables[table].pop(rec_id, None) is not None
            if gone:
                self._log_del(table, rec_id)
                self._notify("del", table, rec_id)
            return gone

    def list(self, table: str,
             where: Optional[Callable[[Record], bool]] = None) -> list[Record]:
        with self._lock:
            rows = list(self._tables[table].values())
        if where is not None:
            rows = [r for r in rows if where(r)]
        return sorted(rows, key=lambda r: r.created_at)

    def find_one(self, table: str,
                 where: Callable[[Record], bool]) -> Optional[Record]:
        # hot path (server_by_slug on every heartbeat/alert/inventory):
        # early-exit scan, no copy/sort like list()
        with self._lock:
            for r in self._tables[table].values():
                if where(r):
                    return r
        return None

    # ------------------------------------------------------------------
    # domain queries (the named fns of db.rs)
    # ------------------------------------------------------------------

    # tenants ----------------------------------------------------------
    def tenant_by_name(self, name: str) -> Optional[Tenant]:
        return self.find_one("tenants", lambda t: t.name == name)

    def ensure_tenant(self, name: str) -> Tenant:
        """get-or-create, the way deploy.execute resolves tenants
        (handlers/deploy.rs tenant resolve)."""
        t = self.tenant_by_name(name)
        if t is None:
            t = self.create("tenants", Tenant(name=name, display_name=name))
        return t

    def tenant_users(self, tenant: str) -> list[TenantUser]:
        return self.list("tenant_users", lambda u: u.tenant == tenant)

    def user_by_email(self, tenant: str, email: str) -> Optional[TenantUser]:
        return self.find_one(
            "tenant_users", lambda u: u.tenant == tenant and u.email == email)

    # projects / stages / services ------------------------------------
    def project_by_name(self, tenant: str, name: str) -> Optional[Project]:
        return self.find_one(
            "projects", lambda p: p.tenant == tenant and p.name == name)

    def ensure_project(self, tenant: str, name: str) -> Project:
        p = self.project_by_name(tenant, name)
        if p is None:
            p = self.create("projects", Project(tenant=tenant, name=name))
        return p

    def stages_of(self, project: str) -> list[StageRecord]:
        return self.list("stages", lambda s: s.project == project)

    def stage_by_name(self, project: str, name: str) -> Optional[StageRecord]:
        return self.find_one(
            "stages", lambda s: s.project == project and s.name == name)

    def ensure_stage(self, project: str, name: str, **attrs) -> StageRecord:
        s = self.stage_by_name(project, name)
        if s is None:
            s = self.create("stages",
                            StageRecord(project=project, name=name, **attrs))
        elif attrs:
            self.update("stages", s.id, **attrs)
        return s

    def adopt_stage(self, stage_id: str) -> Optional[StageRecord]:
        """Stage adoption (db.rs:480): claim an observed stage as managed."""
        return self.update("stages", stage_id, adopted=True)

    def services_of(self, stage: str) -> list[ServiceRecord]:
        return self.list("services", lambda s: s.stage == stage)

    def upsert_service(self, stage: str, name: str, **attrs) -> ServiceRecord:
        s = self.find_one("services",
                          lambda r: r.stage == stage and r.name == name)
        if s is None:
            return self.create("services",
                               ServiceRecord(stage=stage, name=name, **attrs))
        return self.update("services", s.id, **attrs)  # type: ignore[return-value]

    # servers ----------------------------------------------------------
    def server_by_slug(self, slug: str) -> Optional[Server]:
        return self.find_one("servers", lambda s: s.slug == slug)

    def register_server(self, slug: str, tenant: str = "default",
                        **attrs) -> Server:
        """Agent registration upsert (handlers/server.rs register)."""
        s = self.server_by_slug(slug)
        if s is None:
            return self.create("servers",
                               Server(slug=slug, tenant=tenant, **attrs))
        return self.update("servers", s.id, **attrs)  # type: ignore[return-value]

    def heartbeat(self, slug: str, version: str = "") -> Optional[Server]:
        """db.rs heartbeat update (handlers/agent.rs:84-91)."""
        s = self.server_by_slug(slug)
        if s is None:
            return None
        _M_HEARTBEATS.inc()
        changes: dict = {"last_heartbeat": self._clock(), "status": "online"}
        if version:
            changes["agent_version"] = version
        return self.update("servers", s.id, **changes)

    def bulk_server_status(self, statuses: dict[str, str]) -> int:
        """Health-checker bulk update (db.rs:779; fleetflowd health.rs:34-69)."""
        n = 0
        for slug, status in statuses.items():
            s = self.server_by_slug(slug)
            if s is not None and s.status != status:
                self.update("servers", s.id, status=status)
                n += 1
        return n

    def schedulable_servers(self, tenant: Optional[str] = None) -> list[Server]:
        return self.list("servers", lambda s: s.schedulable and
                         (tenant is None or s.tenant == tenant))

    # deployments ------------------------------------------------------
    def deployment_history(self, stage: Optional[str] = None,
                           limit: int = 50) -> list[Deployment]:
        rows = self.list("deployments",
                         (lambda d: d.stage == stage) if stage else None)
        return list(reversed(rows))[:limit]

    def finish_deployment(self, dep_id: str, status: DeploymentStatus,
                          log: str = "", error: str = "") -> Optional[Deployment]:
        return self.update("deployments", dep_id, status=status.value,
                           log=log, error=error, finished_at=self._clock())

    # alerts -----------------------------------------------------------
    def upsert_alert(self, server: str, container: str, kind: str,
                     message: str, tenant: str = "default") -> Alert:
        """Active-alert upsert (db.rs:1052; handlers/agent.rs:203-241)."""
        a = self.find_one("alerts", lambda r: r.server == server and
                          r.container == container and r.kind == kind and r.active)
        if a is not None:
            return self.update("alerts", a.id, message=message)  # type: ignore
        return self.create("alerts", Alert(
            tenant=tenant, server=server, container=container,
            kind=kind, message=message))

    def resolve_alert(self, server: str, container: str, kind: str) -> bool:
        a = self.find_one("alerts", lambda r: r.server == server and
                          r.container == container and r.kind == kind and r.active)
        if a is None:
            return False
        self.update("alerts", a.id, active=False, resolved_at=self._clock())
        return True

    def active_alerts(self, tenant: Optional[str] = None) -> list[Alert]:
        return self.list("alerts", lambda a: a.active and
                         (tenant is None or a.tenant == tenant))

    # observed containers ---------------------------------------------
    def replace_observed(self, server: str,
                         rows: list[ObservedContainer]) -> None:
        """Inventory report replaces that server's slice (db.rs:1153-1219).
        One journal write for the whole batch, not one per record."""
        with self._lock, self.batch():
            table = self._tables["observed_containers"]
            for rid in [k for k, v in table.items() if v.server == server]:
                self.delete("observed_containers", rid)
            for rec in rows:
                rec.server = server
                self.create("observed_containers", rec)

    def observed_on(self, server: str) -> list[ObservedContainer]:
        return self.list("observed_containers", lambda o: o.server == server)

    # cost -------------------------------------------------------------
    def monthly_cost(self, tenant: str, month: str) -> float:
        """db.rs:896-947 monthly summary."""
        return sum(c.amount for c in self.list(
            "cost_entries", lambda c: c.tenant == tenant and c.month == month))

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------

    def batch(self):
        """Context manager coalescing journal appends for bulk mutations:
        one file write (and at most one compaction check) on exit."""
        store = self

        class _Batch:
            def __enter__(self):
                with store._lock:
                    store._batch_depth += 1
                return self

            def __exit__(self, *exc):
                with store._lock:
                    store._batch_depth -= 1
                    if store._batch_depth == 0 and store._batch_buf:
                        lines, store._batch_buf = store._batch_buf, []
                        store._append_lines(lines)
                return False

        return _Batch()

    def journal_stats(self) -> dict:
        """Write-amplification counters for tests/ops: entries and bytes
        appended since the last compaction, and compactions so far."""
        with self._lock:
            return {"entries": self._journal_entries,
                    "bytes": self._journal_bytes,
                    "compactions": self._compactions}

    def _log_put(self, table: str, rec: Record) -> None:
        if self._journal_path is None:
            return
        line = json.dumps({"op": "put", "t": table, "r": rec.to_dict()})
        self._log_line(line)

    def _log_del(self, table: str, rec_id: str) -> None:
        if self._journal_path is None:
            return
        self._log_line(json.dumps({"op": "del", "t": table, "id": rec_id}))

    def _log_line(self, line: str) -> None:
        # caller holds the lock (all mutators do)
        if self._batch_depth > 0:
            self._batch_buf.append(line)
            return
        self._append_lines([line])

    def _append_lines(self, lines: list[str]) -> None:
        if self._journal_file is None:
            self._journal_file = open(self._journal_path, "a",
                                      encoding="utf-8")
        data = "".join(ln + "\n" for ln in lines)
        self._journal_file.write(data)
        self._journal_file.flush()
        if self._fsync:
            os.fsync(self._journal_file.fileno())
        self._journal_entries += len(lines)
        self._journal_bytes += len(data)
        if (self._journal_bytes >= self._journal_max_bytes
                or self._journal_entries >= self._journal_max_entries):
            self.flush()

    def flush(self) -> None:
        """Compact: write the full snapshot (tmp + atomic rename), then
        truncate the journal. Also the explicit snapshot entry point the
        daemon calls on shutdown."""
        if self._path is None:
            return
        # serialize AND write under the lock: concurrent flushes from
        # executor threads must not interleave on the shared tmp file
        with self._lock:
            doc = {t: [r.to_dict() for r in rows.values()]
                   for t, rows in self._tables.items()}
            tmp = self._path.with_suffix(f".tmp{threading.get_ident()}")
            if self._fsync:
                # the WAL guarantee must survive compaction: the snapshot
                # data (and its directory entry) must be on disk BEFORE the
                # journal is unlinked, or power loss between the two loses
                # every fsynced record
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(json.dumps(doc))
                    f.flush()
                    os.fsync(f.fileno())
                tmp.replace(self._path)
                dir_fd = os.open(str(self._path.parent), os.O_RDONLY)
                try:
                    os.fsync(dir_fd)
                finally:
                    os.close(dir_fd)
            else:
                tmp.write_text(json.dumps(doc))
                tmp.replace(self._path)
            if self._journal_file is not None:
                self._journal_file.close()
                self._journal_file = None
            if self._journal_path is not None and self._journal_path.exists():
                self._journal_path.unlink()
            self._journal_entries = 0
            self._journal_bytes = 0
            self._compactions += 1
            _M_COMPACTIONS.inc()

    def _load(self) -> None:
        doc = json.loads(self._path.read_text())
        for table, cls in _TABLES.items():
            for row in doc.get(table, []):
                rec = cls.from_dict(row)
                self._tables[table][rec.id] = rec

    def _replay_journal(self) -> None:
        """Apply surviving journal entries over the loaded snapshot.
        Tolerates exactly one torn FINAL line (crash mid-append); an
        undecodable line anywhere else means real corruption, and replay
        STOPS there with a loud warning — applying later entries over a
        lost one could resurrect deleted rows or drop updates silently.
        Unknown tables are skipped (forward compatibility); replay over an
        already-compacted snapshot is idempotent by construction."""
        text = self._journal_path.read_text(encoding="utf-8", errors="replace")
        lines = [ln for ln in text.splitlines() if ln.strip()]
        for i, line in enumerate(lines):
            try:
                entry = json.loads(line)
            except ValueError:
                if i == len(lines) - 1:
                    break    # torn tail: the expected crash artifact
                from ..obs import get_logger
                get_logger("cp.store").warning(
                    "journal corrupt at line %d of %d; replay stopped there "
                    "(%d trailing entries NOT applied)",
                    i + 1, len(lines), len(lines) - i - 1)
                break
            table = entry.get("t")
            cls = _TABLES.get(table)
            if cls is None:
                continue
            if entry.get("op") == "put":
                try:
                    rec = cls.from_dict(entry["r"])
                except (KeyError, TypeError):
                    continue
                self._tables[table][rec.id] = rec
            elif entry.get("op") == "del":
                self._tables[table].pop(entry.get("id"), None)
