"""Control-plane store.

Analog of the reference's SurrealDB data layer (controlplane db.rs, 3,421
LoC of async CRUD over ~14 tables). The reference runs embedded `kv-mem`
for tests and RocksDB-backed SurrealDB in production (db.rs:41,76); here the
store keeps the same test-vs-durable split with no external database
process: in-memory tables, plus — when a path is given — an append-only
JSON-lines journal with periodic compaction into a snapshot file (the
LSM-ish shape RocksDB gives the reference).

Durability model (VERDICT r2 item 3: mutations must not rewrite the whole
database): every create/update/delete appends ONE journal line
(`{"op": "put"|"del", "t": table, ...}`), O(record) not O(database);
when the journal passes `journal_max_bytes` or `journal_max_entries` the
store compacts: full snapshot via tmp+rename, then journal truncate.
Recovery loads the snapshot and replays the journal; replaying a journal
that was already folded into the snapshot (crash between snapshot rename
and truncate) is idempotent — puts overwrite with identical rows, deletes
of absent rows are no-ops. A torn final line (crash mid-append) is
detected and dropped. Writes are flushed to the OS on every append;
`fsync=True` (or `FLEET_STORE_FSYNC=1`, honored by every construction
site) additionally fsyncs each append and crash-orders compaction — the
snapshot bytes and directory entry reach disk before the journal is
truncated — matching the reference's RocksDB WAL guarantee at a
throughput cost.


Thread-safe: one RLock guards all tables (handler tasks run on one asyncio
loop, but the REST surface and background checkers may call from executor
threads).

Replication (docs/guide/13-cp-replication.md): every journal entry —
including the batched/coalesced paths — carries a monotonic sequence
number (`"q"`) and the store's fencing epoch (`"e"`), and is handed to an
optional `replication_sink` so a primary CP can stream its journal to warm
standbys. A standby applies the stream with `apply_replicated` (gap
detection by sequence, stale-epoch fencing) or bootstraps/catches up from
`snapshot_doc`/`install_snapshot`. The epoch is bumped exactly once per
primary promotion (`bump_epoch`) and persists through both the snapshot
(`_meta`) and a dedicated `{"op": "epoch"}` journal line, so a zombie
ex-primary's entries are refusable forever after a failover.
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import Callable, Optional, TypeVar

from .models import (Alert, BuildJob, CostEntry, Deployment, DeploymentStatus,
                     DnsRecord, ObservedContainer, ParkedArrival, ParkedWork,
                     PlacementRecord, Project, Record, Server, ServiceRecord,
                     StageRecord, Tenant, TenantUser, VolumeRecord,
                     VolumeSnapshot, WorkerPool, new_id, now_ts)
from ..core.errors import ControlPlaneError
from ..obs.metrics import REGISTRY

__all__ = ["Store", "ReplicationGap", "ReplicationFenced"]


class ReplicationGap(ControlPlaneError):
    """The replication stream skipped a sequence number: the standby must
    catch up from a snapshot before applying further entries."""


class ReplicationFenced(ControlPlaneError):
    """A replicated entry carried a stale fencing epoch: it came from a
    zombie ex-primary and must never be applied."""

# metric catalog: docs/guide/10-observability.md. Counted via the store's
# own mutation-observer hook so the change-data-capture path and the
# metrics path can never disagree about what a mutation is.
_M_STORE_OPS = REGISTRY.counter(
    "fleet_store_ops_total", "Store mutations by table and op (put/del)",
    labels=("table", "op"))
_M_HEARTBEATS = REGISTRY.counter(
    "fleet_heartbeats_total", "Agent heartbeats recorded")
_M_COMPACTIONS = REGISTRY.counter(
    "fleet_store_compactions_total", "Journal compactions (snapshot writes)")
_M_FENCING = REGISTRY.counter(
    "fleet_replication_fencing_rejections_total",
    "Stale-epoch writes refused after a failover, by side (store: "
    "replicated entries from a zombie ex-primary; cp: rejected "
    "replication RPCs; agent: fenced agent commands)", labels=("side",))


def _count_op(op: str, table: str, _payload: object) -> None:
    _M_STORE_OPS.inc(table=table, op=op)

R = TypeVar("R", bound=Record)

_TABLES: dict[str, type] = {
    "tenants": Tenant, "tenant_users": TenantUser, "projects": Project,
    "stages": StageRecord, "services": ServiceRecord, "servers": Server,
    "worker_pools": WorkerPool, "deployments": Deployment, "alerts": Alert,
    "observed_containers": ObservedContainer, "volumes": VolumeRecord,
    "volume_snapshots": VolumeSnapshot, "build_jobs": BuildJob,
    "cost_entries": CostEntry, "dns_records": DnsRecord,
    "parked_work": ParkedWork, "placements": PlacementRecord,
    "admission_parked": ParkedArrival,
}


class Store:
    def __init__(self, path: Optional[str] = None, *,
                 journal_max_bytes: int = 4 * 1024 * 1024,
                 journal_max_entries: int = 20_000,
                 fsync: Optional[bool] = None,
                 clock: Callable[[], float] = now_ts):
        self._lock = threading.RLock()
        # record timestamps come from this clock (create/update/heartbeat
        # /finish/resolve stamps): wall time in production, the virtual
        # clock in the chaos harness — so record ages are deterministic
        # under replay instead of depending on real elapsed time
        self._clock = clock
        self._tables: dict[str, dict[str, Record]] = {t: {} for t in _TABLES}
        self._path = Path(path) if path else None
        self._journal_path = (self._path.with_name(self._path.name + ".journal")
                              if self._path else None)
        self._journal_max_bytes = journal_max_bytes
        self._journal_max_entries = journal_max_entries
        if fsync is None:   # FLEET_STORE_FSYNC=1 opts any deployment in
            fsync = os.environ.get("FLEET_STORE_FSYNC", "").strip().lower() \
                in ("1", "true", "yes", "on")
        self._fsync = fsync
        self._journal_file = None          # lazily-opened append handle
        self._journal_bytes = 0
        self._journal_entries = 0
        self._compactions = 0
        self._batch_depth = 0
        self._batch_buf: list[str] = []
        # replication: every emitted journal entry carries (seq, epoch);
        # the sink — when set — receives [(seq, line), ...] under the
        # store lock (same contract as observers: fast, no re-entry).
        # Batched mutations hand the sink ONE coalesced list on batch
        # exit, mirroring the single journal write.
        self._seq = 0
        self._epoch = 1
        self.replication_sink: Optional[
            Callable[[list[tuple[int, str]]], None]] = None
        self._repl_buf: list[tuple[int, str]] = []
        # mutation observers: fn(op, table, rec_or_id) called under the
        # store lock AFTER each create/update/delete. This is the
        # change-data-capture hook the chaos harness builds its causal
        # event log on; it doubles as a general extension point (metrics,
        # cache invalidation). Observers must be fast and must not
        # re-enter the store's mutators.
        self._observers: list[Callable[[str, str, object], None]] = [_count_op]
        if self._path and self._path.exists():
            self._load()
        if self._journal_path and self._journal_path.exists():
            self._replay_journal()
            # fold the surviving journal into a fresh snapshot so repeated
            # crash/restart cycles cannot grow an unbounded replay tail
            self.flush()

    @classmethod
    def connect_memory(cls) -> "Store":
        """Test constructor (db.rs connect_memory:76)."""
        return cls(path=None)

    def subscribe(self, fn: Callable[[str, str, object], None]) -> None:
        """Register a mutation observer: fn("put"|"del", table, rec|id)."""
        with self._lock:
            self._observers.append(fn)

    def unsubscribe(self, fn: Callable[[str, str, object], None]) -> None:
        with self._lock:
            if fn in self._observers:
                self._observers.remove(fn)

    def _notify(self, op: str, table: str, payload: object) -> None:
        for fn in self._observers:
            fn(op, table, payload)

    # ------------------------------------------------------------------
    # generic CRUD
    # ------------------------------------------------------------------

    def create(self, table: str, rec: R) -> R:
        with self._lock:
            if not rec.id:
                rec.id = new_id(table.rstrip("s"))
            rec.created_at = rec.created_at or self._clock()
            rec.updated_at = self._clock()
            self._tables[table][rec.id] = rec
            self._log_put(table, rec)
            self._notify("put", table, rec)
            return rec

    def get(self, table: str, rec_id: str) -> Optional[Record]:
        with self._lock:
            return self._tables[table].get(rec_id)

    def update(self, table: str, rec_id: str, **changes) -> Optional[Record]:
        with self._lock:
            rec = self._tables[table].get(rec_id)
            if rec is None:
                return None
            for k, v in changes.items():
                setattr(rec, k, v)
            rec.updated_at = self._clock()
            self._log_put(table, rec)
            self._notify("put", table, rec)
            return rec

    def delete(self, table: str, rec_id: str) -> bool:
        with self._lock:
            gone = self._tables[table].pop(rec_id, None) is not None
            if gone:
                self._log_del(table, rec_id)
                self._notify("del", table, rec_id)
            return gone

    def list(self, table: str,
             where: Optional[Callable[[Record], bool]] = None) -> list[Record]:
        with self._lock:
            rows = list(self._tables[table].values())
        if where is not None:
            rows = [r for r in rows if where(r)]
        return sorted(rows, key=lambda r: r.created_at)

    def find_one(self, table: str,
                 where: Callable[[Record], bool]) -> Optional[Record]:
        # hot path (server_by_slug on every heartbeat/alert/inventory):
        # early-exit scan, no copy/sort like list()
        with self._lock:
            for r in self._tables[table].values():
                if where(r):
                    return r
        return None

    # ------------------------------------------------------------------
    # domain queries (the named fns of db.rs)
    # ------------------------------------------------------------------

    # tenants ----------------------------------------------------------
    def tenant_by_name(self, name: str) -> Optional[Tenant]:
        return self.find_one("tenants", lambda t: t.name == name)

    def ensure_tenant(self, name: str) -> Tenant:
        """get-or-create, the way deploy.execute resolves tenants
        (handlers/deploy.rs tenant resolve)."""
        t = self.tenant_by_name(name)
        if t is None:
            t = self.create("tenants", Tenant(name=name, display_name=name))
        return t

    def tenant_users(self, tenant: str) -> list[TenantUser]:
        return self.list("tenant_users", lambda u: u.tenant == tenant)

    def user_by_email(self, tenant: str, email: str) -> Optional[TenantUser]:
        return self.find_one(
            "tenant_users", lambda u: u.tenant == tenant and u.email == email)

    # projects / stages / services ------------------------------------
    def project_by_name(self, tenant: str, name: str) -> Optional[Project]:
        return self.find_one(
            "projects", lambda p: p.tenant == tenant and p.name == name)

    def ensure_project(self, tenant: str, name: str) -> Project:
        p = self.project_by_name(tenant, name)
        if p is None:
            p = self.create("projects", Project(tenant=tenant, name=name))
        return p

    def stages_of(self, project: str) -> list[StageRecord]:
        return self.list("stages", lambda s: s.project == project)

    def stage_by_name(self, project: str, name: str) -> Optional[StageRecord]:
        return self.find_one(
            "stages", lambda s: s.project == project and s.name == name)

    def ensure_stage(self, project: str, name: str, **attrs) -> StageRecord:
        s = self.stage_by_name(project, name)
        if s is None:
            s = self.create("stages",
                            StageRecord(project=project, name=name, **attrs))
        elif attrs:
            self.update("stages", s.id, **attrs)
        return s

    def adopt_stage(self, stage_id: str) -> Optional[StageRecord]:
        """Stage adoption (db.rs:480): claim an observed stage as managed."""
        return self.update("stages", stage_id, adopted=True)

    def services_of(self, stage: str) -> list[ServiceRecord]:
        return self.list("services", lambda s: s.stage == stage)

    def upsert_service(self, stage: str, name: str, **attrs) -> ServiceRecord:
        s = self.find_one("services",
                          lambda r: r.stage == stage and r.name == name)
        if s is None:
            return self.create("services",
                               ServiceRecord(stage=stage, name=name, **attrs))
        return self.update("services", s.id, **attrs)  # type: ignore[return-value]

    # servers ----------------------------------------------------------
    def server_by_slug(self, slug: str) -> Optional[Server]:
        return self.find_one("servers", lambda s: s.slug == slug)

    def register_server(self, slug: str, tenant: str = "default",
                        **attrs) -> Server:
        """Agent registration upsert (handlers/server.rs register)."""
        s = self.server_by_slug(slug)
        if s is None:
            return self.create("servers",
                               Server(slug=slug, tenant=tenant, **attrs))
        return self.update("servers", s.id, **attrs)  # type: ignore[return-value]

    def heartbeat(self, slug: str, version: str = "") -> Optional[Server]:
        """db.rs heartbeat update (handlers/agent.rs:84-91)."""
        s = self.server_by_slug(slug)
        if s is None:
            return None
        _M_HEARTBEATS.inc()
        changes: dict = {"last_heartbeat": self._clock(), "status": "online"}
        if version:
            changes["agent_version"] = version
        return self.update("servers", s.id, **changes)

    def bulk_server_status(self, statuses: dict[str, str]) -> int:
        """Health-checker bulk update (db.rs:779; fleetflowd health.rs:34-69)."""
        n = 0
        for slug, status in statuses.items():
            s = self.server_by_slug(slug)
            if s is not None and s.status != status:
                self.update("servers", s.id, status=status)
                n += 1
        return n

    def schedulable_servers(self, tenant: Optional[str] = None) -> list[Server]:
        return self.list("servers", lambda s: s.schedulable and
                         (tenant is None or s.tenant == tenant))

    # deployments ------------------------------------------------------
    def deployment_history(self, stage: Optional[str] = None,
                           limit: int = 50) -> list[Deployment]:
        rows = self.list("deployments",
                         (lambda d: d.stage == stage) if stage else None)
        return list(reversed(rows))[:limit]

    def finish_deployment(self, dep_id: str, status: DeploymentStatus,
                          log: str = "", error: str = "") -> Optional[Deployment]:
        return self.update("deployments", dep_id, status=status.value,
                           log=log, error=error, finished_at=self._clock())

    # alerts -----------------------------------------------------------
    def upsert_alert(self, server: str, container: str, kind: str,
                     message: str, tenant: str = "default") -> Alert:
        """Active-alert upsert (db.rs:1052; handlers/agent.rs:203-241)."""
        a = self.find_one("alerts", lambda r: r.server == server and
                          r.container == container and r.kind == kind and r.active)
        if a is not None:
            return self.update("alerts", a.id, message=message)  # type: ignore
        return self.create("alerts", Alert(
            tenant=tenant, server=server, container=container,
            kind=kind, message=message))

    def resolve_alert(self, server: str, container: str, kind: str) -> bool:
        a = self.find_one("alerts", lambda r: r.server == server and
                          r.container == container and r.kind == kind and r.active)
        if a is None:
            return False
        self.update("alerts", a.id, active=False, resolved_at=self._clock())
        return True

    def active_alerts(self, tenant: Optional[str] = None) -> list[Alert]:
        return self.list("alerts", lambda a: a.active and
                         (tenant is None or a.tenant == tenant))

    # observed containers ---------------------------------------------
    def replace_observed(self, server: str,
                         rows: list[ObservedContainer]) -> None:
        """Inventory report replaces that server's slice (db.rs:1153-1219).
        One journal write for the whole batch, not one per record."""
        with self._lock, self.batch():
            table = self._tables["observed_containers"]
            for rid in [k for k, v in table.items() if v.server == server]:
                self.delete("observed_containers", rid)
            for rec in rows:
                rec.server = server
                self.create("observed_containers", rec)

    def observed_on(self, server: str) -> list[ObservedContainer]:
        return self.list("observed_containers", lambda o: o.server == server)

    # cost -------------------------------------------------------------
    def monthly_cost(self, tenant: str, month: str) -> float:
        """db.rs:896-947 monthly summary."""
        return sum(c.amount for c in self.list(
            "cost_entries", lambda c: c.tenant == tenant and c.month == month))

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------

    def batch(self):
        """Context manager coalescing journal appends for bulk mutations:
        one file write (and at most one compaction check) on exit."""
        store = self

        class _Batch:
            def __enter__(self):
                with store._lock:
                    store._batch_depth += 1
                return self

            def __exit__(self, *exc):
                with store._lock:
                    store._batch_depth -= 1
                    if store._batch_depth == 0 and store._batch_buf:
                        lines, store._batch_buf = store._batch_buf, []
                        store._append_lines(lines)
                    if store._batch_depth == 0 and store._repl_buf:
                        entries, store._repl_buf = store._repl_buf, []
                        if store.replication_sink is not None:
                            store.replication_sink(entries)
                return False

        return _Batch()

    def journal_stats(self) -> dict:
        """Write-amplification counters for tests/ops: entries and bytes
        appended since the last compaction, and compactions so far."""
        with self._lock:
            return {"entries": self._journal_entries,
                    "bytes": self._journal_bytes,
                    "compactions": self._compactions}

    def _log_put(self, table: str, rec: Record) -> None:
        self._emit({"op": "put", "t": table, "r": rec.to_dict()})

    def _log_del(self, table: str, rec_id: str) -> None:
        self._emit({"op": "del", "t": table, "id": rec_id})

    def _emit(self, entry: dict) -> None:
        """Serialize one journal entry with its sequence number and epoch,
        then hand it to the local journal and/or the replication sink.
        Caller holds the lock (all mutators do). A store with neither a
        journal nor a sink skips the serialization entirely."""
        if self._journal_path is None and self.replication_sink is None:
            return
        self._seq += 1
        entry["q"] = self._seq
        entry["e"] = self._epoch
        line = json.dumps(entry)
        if self._journal_path is not None:
            self._log_line(line)
        if self.replication_sink is not None:
            if self._batch_depth > 0:
                self._repl_buf.append((self._seq, line))
            else:
                self.replication_sink([(self._seq, line)])

    def _log_line(self, line: str) -> None:
        # caller holds the lock (all mutators do)
        if self._batch_depth > 0:
            self._batch_buf.append(line)
            return
        self._append_lines([line])

    def _append_lines(self, lines: list[str]) -> None:
        if self._journal_file is None:
            self._journal_file = open(self._journal_path, "a",
                                      encoding="utf-8")
        data = "".join(ln + "\n" for ln in lines)
        self._journal_file.write(data)
        self._journal_file.flush()
        if self._fsync:
            os.fsync(self._journal_file.fileno())
        self._journal_entries += len(lines)
        self._journal_bytes += len(data)
        if (self._journal_bytes >= self._journal_max_bytes
                or self._journal_entries >= self._journal_max_entries):
            self.flush()

    def flush(self) -> None:
        """Compact: write the full snapshot (tmp + atomic rename), then
        truncate the journal. Also the explicit snapshot entry point the
        daemon calls on shutdown."""
        if self._path is None:
            return
        # serialize AND write under the lock: concurrent flushes from
        # executor threads must not interleave on the shared tmp file
        with self._lock:
            doc = self._snapshot_doc_locked()
            tmp = self._path.with_suffix(f".tmp{threading.get_ident()}")
            if self._fsync:
                # the WAL guarantee must survive compaction: the snapshot
                # data (and its directory entry) must be on disk BEFORE the
                # journal is unlinked, or power loss between the two loses
                # every fsynced record
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(json.dumps(doc))
                    f.flush()
                    os.fsync(f.fileno())
                tmp.replace(self._path)
                dir_fd = os.open(str(self._path.parent), os.O_RDONLY)
                try:
                    os.fsync(dir_fd)
                finally:
                    os.close(dir_fd)
            else:
                tmp.write_text(json.dumps(doc))
                tmp.replace(self._path)
            if self._journal_file is not None:
                self._journal_file.close()
                self._journal_file = None
            if self._journal_path is not None and self._journal_path.exists():
                self._journal_path.unlink()
            self._journal_entries = 0
            self._journal_bytes = 0
            self._compactions += 1
            _M_COMPACTIONS.inc()

    def _snapshot_doc_locked(self) -> dict:
        doc = {t: [r.to_dict() for r in rows.values()]
               for t, rows in self._tables.items()}
        # replication metadata rides the snapshot: a standby installing it
        # (or this store reloading it) resumes sequence numbering and the
        # fencing epoch exactly where the journal left off. Old readers
        # iterate _TABLES only, so the extra key is forward-compatible.
        doc["_meta"] = {"seq": self._seq, "epoch": self._epoch}
        return doc

    def snapshot_doc(self) -> dict:
        """Full-state snapshot for standby catch-up (the same document
        `flush` writes to disk, including the `_meta` seq/epoch)."""
        with self._lock:
            return self._snapshot_doc_locked()

    def install_snapshot(self, doc: dict) -> None:
        """Replace ALL state with a primary's snapshot (standby bootstrap
        or catch-up after a stream gap), then persist locally so a standby
        restart doesn't re-fetch. Sequence numbering and epoch resume from
        the snapshot's `_meta`."""
        with self._lock:
            self._tables = {t: {} for t in _TABLES}
            self._load_doc(doc)
            meta = doc.get("_meta") or {}
            self._seq = int(meta.get("seq", self._seq))
            self._epoch = int(meta.get("epoch", self._epoch))
            self.flush()

    # ------------------------------------------------------------------
    # replication (primary journal shipping -> standby apply)
    # ------------------------------------------------------------------

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def bump_epoch(self) -> int:
        """Primary promotion: advance the fencing epoch by one and journal
        the transition (it replicates and persists like any mutation), so
        every entry the NEW primary emits outranks the old one's."""
        with self._lock:
            self._epoch += 1
            self._emit({"op": "epoch"})
            return self._epoch

    def apply_replicated(self, entries: list[tuple[int, str]]) -> int:
        """Standby-side: apply sequence-numbered journal lines shipped by
        the primary. Enforces the two stream invariants:

          * gap detection — entries must arrive at exactly seq+1; a skip
            raises ReplicationGap (the standby re-syncs from a snapshot);
          * fencing — an entry whose epoch is below this store's raises
            ReplicationFenced (zombie ex-primary; never applied).

        Applied entries are re-journaled locally (when this store has a
        path) so a promoted standby is durable without a re-snapshot.
        Returns the number of entries applied."""
        applied = 0
        with self._lock:
            for seq, line in entries:
                entry = json.loads(line)
                epoch = int(entry.get("e", self._epoch))
                # fencing FIRST: a zombie's entry must be refused loudly
                # even when its seq falls inside already-applied history
                if epoch < self._epoch:
                    _M_FENCING.inc(side="store")
                    raise ReplicationFenced(
                        f"entry seq={seq} epoch={epoch} < local epoch "
                        f"{self._epoch}: refusing zombie write")
                if seq <= self._seq:
                    # already applied (a batch queued before a snapshot
                    # resync): replay is idempotent by sequence — skip
                    # instead of forcing another full resync
                    continue
                if seq != self._seq + 1:
                    raise ReplicationGap(
                        f"stream gap: got seq={seq}, expected "
                        f"{self._seq + 1}")
                self._apply_entry(entry)
                self._seq = seq
                self._epoch = epoch
                if self._journal_path is not None:
                    self._log_line(line)
                applied += 1
        return applied

    def _apply_entry(self, entry: dict, notify: bool = True) -> None:
        """Apply one decoded journal entry to the tables (shared by local
        replay and the replication stream). Caller holds the lock. Local
        boot replay passes notify=False — observers see live mutations,
        not recovery; the replication stream notifies (the standby's CDC
        hooks and metrics see applied entries as the mutations they are)."""
        op = entry.get("op")
        if op == "epoch":
            self._epoch = int(entry.get("e", self._epoch))
            return
        table = entry.get("t")
        cls = _TABLES.get(table)
        if cls is None:
            return
        if op == "put":
            try:
                rec = cls.from_dict(entry["r"])
            except (KeyError, TypeError):
                return
            self._tables[table][rec.id] = rec
            if notify:
                self._notify("put", table, rec)
        elif op == "del":
            rid = entry.get("id")
            if self._tables[table].pop(rid, None) is not None and notify:
                self._notify("del", table, rid)

    def _load(self) -> None:
        doc = json.loads(self._path.read_text())
        self._load_doc(doc)
        meta = doc.get("_meta") or {}
        self._seq = int(meta.get("seq", 0))
        self._epoch = int(meta.get("epoch", 1))

    def _load_doc(self, doc: dict) -> None:
        for table, cls in _TABLES.items():
            for row in doc.get(table, []):
                rec = cls.from_dict(row)
                self._tables[table][rec.id] = rec

    def _replay_journal(self) -> None:
        """Apply surviving journal entries over the loaded snapshot.
        Tolerates exactly one torn FINAL line (crash mid-append); an
        undecodable line anywhere else means real corruption, and replay
        STOPS there with a loud warning — applying later entries over a
        lost one could resurrect deleted rows or drop updates silently.
        Unknown tables are skipped (forward compatibility); replay over an
        already-compacted snapshot is idempotent by construction."""
        text = self._journal_path.read_text(encoding="utf-8", errors="replace")
        lines = [ln for ln in text.splitlines() if ln.strip()]
        for i, line in enumerate(lines):
            try:
                entry = json.loads(line)
            except ValueError:
                if i == len(lines) - 1:
                    break    # torn tail: the expected crash artifact
                from ..obs import get_logger
                get_logger("cp.store").warning(
                    "journal corrupt at line %d of %d; replay stopped there "
                    "(%d trailing entries NOT applied)",
                    i + 1, len(lines), len(lines) - i - 1)
                break
            self._apply_entry(entry, notify=False)
            # resume sequence numbering past the surviving tail (entries
            # predating the seq field leave the counter where _load set it)
            if "q" in entry:
                self._seq = max(self._seq, int(entry["q"]))
