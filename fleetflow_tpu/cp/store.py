"""Control-plane store.

Analog of the reference's SurrealDB data layer (controlplane db.rs, 3,421
LoC of async CRUD over ~14 tables). The reference runs embedded `kv-mem`
for tests and RocksDB-backed SurrealDB in production (db.rs:41,76); here the
store is in-memory tables with an optional JSON snapshot file — same
test-vs-durable split, no external database process.

Thread-safe: one RLock guards all tables (handler tasks run on one asyncio
loop, but the REST surface and background checkers may call from executor
threads).
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Callable, Optional, TypeVar

from .models import (Alert, BuildJob, CostEntry, Deployment, DeploymentStatus,
                     DnsRecord, ObservedContainer, Project, Record, Server,
                     ServiceRecord, StageRecord, Tenant, TenantUser,
                     VolumeRecord, VolumeSnapshot, WorkerPool, new_id, now_ts)

__all__ = ["Store"]

R = TypeVar("R", bound=Record)

_TABLES: dict[str, type] = {
    "tenants": Tenant, "tenant_users": TenantUser, "projects": Project,
    "stages": StageRecord, "services": ServiceRecord, "servers": Server,
    "worker_pools": WorkerPool, "deployments": Deployment, "alerts": Alert,
    "observed_containers": ObservedContainer, "volumes": VolumeRecord,
    "volume_snapshots": VolumeSnapshot, "build_jobs": BuildJob,
    "cost_entries": CostEntry, "dns_records": DnsRecord,
}


class Store:
    def __init__(self, path: Optional[str] = None):
        self._lock = threading.RLock()
        self._tables: dict[str, dict[str, Record]] = {t: {} for t in _TABLES}
        self._path = Path(path) if path else None
        self._batch_depth = 0
        self._pending_flush = False
        if self._path and self._path.exists():
            self._load()

    @classmethod
    def connect_memory(cls) -> "Store":
        """Test constructor (db.rs connect_memory:76)."""
        return cls(path=None)

    # ------------------------------------------------------------------
    # generic CRUD
    # ------------------------------------------------------------------

    def create(self, table: str, rec: R) -> R:
        with self._lock:
            if not rec.id:
                rec.id = new_id(table.rstrip("s"))
            rec.created_at = rec.created_at or now_ts()
            rec.updated_at = now_ts()
            self._tables[table][rec.id] = rec
            self._dirty()
            return rec

    def get(self, table: str, rec_id: str) -> Optional[Record]:
        with self._lock:
            return self._tables[table].get(rec_id)

    def update(self, table: str, rec_id: str, **changes) -> Optional[Record]:
        with self._lock:
            rec = self._tables[table].get(rec_id)
            if rec is None:
                return None
            for k, v in changes.items():
                setattr(rec, k, v)
            rec.updated_at = now_ts()
            self._dirty()
            return rec

    def delete(self, table: str, rec_id: str) -> bool:
        with self._lock:
            gone = self._tables[table].pop(rec_id, None) is not None
            if gone:
                self._dirty()
            return gone

    def list(self, table: str,
             where: Optional[Callable[[Record], bool]] = None) -> list[Record]:
        with self._lock:
            rows = list(self._tables[table].values())
        if where is not None:
            rows = [r for r in rows if where(r)]
        return sorted(rows, key=lambda r: r.created_at)

    def find_one(self, table: str,
                 where: Callable[[Record], bool]) -> Optional[Record]:
        # hot path (server_by_slug on every heartbeat/alert/inventory):
        # early-exit scan, no copy/sort like list()
        with self._lock:
            for r in self._tables[table].values():
                if where(r):
                    return r
        return None

    # ------------------------------------------------------------------
    # domain queries (the named fns of db.rs)
    # ------------------------------------------------------------------

    # tenants ----------------------------------------------------------
    def tenant_by_name(self, name: str) -> Optional[Tenant]:
        return self.find_one("tenants", lambda t: t.name == name)

    def ensure_tenant(self, name: str) -> Tenant:
        """get-or-create, the way deploy.execute resolves tenants
        (handlers/deploy.rs tenant resolve)."""
        t = self.tenant_by_name(name)
        if t is None:
            t = self.create("tenants", Tenant(name=name, display_name=name))
        return t

    def tenant_users(self, tenant: str) -> list[TenantUser]:
        return self.list("tenant_users", lambda u: u.tenant == tenant)

    def user_by_email(self, tenant: str, email: str) -> Optional[TenantUser]:
        return self.find_one(
            "tenant_users", lambda u: u.tenant == tenant and u.email == email)

    # projects / stages / services ------------------------------------
    def project_by_name(self, tenant: str, name: str) -> Optional[Project]:
        return self.find_one(
            "projects", lambda p: p.tenant == tenant and p.name == name)

    def ensure_project(self, tenant: str, name: str) -> Project:
        p = self.project_by_name(tenant, name)
        if p is None:
            p = self.create("projects", Project(tenant=tenant, name=name))
        return p

    def stages_of(self, project: str) -> list[StageRecord]:
        return self.list("stages", lambda s: s.project == project)

    def stage_by_name(self, project: str, name: str) -> Optional[StageRecord]:
        return self.find_one(
            "stages", lambda s: s.project == project and s.name == name)

    def ensure_stage(self, project: str, name: str, **attrs) -> StageRecord:
        s = self.stage_by_name(project, name)
        if s is None:
            s = self.create("stages",
                            StageRecord(project=project, name=name, **attrs))
        elif attrs:
            self.update("stages", s.id, **attrs)
        return s

    def adopt_stage(self, stage_id: str) -> Optional[StageRecord]:
        """Stage adoption (db.rs:480): claim an observed stage as managed."""
        return self.update("stages", stage_id, adopted=True)

    def services_of(self, stage: str) -> list[ServiceRecord]:
        return self.list("services", lambda s: s.stage == stage)

    def upsert_service(self, stage: str, name: str, **attrs) -> ServiceRecord:
        s = self.find_one("services",
                          lambda r: r.stage == stage and r.name == name)
        if s is None:
            return self.create("services",
                               ServiceRecord(stage=stage, name=name, **attrs))
        return self.update("services", s.id, **attrs)  # type: ignore[return-value]

    # servers ----------------------------------------------------------
    def server_by_slug(self, slug: str) -> Optional[Server]:
        return self.find_one("servers", lambda s: s.slug == slug)

    def register_server(self, slug: str, tenant: str = "default",
                        **attrs) -> Server:
        """Agent registration upsert (handlers/server.rs register)."""
        s = self.server_by_slug(slug)
        if s is None:
            return self.create("servers",
                               Server(slug=slug, tenant=tenant, **attrs))
        return self.update("servers", s.id, **attrs)  # type: ignore[return-value]

    def heartbeat(self, slug: str, version: str = "") -> Optional[Server]:
        """db.rs heartbeat update (handlers/agent.rs:84-91)."""
        s = self.server_by_slug(slug)
        if s is None:
            return None
        changes: dict = {"last_heartbeat": now_ts(), "status": "online"}
        if version:
            changes["agent_version"] = version
        return self.update("servers", s.id, **changes)

    def bulk_server_status(self, statuses: dict[str, str]) -> int:
        """Health-checker bulk update (db.rs:779; fleetflowd health.rs:34-69)."""
        n = 0
        for slug, status in statuses.items():
            s = self.server_by_slug(slug)
            if s is not None and s.status != status:
                self.update("servers", s.id, status=status)
                n += 1
        return n

    def schedulable_servers(self, tenant: Optional[str] = None) -> list[Server]:
        return self.list("servers", lambda s: s.schedulable and
                         (tenant is None or s.tenant == tenant))

    # deployments ------------------------------------------------------
    def deployment_history(self, stage: Optional[str] = None,
                           limit: int = 50) -> list[Deployment]:
        rows = self.list("deployments",
                         (lambda d: d.stage == stage) if stage else None)
        return list(reversed(rows))[:limit]

    def finish_deployment(self, dep_id: str, status: DeploymentStatus,
                          log: str = "", error: str = "") -> Optional[Deployment]:
        return self.update("deployments", dep_id, status=status.value,
                           log=log, error=error, finished_at=now_ts())

    # alerts -----------------------------------------------------------
    def upsert_alert(self, server: str, container: str, kind: str,
                     message: str, tenant: str = "default") -> Alert:
        """Active-alert upsert (db.rs:1052; handlers/agent.rs:203-241)."""
        a = self.find_one("alerts", lambda r: r.server == server and
                          r.container == container and r.kind == kind and r.active)
        if a is not None:
            return self.update("alerts", a.id, message=message)  # type: ignore
        return self.create("alerts", Alert(
            tenant=tenant, server=server, container=container,
            kind=kind, message=message))

    def resolve_alert(self, server: str, container: str, kind: str) -> bool:
        a = self.find_one("alerts", lambda r: r.server == server and
                          r.container == container and r.kind == kind and r.active)
        if a is None:
            return False
        self.update("alerts", a.id, active=False, resolved_at=now_ts())
        return True

    def active_alerts(self, tenant: Optional[str] = None) -> list[Alert]:
        return self.list("alerts", lambda a: a.active and
                         (tenant is None or a.tenant == tenant))

    # observed containers ---------------------------------------------
    def replace_observed(self, server: str,
                         rows: list[ObservedContainer]) -> None:
        """Inventory report replaces that server's slice (db.rs:1153-1219).
        One flush for the whole batch, not one per record."""
        with self._lock, self.batch():
            table = self._tables["observed_containers"]
            for rid in [k for k, v in table.items() if v.server == server]:
                del table[rid]
            for rec in rows:
                rec.server = server
                self.create("observed_containers", rec)

    def observed_on(self, server: str) -> list[ObservedContainer]:
        return self.list("observed_containers", lambda o: o.server == server)

    # cost -------------------------------------------------------------
    def monthly_cost(self, tenant: str, month: str) -> float:
        """db.rs:896-947 monthly summary."""
        return sum(c.amount for c in self.list(
            "cost_entries", lambda c: c.tenant == tenant and c.month == month))

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------

    def batch(self):
        """Context manager suppressing write-through for bulk mutations;
        one flush on exit."""
        store = self

        class _Batch:
            def __enter__(self):
                with store._lock:
                    store._batch_depth += 1
                return self

            def __exit__(self, *exc):
                with store._lock:
                    store._batch_depth -= 1
                    pending = store._batch_depth == 0 and store._pending_flush
                if pending:
                    store.flush()
                return False

        return _Batch()

    def _dirty(self) -> None:
        if self._path is None:
            return
        with self._lock:
            if self._batch_depth > 0:
                self._pending_flush = True
                return
        self.flush()

    def flush(self) -> None:
        if self._path is None:
            return
        # serialize AND write under the lock: concurrent flushes from
        # executor threads must not interleave on the shared tmp file
        with self._lock:
            self._pending_flush = False
            doc = {t: [r.to_dict() for r in rows.values()]
                   for t, rows in self._tables.items()}
            tmp = self._path.with_suffix(f".tmp{threading.get_ident()}")
            tmp.write_text(json.dumps(doc))
            tmp.replace(self._path)

    def _load(self) -> None:
        doc = json.loads(self._path.read_text())
        for table, cls in _TABLES.items():
            for row in doc.get(table, []):
                rec = cls.from_dict(row)
                self._tables[table][rec.id] = rec
