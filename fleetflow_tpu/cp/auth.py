"""Auth providers.

Analog of controlplane auth.rs:17-38: an enum-dispatched provider — NoAuth
for local/dev, and a JWT verifier for production. The reference verifies
Auth0 RS256 tokens against a cached JWKS; this build issues and verifies
HS256 tokens with a shared secret (the CP is its own identity provider —
the Device-Flow login of the reference CLI maps to `fleet cp login` minting
one of these). Claims carry email + permissions like the reference's.

JWT is implemented inline (HMAC-SHA256 + base64url): no external deps, and
the token format stays interoperable with standard tooling.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from dataclasses import dataclass, field
from typing import Optional

from ..core.errors import ControlPlaneError

__all__ = ["AuthError", "Claims", "NoAuth", "TokenAuth", "make_provider"]


class AuthError(ControlPlaneError):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


@dataclass
class Claims:
    """auth.rs Claims: subject email + permission strings."""
    sub: str = ""
    email: str = ""
    permissions: list[str] = field(default_factory=list)
    tenant: str = "default"
    exp: float = 0.0

    def has(self, perm: str) -> bool:
        return perm in self.permissions or "admin:all" in self.permissions


class NoAuth:
    """auth.rs NoAuth: everything is the anonymous admin."""

    def verify(self, token: Optional[str]) -> Claims:
        return Claims(sub="anonymous", email="anonymous@local",
                      permissions=["admin:all"], exp=time.time() + 3600)

    def issue(self, email: str, permissions: list[str],
              tenant: str = "default", ttl_s: float = 86400.0) -> str:
        return ""


class TokenAuth:
    """HS256 JWT issue + verify with a shared secret."""

    def __init__(self, secret: str):
        if not secret:
            raise AuthError("TokenAuth requires a non-empty secret")
        self._key = secret.encode()

    def issue(self, email: str, permissions: list[str],
              tenant: str = "default", ttl_s: float = 86400.0) -> str:
        header = {"alg": "HS256", "typ": "JWT"}
        now = time.time()
        payload = {"sub": email, "email": email, "permissions": permissions,
                   "tenant": tenant, "iat": int(now), "exp": int(now + ttl_s)}
        signing = (_b64url(json.dumps(header, separators=(",", ":")).encode())
                   + "." +
                   _b64url(json.dumps(payload, separators=(",", ":")).encode()))
        sig = hmac.new(self._key, signing.encode(), hashlib.sha256).digest()
        return signing + "." + _b64url(sig)

    def verify(self, token: Optional[str]) -> Claims:
        if not token:
            raise AuthError("missing token")
        try:
            signing, _, sig_part = token.rpartition(".")
            header_part, _, payload_part = signing.partition(".")
            header = json.loads(_unb64url(header_part))
            if header.get("alg") != "HS256":
                raise AuthError(f"unsupported alg {header.get('alg')!r}")
            expected = hmac.new(self._key, signing.encode(),
                                hashlib.sha256).digest()
            if not hmac.compare_digest(expected, _unb64url(sig_part)):
                raise AuthError("bad signature")
            payload = json.loads(_unb64url(payload_part))
        except AuthError:
            raise
        except Exception as e:
            raise AuthError(f"malformed token: {e}") from None
        exp = float(payload.get("exp", 0))
        if exp and exp < time.time():
            raise AuthError("token expired")
        return Claims(sub=str(payload.get("sub", "")),
                      email=str(payload.get("email", "")),
                      permissions=list(payload.get("permissions", [])),
                      tenant=str(payload.get("tenant", "default")),
                      exp=exp)


def make_provider(kind: str, secret: Optional[str] = None):
    """auth.rs AuthProviderKind enum dispatch."""
    if kind in ("none", "noauth", ""):
        return NoAuth()
    if kind in ("token", "jwt"):
        return TokenAuth(secret or "")
    raise AuthError(f"unknown auth provider {kind!r}")
