"""Auth providers.

Analog of controlplane auth.rs:17-38: an enum-dispatched provider — NoAuth
for local/dev, TokenAuth (self-issued HS256 with a shared secret, the CP as
its own identity provider), and JwksAuth: RS256 verification against a
cached JWKS document, the reference's production path (auth.rs:26-38
Auth0Verifier: JWKS cache + semaphore, Claims with permissions). Claims
carry email + permissions like the reference's; `fleet cp login` obtains a
token either by minting (shared secret) or via the OAuth Device Flow
against the external IdP (fleetflow/src/auth.rs:68-263 analog in
cli/device_flow.py).

HS256 JWT is implemented inline (HMAC-SHA256 + base64url): no external
deps, and the token format stays interoperable with standard tooling.
RS256 verification uses the `cryptography` package (already a dependency
of the mesh-CA layer, cp/cert.py).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import threading
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional, Union

from ..core.errors import ControlPlaneError

__all__ = ["AuthError", "Claims", "NoAuth", "TokenAuth", "JwksAuth",
           "make_provider"]


class AuthError(ControlPlaneError):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


@dataclass
class Claims:
    """auth.rs Claims: subject email + permission strings."""
    sub: str = ""
    email: str = ""
    permissions: list[str] = field(default_factory=list)
    tenant: str = "default"
    exp: float = 0.0

    def has(self, perm: str) -> bool:
        """Permission check: exact grant, `admin:all`, or a verb wildcard
        (`read:*` satisfies any `read:<area>`)."""
        if perm in self.permissions or "admin:all" in self.permissions:
            return True
        verb, _, _area = perm.partition(":")
        return f"{verb}:*" in self.permissions


class NoAuth:
    """auth.rs NoAuth: everything is the anonymous admin."""

    def verify(self, token: Optional[str]) -> Claims:
        return Claims(sub="anonymous", email="anonymous@local",
                      permissions=["admin:all"], exp=time.time() + 3600)

    def issue(self, email: str, permissions: list[str],
              tenant: str = "default", ttl_s: float = 86400.0) -> str:
        return ""


class TokenAuth:
    """HS256 JWT issue + verify with a shared secret."""

    def __init__(self, secret: str):
        if not secret:
            raise AuthError("TokenAuth requires a non-empty secret")
        self._key = secret.encode()

    def issue(self, email: str, permissions: list[str],
              tenant: str = "default", ttl_s: float = 86400.0) -> str:
        header = {"alg": "HS256", "typ": "JWT"}
        now = time.time()
        payload = {"sub": email, "email": email, "permissions": permissions,
                   "tenant": tenant, "iat": int(now), "exp": int(now + ttl_s)}
        signing = (_b64url(json.dumps(header, separators=(",", ":")).encode())
                   + "." +
                   _b64url(json.dumps(payload, separators=(",", ":")).encode()))
        sig = hmac.new(self._key, signing.encode(), hashlib.sha256).digest()
        return signing + "." + _b64url(sig)

    def verify(self, token: Optional[str]) -> Claims:
        if not token:
            raise AuthError("missing token")
        try:
            signing, _, sig_part = token.rpartition(".")
            header_part, _, payload_part = signing.partition(".")
            header = json.loads(_unb64url(header_part))
            if header.get("alg") != "HS256":
                raise AuthError(f"unsupported alg {header.get('alg')!r}")
            expected = hmac.new(self._key, signing.encode(),
                                hashlib.sha256).digest()
            if not hmac.compare_digest(expected, _unb64url(sig_part)):
                raise AuthError("bad signature")
            payload = json.loads(_unb64url(payload_part))
        except AuthError:
            raise
        except Exception as e:
            raise AuthError(f"malformed token: {e}") from None
        exp = float(payload.get("exp", 0))
        if exp and exp < time.time():
            raise AuthError("token expired")
        return Claims(sub=str(payload.get("sub", "")),
                      email=str(payload.get("email", "")),
                      permissions=list(payload.get("permissions", [])),
                      tenant=str(payload.get("tenant", "default")),
                      exp=exp)


def _on_event_loop() -> bool:
    """True when the calling thread is running an asyncio event loop (the
    CP handshake / web authorize paths) — blocking there is forbidden."""
    import asyncio
    try:
        asyncio.get_running_loop()
        return True
    except RuntimeError:
        return False


class JwksAuth:
    """RS256 verification against a cached JWKS (auth.rs:26-38).

    `source` is a JWKS document location: an http(s) URL (the reference's
    `https://{domain}/.well-known/jwks.json`), a local file path (tests,
    air-gapped deploys), or an already-parsed dict. Keys are cached by
    `kid`; an unknown kid triggers ONE refetch (rate-limited to one per
    `refresh_cooldown_s`, the analog of the reference's semaphore-guarded
    JWKS cache) so key rotation works without restarting the CP.

    Verification enforces: RS256 alg, known kid, RSA-PKCS1v15-SHA256
    signature, `exp`, and — when configured — `iss` and `aud`. Permissions
    come from the `permissions` claim (Auth0 RBAC) with fallback to the
    space-separated `scope` claim. The CP cannot ISSUE tokens under this
    provider; issue() raises (the IdP owns identity)."""

    def __init__(self, source: Union[str, dict], issuer: Optional[str] = None,
                 audience: Optional[str] = None,
                 refresh_cooldown_s: float = 300.0):
        # ADVICE r3: signing keys fetched over cleartext can be swapped by
        # an on-path attacker, forging every identity the CP accepts.
        # Plain http is allowed only for loopback (the mock-IdP test rig).
        if isinstance(source, str) and source.startswith("http://"):
            host = urllib.parse.urlsplit(source).hostname or ""
            if host not in ("127.0.0.1", "localhost", "::1"):
                raise AuthError(
                    f"refusing cleartext JWKS source {source!r}: use https "
                    "or a local file path (http is allowed for loopback only)")
        self._source = source
        self._issuer = issuer
        self._audience = audience
        self._cooldown = refresh_cooldown_s
        self._keys: dict[str, object] = {}
        self._last_fetch = 0.0
        self._lock = threading.Lock()
        if isinstance(source, dict):
            self._install(source)
        else:
            self._refresh(force=True)

    # -- JWKS handling ----------------------------------------------------
    def _install(self, doc: dict) -> None:
        from cryptography.hazmat.primitives.asymmetric.rsa import (
            RSAPublicNumbers)
        keys = {}
        for k in doc.get("keys", []):
            if k.get("kty") != "RSA" or not k.get("kid"):
                continue
            try:
                n = int.from_bytes(_unb64url(k["n"]), "big")
                e = int.from_bytes(_unb64url(k["e"]), "big")
                keys[k["kid"]] = RSAPublicNumbers(e, n).public_key()
            except (KeyError, ValueError):
                continue
        self._keys = keys

    def _fetch(self) -> dict:
        src = self._source
        if isinstance(src, str) and src.startswith(("http://", "https://")):
            with urllib.request.urlopen(src, timeout=10) as resp:
                return json.loads(resp.read())
        if isinstance(src, str):
            return json.loads(Path(src).read_text())
        return src

    def _refresh(self, force: bool = False) -> Optional[threading.Thread]:
        """Refresh the key cache. Local/dict sources refresh inline (a
        disk read). An http(s) source refreshes in a BACKGROUND thread:
        verify() runs on the CP's event loop (protocol handshake, web
        _authorize), and a synchronous 10 s fetch there would stall every
        heartbeat and RPC in the process. The spawned thread is returned
        so the unknown-kid path can grant it a short bounded join (ADVICE
        r3): a fast fetch completes in-request and the rotated token
        verifies immediately; a slow fetch keeps the no-stall property and
        the client retries against the updated cache. `force` (constructor)
        fetches inline regardless: it runs before the server serves
        traffic and must fail loudly."""
        is_http = (isinstance(self._source, str)
                   and self._source.startswith(("http://", "https://")))
        with self._lock:
            now = time.monotonic()
            if not force and now - self._last_fetch < self._cooldown:
                return None
            self._last_fetch = now
        if force or not is_http:
            try:
                doc = self._fetch()
            except Exception as e:
                if force:
                    raise AuthError(
                        f"cannot load JWKS from {self._source!r}: {e}") \
                        from None
                return None  # rotation refetch failed: keep cached keys
            with self._lock:
                self._install(doc)
            return None

        def bg():
            try:
                doc = self._fetch()
            except Exception:
                return   # keep serving cached keys
            with self._lock:
                self._install(doc)

        t = threading.Thread(target=bg, name="jwks-refresh", daemon=True)
        t.start()
        return t

    # -- provider API -----------------------------------------------------
    def issue(self, email: str, permissions: list[str],
              tenant: str = "default", ttl_s: float = 86400.0) -> str:
        raise AuthError("JwksAuth cannot issue tokens; the external IdP "
                        "owns identity (use its device flow to log in)")

    def verify(self, token: Optional[str]) -> Claims:
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding

        if not token:
            raise AuthError("missing token")
        try:
            signing, _, sig_part = token.rpartition(".")
            header_part, _, payload_part = signing.partition(".")
            header = json.loads(_unb64url(header_part))
            sig = _unb64url(sig_part)
        except Exception as e:
            raise AuthError(f"malformed token: {e}") from None
        if header.get("alg") != "RS256":
            raise AuthError(f"unsupported alg {header.get('alg')!r}")
        kid = header.get("kid", "")
        key = self._keys.get(kid)
        if key is None:
            # key rotation: one cooldown-limited hit; give a background
            # http fetch up to 1.5s to land so the first post-rotation
            # verify usually succeeds in-request (ADVICE r3) — but NEVER
            # block the CP's event loop (a bogus-kid token is pre-auth
            # input, and the no-stall property is the whole point of the
            # background fetch): join only from plain threads.
            fetcher = self._refresh()
            if fetcher is not None and not _on_event_loop():
                fetcher.join(timeout=1.5)
            key = self._keys.get(kid)
        if key is None:
            raise AuthError(f"unknown signing key {kid!r}")
        try:
            key.verify(sig, signing.encode(), padding.PKCS1v15(),
                       hashes.SHA256())
        except InvalidSignature:
            raise AuthError("bad signature") from None
        try:
            payload = json.loads(_unb64url(payload_part))
        except Exception as e:
            raise AuthError(f"malformed payload: {e}") from None
        exp = float(payload.get("exp", 0))
        if not exp:
            # external tokens without expiry are irrevocable short of a
            # key rotation; a strict verifier refuses them
            raise AuthError("token missing exp")
        if exp < time.time():
            raise AuthError("token expired")
        if self._issuer and payload.get("iss") != self._issuer:
            raise AuthError(f"wrong issuer {payload.get('iss')!r}")
        if self._audience:
            aud = payload.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self._audience not in auds:
                raise AuthError(f"wrong audience {aud!r}")
        perms = list(payload.get("permissions", []))
        if not perms and payload.get("scope"):
            perms = str(payload["scope"]).split()
        return Claims(sub=str(payload.get("sub", "")),
                      email=str(payload.get("email", payload.get("sub", ""))),
                      permissions=perms,
                      tenant=str(payload.get("tenant", "default")),
                      exp=exp)


def make_provider(kind: str, secret: Optional[str] = None,
                  jwks: Optional[Union[str, dict]] = None,
                  issuer: Optional[str] = None,
                  audience: Optional[str] = None):
    """auth.rs AuthProviderKind enum dispatch."""
    if kind in ("none", "noauth", ""):
        return NoAuth()
    if kind in ("token", "jwt"):
        return TokenAuth(secret or "")
    if kind in ("jwks", "auth0", "oidc"):
        if not jwks:
            raise AuthError(f"{kind!r} auth requires a JWKS url/path")
        return JwksAuth(jwks, issuer=issuer, audience=audience)
    raise AuthError(f"unknown auth provider {kind!r}")
