"""Log router: pub/sub fan-out for container logs.

Analog of controlplane log_router.rs: topics named
`logs/{server}/{container}`, a retained ring buffer of 200 lines per topic
(:31), and subscribers with topic-prefix + minimum-level filters (:48-67).
Subscribers are asyncio queues; slow consumers drop oldest (bounded queues
never block the publisher — same motivation as the reference's lock-scope
discipline, agent_registry.rs:104-112).
"""

from __future__ import annotations

import asyncio
import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from .models import now_ts
from ..obs.metrics import REGISTRY

__all__ = ["LogEntry", "LogRouter", "RETAIN_LINES"]

RETAIN_LINES = 200  # log_router.rs:31

_LEVELS = {"trace": 0, "debug": 1, "info": 2, "warn": 3, "error": 4}

# metric catalog: docs/guide/10-observability.md
_M_PUBLISHED = REGISTRY.counter(
    "fleet_log_lines_published_total", "Lines published into the log router")
_M_DELIVERED = REGISTRY.counter(
    "fleet_log_lines_delivered_total", "Line deliveries to subscriber queues")
_M_DROPPED = REGISTRY.counter(
    "fleet_log_lines_dropped_total",
    "Lines evicted from full subscriber queues (slow consumers)")


@dataclass
class LogEntry:
    """log_router.rs:19."""
    topic: str
    line: str
    level: str = "info"
    ts: float = field(default_factory=now_ts)

    def to_dict(self) -> dict:
        return {"topic": self.topic, "line": self.line,
                "level": self.level, "ts": self.ts}


def topic_for(server: str, container: str) -> str:
    return f"logs/{server}/{container}"


@dataclass
class _Subscriber:
    id: int
    prefix: str
    min_level: int
    queue: asyncio.Queue
    # lines evicted from THIS subscriber's full queue — slow-consumer
    # drops were previously silent (satellite, ISSUE 3); the aggregate
    # rides fleet_log_lines_dropped_total
    dropped: int = 0


class LogRouter:
    def __init__(self, retain: int = RETAIN_LINES, queue_size: int = 1000):
        self._retained: dict[str, deque[LogEntry]] = {}
        self._subs: dict[int, _Subscriber] = {}
        self._ids = itertools.count(1)
        self.retain = retain
        self.queue_size = queue_size

    # ------------------------------------------------------------------
    def publish(self, entry: LogEntry) -> int:
        """Retain + fan out; returns delivered count (log_router.rs:67)."""
        ring = self._retained.setdefault(entry.topic,
                                         deque(maxlen=self.retain))
        ring.append(entry)
        _M_PUBLISHED.inc()
        delivered = 0
        lvl = _LEVELS.get(entry.level, 2)
        for sub in self._subs.values():
            if not entry.topic.startswith(sub.prefix):
                continue
            if lvl < sub.min_level:
                continue
            if sub.queue.full():        # drop oldest, never block
                try:
                    sub.queue.get_nowait()
                    sub.dropped += 1
                    _M_DROPPED.inc()
                except asyncio.QueueEmpty:
                    pass
            sub.queue.put_nowait(entry)
            delivered += 1
        if delivered:
            _M_DELIVERED.inc(delivered)
        return delivered

    def publish_line(self, server: str, container: str, line: str,
                     level: str = "info") -> int:
        return self.publish(LogEntry(topic=topic_for(server, container),
                                     line=line, level=level))

    # ------------------------------------------------------------------
    def subscribe(self, prefix: str = "logs/",
                  min_level: str = "trace") -> tuple[int, asyncio.Queue]:
        sid = next(self._ids)
        sub = _Subscriber(id=sid, prefix=prefix,
                          min_level=_LEVELS.get(min_level, 0),
                          queue=asyncio.Queue(self.queue_size))
        self._subs[sid] = sub
        return sid, sub.queue

    def unsubscribe(self, sid: int) -> None:
        self._subs.pop(sid, None)

    def subscriber(self, sid: int) -> Optional[_Subscriber]:
        """The live subscriber record (drop count and filters) — ops
        surfaces read `.dropped` to tell a slow consumer from a quiet
        topic."""
        return self._subs.get(sid)

    def backlog(self) -> tuple[int, list[dict]]:
        """(total queued lines, per-subscriber census) — the collector's
        deep gauge: the aggregate rides `/metrics`, the per-subscriber
        rows go TSDB-only (subscriber ids are unbounded cardinality).
        qsize() is a plain length read; safe from the sampler."""
        subs = [{"subscriber": s.id, "prefix": s.prefix,
                 "queued": s.queue.qsize(), "dropped": s.dropped}
                for s in self._subs.values()]
        return sum(s["queued"] for s in subs), subs

    # ------------------------------------------------------------------
    def retained(self, topic: str, limit: Optional[int] = None) -> list[LogEntry]:
        """The cached tail served to CLI/MCP/REST without touching the agent
        (web.rs:1074; mcp lib.rs:878)."""
        ring = self._retained.get(topic, ())
        rows = list(ring)
        return rows[-limit:] if limit else rows

    def topics(self, prefix: str = "logs/") -> list[str]:
        return sorted(t for t in self._retained if t.startswith(prefix))
