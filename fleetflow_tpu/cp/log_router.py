"""Log router: pub/sub fan-out for container logs.

Analog of controlplane log_router.rs: topics named
`logs/{server}/{container}`, a retained ring buffer of 200 lines per topic
(:31), and subscribers with topic-prefix + minimum-level filters (:48-67).
Subscribers drain lane queues; slow consumers drop oldest (bounded lanes
never block the publisher — same motivation as the reference's lock-scope
discipline, agent_registry.rs:104-112).

Sharded backpressure (docs/guide/17-cp-sharding.md): each subscriber's
buffer is split into PER-SHARD LANES keyed by the publishing agent's
shard (cp/shards.py hashes the topic's server segment). A log storm from
one shard's agents — or a consumer stuck mid-drain on one shard's
output — fills and drops only that shard's lane; every other shard's
lines keep flowing to the same subscriber. Drops are counted per lane
(`fleet_cp_shard_log_dropped_total{shard=}`) on top of the aggregate,
so "which partition is being flooded" is one metric query. A router
without a shard table degrades to a single lane with the exact bounded
drop-oldest semantics the unsharded router had.
"""

from __future__ import annotations

import asyncio
import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from .models import now_ts
from .shards import ShardTable
from ..obs.metrics import REGISTRY

__all__ = ["LogEntry", "LogRouter", "RETAIN_LINES"]

RETAIN_LINES = 200  # log_router.rs:31

_LEVELS = {"trace": 0, "debug": 1, "info": 2, "warn": 3, "error": 4}

# metric catalog: docs/guide/10-observability.md
_M_PUBLISHED = REGISTRY.counter(
    "fleet_log_lines_published_total", "Lines published into the log router")
_M_DELIVERED = REGISTRY.counter(
    "fleet_log_lines_delivered_total", "Line deliveries to subscriber queues")
_M_DROPPED = REGISTRY.counter(
    "fleet_log_lines_dropped_total",
    "Lines evicted from full subscriber queues (slow consumers)")


@dataclass
class LogEntry:
    """log_router.rs:19."""
    topic: str
    line: str
    level: str = "info"
    ts: float = field(default_factory=now_ts)

    def to_dict(self) -> dict:
        return {"topic": self.topic, "line": self.line,
                "level": self.level, "ts": self.ts}


def topic_for(server: str, container: str) -> str:
    return f"logs/{server}/{container}"


class _LaneQueue:
    """Per-shard lane buffers behind an asyncio.Queue-shaped facade.

    Consumers keep the queue API they always had (`await get()`,
    `get_nowait()`, `qsize()`, `empty()`); internally each publishing
    shard owns a bounded deque of `lane_size` lines, and a ready-token
    queue (one token per buffered line, in publish order) wakes the
    reader. Drop-oldest within a lane evicts a line AND leaves the token
    count intact (one out, one in), so tokens == buffered lines always.
    """

    def __init__(self, lane_size: int):
        self.lane_size = lane_size
        self._lanes: dict[int, deque[LogEntry]] = {}
        self._ready: asyncio.Queue[int] = asyncio.Queue()

    # -- publisher side (router only) ----------------------------------
    def _push(self, shard: int, entry: LogEntry) -> bool:
        """Append to the shard's lane; returns False when the lane was
        full and its oldest line was evicted to make room."""
        lane = self._lanes.get(shard)
        if lane is None:
            lane = self._lanes[shard] = deque()
        if len(lane) >= self.lane_size:
            lane.popleft()              # drop oldest, never block
            lane.append(entry)
            return False
        lane.append(entry)
        self._ready.put_nowait(shard)
        return True

    def _pop(self, shard: int) -> LogEntry:
        return self._lanes[shard].popleft()

    # -- consumer side (asyncio.Queue surface) -------------------------
    async def get(self) -> LogEntry:
        return self._pop(await self._ready.get())

    def get_nowait(self) -> LogEntry:
        return self._pop(self._ready.get_nowait())   # raises QueueEmpty

    def qsize(self) -> int:
        return self._ready.qsize()

    def empty(self) -> bool:
        return self._ready.empty()

    def full(self) -> bool:
        """Every populated lane at capacity — diagnostic only; the
        router checks individual lanes, not the whole subscriber."""
        return bool(self._lanes) and all(
            len(lane) >= self.lane_size for lane in self._lanes.values())


@dataclass
class _Subscriber:
    id: int
    prefix: str
    min_level: int
    queue: _LaneQueue
    # lines evicted from THIS subscriber's full lanes — slow-consumer
    # drops were previously silent (satellite, ISSUE 3); the aggregate
    # rides fleet_log_lines_dropped_total, the per-shard split
    # fleet_cp_shard_log_dropped_total
    dropped: int = 0
    dropped_by_shard: dict = field(default_factory=dict)


class LogRouter:
    def __init__(self, retain: int = RETAIN_LINES, queue_size: int = 1000,
                 shard_table: Optional[ShardTable] = None):
        self._retained: dict[str, deque[LogEntry]] = {}
        self._subs: dict[int, _Subscriber] = {}
        self._ids = itertools.count(1)
        self.retain = retain
        # per-LANE capacity: sharding must never shrink what a consumer
        # of a single agent's logs could buffer before drops started
        self.queue_size = queue_size
        self.shard_table = shard_table

    def _shard_of_topic(self, topic: str) -> int:
        if self.shard_table is None:
            return 0
        # topic layout logs/{server}/{container}: the SERVER owns the
        # line, so its lane is the publishing agent's registry shard
        parts = topic.split("/", 2)
        return self.shard_table.shard_of(parts[1] if len(parts) > 1 else "")

    # ------------------------------------------------------------------
    def publish(self, entry: LogEntry) -> int:
        """Retain + fan out; returns delivered count (log_router.rs:67)."""
        ring = self._retained.setdefault(entry.topic,
                                         deque(maxlen=self.retain))
        ring.append(entry)
        _M_PUBLISHED.inc()
        delivered = 0
        lvl = _LEVELS.get(entry.level, 2)
        shard = self._shard_of_topic(entry.topic)   # once per entry
        for sub in self._subs.values():
            if not entry.topic.startswith(sub.prefix):
                continue
            if lvl < sub.min_level:
                continue
            if not sub.queue._push(shard, entry):
                sub.dropped += 1
                sub.dropped_by_shard[shard] = (
                    sub.dropped_by_shard.get(shard, 0) + 1)
                _M_DROPPED.inc()
                if self.shard_table is not None:
                    self.shard_table.count_log_drop(shard)
            delivered += 1
        if delivered:
            _M_DELIVERED.inc(delivered)
        return delivered

    def publish_line(self, server: str, container: str, line: str,
                     level: str = "info") -> int:
        return self.publish(LogEntry(topic=topic_for(server, container),
                                     line=line, level=level))

    # ------------------------------------------------------------------
    def subscribe(self, prefix: str = "logs/",
                  min_level: str = "trace") -> tuple[int, _LaneQueue]:
        sid = next(self._ids)
        sub = _Subscriber(id=sid, prefix=prefix,
                          min_level=_LEVELS.get(min_level, 0),
                          queue=_LaneQueue(self.queue_size))
        self._subs[sid] = sub
        return sid, sub.queue

    def unsubscribe(self, sid: int) -> None:
        self._subs.pop(sid, None)

    def subscriber(self, sid: int) -> Optional[_Subscriber]:
        """The live subscriber record (drop count and filters) — ops
        surfaces read `.dropped` to tell a slow consumer from a quiet
        topic."""
        return self._subs.get(sid)

    def backlog(self) -> tuple[int, list[dict]]:
        """(total queued lines, per-subscriber census) — the collector's
        deep gauge: the aggregate rides `/metrics`, the per-subscriber
        rows go TSDB-only (subscriber ids are unbounded cardinality).
        qsize() is a plain length read; safe from the sampler."""
        subs = [{"subscriber": s.id, "prefix": s.prefix,
                 "queued": s.queue.qsize(), "dropped": s.dropped}
                for s in self._subs.values()]
        return sum(s["queued"] for s in subs), subs

    # ------------------------------------------------------------------
    def retained(self, topic: str, limit: Optional[int] = None) -> list[LogEntry]:
        """The cached tail served to CLI/MCP/REST without touching the agent
        (web.rs:1074; mcp lib.rs:878)."""
        ring = self._retained.get(topic, ())
        rows = list(ring)
        return rows[-limit:] if limit else rows

    def topics(self, prefix: str = "logs/") -> list[str]:
        return sorted(t for t in self._retained if t.startswith(prefix))
