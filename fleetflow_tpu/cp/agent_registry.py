"""Agent registry: routing commands to connected node agents.

Analog of controlplane agent_registry.rs: an in-memory map server_slug ->
live connection, request/response with per-call timeouts (60s default,
600s deploys, 1800s builds — agent_registry.rs:86-96), fire-and-forget
sends, and re-register-overwrites-previous semantics (:51-53).

The correlation contract matches the reference exactly (handlers/agent.rs
:97-112 + fleet-agent agent.rs:215-254): the CP wraps each command as
{"request_id": ..., "payload": ...} and the agent answers with a
`command_result` EVENT carrying the same request_id — not a protocol-level
response — which the registry correlates back to the waiting future.
"""

from __future__ import annotations

import asyncio
import itertools
import time
from typing import Callable, Optional, Sequence, Union

from ..core.errors import (AgentCommandError, AgentCommandFailed,
                           AgentUnreachable, ControlPlaneError)
from ..obs import get_logger, kv
from ..obs.metrics import REGISTRY
from .protocol import Connection
from .shards import ShardTable

log = get_logger("cp.agents")

# metric catalog: docs/guide/10-observability.md
# The gauge is process-global with last-writer-wins set() semantics: a
# production daemon has exactly one registry, and in multi-registry
# processes (tests, chaos worlds run back-to-back) it reflects whichever
# registry mutated last — which is what the chaos invariant
# (agents_gauge_consistent) relies on, since every world's bootstrap
# registers its own agents before any check runs.
_M_CONNECTED = REGISTRY.gauge(
    "fleet_agents_connected", "Node agents with a live registered session")
_M_REGISTRATIONS = REGISTRY.counter(
    "fleet_agent_registrations_total", "Agent register calls accepted")
_M_COMMANDS = REGISTRY.counter(
    "fleet_agent_commands_total", "Commands sent to agents, by command",
    labels=("command",))
_M_COMMAND_ERRORS = REGISTRY.counter(
    "fleet_agent_command_errors_total",
    "Agent commands that failed, by reason",
    labels=("reason",))

__all__ = ["AgentRegistry", "DEFAULT_TIMEOUT", "DEPLOY_TIMEOUT",
           "BUILD_TIMEOUT", "PER_SHARD_CONCURRENCY"]

DEFAULT_TIMEOUT = 60.0     # agent_registry.rs:86
DEPLOY_TIMEOUT = 600.0     # :94 (sized for image pulls)
BUILD_TIMEOUT = 1800.0     # :95

# Pipeline depth per shard lane for send_batch: up to this many commands
# of one shard's batch slice are in flight at once. Sized so a 10k-agent
# fan-out across 4 shards keeps the wire busy without unbounded task
# creation hammering one slow shard's agents.
PER_SHARD_CONCURRENCY = 32

# one batch item: (slug, command, payload)
BatchItem = tuple[str, str, Optional[dict]]


class AgentRegistry:
    def __init__(self, shard_table: Optional[ShardTable] = None):
        self._agents: dict[str, Connection] = {}
        self._principals: dict[str, str] = {}   # slug -> auth principal
        self._pending: dict[str, asyncio.Future] = {}
        # request_id -> the connection the command went to, so a
        # disconnect can fail its in-flight commands IMMEDIATELY instead
        # of letting callers sit out the full per-call timeout (a deploy
        # to a crashing agent would otherwise stall up to 600 s)
        self._pending_conn: dict[str, Connection] = {}
        # request_id -> owning shard, for the per-shard in-flight census
        self._pending_shard: dict[str, int] = {}
        self._ids = itertools.count(1)
        # Shard partitioning (cp/shards.py): every agent belongs to one
        # worker shard; send_batch pipelines each shard's batch slice
        # under that shard's concurrency bound. A registry without a
        # table (unit tests, tiny fleets) is one shard that owns all.
        self.shard_table = shard_table
        self._shard_counts: dict[int, int] = {}
        # shard id -> pipeline semaphore; rebuilt when the running loop
        # changes (tests spin a fresh loop per case)
        self._shard_sems: dict[int, asyncio.Semaphore] = {}
        self._sems_loop: Optional[asyncio.AbstractEventLoop] = None
        # stats of the most recent send_batch, pinned by the bench
        # (BENCH_AGENTS_ASSERT): label_lookups < items proves the
        # per-command metric lookups stayed coalesced out of the loop
        self.last_batch_stats: dict = {}
        # delivery hook: fn(slug, command) consulted before every command
        # send. Raising ControlPlaneError surfaces to the caller exactly
        # like a dead-agent send failure — the chaos harness injects
        # partitions/latency here; it doubles as an extension point for
        # per-command routing policy (rate limits, circuit breakers).
        self.delivery_hook: Optional[Callable[[str, str], None]] = None
        # fencing (docs/guide/13-cp-replication.md): when set, every
        # command envelope is stamped with the CP's current epoch; agents
        # that have seen a newer epoch refuse the command — a zombie
        # ex-primary cannot drive stale deploys through a window it no
        # longer owns
        self.epoch_source: Optional[Callable[[], int]] = None

    # ------------------------------------------------------------------
    def register(self, slug: str, conn: Connection,
                 principal: str = "") -> None:
        """Bind slug -> live connection + auth principal.

        The reference lets any re-registration overwrite the previous
        session (agent_registry.rs:51-53) — fine when every agent is
        trusted, but it lets one compromised client hijack another node's
        command stream (VERDICT r3 weak #7). Here the reconnect-wins
        semantics are kept only for the *same principal* (claims subject,
        or handshake identity when unauthenticated): a register for a slug
        whose current session is still live under a different principal is
        refused, and commands keep routing to the original session.

        The fence is only as strong as the principal: under NoAuth the
        principal is the client-chosen hello identity, and a shared token
        gives every node the same subject — mint per-node agent tokens
        (`fleet cp token --email agent@<slug> --permissions write:agent`)
        for it to bite. If a rogue session does hold a slug, the operator
        escape hatch is `server delete <slug>`, which evicts the live
        session (handlers._server delete).
        """
        existing = self._agents.get(slug)
        if (existing is not None and existing is not conn
                and not getattr(existing, "_closed", False)
                and principal != self._principals.get(slug, principal)):
            log.warning("register refused %s", kv(
                slug=slug, principal=principal,
                holder=self._principals.get(slug, "")))
            raise ControlPlaneError(
                f"agent slug {slug!r} is already registered by a live "
                f"session under a different identity")
        fresh = slug not in self._agents
        self._agents[slug] = conn
        self._principals[slug] = principal
        _M_REGISTRATIONS.inc()
        _M_CONNECTED.set(len(self._agents))
        if fresh:
            self._shard_census_delta(slug, +1)

    def unregister(self, slug: str, conn: Optional[Connection] = None) -> None:
        if conn is None or self._agents.get(slug) is conn:
            if slug in self._agents:
                self._shard_census_delta(slug, -1)
            self._agents.pop(slug, None)
            self._principals.pop(slug, None)
            _M_CONNECTED.set(len(self._agents))
        # fail the dead session's in-flight commands NOW — their results
        # can never arrive, and callers (deploys especially) must not sit
        # out the full per-call timeout against a crashed agent
        if conn is not None:
            for rid, c in list(self._pending_conn.items()):
                if c is conn:
                    fut = self._pending.get(rid)
                    if fut is not None and not fut.done():
                        fut.set_exception(AgentUnreachable(
                            f"agent {slug!r} disconnected mid-command",
                            reason="disconnected"))

    def is_connected(self, slug: str) -> bool:
        return slug in self._agents

    def list_connected(self) -> list[str]:
        return sorted(self._agents)

    def connection_of(self, slug: str) -> Optional[Connection]:
        return self._agents.get(slug)

    def inflight(self) -> int:
        """Commands awaiting a command_result — the fan-out depth the
        obs collector samples (TSDB series fleet_agent_commands_in_flight):
        ROADMAP item 3's registry bottleneck shows up here first."""
        return len(self._pending)

    # ------------------------------------------------------------------
    # shard partition bookkeeping (cp/shards.py)
    # ------------------------------------------------------------------

    def shard_of(self, slug: str) -> int:
        return self.shard_table.shard_of(slug) if self.shard_table else 0

    def _shard_census_delta(self, slug: str, delta: int) -> None:
        shard = self.shard_of(slug)
        n = self._shard_counts.get(shard, 0) + delta
        self._shard_counts[shard] = max(n, 0)
        if self.shard_table is not None:
            self.shard_table.set_shard_agents(self._shard_counts)

    def _shard_sem(self, shard: int) -> asyncio.Semaphore:
        loop = asyncio.get_running_loop()
        if loop is not self._sems_loop:
            self._shard_sems = {}
            self._sems_loop = loop
        sem = self._shard_sems.get(shard)
        if sem is None:
            sem = self._shard_sems[shard] = asyncio.Semaphore(
                PER_SHARD_CONCURRENCY)
        return sem

    def rebalance(self, shards: int) -> int:
        """Resize the shard table (FLEET_CP_SHARDS changed on a live CP)
        and re-bucket the census. No persistent state: the connected-set
        IS the journaled server/lease population, and every mapping is
        recomputed from (slug, new count). Returns moved-slug count."""
        if self.shard_table is None:
            return 0
        moved = self.shard_table.resize(shards, self._agents.keys())
        counts: dict[int, int] = {}
        for slug in self._agents:
            s = self.shard_table.shard_of(slug)
            counts[s] = counts.get(s, 0) + 1
        self._shard_counts = counts
        self.shard_table.set_shard_agents(counts)
        return moved

    def shard_census(self) -> list[dict]:
        """Per-shard occupancy + in-flight depth, sorted by shard id —
        the `fleet cp heal status` / `fleet top` shard rows."""
        shards = self.shard_table.shards if self.shard_table else 1
        pending: dict[int, int] = {}
        for sid in self._pending_shard.values():
            pending[sid] = pending.get(sid, 0) + 1
        return [{"shard": s,
                 "agents": self._shard_counts.get(s, 0),
                 "inflight": pending.get(s, 0)}
                for s in range(shards)]

    # ------------------------------------------------------------------
    async def send_command(self, slug: str, command: str,
                           payload: dict | None = None,
                           timeout: float = DEFAULT_TIMEOUT) -> dict:
        """Request/response via the command_result correlation protocol
        (agent_registry.rs send_command_with_timeout:97-134).

        Failures are STRUCTURED (core.errors): `AgentUnreachable`
        (retryable — dead/absent session, timeout, delivery refused; the
        command may never have arrived) vs `AgentCommandFailed` (fatal —
        the agent executed it and reported an error). The reconverger and
        handler callers branch on `.retryable`/type instead of
        string-matching one opaque exception. Both subclass
        ControlPlaneError, so pre-existing catch sites keep working."""
        epoch = self.epoch_source() if self.epoch_source is not None else None
        return await self._send_one(slug, command, payload, timeout,
                                    epoch=epoch, metered=True)

    async def _send_one(self, slug: str, command: str,
                        payload: Optional[dict], timeout: float, *,
                        epoch: Optional[int], metered: bool) -> dict:
        """One command send/await. `metered=False` is the batch path:
        the per-command counter and the fencing epoch were already
        resolved ONCE for the whole batch (coalesced out of the await
        loop — at 10k items the per-call label-key set comparison and
        epoch indirection are measurable in the fan-out profile)."""
        conn = self._agents.get(slug)
        if conn is None:
            _M_COMMAND_ERRORS.inc(reason="not-connected")
            raise AgentUnreachable(f"agent {slug!r} is not connected",
                                   reason="not-connected")
        if self.delivery_hook is not None:
            try:
                self.delivery_hook(slug, command)
            except AgentCommandError:
                _M_COMMAND_ERRORS.inc(reason="delivery")
                raise
            except ControlPlaneError as e:
                # hook contract: a raise means "the send failed" — which
                # is a transport failure, i.e. retryable
                _M_COMMAND_ERRORS.inc(reason="delivery")
                raise AgentUnreachable(str(e), reason="delivery") from e
        if metered:
            _M_COMMANDS.inc(command=command)
        request_id = f"req_{next(self._ids)}"
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        self._pending_conn[request_id] = conn
        self._pending_shard[request_id] = self.shard_of(slug)
        envelope = {"request_id": request_id, "payload": payload or {}}
        if epoch is not None:
            envelope["epoch"] = epoch
        try:
            await conn.send_event("agent", command, envelope)
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            _M_COMMAND_ERRORS.inc(reason="timeout")
            raise AgentUnreachable(
                f"agent {slug!r} command {command!r} timed out "
                f"after {timeout:.0f}s", reason="timeout") from None
        except AgentCommandError as e:
            _M_COMMAND_ERRORS.inc(reason=e.reason)
            raise
        except ControlPlaneError as e:
            # a raw send_event failure (socket died under the write) is a
            # transport failure like any other: classify it retryable
            _M_COMMAND_ERRORS.inc(reason="send")
            raise AgentUnreachable(str(e), reason="send") from e
        finally:
            self._pending.pop(request_id, None)
            self._pending_conn.pop(request_id, None)
            self._pending_shard.pop(request_id, None)
            # if the disconnect path set an exception while send_event was
            # failing, retrieve it so asyncio doesn't log "exception was
            # never retrieved" at GC
            if fut.done() and not fut.cancelled():
                fut.exception()

    async def send_batch(self, items: Sequence[BatchItem], *,
                         timeout: float = DEFAULT_TIMEOUT
                         ) -> list[Union[dict, BaseException]]:
        """Shard-parallel batched delivery: the reconverger and deploy
        engine hand the registry a whole fan-out at once instead of
        gathering one-future-per-command. Each item is routed to its
        owning shard's pipeline lane and at most PER_SHARD_CONCURRENCY
        of a lane's items are in flight at a time — bounded pressure per
        shard, full parallelism across shards.

        Returns results aligned with `items` (a result dict, or the
        exception that send raised — the asyncio.gather
        return_exceptions=True shape the callers already classify).
        Per-item failures never abort the batch: a member disconnecting
        mid-batch fails only its own in-flight futures (the `_pending`
        fast-fail contract in unregister()).

        Batch-level coalescing (vs the per-call path): one per-command
        counter bump per DISTINCT command, one fencing-epoch resolution
        for the whole batch — `last_batch_stats` exposes the counts the
        bench pins (BENCH_AGENTS_ASSERT=1)."""
        items = list(items)
        if not items:
            self.last_batch_stats = {"items": 0, "label_lookups": 0,
                                     "epoch_lookups": 0, "shards": 0}
            return []
        counts: dict[str, int] = {}
        for _, command, _ in items:
            counts[command] = counts.get(command, 0) + 1
        for command, n in counts.items():
            _M_COMMANDS.inc(n, command=command)
        epoch = self.epoch_source() if self.epoch_source is not None else None
        shards = [self.shard_of(slug) for slug, _, _ in items]
        t0 = time.perf_counter()
        done_at: dict[int, float] = {}

        async def run(shard: int, slug: str, command: str,
                      payload: Optional[dict]) -> dict:
            async with self._shard_sem(shard):
                try:
                    return await self._send_one(slug, command, payload,
                                                timeout, epoch=epoch,
                                                metered=False)
                finally:
                    done_at[shard] = time.perf_counter()

        # tasks start in item order: in production the per-shard
        # semaphores pipeline each lane independently; under the chaos
        # harness's inline sim transport nothing blocks, so execution
        # stays in creation order and schedules replay digest-stable
        tasks = [asyncio.ensure_future(run(shard, slug, command, payload))
                 for shard, (slug, command, payload) in zip(shards, items)]
        results = await asyncio.gather(*tasks, return_exceptions=True)
        if self.shard_table is not None:
            for shard, at in sorted(done_at.items()):
                self.shard_table.observe_fanout_ms(
                    shard, (at - t0) * 1000.0)
        self.last_batch_stats = {
            "items": len(items), "label_lookups": len(counts),
            "epoch_lookups": 0 if epoch is None else 1,
            "shards": len(done_at)}
        return list(results)

    async def fire_and_forget(self, slug: str, command: str,
                              payload: dict | None = None) -> None:
        conn = self._agents.get(slug)
        if conn is None:
            raise AgentUnreachable(f"agent {slug!r} is not connected",
                                   reason="not-connected")
        if self.delivery_hook is not None:
            self.delivery_hook(slug, command)
        _M_COMMANDS.inc(command=command)
        envelope = {"request_id": None, "payload": payload or {}}
        if self.epoch_source is not None:
            envelope["epoch"] = self.epoch_source()
        await conn.send_event("agent", command, envelope)

    def resolve_result(self, request_id: str, payload: dict) -> bool:
        """Called by the agent channel handler on an inbound command_result
        event (handlers/agent.rs:97-112). Returns False for unknown/expired
        ids (late results after timeout are dropped, like the reference)."""
        fut = self._pending.get(request_id)
        if fut is None or fut.done():
            return False
        if payload.get("error"):
            # the agent ran the command and said no: NOT retryable
            fut.set_exception(AgentCommandFailed(str(payload["error"])))
        else:
            fut.set_result(payload.get("result", payload))
        return True
