"""Agent registry: routing commands to connected node agents.

Analog of controlplane agent_registry.rs: an in-memory map server_slug ->
live connection, request/response with per-call timeouts (60s default,
600s deploys, 1800s builds — agent_registry.rs:86-96), fire-and-forget
sends, and re-register-overwrites-previous semantics (:51-53).

The correlation contract matches the reference exactly (handlers/agent.rs
:97-112 + fleet-agent agent.rs:215-254): the CP wraps each command as
{"request_id": ..., "payload": ...} and the agent answers with a
`command_result` EVENT carrying the same request_id — not a protocol-level
response — which the registry correlates back to the waiting future.
"""

from __future__ import annotations

import asyncio
import itertools
from typing import Optional

from ..core.errors import ControlPlaneError
from .protocol import Connection

__all__ = ["AgentRegistry", "DEFAULT_TIMEOUT", "DEPLOY_TIMEOUT",
           "BUILD_TIMEOUT"]

DEFAULT_TIMEOUT = 60.0     # agent_registry.rs:86
DEPLOY_TIMEOUT = 600.0     # :94 (sized for image pulls)
BUILD_TIMEOUT = 1800.0     # :95


class AgentRegistry:
    def __init__(self):
        self._agents: dict[str, Connection] = {}
        self._pending: dict[str, asyncio.Future] = {}
        self._ids = itertools.count(1)

    # ------------------------------------------------------------------
    def register(self, slug: str, conn: Connection) -> None:
        """Re-registration overwrites the previous session
        (agent_registry.rs:51-53): a reconnecting agent wins."""
        self._agents[slug] = conn

    def unregister(self, slug: str, conn: Optional[Connection] = None) -> None:
        if conn is None or self._agents.get(slug) is conn:
            self._agents.pop(slug, None)

    def is_connected(self, slug: str) -> bool:
        return slug in self._agents

    def list_connected(self) -> list[str]:
        return sorted(self._agents)

    def connection_of(self, slug: str) -> Optional[Connection]:
        return self._agents.get(slug)

    # ------------------------------------------------------------------
    async def send_command(self, slug: str, command: str,
                           payload: dict | None = None,
                           timeout: float = DEFAULT_TIMEOUT) -> dict:
        """Request/response via the command_result correlation protocol
        (agent_registry.rs send_command_with_timeout:97-134)."""
        conn = self._agents.get(slug)
        if conn is None:
            raise ControlPlaneError(f"agent {slug!r} is not connected")
        request_id = f"req_{next(self._ids)}"
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        try:
            await conn.send_event("agent", command, {
                "request_id": request_id, "payload": payload or {}})
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise ControlPlaneError(
                f"agent {slug!r} command {command!r} timed out "
                f"after {timeout:.0f}s") from None
        finally:
            self._pending.pop(request_id, None)

    async def fire_and_forget(self, slug: str, command: str,
                              payload: dict | None = None) -> None:
        conn = self._agents.get(slug)
        if conn is None:
            raise ControlPlaneError(f"agent {slug!r} is not connected")
        await conn.send_event("agent", command,
                              {"request_id": None, "payload": payload or {}})

    def resolve_result(self, request_id: str, payload: dict) -> bool:
        """Called by the agent channel handler on an inbound command_result
        event (handlers/agent.rs:97-112). Returns False for unknown/expired
        ids (late results after timeout are dropped, like the reference)."""
        fut = self._pending.get(request_id)
        if fut is None or fut.done():
            return False
        if payload.get("error"):
            fut.set_exception(ControlPlaneError(str(payload["error"])))
        else:
            fut.set_result(payload.get("result", payload))
        return True
