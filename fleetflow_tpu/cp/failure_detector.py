"""Lease-based failure detection: missed heartbeats become verdicts.

The CP records every heartbeat (store.heartbeat, fleet_heartbeats_total)
but nothing ever turned a MISSED heartbeat into a node event — a killed
agent stranded its services until an operator called placement.node_event
by hand. This module is the missing half: each agent holds a lease renewed
by its heartbeats; an expired lease moves the agent through a
suspect -> dead state machine whose DEAD verdicts the reconverger
(cp/reconverge.py) turns into coalesced churn re-solves and redeploys.
Borg makes automatic re-placement after machine failure the defining
control-plane behavior (Verma et al., EuroSys '15 §3.1); crash-only design
(Candea & Fox, HotOS '03) wants recovery to be the normal code path — so
the detector is always on, cheap, and driven by the same sweep whether the
clock is wall time or the chaos harness's virtual clock.

State machine per agent:

    ALIVE --lease expired / disconnect--> SUSPECT
    SUSPECT --heartbeat--> ALIVE            (silent revive: no verdict)
    SUSPECT --grace expired--> DEAD         (verdict: reconverge)
    DEAD --heartbeat--> ALIVE               (verdict: node online, unpark)

Verdicts are only the DEAD and DEAD->ALIVE transitions — the expensive
ones, each costing a warm re-solve + redeploy fan-out. SUSPECT is free and
absorbs fast reconnects (an agent session bounce never reaches the solver).

Flap damping: a bouncing agent (crashlooping host, flapping link) would
otherwise emit a dead verdict per bounce and trigger a re-solve storm.
The detector counts verdicts per agent in a rolling window; past
`flap_threshold` the agent is DAMPED — further dead verdicts are held
until it has been continuously suspect for `damp_hold_s` (hysteresis: one
verdict per hold period at most). Revive verdicts are never held: retrying
parked work against a returned node is cheap and correct.

Thread-safe (heartbeats land on the asyncio loop; sweeps may run on
executor threads). The clock is injectable and MONOTONIC — wall-clock
jumps must not kill a fleet (time.monotonic in production, the chaos
VirtualClock in tests/scenarios).

Sweep cost (ISSUE 19): the sweep used to scan EVERY lease under the
lock on every tick — O(agents) per tick, and at 10k leases the scan
dominated the reconverge loop while holding the lock heartbeats need.
The default sweep now pops a min-expiry heap of attention times (lease
deadlines / suspect-grace expiries / damp-hold releases): a quiet fleet
costs O(expired · log n) per sweep, independent of fleet size.
Heartbeats invalidate LAZILY — renewing a lease just moves its
deadline; the stale heap entry pops at the old deadline, re-derives the
lease's real state, and re-schedules itself. Entry staleness is tracked
with per-lease generation counters; `use_heap=False` retains the full
scan, which doubles as the property-test oracle (the two sweeps must
emit identical verdict streams on any schedule) and the bench's
unsharded baseline.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..obs import get_logger, kv
from ..obs.metrics import REGISTRY

log = get_logger("cp.lease")

__all__ = ["LeaseConfig", "LeaseEvent", "FailureDetector",
           "ALIVE", "SUSPECT", "DEAD"]

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"

# metric catalog: docs/guide/10-observability.md
_M_TRANSITIONS = REGISTRY.counter(
    "fleet_lease_transitions_total",
    "Lease state-machine transitions, by target state", labels=("to",))
_M_AGENTS = REGISTRY.gauge(
    "fleet_lease_agents", "Agents tracked by the failure detector, by "
    "lease state", labels=("state",))
_M_DAMPED = REGISTRY.counter(
    "fleet_lease_flap_damped_total",
    "Dead verdicts deferred by flap damping (hysteresis holds)")


@dataclass
class LeaseConfig:
    """Tuning knobs (docs/guide/12-self-healing.md has the sizing math).

    `lease_s` should be >= 3x the agent heartbeat interval: one lost
    heartbeat must not start the clock toward a re-solve. The detection
    budget for a hard-killed node is lease_s + suspect_grace_s (a
    disconnect fast-paths to SUSPECT, so a crashed session pays only
    suspect_grace_s)."""
    lease_s: float = 90.0            # silence this long -> SUSPECT
    suspect_grace_s: float = 30.0    # suspect this long -> DEAD verdict
    flap_window_s: float = 600.0     # rolling window for verdict counting
    flap_threshold: int = 3          # >= verdicts in window -> damped
    damp_hold_s: float = 180.0       # damped: continuous-suspect hold


@dataclass
class LeaseEvent:
    """One verdict: `online=False` (DEAD) or `online=True` (revive).
    `at` is detector-clock time; `state` the new lease state."""
    slug: str
    online: bool
    at: float
    state: str


@dataclass
class _Lease:
    deadline: float = 0.0            # heartbeat lease expiry
    state: str = ALIVE
    suspect_since: float = 0.0
    connected: bool = True
    # verdict timestamps (dead + revive) for flap counting
    verdicts: deque = field(default_factory=lambda: deque(maxlen=32))
    damped_logged: bool = False      # one damped log/metric per hold
    # generation of this lease's live min-expiry-heap entry; -1 = no
    # timed attention scheduled (DEAD leases wait on a heartbeat, not
    # the clock). A popped entry with a stale generation is discarded.
    gen: int = -1


class FailureDetector:
    def __init__(self, config: Optional[LeaseConfig] = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 use_heap: bool = True):
        self.config = config or LeaseConfig()
        self.clock = clock
        self.use_heap = use_heap
        self._lock = threading.Lock()
        self._leases: dict[str, _Lease] = {}
        self._pending: list[LeaseEvent] = []   # revives awaiting a sweep
        # min-expiry heap of (attention_time, slug, generation)
        self._heap: list[tuple[float, str, int]] = []
        self._gen = 0
        # incremental per-state census (the fleet_lease_agents gauge
        # without an O(agents) recount per sweep)
        self._counts = {ALIVE: 0, SUSPECT: 0, DEAD: 0}

    # ------------------------------------------------------------------
    # observations (called from the agent channel / registry paths)
    # ------------------------------------------------------------------

    def _schedule(self, slug: str, lease: _Lease, at: float) -> None:
        """(Re)arm the lease's heap entry; any previous entry for the
        slug goes stale (generation mismatch) and is dropped on pop."""
        if not self.use_heap:
            return
        self._gen += 1
        lease.gen = self._gen
        heapq.heappush(self._heap, (at, slug, self._gen))

    def observe_heartbeat(self, slug: str) -> None:
        """Renew the lease. A heartbeat from a SUSPECT agent revives it
        silently; from a DEAD one it queues a node-online verdict (the
        reconverger retries parked work against returned capacity).

        Heap note: renewing an ALIVE lease does NOT touch the heap (the
        10k-agents-heartbeating hot path) — the entry at the old
        deadline lazily re-derives and re-arms itself when it pops."""
        now = self.clock()
        with self._lock:
            lease = self._leases.get(slug)
            if lease is None:
                lease = self._leases[slug] = _Lease()
                self._counts[ALIVE] += 1
                _M_TRANSITIONS.inc(to=ALIVE)
            lease.deadline = now + self.config.lease_s
            lease.connected = True
            if lease.gen == -1:
                # fresh lease, or revive of a DEAD one (no timed
                # attention while dead): arm the expiry timer
                self._schedule(slug, lease, lease.deadline)
            if lease.state == ALIVE:
                return
            was = lease.state
            lease.state = ALIVE
            lease.damped_logged = False
            self._counts[was] -= 1
            self._counts[ALIVE] += 1
            _M_TRANSITIONS.inc(to=ALIVE)
            log.info("agent revived %s", kv(slug=slug, was=was))
            if was == DEAD:
                lease.verdicts.append(now)
                self._pending.append(LeaseEvent(slug, True, now, ALIVE))

    def prime(self, slug: str) -> None:
        """Start tracking a known-but-not-yet-heard-from agent: the lease
        clock starts NOW without a heartbeat. Called at CP boot and on
        standby promotion for every server record that was online — a
        node that died together with (or during the absence of) the old
        primary never heartbeats the new one, so without priming its
        death would be invisible forever. A live agent's first heartbeat
        simply renews the primed lease; a dead one expires through the
        normal SUSPECT -> DEAD path and gets its verdict."""
        now = self.clock()
        with self._lock:
            if slug in self._leases:
                return
            lease = self._leases[slug] = _Lease()
            lease.deadline = now + self.config.lease_s
            lease.connected = False
            self._counts[ALIVE] += 1
            self._schedule(slug, lease, lease.deadline)
            _M_TRANSITIONS.inc(to=ALIVE)
            log.debug("lease primed %s", kv(slug=slug,
                                            lease_s=self.config.lease_s))

    def observe_disconnect(self, slug: str) -> None:
        """Session gone: fast-path ALIVE -> SUSPECT (the lease no longer
        means anything — its renewals came over the dead session). A fast
        reconnect re-heartbeats within the grace and nothing fires."""
        now = self.clock()
        with self._lock:
            lease = self._leases.get(slug)
            if lease is None:
                return
            lease.connected = False
            if lease.state == ALIVE:
                lease.state = SUSPECT
                lease.suspect_since = now
                self._counts[ALIVE] -= 1
                self._counts[SUSPECT] += 1
                # the fast path moves attention EARLIER than the armed
                # lease deadline: re-arm at the grace expiry
                self._schedule(slug, lease,
                               now + self.config.suspect_grace_s)
                _M_TRANSITIONS.inc(to=SUSPECT)
                log.debug("agent suspect %s", kv(slug=slug,
                                                 reason="disconnect"))

    def forget(self, slug: str) -> None:
        """Server deleted/deprovisioned: stop tracking (no verdict — the
        operator path already ran its own node_event)."""
        with self._lock:
            lease = self._leases.pop(slug, None)
            if lease is not None:
                self._counts[lease.state] -= 1

    # ------------------------------------------------------------------
    # the sweep (called by the reconverger loop / chaos runner)
    # ------------------------------------------------------------------

    def _flapping(self, lease: _Lease, now: float) -> bool:
        cutoff = now - self.config.flap_window_s
        return sum(1 for t in lease.verdicts
                   if t > cutoff) >= self.config.flap_threshold

    def sweep(self) -> list[LeaseEvent]:
        """Advance the leases against the clock; return the verdicts
        (DEAD + queued revives) since the last sweep, sorted by slug for
        deterministic replay.

        Two equivalent engines behind one contract (their verdict
        streams are property-tested identical on seeded schedules):
        the default expiry heap touches only due leases — O(expired ·
        log n); `use_heap=False` scans the full table — O(agents) — and
        serves as oracle and bench baseline."""
        now = self.clock()
        with self._lock:
            out, self._pending = self._pending, []
            if self.use_heap:
                self._sweep_heap(now, out)
            else:
                self._sweep_scan(now, out)
            for state, n in self._counts.items():
                _M_AGENTS.set(n, state=state)
        out.sort(key=lambda e: e.slug)
        return out

    def _sweep_scan(self, now: float, out: list[LeaseEvent]) -> None:
        """The original full-table sweep (lock held by caller)."""
        for slug in sorted(self._leases):
            self._advance(slug, self._leases[slug], now, out)

    def _sweep_heap(self, now: float, out: list[LeaseEvent]) -> None:
        """Pop only the leases whose attention time has arrived (lock
        held by caller). Stale entries (generation mismatch after a
        disconnect re-arm, or a forgotten slug) are discarded; live ones
        re-derive the lease's true condition at `now` — a heartbeat that
        moved the deadline since the entry was pushed simply re-arms at
        the new deadline (lazy invalidation)."""
        repush: list[tuple[float, str, int]] = []
        while self._heap and self._heap[0][0] <= now:
            _, slug, gen = heapq.heappop(self._heap)
            lease = self._leases.get(slug)
            if lease is None or lease.gen != gen:
                continue
            lease.gen = -1
            nxt = self._advance(slug, lease, now, out)
            if nxt is not None:
                # defer the push: an entry at exactly `now` must wait
                # for the NEXT sweep, not loop inside this one
                self._gen += 1
                lease.gen = self._gen
                repush.append((nxt, slug, self._gen))
        for entry in repush:
            heapq.heappush(self._heap, entry)
        if len(self._heap) > max(64, 4 * len(self._leases)):
            self._compact()

    def _advance(self, slug: str, lease: _Lease, now: float,
                 out: list[LeaseEvent]) -> Optional[float]:
        """Advance ONE lease's state machine to `now`; returns when it
        next needs clock attention (None: only a heartbeat can move it).
        This is the single transition body both sweep engines share, so
        they cannot drift."""
        cfg = self.config
        if lease.state == ALIVE:
            if not now > lease.deadline:
                return lease.deadline
            lease.state = SUSPECT
            lease.suspect_since = now
            self._counts[ALIVE] -= 1
            self._counts[SUSPECT] += 1
            _M_TRANSITIONS.inc(to=SUSPECT)
            log.info("agent suspect %s", kv(
                slug=slug, reason="lease-expired", lease_s=cfg.lease_s))
        if lease.state != SUSPECT:
            return None               # DEAD: waits on a heartbeat
        suspect_for = now - lease.suspect_since
        if suspect_for < cfg.suspect_grace_s:
            return lease.suspect_since + cfg.suspect_grace_s
        if self._flapping(lease, now) and suspect_for < cfg.damp_hold_s:
            if not lease.damped_logged:
                lease.damped_logged = True
                _M_DAMPED.inc()
                log.warning("dead verdict damped %s", kv(
                    slug=slug, hold_s=cfg.damp_hold_s,
                    window_s=cfg.flap_window_s))
            # earliest possible flip: the hold expires, or enough
            # verdicts age out of the flap window — whichever is first
            vs = list(lease.verdicts)
            unflap_at = vs[-cfg.flap_threshold] + cfg.flap_window_s
            return min(lease.suspect_since + cfg.damp_hold_s, unflap_at)
        lease.state = DEAD
        lease.damped_logged = False
        lease.verdicts.append(now)
        self._counts[SUSPECT] -= 1
        self._counts[DEAD] += 1
        _M_TRANSITIONS.inc(to=DEAD)
        log.warning("agent dead %s", kv(
            slug=slug, suspect_for_s=round(suspect_for, 1)))
        out.append(LeaseEvent(slug, False, now, DEAD))
        return None

    def _compact(self) -> None:
        """Rebuild the heap with one entry per timed lease, shedding the
        stale-generation residue disconnect re-arms leave behind. The
        rebuilt times are safe LOWER bounds (an early pop just
        re-derives and re-arms)."""
        self._heap = []
        for slug, lease in self._leases.items():
            if lease.gen == -1:
                continue
            at = (lease.deadline if lease.state == ALIVE
                  else lease.suspect_since + self.config.suspect_grace_s)
            self._gen += 1
            lease.gen = self._gen
            self._heap.append((at, slug, self._gen))
        heapq.heapify(self._heap)

    def requeue(self, events: list[LeaseEvent]) -> None:
        """The reconverger failed to process these verdicts (e.g. the
        re-solve burst crashed): put them back so the next sweep hands
        them out again — a verdict must never be silently lost."""
        with self._lock:
            self._pending.extend(events)

    # ------------------------------------------------------------------
    # introspection (fleet cp heal status)
    # ------------------------------------------------------------------

    def state_of(self, slug: str) -> Optional[str]:
        with self._lock:
            lease = self._leases.get(slug)
            return lease.state if lease else None

    def status(self) -> dict:
        now = self.clock()
        with self._lock:
            agents = {}
            for slug in sorted(self._leases):
                lease = self._leases[slug]
                agents[slug] = {
                    "state": lease.state,
                    "connected": lease.connected,
                    "lease_remaining_s": round(lease.deadline - now, 3),
                    "recent_verdicts": len(lease.verdicts),
                    "damped": (lease.state == SUSPECT
                               and self._flapping(lease, now)),
                }
            return {"config": {
                        "lease_s": self.config.lease_s,
                        "suspect_grace_s": self.config.suspect_grace_s,
                        "flap_window_s": self.config.flap_window_s,
                        "flap_threshold": self.config.flap_threshold,
                        "damp_hold_s": self.config.damp_hold_s},
                    "agents": agents}
