"""Lease-based failure detection: missed heartbeats become verdicts.

The CP records every heartbeat (store.heartbeat, fleet_heartbeats_total)
but nothing ever turned a MISSED heartbeat into a node event — a killed
agent stranded its services until an operator called placement.node_event
by hand. This module is the missing half: each agent holds a lease renewed
by its heartbeats; an expired lease moves the agent through a
suspect -> dead state machine whose DEAD verdicts the reconverger
(cp/reconverge.py) turns into coalesced churn re-solves and redeploys.
Borg makes automatic re-placement after machine failure the defining
control-plane behavior (Verma et al., EuroSys '15 §3.1); crash-only design
(Candea & Fox, HotOS '03) wants recovery to be the normal code path — so
the detector is always on, cheap, and driven by the same sweep whether the
clock is wall time or the chaos harness's virtual clock.

State machine per agent:

    ALIVE --lease expired / disconnect--> SUSPECT
    SUSPECT --heartbeat--> ALIVE            (silent revive: no verdict)
    SUSPECT --grace expired--> DEAD         (verdict: reconverge)
    DEAD --heartbeat--> ALIVE               (verdict: node online, unpark)

Verdicts are only the DEAD and DEAD->ALIVE transitions — the expensive
ones, each costing a warm re-solve + redeploy fan-out. SUSPECT is free and
absorbs fast reconnects (an agent session bounce never reaches the solver).

Flap damping: a bouncing agent (crashlooping host, flapping link) would
otherwise emit a dead verdict per bounce and trigger a re-solve storm.
The detector counts verdicts per agent in a rolling window; past
`flap_threshold` the agent is DAMPED — further dead verdicts are held
until it has been continuously suspect for `damp_hold_s` (hysteresis: one
verdict per hold period at most). Revive verdicts are never held: retrying
parked work against a returned node is cheap and correct.

Thread-safe (heartbeats land on the asyncio loop; sweeps may run on
executor threads). The clock is injectable and MONOTONIC — wall-clock
jumps must not kill a fleet (time.monotonic in production, the chaos
VirtualClock in tests/scenarios).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..obs import get_logger, kv
from ..obs.metrics import REGISTRY

log = get_logger("cp.lease")

__all__ = ["LeaseConfig", "LeaseEvent", "FailureDetector",
           "ALIVE", "SUSPECT", "DEAD"]

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"

# metric catalog: docs/guide/10-observability.md
_M_TRANSITIONS = REGISTRY.counter(
    "fleet_lease_transitions_total",
    "Lease state-machine transitions, by target state", labels=("to",))
_M_AGENTS = REGISTRY.gauge(
    "fleet_lease_agents", "Agents tracked by the failure detector, by "
    "lease state", labels=("state",))
_M_DAMPED = REGISTRY.counter(
    "fleet_lease_flap_damped_total",
    "Dead verdicts deferred by flap damping (hysteresis holds)")


@dataclass
class LeaseConfig:
    """Tuning knobs (docs/guide/12-self-healing.md has the sizing math).

    `lease_s` should be >= 3x the agent heartbeat interval: one lost
    heartbeat must not start the clock toward a re-solve. The detection
    budget for a hard-killed node is lease_s + suspect_grace_s (a
    disconnect fast-paths to SUSPECT, so a crashed session pays only
    suspect_grace_s)."""
    lease_s: float = 90.0            # silence this long -> SUSPECT
    suspect_grace_s: float = 30.0    # suspect this long -> DEAD verdict
    flap_window_s: float = 600.0     # rolling window for verdict counting
    flap_threshold: int = 3          # >= verdicts in window -> damped
    damp_hold_s: float = 180.0       # damped: continuous-suspect hold


@dataclass
class LeaseEvent:
    """One verdict: `online=False` (DEAD) or `online=True` (revive).
    `at` is detector-clock time; `state` the new lease state."""
    slug: str
    online: bool
    at: float
    state: str


@dataclass
class _Lease:
    deadline: float = 0.0            # heartbeat lease expiry
    state: str = ALIVE
    suspect_since: float = 0.0
    connected: bool = True
    # verdict timestamps (dead + revive) for flap counting
    verdicts: deque = field(default_factory=lambda: deque(maxlen=32))
    damped_logged: bool = False      # one damped log/metric per hold


class FailureDetector:
    def __init__(self, config: Optional[LeaseConfig] = None, *,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or LeaseConfig()
        self.clock = clock
        self._lock = threading.Lock()
        self._leases: dict[str, _Lease] = {}
        self._pending: list[LeaseEvent] = []   # revives awaiting a sweep

    # ------------------------------------------------------------------
    # observations (called from the agent channel / registry paths)
    # ------------------------------------------------------------------

    def observe_heartbeat(self, slug: str) -> None:
        """Renew the lease. A heartbeat from a SUSPECT agent revives it
        silently; from a DEAD one it queues a node-online verdict (the
        reconverger retries parked work against returned capacity)."""
        now = self.clock()
        with self._lock:
            lease = self._leases.get(slug)
            if lease is None:
                lease = self._leases[slug] = _Lease()
                _M_TRANSITIONS.inc(to=ALIVE)
            lease.deadline = now + self.config.lease_s
            lease.connected = True
            if lease.state == ALIVE:
                return
            was = lease.state
            lease.state = ALIVE
            lease.damped_logged = False
            _M_TRANSITIONS.inc(to=ALIVE)
            log.info("agent revived %s", kv(slug=slug, was=was))
            if was == DEAD:
                lease.verdicts.append(now)
                self._pending.append(LeaseEvent(slug, True, now, ALIVE))

    def prime(self, slug: str) -> None:
        """Start tracking a known-but-not-yet-heard-from agent: the lease
        clock starts NOW without a heartbeat. Called at CP boot and on
        standby promotion for every server record that was online — a
        node that died together with (or during the absence of) the old
        primary never heartbeats the new one, so without priming its
        death would be invisible forever. A live agent's first heartbeat
        simply renews the primed lease; a dead one expires through the
        normal SUSPECT -> DEAD path and gets its verdict."""
        now = self.clock()
        with self._lock:
            if slug in self._leases:
                return
            lease = self._leases[slug] = _Lease()
            lease.deadline = now + self.config.lease_s
            lease.connected = False
            _M_TRANSITIONS.inc(to=ALIVE)
            log.debug("lease primed %s", kv(slug=slug,
                                            lease_s=self.config.lease_s))

    def observe_disconnect(self, slug: str) -> None:
        """Session gone: fast-path ALIVE -> SUSPECT (the lease no longer
        means anything — its renewals came over the dead session). A fast
        reconnect re-heartbeats within the grace and nothing fires."""
        now = self.clock()
        with self._lock:
            lease = self._leases.get(slug)
            if lease is None:
                return
            lease.connected = False
            if lease.state == ALIVE:
                lease.state = SUSPECT
                lease.suspect_since = now
                _M_TRANSITIONS.inc(to=SUSPECT)
                log.debug("agent suspect %s", kv(slug=slug,
                                                 reason="disconnect"))

    def forget(self, slug: str) -> None:
        """Server deleted/deprovisioned: stop tracking (no verdict — the
        operator path already ran its own node_event)."""
        with self._lock:
            self._leases.pop(slug, None)

    # ------------------------------------------------------------------
    # the sweep (called by the reconverger loop / chaos runner)
    # ------------------------------------------------------------------

    def _flapping(self, lease: _Lease, now: float) -> bool:
        cutoff = now - self.config.flap_window_s
        return sum(1 for t in lease.verdicts
                   if t > cutoff) >= self.config.flap_threshold

    def sweep(self) -> list[LeaseEvent]:
        """Advance every lease against the clock; return the verdicts
        (DEAD + queued revives) since the last sweep, sorted by slug for
        deterministic replay."""
        now = self.clock()
        cfg = self.config
        out: list[LeaseEvent] = []
        with self._lock:
            out, self._pending = self._pending, []
            for slug in sorted(self._leases):
                lease = self._leases[slug]
                if lease.state == ALIVE and now > lease.deadline:
                    lease.state = SUSPECT
                    lease.suspect_since = now
                    _M_TRANSITIONS.inc(to=SUSPECT)
                    log.info("agent suspect %s", kv(
                        slug=slug, reason="lease-expired",
                        lease_s=cfg.lease_s))
                if lease.state != SUSPECT:
                    continue
                suspect_for = now - lease.suspect_since
                if suspect_for < cfg.suspect_grace_s:
                    continue
                if self._flapping(lease, now) and suspect_for < cfg.damp_hold_s:
                    if not lease.damped_logged:
                        lease.damped_logged = True
                        _M_DAMPED.inc()
                        log.warning("dead verdict damped %s", kv(
                            slug=slug, hold_s=cfg.damp_hold_s,
                            window_s=cfg.flap_window_s))
                    continue
                lease.state = DEAD
                lease.damped_logged = False
                lease.verdicts.append(now)
                _M_TRANSITIONS.inc(to=DEAD)
                log.warning("agent dead %s", kv(
                    slug=slug, suspect_for_s=round(suspect_for, 1)))
                out.append(LeaseEvent(slug, False, now, DEAD))
            counts = {ALIVE: 0, SUSPECT: 0, DEAD: 0}
            for lease in self._leases.values():
                counts[lease.state] += 1
            for state, n in counts.items():
                _M_AGENTS.set(n, state=state)
        out.sort(key=lambda e: e.slug)
        return out

    def requeue(self, events: list[LeaseEvent]) -> None:
        """The reconverger failed to process these verdicts (e.g. the
        re-solve burst crashed): put them back so the next sweep hands
        them out again — a verdict must never be silently lost."""
        with self._lock:
            self._pending.extend(events)

    # ------------------------------------------------------------------
    # introspection (fleet cp heal status)
    # ------------------------------------------------------------------

    def state_of(self, slug: str) -> Optional[str]:
        with self._lock:
            lease = self._leases.get(slug)
            return lease.state if lease else None

    def status(self) -> dict:
        now = self.clock()
        with self._lock:
            agents = {}
            for slug in sorted(self._leases):
                lease = self._leases[slug]
                agents[slug] = {
                    "state": lease.state,
                    "connected": lease.connected,
                    "lease_remaining_s": round(lease.deadline - now, 3),
                    "recent_verdicts": len(lease.verdicts),
                    "damped": (lease.state == SUSPECT
                               and self._flapping(lease, now)),
                }
            return {"config": {
                        "lease_s": self.config.lease_s,
                        "suspect_grace_s": self.config.suspect_grace_s,
                        "flap_window_s": self.config.flap_window_s,
                        "flap_threshold": self.config.flap_threshold,
                        "damp_hold_s": self.config.damp_hold_s},
                    "agents": agents}
