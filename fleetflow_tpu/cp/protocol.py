"""Channel-based wire protocol (the club-unison analog).

The reference's transport is QUIC (quinn) with named channels, id-correlated
request/response, fire-and-forget events, an identity handshake, and MeshCa
mTLS (SURVEY.md §2.10 comms row; server.rs:101-162, cp_client.rs:18-105).
This build keeps the exact message shapes over asyncio TCP, optionally
wrapped in TLS from cp/cert.py:

  frame    = 4-byte big-endian length ‖ utf-8 JSON body (1 MiB cap)
  hello    = {"type":"hello","identity":str,"token":str|None,
              "channels":[...]}            client -> server, once
  welcome  = {"type":"welcome","server":str}
  request  = {"type":"request","id":int,"channel":str,"method":str,
              "payload":{}}
  response = {"type":"response","id":int,"payload":{},"error":str|None}
  event    = {"type":"event","channel":str,"method":str,"payload":{}}

Requests flow BOTH ways on a connection (the agent channel is duplex: the
CP sends commands to agents, handlers/agent.rs:129-159), so both endpoints
run the same dispatch loop; only the handshake differs.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import ssl
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from ..core.errors import ControlPlaneError
from ..obs import get_logger, kv

log = get_logger("cp.protocol")

__all__ = ["Connection", "ProtocolServer", "ProtocolClient", "RpcError",
           "MAX_FRAME"]

MAX_FRAME = 1 << 20


class RpcError(ControlPlaneError):
    pass


async def read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    size = int.from_bytes(header, "big")
    if size > MAX_FRAME:
        raise RpcError(f"frame too large: {size}")
    try:
        body = await reader.readexactly(size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return json.loads(body)


def encode_frame(msg: dict) -> bytes:
    body = json.dumps(msg, separators=(",", ":")).encode()
    if len(body) > MAX_FRAME:
        raise RpcError(f"frame too large: {len(body)}")
    return len(body).to_bytes(4, "big") + body


# Handler signature: async (conn, method, payload) -> payload
Handler = Callable[["Connection", str, dict], Awaitable[Any]]
# Event handler: async (conn, method, payload) -> None
EventHandler = Callable[["Connection", str, dict], Awaitable[None]]


@dataclass(eq=False)  # identity semantics: connections live in sets/dicts
class Connection:
    """One live peer connection; symmetric request/response + events."""
    reader: asyncio.StreamReader
    writer: asyncio.StreamWriter
    identity: str = "?"
    # Claims attached by the server's authenticate callback (None when the
    # server runs without auth or the callback returns a bare bool); channel
    # handlers enforce per-method permissions against this.
    claims: Optional[object] = None
    handlers: dict[str, Handler] = field(default_factory=dict)
    event_handlers: dict[str, EventHandler] = field(default_factory=dict)
    _ids: itertools.count = field(default_factory=lambda: itertools.count(1))
    _pending: dict[int, asyncio.Future] = field(default_factory=dict)
    _tasks: set = field(default_factory=set)   # strong refs: loop holds weak
    _closed: bool = False
    on_close: Optional[Callable[["Connection"], Awaitable[None]]] = None
    # the server's welcome frame (client side): carries the peer's
    # replication role/epoch when the server advertises them
    welcome: dict = field(default_factory=dict)

    def _spawn(self, coro) -> asyncio.Task:
        """ensure_future with a strong reference: the event loop only keeps
        weak refs to tasks, so an unreferenced in-flight dispatch could be
        garbage-collected mid-execution."""
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    async def _send(self, msg: dict) -> None:
        if self._closed:
            raise RpcError("connection closed")
        self.writer.write(encode_frame(msg))
        await self.writer.drain()

    async def request(self, channel: str, method: str, payload: dict | None = None,
                      timeout: float = 60.0) -> dict:
        """Id-correlated request; raises RpcError on remote error/timeout."""
        mid = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[mid] = fut
        try:
            await self._send({"type": "request", "id": mid, "channel": channel,
                              "method": method, "payload": payload or {}})
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise RpcError(
                f"request {channel}.{method} timed out after {timeout}s") from None
        finally:
            self._pending.pop(mid, None)

    async def send_event(self, channel: str, method: str,
                         payload: dict | None = None) -> None:
        """Fire-and-forget (club-unison send_event)."""
        await self._send({"type": "event", "channel": channel,
                          "method": method, "payload": payload or {}})

    async def run(self) -> None:
        """Dispatch loop: route responses to futures, requests to channel
        handlers, events to event handlers. Returns on disconnect."""
        try:
            while True:
                msg = await read_frame(self.reader)
                if msg is None:
                    break
                t = msg.get("type")
                if t == "response":
                    fut = self._pending.get(msg.get("id"))
                    if fut is not None and not fut.done():
                        if msg.get("error"):
                            fut.set_exception(RpcError(msg["error"]))
                        else:
                            fut.set_result(msg.get("payload", {}))
                elif t == "request":
                    self._spawn(self._dispatch(msg))
                elif t == "event":
                    handler = self.event_handlers.get(msg.get("channel", ""))
                    if handler is not None:
                        self._spawn(handler(
                            self, msg.get("method", ""), msg.get("payload", {})))
        finally:
            await self.close()

    async def _dispatch(self, msg: dict) -> None:
        channel, method = msg.get("channel", ""), msg.get("method", "")
        handler = self.handlers.get(channel)
        resp: dict = {"type": "response", "id": msg.get("id")}
        if handler is None:
            resp["error"] = f"unknown channel {channel!r}"
        else:
            try:
                resp["payload"] = await handler(self, method, msg.get("payload", {}))
            except Exception as e:  # handler errors become remote RpcErrors
                resp["error"] = f"{type(e).__name__}: {e}"
        try:
            await self._send(resp)
        except (RpcError, ConnectionResetError):
            pass

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(RpcError("connection closed"))
        self._pending.clear()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass
        if self.on_close is not None:
            await self.on_close(self)


class ProtocolServer:
    """Accepts connections, performs the hello/welcome handshake, then runs
    the symmetric dispatch loop per connection."""

    def __init__(self, *, name: str = "cp",
                 authenticate: Optional[Callable[[str, Optional[str]], bool]] = None,
                 ssl_context: Optional[ssl.SSLContext] = None,
                 handshake_timeout: float = 10.0,
                 welcome_extra: Optional[Callable[[], dict]] = None):
        self.name = name
        self.authenticate = authenticate
        self.ssl_context = ssl_context
        self.handshake_timeout = handshake_timeout
        # extra key/values merged into every welcome frame — the CP
        # advertises its replication role and fencing epoch here, so a
        # client can refuse a zombie ex-primary BEFORE sending anything
        # (docs/guide/13-cp-replication.md)
        self.welcome_extra = welcome_extra
        self.handlers: dict[str, Handler] = {}
        self.event_handlers: dict[str, EventHandler] = {}
        self.connections: set[Connection] = set()
        self._server: Optional[asyncio.AbstractServer] = None
        self.on_connect: Optional[Callable[[Connection, dict], Awaitable[None]]] = None
        self.on_disconnect: Optional[Callable[[Connection], Awaitable[None]]] = None

    def register_channel(self, channel: str, handler: Handler,
                         event_handler: Optional[EventHandler] = None) -> None:
        self.handlers[channel] = handler
        if event_handler is not None:
            self.event_handlers[channel] = event_handler

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._accept, host, port, ssl=self.ssl_context)
        sock = self._server.sockets[0].getsockname()
        return sock[0], sock[1]

    async def _accept(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        # pre-auth phase is bounded: an idle or malformed client must not
        # pin an accept coroutine forever
        try:
            hello = await asyncio.wait_for(read_frame(reader),
                                           self.handshake_timeout)
        except (asyncio.TimeoutError, RpcError, json.JSONDecodeError):
            writer.close()
            return
        if not hello or hello.get("type") != "hello":
            writer.close()
            return
        identity = str(hello.get("identity", "?"))
        verdict = (self.authenticate(identity, hello.get("token"))
                   if self.authenticate else True)
        if not verdict:
            log.warning("rejected %s", kv(identity=identity,
                                          reason="unauthorized"))
            writer.write(encode_frame({"type": "error", "error": "unauthorized"}))
            await writer.drain()
            writer.close()
            return
        log.info("connected %s", kv(identity=identity,
                                    peers=len(self.connections) + 1))
        conn = Connection(reader=reader, writer=writer, identity=identity,
                          # a truthy non-bool verdict is the peer's Claims
                          claims=None if verdict is True else verdict,
                          handlers=self.handlers,
                          event_handlers=self.event_handlers)
        self.connections.add(conn)
        conn.on_close = self._forget
        try:
            welcome = {"type": "welcome", "server": self.name}
            if self.welcome_extra is not None:
                welcome.update(self.welcome_extra())
            await conn._send(welcome)
            if self.on_connect is not None:
                await self.on_connect(conn, hello)
        except Exception:
            await conn.close()   # client reset mid-welcome: don't leak
            return
        await conn.run()

    async def _forget(self, conn: Connection) -> None:
        self.connections.discard(conn)
        log.info("disconnected %s", kv(identity=conn.identity,
                                       peers=len(self.connections)))
        if self.on_disconnect is not None:
            await self.on_disconnect(conn)

    async def stop(self) -> None:
        # close live connections BEFORE wait_closed(): since 3.12,
        # Server.wait_closed waits for every handler coroutine to finish,
        # and those only return once their connection closes
        for conn in list(self.connections):
            await conn.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class ProtocolClient:
    """Client side: connect + handshake; exposes the same Connection."""

    @staticmethod
    async def connect(host: str, port: int, *, identity: str,
                      token: Optional[str] = None,
                      ssl_context: Optional[ssl.SSLContext] = None,
                      handlers: Optional[dict[str, Handler]] = None,
                      event_handlers: Optional[dict[str, EventHandler]] = None,
                      ) -> tuple[Connection, asyncio.Task]:
        reader, writer = await asyncio.open_connection(
            host, port, ssl=ssl_context)
        conn = Connection(reader=reader, writer=writer, identity=identity,
                          handlers=handlers or {},
                          event_handlers=event_handlers or {})
        try:
            writer.write(encode_frame({
                "type": "hello", "identity": identity, "token": token,
                "channels": sorted((handlers or {}).keys())}))
            await writer.drain()
            welcome = await read_frame(reader)
            if not welcome:
                raise RpcError("connection closed during handshake")
            if welcome.get("type") == "error":
                raise RpcError(welcome.get("error", "handshake rejected"))
            if welcome.get("type") != "welcome":
                raise RpcError(f"unexpected handshake reply: {welcome}")
            conn.welcome = welcome
        except BaseException:
            writer.close()   # failed handshake must not leak the socket
            raise
        task = asyncio.ensure_future(conn.run())
        return conn, task
