"""Fault algebra: the small, composable vocabulary of things that go
wrong in a fleet, plus seeded schedule generation.

Jepsen/Chaos-Monkey shape: a *fault* is a declarative, serializable
description ("node07 crashes at t=60 and revives 240s later"); a
*schedule* is a seeded, sorted list of faults; the *runner* replays a
schedule's expanded primitive timeline against a simulated fleet. The
algebra is deliberately tiny — six fault kinds cover the robustness
machinery the control plane actually carries (churn re-solves, 2-phase
reservations, autoscaler reaping, deploy retry/release):

  NodeCrash      node powers off (containers die); optional revival
  NodeFlap       crash + fast revival (one flap of a flap-storm)
  AgentPartition CP<->agent link drops; the node keeps running
  SlowAgent      agent answers, but after `delay` virtual seconds
  DeployFail     arm the next N service-starts to fail mid-deploy
  ContainerExit  one running container on a node exits unexpectedly
  WorkerKill     crash an autoscaler pool worker (target picked at
                 apply time: the pool's first online worker)
  Redeploy       operator action: redeploy a stage (Jepsen "client op")

The world-simulator pack (chaos/worldgen.py) adds CORRELATED faults —
failures that take out a *domain*, not a random sample:

  SpotReclaim    a provider reclamation storm: warning with lead time
                 (victims cordoned), then the pool members die at once
  ZoneOutage     every node of one region dies in the same instant
  ZoneRevive     the lost region comes back (outage victims reconnect)
  HotspotShift   traffic hotspot migrates onto a tenant (the tenant is
                 marked as deliberately bursting from here on)

Every fault expands into primitive (time, op, params) events; the
runner groups same-instant primitives into one burst so coalesced churn
(`placement.node_events`) is exercised the way production would see it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

__all__ = [
    "Fault", "NodeCrash", "NodeFlap", "AgentPartition", "SlowAgent",
    "DeployFail", "ContainerExit", "WorkerKill", "Redeploy",
    "SilentNodeCrash", "Tick", "PrimaryKill", "AdmissionWave",
    "SpotReclaim", "ZoneOutage", "ZoneRevive", "HotspotShift",
    "FaultSchedule",
]

# primitive ops the runner executes (the fault algebra's normal form)
NODE_DOWN = "node_down"
NODE_UP = "node_up"
NODE_DOWN_SILENT = "node_down_silent"
NODE_UP_SILENT = "node_up_silent"
TICK = "tick"
PARTITION_START = "partition_start"
PARTITION_END = "partition_end"
SLOW_START = "slow_start"
SLOW_END = "slow_end"
ARM_DEPLOY_FAIL = "arm_deploy_fail"
CONTAINER_EXIT = "container_exit"
WORKER_KILL = "worker_kill"
REDEPLOY = "redeploy"
CP_KILL = "cp_kill"
ADMIT = "admit"
SPOT_WARNING = "spot_warning"
SPOT_RECLAIM = "spot_reclaim"
SPOT_REVIVE = "spot_revive"
ZONE_DOWN = "zone_down"
ZONE_UP = "zone_up"
HOTSPOT_SHIFT = "hotspot_shift"


@dataclass(frozen=True)
class Fault:
    """Base fault: `at` is virtual seconds from scenario start."""
    at: float

    def expand(self) -> list[tuple[float, str, dict]]:
        raise NotImplementedError


@dataclass(frozen=True)
class NodeCrash(Fault):
    node: str = ""
    revive_after: Optional[float] = None   # None = stays dead

    def expand(self):
        out = [(self.at, NODE_DOWN, {"node": self.node, "wipe": True})]
        if self.revive_after is not None:
            out.append((self.at + self.revive_after, NODE_UP,
                        {"node": self.node}))
        return out


@dataclass(frozen=True)
class NodeFlap(Fault):
    node: str = ""
    down_for: float = 5.0

    def expand(self):
        return [(self.at, NODE_DOWN, {"node": self.node, "wipe": True}),
                (self.at + self.down_for, NODE_UP, {"node": self.node})]


@dataclass(frozen=True)
class SilentNodeCrash(Fault):
    """NodeCrash WITHOUT the runner informing the placement service: no
    node_event, no operator redeploy — the CP must NOTICE the death by
    itself (missed heartbeats -> lease expiry -> dead verdict,
    cp/failure_detector.py) and the reconverger must re-place and
    redeliver the stranded services. The self-healing scenario's whole
    point: detection is part of the system under test."""
    node: str = ""
    revive_after: Optional[float] = None   # None = stays dead

    def expand(self):
        out = [(self.at, NODE_DOWN_SILENT, {"node": self.node})]
        if self.revive_after is not None:
            out.append((self.at + self.revive_after, NODE_UP_SILENT,
                        {"node": self.node}))
        return out


@dataclass(frozen=True)
class Tick(Fault):
    """Pure pacing: advances the clock to `at` and forces a reconcile
    (heartbeats + detector sweep + heal pass). Lease expiry only fires
    when a sweep OBSERVES it, so silent-crash schedules interleave ticks
    to bound the detection latency on the virtual clock."""

    def expand(self):
        return [(self.at, TICK, {})]


@dataclass(frozen=True)
class AgentPartition(Fault):
    """The CP cannot reach the agent; the node keeps its containers."""
    node: str = ""
    duration: float = 60.0

    def expand(self):
        return [(self.at, PARTITION_START, {"node": self.node}),
                (self.at + self.duration, PARTITION_END,
                 {"node": self.node})]


@dataclass(frozen=True)
class SlowAgent(Fault):
    """Commands to the agent take `delay` virtual seconds; a delay past
    the command's timeout is a timeout failure."""
    node: str = ""
    delay: float = 30.0
    duration: float = 120.0

    def expand(self):
        return [(self.at, SLOW_START, {"node": self.node,
                                       "delay": self.delay}),
                (self.at + self.duration, SLOW_END, {"node": self.node})]


@dataclass(frozen=True)
class DeployFail(Fault):
    """Arm the injector: the next `count` service-starts anywhere in the
    fleet raise at the deploy engine's fault hook."""
    count: int = 1

    def expand(self):
        return [(self.at, ARM_DEPLOY_FAIL, {"count": self.count})]


@dataclass(frozen=True)
class ContainerExit(Fault):
    """One running fleet container on `node` exits (first by sorted
    name — deterministic); the runner's monitor pass restarts it."""
    node: str = ""

    def expand(self):
        return [(self.at, CONTAINER_EXIT, {"node": self.node})]


@dataclass(frozen=True)
class WorkerKill(Fault):
    """Crash an autoscaler pool worker; the target is resolved at apply
    time (first online worker of `pool`, sorted by slug)."""
    pool: str = "workers"

    def expand(self):
        return [(self.at, WORKER_KILL, {"pool": self.pool})]


@dataclass(frozen=True)
class Redeploy(Fault):
    """Operator redeploy of a stage (the Jepsen 'client operation' that
    races whatever else the schedule is doing at this instant)."""
    stage: str = ""

    def expand(self):
        return [(self.at, REDEPLOY, {"stage": self.stage})]


@dataclass(frozen=True)
class PrimaryKill(Fault):
    """Kill the control-plane PRIMARY itself (cp-failover scenario,
    docs/guide/13-cp-replication.md): the warm standby — fed by the
    store's replication stream — must promote (epoch bump, fencing),
    resume the dead primary's convergence debt, and re-home the agents.
    `phase` picks the crash window:

      burst       die in the same instant nodes are dying silently — the
                  verdicts exist nowhere yet; the new primary must
                  re-detect through its primed leases
      redelivery  die BETWEEN enqueuing redelivery work and delivering
                  it — the parked_work rows are on the standby via
                  replication, and the new primary must finish exactly
                  once
      compaction  force a journal compaction (snapshot + truncate), then
                  die — proving the shipped stream and the local journal
                  lifecycle are independent
    """
    phase: str = "burst"     # burst | redelivery | compaction

    def expand(self):
        return [(self.at, CP_KILL, {"phase": self.phase})]


@dataclass(frozen=True)
class AdmissionWave(Fault):
    """One tenant's slice of the continuous arrival stream (the streaming
    admission scenario, cp/admission.py): submit `arrivals` fresh services
    and `departures` of the tenant's oldest live streamed services through
    the admission queue. `burst=True` marks a wave that deliberately
    exceeds the tenant's fair share — the admission-fair invariant exempts
    bursting tenants from the latency bound (they PAY for the burst; the
    point is that nobody else does)."""
    tenant: str = ""
    arrivals: int = 0
    departures: int = 0
    burst: bool = False
    # which stage stream the wave targets, by sorted index (clamped to
    # the flow's stage count at apply time): multi-stage storms drive
    # several different-size streaming problems through one controller
    stage: int = 0

    def expand(self):
        return [(self.at, ADMIT, {"tenant": self.tenant,
                                  "arrivals": self.arrivals,
                                  "departures": self.departures,
                                  "burst": self.burst,
                                  "stage": self.stage})]


@dataclass(frozen=True)
class SpotReclaim(Fault):
    """A spot/preemptible reclamation storm against one declared pool
    (worldgen.SpotPoolSpec): the provider announces at `at` with
    `warning_s` of lead time — the runner resolves the victims THEN
    (first `count` online members, sorted) and cordons them, so new
    placements route around doomed machines — and reclaims them all in
    ONE instant at `at + warning_s` (correlated, silent: the CP's lease
    detector must still notice the deaths). `revive_after` reconnects
    the reclaimed victims that much later (capacity returning to the
    market); None means the pool stays shrunk."""
    pool: str = ""
    count: int = 1
    warning_s: float = 30.0
    revive_after: Optional[float] = None

    def expand(self):
        out = [(self.at, SPOT_WARNING, {"pool": self.pool,
                                        "count": self.count}),
               (self.at + self.warning_s, SPOT_RECLAIM,
                {"pool": self.pool, "count": self.count})]
        if self.revive_after is not None:
            out.append((self.at + self.warning_s + self.revive_after,
                        SPOT_REVIVE, {"pool": self.pool}))
        return out


@dataclass(frozen=True)
class ZoneOutage(Fault):
    """A whole failure DOMAIN dies at once: every online node of
    `region` (schedule.world region membership) disconnects silently in
    one instant — no node_events, no operator help. Only the lost
    domain's work may park; the `degraded-gracefully` invariant judges
    the rest of the fleet through the outage."""
    region: str = ""

    def expand(self):
        return [(self.at, ZONE_DOWN, {"region": self.region})]


@dataclass(frozen=True)
class ZoneRevive(Fault):
    """The lost region comes back: exactly the nodes the matching
    ZoneOutage killed reconnect. Revival must converge — parked stages
    un-park, and no idempotency-keyed redelivery may execute twice."""
    region: str = ""

    def expand(self):
        return [(self.at, ZONE_UP, {"region": self.region})]


@dataclass(frozen=True)
class HotspotShift(Fault):
    """The traffic hotspot migrates onto `tenant`: from this instant the
    generator's arrival waves favor the tenant (already baked into the
    sampled AdmissionWave counts) and the runner marks it as
    deliberately bursting, so `admission-fair` exempts it — the hotspot
    pays for its own flood; the invariant is that nobody else does."""
    tenant: str = ""

    def expand(self):
        return [(self.at, HOTSPOT_SHIFT, {"tenant": self.tenant})]


@dataclass
class FaultSchedule:
    """A seeded, replayable fault plan."""
    scenario: str
    seed: int
    faults: list[Fault] = field(default_factory=list)
    horizon: float = 0.0       # virtual end-of-scenario settle point
    # per-tenant hard admission caps (cp/admission.py tenant_caps) the
    # runner wires into the world's AdmissionConfig; empty = uncapped
    tenant_caps: dict[str, int] = field(default_factory=dict)
    # world topology metadata (chaos/worldgen.py): region -> node INDEX
    # list ("regions"), per-region capacity scale ("capacity_scale"),
    # spot pool -> node INDEX list ("spot_pools"). The runner turns it
    # into region-labeled servers, region-homed stages, and resolvable
    # zone/spot fault targets; empty = the classic single-domain fleet
    world: dict = field(default_factory=dict)

    def events(self) -> list[tuple[float, str, dict]]:
        """Expanded primitive timeline, stably sorted by time (ties keep
        declaration order, so a schedule is exactly reproducible)."""
        prims: list[tuple[float, str, dict]] = []
        for f in self.faults:
            prims.extend(f.expand())
        return sorted(prims, key=lambda e: e[0])

    def describe(self) -> list[str]:
        return [f"t={f.at:>7.1f}s {type(f).__name__} "
                + " ".join(f"{k}={v}" for k, v in vars(f).items()
                           if k != "at" and v is not None)
                for f in sorted(self.faults, key=lambda f: f.at)]
