"""Fault delivery: the bridge between a fault schedule and the hook
points threaded through the production code.

No monkeypatching — every fault lands through an explicit, documented
extension point that exists in the real control plane:

  AgentRegistry.delivery_hook   partitions + slow agents (a raised
                                ControlPlaneError surfaces to callers
                                exactly like a dead-agent send failure)
  DeployEngine.fault_hook       armed DeployFail faults (a raised
                                BackendError fails the service through
                                the engine's normal error path)
  MockBackend.fault_hook        per-op backend faults (reserved for
                                scenario packs that fail pulls/creates)
  AppState.chaos                the injector itself, so anything holding
                                AppState can consult the active fault set

The injector is pure state + hook callables; the runner mutates it as it
replays the schedule (partition_start/end, slow_start/end, arm counts).
"""

from __future__ import annotations

from ..core.errors import ControlPlaneError
from ..runtime.backend import BackendError
from ..cp.agent_registry import (BUILD_TIMEOUT, DEFAULT_TIMEOUT,
                                 DEPLOY_TIMEOUT)

__all__ = ["FaultInjector"]

_TIMEOUTS = {"deploy.execute": DEPLOY_TIMEOUT, "deploy.down": DEPLOY_TIMEOUT,
             "build": BUILD_TIMEOUT}


class FaultInjector:
    """Active-fault state + the hook implementations that deliver it."""

    def __init__(self, clock=None, on_fire=None):
        self.clock = clock                    # VirtualClock or None
        self.on_fire = on_fire                # fn(kind, **detail) -> None
        self.partitioned: set[str] = set()    # slugs the CP cannot reach
        self.slow: dict[str, float] = {}      # slug -> delay (virtual s)
        self.deploy_fail_budget: int = 0      # armed service-start failures
        self.fired: list[tuple[str, str]] = []   # (kind, target) audit

    # ------------------------------------------------------------------
    # schedule-driven state transitions (called by the runner)
    # ------------------------------------------------------------------

    def partition(self, slug: str) -> None:
        self.partitioned.add(slug)

    def heal_partition(self, slug: str) -> None:
        self.partitioned.discard(slug)

    def slow_agent(self, slug: str, delay: float) -> None:
        self.slow[slug] = float(delay)

    def heal_slow(self, slug: str) -> None:
        self.slow.pop(slug, None)

    def arm_deploy_fail(self, count: int) -> None:
        self.deploy_fail_budget += int(count)

    # ------------------------------------------------------------------
    # hook implementations
    # ------------------------------------------------------------------

    def _fire(self, kind: str, target: str) -> None:
        self.fired.append((kind, target))
        if self.on_fire is not None:
            self.on_fire(kind, target)

    def delivery_hook(self, slug: str, command: str) -> None:
        """AgentRegistry.delivery_hook: raise = the send failed."""
        if slug in self.partitioned:
            self._fire("partition", slug)
            raise ControlPlaneError(
                f"chaos: agent {slug!r} unreachable (partition)")
        delay = self.slow.get(slug)
        if delay is not None:
            timeout = _TIMEOUTS.get(command, DEFAULT_TIMEOUT)
            if delay >= timeout:
                self._fire("slow-timeout", slug)
                raise ControlPlaneError(
                    f"chaos: agent {slug!r} command {command!r} timed out "
                    f"after {timeout:.0f}s (slow agent, {delay:.0f}s)")
            self._fire("slow", slug)
            if self.clock is not None:
                self.clock.advance(delay)

    def engine_hook(self, slug: str):
        """Per-node DeployEngine.fault_hook closure."""
        def hook(step: str, row: str) -> None:
            if self.deploy_fail_budget > 0:
                self.deploy_fail_budget -= 1
                self._fire("deploy-fail", f"{slug}/{row}")
                raise BackendError(
                    f"chaos: injected {step} failure for {row} on {slug}")
        return hook

    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        return {"partitioned": sorted(self.partitioned),
                "slow": dict(sorted(self.slow.items())),
                "deploy_fail_budget": self.deploy_fail_budget,
                "fired": len(self.fired)}
