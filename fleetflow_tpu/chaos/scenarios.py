"""Canned scenario pack: seeded schedule generators.

Each scenario is a pure function (seed, services, nodes) -> FaultSchedule
— the same triple always yields the same schedule, and the runner's
replay of it the same event log, so every scenario run is a shareable
repro ("rolling-kill seed 7 at 1000x100").

Sizing rule: scenarios must stay *feasible by construction* — the
synthetic fleet carries roughly 2x capacity headroom, so schedules keep
concurrent dead nodes under ~a third of the fleet. An infeasible
re-solve is a sizing bug in the scenario, not a robustness finding.
"""

from __future__ import annotations

import random
from typing import Callable

from .faults import (AdmissionWave, AgentPartition, ContainerExit,
                     DeployFail, FaultSchedule, NodeCrash, NodeFlap,
                     PrimaryKill, Redeploy, SilentNodeCrash, SlowAgent,
                     Tick, WorkerKill)
from .runner import node_slug
from .worldgen import WORLD_SCENARIOS, validate_schedule

__all__ = ["SCENARIOS", "build_schedule", "scenario_names",
           "scenario_info", "validate_schedule"]


def _rolling_kill(seed: int, services: int, nodes: int) -> FaultSchedule:
    """Kill nodes one at a time on a cadence, each revived later; a pool
    worker dies mid-roll and a few containers exit on survivors. At most
    ~4 nodes are dead at once.

    Sizing: services=60 nodes=10 stages=2
    """
    rng = random.Random(seed)
    # never make every node a victim: survivors must exist to absorb the
    # displaced services (and to host the container-exit faults)
    kills = min(max(2, min(nodes // 10, 8)), nodes - 1)
    victims = rng.sample(range(nodes), kills)
    survivors = [n for n in range(nodes) if n not in victims]
    faults = []
    t = 30.0
    for i, v in enumerate(victims):
        faults.append(NodeCrash(at=t, node=node_slug(v),
                                revive_after=240.0))
        if i == kills // 2:
            faults.append(WorkerKill(at=t + 5.0))
        if i % 2 == 0:
            faults.append(ContainerExit(at=t + 10.0,
                                        node=node_slug(rng.choice(survivors))))
        t += 60.0
    return FaultSchedule("rolling-kill", seed, faults, horizon=t + 300.0)


def _rolling_kill_selfheal(seed: int, services: int,
                           nodes: int) -> FaultSchedule:
    """Rolling SILENT kills: nodes die without any operator RPC or runner
    assistance — missed heartbeats are the only signal. The lease-based
    failure detector must notice each death (suspect -> dead on the
    virtual clock) and the reconverger must warm re-solve and redeliver
    the stranded services to survivors (the `selfheal-converged`
    invariant judges the outcome). Ticks pace the replay so detector
    sweeps observe lease expiry with bounded latency; each victim
    revives later, exercising the node-online unpark path.

    Sizing: services=60 nodes=10 stages=2
    """
    rng = random.Random(seed)
    kills = min(max(2, min(nodes // 10, 6)), nodes - 1)
    victims = rng.sample(range(nodes), kills)
    faults: list = []
    t = 30.0
    for v in victims:
        faults.append(SilentNodeCrash(at=t, node=node_slug(v),
                                      revive_after=400.0))
        t += 120.0
    horizon = t + 600.0
    # lease 60s + grace 30s (runner config): 30s ticks bound detection
    # at ~2 sweeps past expiry
    tick = 15.0
    while tick < horizon:
        faults.append(Tick(at=tick))
        tick += 30.0
    return FaultSchedule("rolling-kill-selfheal", seed, faults,
                         horizon=horizon)


def _cp_failover(seed: int, services: int, nodes: int) -> FaultSchedule:
    """Kill the control-plane PRIMARY three times — mid-redelivery,
    mid-burst, and mid-compaction — while nodes die silently around it.
    Each kill promotes the warm standby (journal-shipping replication),
    which must resume the dead primary's convergence debt, re-detect
    in-flight node deaths through primed leases, and finish every
    redelivery exactly once; a zombie write from each dead primary must
    bounce off the fencing epoch. Judged by `cp-failover-converged` on
    top of the standard invariant pack.

    Timeline choreography (lease 60s + grace 30s on the world clock):
      * A dies at 95 with NO ticks until the kill at 130, so A's dead
        verdict fires INSIDE the kill's half-step — genuine
        mid-redelivery death (PrimaryKill phase="redelivery");
      * B dies in the same instant as the second kill — the burst is in
        flight, nobody has observed it; only the new primary's primed
        leases can find B (phase="burst");
      * the third kill compacts the journal first (phase="compaction");
      * C dies and revives afterwards, exercising plain self-healing +
        unpark on the twice-promoted primary.

    Sizing: services=60 nodes=10 stages=2
    """
    rng = random.Random(seed)
    # survivors must exist: at most nodes-1 victims (tiny fleets get
    # fewer node kills but always all three primary kills)
    k = min(3, nodes - 1)
    victims = [node_slug(v) for v in rng.sample(range(nodes), k)]
    faults: list = [
        SilentNodeCrash(at=95.0, node=victims[0], revive_after=500.0),
        PrimaryKill(at=130.0, phase="redelivery"),
        PrimaryKill(at=250.0, phase="burst"),
        PrimaryKill(at=500.0, phase="compaction"),
    ]
    if k >= 2:   # dies in the same instant as the burst kill
        faults.insert(2, SilentNodeCrash(at=250.0, node=victims[1]))
    if k >= 3:   # plain self-heal + unpark on the final primary
        faults.append(SilentNodeCrash(at=560.0, node=victims[2],
                                      revive_after=240.0))
    horizon = 1000.0
    # ticks pace detector sweeps — EXCEPT inside (95, 130): a sweep
    # there would consume A's verdict before the mid-redelivery kill
    tick = 15.0
    while tick < horizon:
        if not (95.0 < tick < 130.0):
            faults.append(Tick(at=tick))
        tick += 30.0
    return FaultSchedule("cp-failover", seed, faults, horizon=horizon)


def _flap_storm(seed: int, services: int, nodes: int) -> FaultSchedule:
    """Waves of short node flaps (the churn-coalescing stress): each wave
    flaps ~20% of the fleet within one instant, down for 5-20s, plus
    container exits during the instability.

    Sizing: services=60 nodes=10 stages=2
    """
    rng = random.Random(seed)
    per_wave = max(1, min(nodes // 5, nodes - 1))
    faults = []
    t = 20.0
    for _wave in range(3):
        flappers = rng.sample(range(nodes), per_wave)
        survivor = node_slug(rng.choice(
            [n for n in range(nodes) if n not in flappers]))
        for v in flappers:
            faults.append(NodeFlap(at=t, node=node_slug(v),
                                   down_for=float(rng.choice((5, 10, 20)))))
        faults.append(ContainerExit(at=t + 2.0, node=survivor))
        faults.append(WorkerKill(at=t + 3.0))
        t += 90.0
    # horizon past the autoscaler's corpse-reap window: the killed
    # workers' offline records must get reaped AND replaced before the
    # pools-at-min verdict
    return FaultSchedule("flap-storm", seed, faults, horizon=t + 960.0)


def _partition_during_deploy(seed: int, services: int,
                             nodes: int) -> FaultSchedule:
    """Partition a slice of the fleet, then redeploy INTO the partition:
    the deploy must fail cleanly (reservation released, nothing
    half-committed) and succeed after the partition heals.

    Sizing: services=60 nodes=10 stages=2
    """
    rng = random.Random(seed)
    cut = rng.sample(range(nodes), max(1, min(nodes // 5, nodes - 1)))
    faults = [AgentPartition(at=10.0, node=node_slug(v), duration=120.0)
              for v in cut]
    faults.append(SlowAgent(at=10.0, node=node_slug(
        rng.choice([n for n in range(nodes) if n not in cut])),
        delay=30.0, duration=120.0))
    # redeploy every stage while the partition stands, and again after
    faults.append(Redeploy(at=20.0, stage="app0"))
    faults.append(Redeploy(at=200.0, stage="app0"))
    return FaultSchedule("partition-during-deploy", seed, faults,
                         horizon=400.0)


def _deploy_fail_burst(seed: int, services: int,
                       nodes: int) -> FaultSchedule:
    """Arm a burst of injected service-start failures, then redeploy:
    each failed deploy must release its reservation; once the burst is
    spent the redeploy lands. A crash mid-burst stacks churn on top.

    Sizing: services=60 nodes=10 stages=2
    """
    rng = random.Random(seed)
    faults = [
        DeployFail(at=10.0, count=3),
        Redeploy(at=15.0, stage="app0"),
        NodeCrash(at=60.0, node=node_slug(rng.randrange(nodes)),
                  revive_after=180.0),
        DeployFail(at=90.0, count=2),
        Redeploy(at=100.0, stage="app0"),
        Redeploy(at=260.0, stage="app0"),
    ]
    return FaultSchedule("deploy-fail-burst", seed, faults, horizon=420.0)


def _arrival_storm(seed: int, services: int, nodes: int) -> FaultSchedule:
    """Continuous service arrivals/departures through the streaming
    admission pipeline (cp/admission.py), with one tenant bursting 10x
    its weight mid-storm. Three steady tenants submit small waves every
    10 s; `team-a` floods between t=80 and t=200. The admission queue
    must stay fair (DRR: the flood queues behind team-a's own backlog,
    never behind the others' — `admission-fair`) and complete (every
    submitted request ends placed/parked/shed/departed, and every live
    streamed service is in the committed placement — `admission-converged`).
    Ticks keep draining after the last wave so the backlog is judged
    drained, not abandoned.

    Sizing: services=60 nodes=10 stages=2
    """
    rng = random.Random(seed)
    tenants = ["team-a", "team-b", "team-c"]
    faults: list = []
    t = 20.0
    while t < 320.0:
        for tenant in tenants:
            burst = tenant == "team-a" and 80.0 <= t < 200.0
            n = 10 if burst else rng.choice((1, 1, 2))
            # departures only once the tenant has built up live services
            dep = rng.choice((0, 1)) if t >= 60.0 else 0
            faults.append(AdmissionWave(at=t, tenant=tenant, arrivals=n,
                                        departures=dep, burst=burst))
        t += 10.0
    horizon = t + 300.0
    tick = 15.0
    while tick < horizon:
        faults.append(Tick(at=tick))
        tick += 15.0
    return FaultSchedule("arrival-storm", seed, faults, horizon=horizon)


def _tenant_storm(seed: int, services: int, nodes: int) -> FaultSchedule:
    """Hard-quota storm across MULTIPLE stage streams with a primary
    kill in the middle. `team-cap` carries a hard cap of 6 and floods
    well past it: the overflow must PARK with the quota reason —
    accepted and journaled, never shed — while two uncapped tenants
    stream normally on rotating stages. Mid-storm the CP PRIMARY dies
    with quota parks outstanding; the promoted standby must restore the
    journaled parked arrivals and place them as the capped tenant's
    drain-phase departures free headroom (admission-quota +
    admission-converged + slo-met judged).

    Sizing: services=60 nodes=10 stages=2
    """
    rng = random.Random(seed)
    faults: list = []
    t = 20.0
    i = 0
    while t < 300.0:
        for j, tenant in enumerate(("team-cap", "team-d", "team-e")):
            stage = (i + j) % 3   # clamped to the flow's stage count
            if tenant == "team-cap":
                # flood phase: pile up quota parks; drain phase: pure
                # departures so headroom frees and the parks place
                n, dep = (2, 0) if t < 140.0 else (0, 1)
            else:
                n = rng.choice((1, 1, 2))
                dep = rng.choice((0, 1)) if t >= 60.0 else 0
            if n or dep:
                faults.append(AdmissionWave(at=t, tenant=tenant,
                                            arrivals=n, departures=dep,
                                            stage=stage))
        i += 1
        t += 10.0
    # die while the capped tenant's overflow is parked: the journaled
    # parked arrivals (admission_parked table) ride the replication
    # stream and must be restored by the promoted CP
    faults.append(PrimaryKill(at=145.0, phase="burst"))
    horizon = t + 300.0
    tick = 15.0
    while tick < horizon:
        faults.append(Tick(at=tick))
        tick += 15.0
    return FaultSchedule("tenant-storm", seed, faults, horizon=horizon,
                         tenant_caps={"team-cap": 6})


SCENARIOS: dict[str, tuple[Callable, str]] = {
    "rolling-kill": (_rolling_kill,
                     "serial node kills with revival + a pool worker "
                     "death + container exits"),
    "rolling-kill-selfheal": (_rolling_kill_selfheal,
                              "SILENT serial kills: only missed "
                              "heartbeats signal them — the lease "
                              "detector + reconverger must heal the "
                              "fleet unassisted"),
    "cp-failover": (_cp_failover,
                    "kill the CP PRIMARY mid-redelivery, mid-burst and "
                    "mid-compaction — the journal-shipping standby must "
                    "promote, fence the zombie, and finish every "
                    "redelivery exactly once"),
    "flap-storm": (_flap_storm,
                   "waves of coalesced short flaps across ~20% of the "
                   "fleet"),
    "partition-during-deploy": (_partition_during_deploy,
                                "deploys into a standing agent partition "
                                "+ one slow agent"),
    "deploy-fail-burst": (_deploy_fail_burst,
                          "injected mid-deploy service failures with a "
                          "crash stacked on top"),
    "arrival-storm": (_arrival_storm,
                      "continuous arrivals/departures through streaming "
                      "admission with one tenant bursting 10x its weight "
                      "— DRR fairness + completeness judged"),
    "tenant-storm": (_tenant_storm,
                     "hard-quota storm over rotating stage streams: a "
                     "capped tenant floods past its quota (overflow "
                     "parks, journaled) and the CP primary dies with "
                     "parks outstanding — the promoted standby must "
                     "restore and place them"),
}

# the world-simulator production pack (chaos/worldgen.py): declarative
# WorldSpecs compiled into the SAME FaultSchedule contract, so they list
# and run exactly like the hand-written scenarios above
SCENARIOS.update(WORLD_SCENARIOS)


def scenario_names() -> list[str]:
    return sorted(SCENARIOS)


def scenario_info(name: str) -> dict:
    """Description plus default sizing for `fleet chaos list`, read from
    the generator's docstring (the `Sizing: ...` convention every
    builder follows)."""
    builder, desc = SCENARIOS[name]
    sizing = ""
    for line in (builder.__doc__ or "").splitlines():
        line = line.strip()
        if line.startswith("Sizing:"):
            sizing = line[len("Sizing:"):].strip()
            break
    return {"name": name, "description": desc, "sizing": sizing}


def build_schedule(name: str, seed: int, services: int,
                   nodes: int) -> FaultSchedule:
    if nodes < 2 or services < 1:
        raise ValueError(
            f"chaos scenarios need at least 2 nodes and 1 service "
            f"(got nodes={nodes}, services={services}): every scenario "
            f"keeps survivors to absorb displaced services")
    try:
        builder, _desc = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; available: "
            f"{', '.join(scenario_names())}") from None
    return builder(seed, services, nodes)
