"""Traffic traces: record a chaos run's primitive timeline, replay it
against a DIFFERENT world (docs/guide/18-world-simulator.md).

A trace is the bridge between the chaos harness and `fleet plan
simulate`: `fleet chaos run --record-trace` writes the schedule's fully
expanded (time, op, params) stream — arrivals, departures, correlated
faults, ticks — plus the world topology and the run's outcome, and the
simulator replays that EXACT traffic against a proposed KDL flow
through the real control-plane paths on the virtual clock.

Format: JSONL, one object per line, `kind` discriminated.

  header   {"kind": "header", "version": 1, scenario/seed/sizes,
            "tenant_caps": ..., "world": ...}
  event    {"kind": "event", "t": ..., "op": ..., "p": {...}}  (sorted)
  footer   {"kind": "footer", "digest": ..., "ok": ...,
            "baseline": <slo_summary virtual+wall buckets>,
            "stats": ...}

Every line is canonical JSON (sorted keys), so a recorded trace is
byte-reproducible from the same (scenario, seed, size) — the trace
format inherits the chaos digest contract. The footer carries the
recording run's OWN outcome: the simulator diffs a proposal's SLO
quantiles against `baseline` without re-running the baseline world.

`TraceSchedule` duck-types `faults.FaultSchedule` (events(), scenario,
seed, horizon, tenant_caps, world), so `run_schedule` replays a loaded
trace unchanged.
"""

from __future__ import annotations

import json
from pathlib import Path

__all__ = ["TRACE_VERSION", "TraceSchedule", "write_trace", "load_trace"]

TRACE_VERSION = 1


class TraceSchedule:
    """A recorded primitive timeline wearing the FaultSchedule duck:
    `events()` returns the trace's exact (t, op, params) stream — no
    re-expansion, no re-seeding, byte-for-byte what the recording run
    applied."""

    def __init__(self, scenario: str, seed: int,
                 events: list[tuple[float, str, dict]],
                 horizon: float, tenant_caps: dict, world: dict):
        self.scenario = scenario
        self.seed = seed
        self.horizon = horizon
        self.tenant_caps = dict(tenant_caps or {})
        self.world = dict(world or {})
        self._events = list(events)

    def events(self) -> list[tuple[float, str, dict]]:
        return list(self._events)

    def describe(self) -> list[str]:
        return [f"t={t:>7.1f}s {op} "
                + " ".join(f"{k}={v}" for k, v in sorted(p.items()))
                for t, op, p in self._events]


def write_trace(path, schedule, report, *, services: int, nodes: int,
                stages: int, pool_min: int) -> None:
    """Record one run: the schedule's expanded timeline plus the run's
    sizes and outcome, as canonical JSONL."""
    lines = [json.dumps({
        "kind": "header", "version": TRACE_VERSION,
        "scenario": schedule.scenario, "seed": schedule.seed,
        "services": services, "nodes": nodes, "stages": stages,
        "pool_min": pool_min, "horizon": schedule.horizon,
        "tenant_caps": getattr(schedule, "tenant_caps", {}) or {},
        "world": getattr(schedule, "world", {}) or {},
    }, sort_keys=True)]
    for t, op, p in schedule.events():
        lines.append(json.dumps({"kind": "event", "t": t, "op": op,
                                 "p": p}, sort_keys=True))
    lines.append(json.dumps({
        "kind": "footer", "digest": report.digest(), "ok": report.ok,
        "baseline": report.slo, "stats": report.stats,
    }, sort_keys=True))
    Path(path).write_text("\n".join(lines) + "\n")


def load_trace(path) -> tuple[TraceSchedule, dict, dict]:
    """Parse a recorded trace back into a replayable schedule. Returns
    (schedule, header, footer); `footer` may be empty for a truncated
    recording (the simulator then has no baseline to diff against)."""
    header: dict = {}
    footer: dict = {}
    events: list[tuple[float, str, dict]] = []
    for i, raw in enumerate(Path(path).read_text().splitlines()):
        raw = raw.strip()
        if not raw:
            continue
        row = json.loads(raw)
        kind = row.get("kind")
        if kind == "header":
            header = row
        elif kind == "event":
            events.append((float(row["t"]), str(row["op"]),
                           dict(row["p"])))
        elif kind == "footer":
            footer = row
        else:
            raise ValueError(f"{path}: line {i + 1} has unknown "
                             f"kind {kind!r}")
    if not header:
        raise ValueError(f"{path}: no trace header found — not a "
                         f"recorded trace?")
    if header.get("version") != TRACE_VERSION:
        raise ValueError(
            f"{path}: trace version {header.get('version')!r} != "
            f"supported {TRACE_VERSION}")
    sched = TraceSchedule(
        scenario=str(header["scenario"]), seed=int(header["seed"]),
        events=events, horizon=float(header["horizon"]),
        tenant_caps=header.get("tenant_caps") or {},
        world=header.get("world") or {})
    return sched, header, footer
